"""Hierarchical KV memory: a radix prefix tree over the paged block pool
with a host-RAM offload tier and an export format for cross-replica
migration.

The flat :class:`~tpu_parallel.serving.prefix_cache.PrefixCache` shares
only BUCKET-aligned whole prefixes under plain LRU: a prompt hits iff an
exact bucket-length key was stored, a Zipf-skewed multi-tenant mix
thrashes the LRU (every cold tenant's store evicts a hot tenant's
entry), and an evicted prefix is gone — the next request recomputes it.
This module is the three-level memory hierarchy that replaces it on the
block-paged path (RadixAttention, Zheng et al./SGLang, over the
refcounted block pool of Kwon et al./vLLM — see PAPERS.md):

**Level 1 — HBM, the radix tree** (:class:`RadixPrefixCache`).  A tree
keyed on token sequences whose nodes each hold ONE refcounted physical
block: an edge is exactly ``block_tokens`` token ids, so walking the
tree IS longest-common-prefix matching at block granularity — *any*
shared prefix hits, not just bucket-aligned ones, and a hit of k blocks
is k table pointer writes through the existing
:meth:`~tpu_parallel.serving.cache_pool.PagedCachePool.map_prefix` COW
machinery (partial-block tails never arise: the tree stores only FULL
blocks, so remainders always start on a block boundary and the engine's
copy-on-write reserve drops to zero).  Eviction is FREQUENCY-AWARE, not
LRU: the victim is the resident leaf minimizing ``last_use +
hit_recency_bonus * hits`` — a hot tenant's header survives a flood of
one-shot cold prompts that would have LRU-evicted it.

**Level 2 — host RAM, the offload tier.**  An evicted-but-warm node
SPILLS instead of dying: its block's K/V (payloads, positions, int8
scales) copies to pinned host arrays via one batched ``device_get``
(:meth:`PagedCachePool.export_blocks`) and the device block frees.  A
later lookup that walks into host-resident nodes RESTORES them — fresh
blocks allocated, one batched ``device_put`` + scatter
(:meth:`PagedCachePool.import_stored`) — and the hit proceeds as if the
prefix had never left HBM: zero recompute, one PCIe copy.  The tier has
its own capacity (``host_capacity_blocks``), its own frequency-aware
eviction, and typed accounting (offloads / restored blocks / host
evictions / restore fallbacks when device blocks are too scarce to
restore without starving admission).

**Level 3 — the wire, cross-replica migration**
(:class:`KVPrefixExport`).  The same export format ships a relocated
request's KV blocks replica-to-replica: the cluster frontend captures an
export before a relocation cancels the source slot (``cluster/swap.py``
drain-timeout relocation), imports it into the target engine's prefix
cache, and the forced-prefix replay's admission HITS instead of
re-prefilling — bitwise-identical continuation (cached K/V is a pure
function of tokens, positions and params; the export carries
``weights_version`` so a cross-version import refuses typed rather than
silently continuing under different weights).  Autopilot scale-ups
reuse it to warm-start a newcomer's cache from the hottest prefixes of
a live donor (``cluster/migration.py``).

Tier invariant: along any root-to-node path, device-resident nodes form
a contiguous PREFIX followed by host-resident nodes — a prefix is only
usable from block 0, so eviction always takes the deepest (leaf-most)
nodes first and restore always fills from the front of a host run.

Ownership: nodes hold allocator REFERENCES, never tables — all block
mutation stays inside ``cache_pool.py`` (references flow through
``pin_blocks`` / ``free_stored`` / ``snapshot_blocks`` /
``import_stored``; the ``scripts/check_blocks.py`` AST gate fences both
raw table writes and direct allocator calls).  Refcount conservation —
Σ node-held refs == the tree's resident block count, audited against
the allocator by ``tests/test_kv_hierarchy.py``'s property suite — is
the hierarchy's load-bearing invariant.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from tpu_parallel.serving.cache_pool import (
    KVIntegrityError,
    block_checksums,
)

# typed verdicts for an export landing in an engine
# (``ServingEngine.import_prefix``); the cluster frontend counts one
# ``cluster_kv_migrations_total{status=...}`` per attempt.  Everything
# except IMPORTED / ALREADY_CACHED is a counted fallback — the replay
# recomputes its forced prefix exactly as before this subsystem existed.
MIGRATE_IMPORTED = "imported"  # blocks landed; the replay will hit
MIGRATE_ALREADY_CACHED = "already_cached"  # target already holds it
MIGRATE_NOT_PAGED = "not_paged"  # fixed-slot target: no block pool
MIGRATE_NO_PREFIX_CACHE = "no_prefix_cache"  # target caches nothing
MIGRATE_NO_BLOCKS = "no_blocks"  # target pool too tight right now
MIGRATE_NO_KEY = "no_key"  # no bucket key fits (aligned-LRU target)
MIGRATE_INCOMPATIBLE = "incompatible"  # block size / leaf shapes differ
MIGRATE_WEIGHTS_VERSION = "weights_version"  # KV from other weights
MIGRATE_INTEGRITY = "integrity"  # payload failed its export checksum
MIGRATION_STATUSES = (
    MIGRATE_IMPORTED,
    MIGRATE_ALREADY_CACHED,
    MIGRATE_NOT_PAGED,
    MIGRATE_NO_PREFIX_CACHE,
    MIGRATE_NO_BLOCKS,
    MIGRATE_NO_KEY,
    MIGRATE_INCOMPATIBLE,
    MIGRATE_WEIGHTS_VERSION,
    MIGRATE_INTEGRITY,
)


@dataclasses.dataclass(frozen=True)
class KVPrefixExport:
    """A block-aligned KV prefix as host bytes — the exchange unit for
    both the offload tier's spill format and replica-to-replica
    migration.

    ``tokens`` is the covered token prefix (``length`` ids, a multiple
    of ``block_tokens``); ``leaves`` holds one numpy array per
    block-axis cache leaf (the :meth:`PagedCachePool.export_blocks`
    layout — flatten order, block dim at axis 0, ``length //
    block_tokens`` rows each); ``meta`` is the exporter's per-block
    shape signature and ``weights_version`` the weight set the K/V was
    computed under — importers refuse on either mismatch, because a
    shape-compatible import under different weights would CONTINUE the
    stream with silently wrong attention reads.  ``checksums`` is one
    CRC32 per block, computed over the leaves AT EXPORT (:func:`~tpu_
    parallel.serving.cache_pool.block_checksums`): a bit flipped in
    transit or at rest is a typed ``integrity`` refusal at import, not
    wrong attention for every request sharing the prefix (empty = a
    legacy export; verified when present)."""

    tokens: Tuple[int, ...]
    length: int
    block_tokens: int
    weights_version: str
    meta: tuple
    leaves: tuple
    checksums: Tuple[int, ...] = ()

    @property
    def n_blocks(self) -> int:
        return self.length // self.block_tokens

    @property
    def payload_bytes(self) -> int:
        """Raw K/V bytes this export ships on the wire (leaf payloads
        only, excluding the frame header) — what the fleet's chunk
        planner sizes segments against and the handoff byte counters
        report."""
        return sum(int(leaf.nbytes) for leaf in self.leaves)

    def verified(self) -> bool:
        """Recompute the leaf checksums against ``checksums`` — True
        when absent (legacy export) or matching."""
        if not self.checksums:
            return True
        return (
            block_checksums(list(self.leaves), self.n_blocks)
            == tuple(self.checksums)
        )


class _Node:
    """One radix-tree node == one KV block.  ``run`` is the
    ``block_tokens``-id edge from ``parent``; at most one of ``block``
    (device-resident, holds one allocator reference) or ``host``
    (offloaded leaf arrays, the export layout at k=1, with
    ``host_crc`` recorded at spill time and verified before any
    restore) is set.  ``disk`` is a blob id in the SSD tier's store —
    INCLUSIVE with the other two: a node promoted back up keeps its
    disk copy, so re-spilling it later is free and the persisted
    manifest keeps covering the chain across a restart."""

    __slots__ = (
        "run", "parent", "children", "block", "host", "host_crc",
        "disk", "hits", "last_use", "born",
    )

    def __init__(self, run, parent, born: int):
        self.run = run
        self.parent = parent
        self.children: Dict[tuple, "_Node"] = {}
        self.block: Optional[int] = None
        self.host: Optional[list] = None
        self.host_crc: Optional[int] = None
        self.disk: Optional[int] = None
        self.hits = 0
        self.last_use = born
        self.born = born


class RadixPrefixCache:
    """Token-level radix prefix index over a
    :class:`~tpu_parallel.serving.cache_pool.PagedCachePool`, with an
    optional host-RAM offload tier (see the module docstring).

    Drop-in for the engine's :class:`PrefixCache` surface on the paged
    path: ``lookup`` returns ``(block_ids, matched_length)`` (lengths
    are block multiples — the ``buckets`` argument is accepted and
    ignored), ``pop_lru`` is the admission gate's block-pressure valve
    (it spills-or-drops one resident node), and ``hits`` / ``misses`` /
    ``evictions`` feed the same metrics mirror.  ``max_device_blocks``
    bounds HBM blocks the tree holds references to;
    ``host_capacity_blocks`` bounds the warm tier (0 disables it —
    evictions then drop outright, the radix-only configuration).
    """

    def __init__(
        self,
        pool,
        max_device_blocks: int,
        host_capacity_blocks: int = 0,
        hit_recency_bonus: int = 8,
        breaker_failures: int = 4,
        breaker_probe_ops: int = 64,
        disk_store=None,
        weights_version: str = "initial",
    ):
        if max_device_blocks < 1:
            raise ValueError(
                f"max_device_blocks={max_device_blocks} < 1"
            )
        if host_capacity_blocks < 0:
            raise ValueError(
                f"host_capacity_blocks={host_capacity_blocks} < 0"
            )
        if breaker_failures < 1:
            raise ValueError(f"breaker_failures={breaker_failures} < 1")
        if breaker_probe_ops < 1:
            raise ValueError(f"breaker_probe_ops={breaker_probe_ops} < 1")
        self.pool = pool
        self.block_tokens = int(pool.block_tokens)
        self.max_device_blocks = int(max_device_blocks)
        self.host_capacity = int(host_capacity_blocks)
        # each hit is worth this many lookup/insert ops of recency in the
        # eviction score — the "frequency-aware" dial (0 = pure recency)
        self.hit_recency_bonus = int(hit_recency_bonus)
        # host-tier circuit breaker: this many CONSECUTIVE restore
        # failures (no blocks, or checksum-failed bytes) take the
        # offload tier DOWN — no spills, no restores, device-only
        # serving continues bitwise via recompute.  After
        # ``breaker_probe_ops`` further cache ops the next host hit is
        # a half-open PROBE: success closes the breaker, failure
        # re-arms the timer.
        self.breaker_failures = int(breaker_failures)
        self.breaker_probe_ops = int(breaker_probe_ops)
        self._consec_restore_failures = 0
        self._tier_down_since: Optional[int] = None  # _seq at trip
        self._seq = 0  # monotone op counter: the deterministic recency axis
        self._root = _Node(None, None, 0)
        self.device_blocks = 0  # resident nodes == device refs held
        self.host_blocks_in_use = 0
        # lookup-level tallies (PrefixCache-compatible: one hit or miss
        # per lookup call) + the hierarchy's own typed accounting
        self.hits = 0
        self.misses = 0
        self.evictions = 0  # device refs dropped (spill or outright)
        self.offloads = 0  # device -> host spills
        self.restored_blocks = 0  # host -> device restores (blocks)
        self.host_evictions = 0  # host copies dropped for good
        self.restore_failures = 0  # host hit unrestorable (no blocks)
        self.integrity_failures = 0  # checksum-failed host bytes dropped
        self.breaker_trips = 0  # times the host tier went down
        # -- SSD tier (optional, UNDER the host tier) ----------------------
        # ``disk_store`` is a ``serving/kv_disk.KVDiskStore``: cold host
        # evictions spill their payload there (prefix-closed, so every
        # disk chain restores from block 0 after a restart), lookups
        # hydrate disk runs back through host to device, and a second
        # breaker — same K-consecutive-failures / half-open shape as the
        # host tier's — takes a sick disk out of the path entirely.
        self.disk = disk_store
        self.weights_version = str(weights_version)
        self._consec_disk_failures = 0
        self._disk_down_since: Optional[int] = None  # _seq at trip
        self.disk_spills = 0  # blobs written (host -> disk)
        self.disk_restores = 0  # blocks hydrated (disk -> host)
        self.disk_restore_failures = 0  # typed hydrate refusals
        self.disk_evictions = 0  # blobs dropped (capacity or subtree)
        self.disk_breaker_trips = 0
        # typed failure tally, keyed on kv_disk.DISK_REASONS — the
        # vocabulary tests pin and the bench's rot leg audits
        self.disk_failure_reasons: Dict[str, int] = {}
        # restart seeding: manifest chains folded back into the tree
        self.disk_seeded_blocks = 0
        self.disk_seeded_chains = 0
        self.disk_orphans_dropped = 0
        if self.disk is not None:
            self._seed_from_disk()

    # -- PrefixCache-compatible surface ------------------------------------

    def __len__(self) -> int:
        """Device-resident entries (one per block) — the entry-count
        gauge's value."""
        return self.device_blocks

    def reset_counters(self) -> None:
        """Zero the tallies (tree contents stay) — the bench's
        measure-after-warmup reset, same contract as PrefixCache."""
        self.hits = self.misses = self.evictions = 0
        self.offloads = self.restored_blocks = 0
        self.host_evictions = self.restore_failures = 0
        self.integrity_failures = self.breaker_trips = 0
        self.disk_spills = self.disk_restores = 0
        self.disk_restore_failures = self.disk_evictions = 0
        self.disk_breaker_trips = 0
        self.disk_failure_reasons = {}

    # -- host-tier breaker -------------------------------------------------

    @property
    def host_tier_up(self) -> bool:
        return self._tier_down_since is None

    @property
    def breaker_state(self) -> int:
        """0 = closed (tier serving), 1 = open (tier down), 2 =
        half-open (down, but the next host hit probes) — the
        ``serving_kv_host_breaker_state`` gauge's encoding."""
        if self._tier_down_since is None:
            return 0
        if self._seq - self._tier_down_since >= self.breaker_probe_ops:
            return 2
        return 1

    def _restore_failed(self) -> None:
        """One restore failure: counted, and past ``breaker_failures``
        consecutive ones the host tier goes DOWN (a probe failure while
        down re-arms the half-open timer)."""
        self.restore_failures += 1
        self._consec_restore_failures += 1
        if self._tier_down_since is not None:
            self._tier_down_since = self._seq  # failed probe: re-arm
        elif self._consec_restore_failures >= self.breaker_failures:
            self._tier_down_since = self._seq
            self.breaker_trips += 1

    def _restore_succeeded(self) -> None:
        self._consec_restore_failures = 0
        self._tier_down_since = None  # a successful probe closes it

    # -- disk-tier breaker (mirror of the host tier's) ---------------------

    @property
    def disk_tier_up(self) -> bool:
        return self._disk_down_since is None

    @property
    def disk_breaker_state(self) -> int:
        """0 = closed, 1 = open (disk out of the path — RAM+device
        serving continues bitwise), 2 = half-open (the next disk
        operation is a probe) — ``serving_kv_disk_breaker_state``."""
        if self.disk is None or self._disk_down_since is None:
            return 0
        if self._seq - self._disk_down_since >= self.breaker_probe_ops:
            return 2
        return 1

    def _disk_failed(self, reason: str) -> None:
        """One typed disk failure (spill or hydrate): tallied by
        reason, and past ``breaker_failures`` consecutive ones the SSD
        tier goes down; a failed half-open probe re-arms the window."""
        self.disk_failure_reasons[reason] = (
            self.disk_failure_reasons.get(reason, 0) + 1
        )
        self._consec_disk_failures += 1
        if self._disk_down_since is not None:
            self._disk_down_since = self._seq  # failed probe: re-arm
        elif self._consec_disk_failures >= self.breaker_failures:
            self._disk_down_since = self._seq
            self.disk_breaker_trips += 1

    def _disk_succeeded(self) -> None:
        """A verified blob load proves the media; write success alone
        does not close the breaker (a disk that takes bytes but cannot
        give them back is still down)."""
        self._consec_disk_failures = 0
        self._disk_down_since = None

    def lookup(
        self,
        prompt: Sequence[int],
        buckets=None,
        reserve: int = 0,
    ):
        """Longest cached prefix of ``prompt`` at block granularity,
        STRICTLY shorter than the prompt (the first sampled token needs
        the last real token's hidden state).  Host-resident tail nodes
        restore to fresh device blocks first — ``reserve`` is how many
        free blocks the caller's own admissions still need, so a restore
        can never consume blocks the admission gate already promised.
        Returns ``(block_ids, length)`` or None; one counted hit or miss
        per call.  ``buckets`` is accepted for PrefixCache call-site
        compatibility and ignored (the tree needs no alignment)."""
        del buckets
        self._seq += 1
        prompt = tuple(int(t) for t in prompt)
        bt = self.block_tokens
        max_blocks = (len(prompt) - 1) // bt
        chain: List[_Node] = []
        cur = self._root
        for j in range(max_blocks):
            child = cur.children.get(prompt[j * bt : (j + 1) * bt])
            if child is None:
                break
            chain.append(child)
            cur = child
        device_n = 0
        for node in chain:
            if node.block is None:
                break
            device_n += 1
        if device_n < len(chain):
            # warm-tier hit: restore the leading host run (partial when
            # device blocks are scarce; the restored prefix still hits)
            device_n += self._restore(
                chain, chain[device_n:], reserve=reserve
            )
        if device_n == 0:
            self.misses += 1
            return None
        for node in chain[:device_n]:
            node.hits += 1
            node.last_use = self._seq
        self.hits += 1
        blocks = tuple(node.block for node in chain[:device_n])
        return blocks, device_n * bt

    def insert(self, tokens: Sequence[int], blocks) -> list:
        """Index a freshly prefilled FULL-block prefix: ``blocks`` are
        handed over with one reference each (the engine's
        ``snapshot_blocks`` bumps).  New nodes keep their block's
        reference; runs already resident return their handed-in block in
        the DUPES list for the caller to release; host-resident runs
        ADOPT the fresh device block (a free promotion — the host copy
        drops).  Capacity is enforced after the walk, never against the
        just-inserted path."""
        self._seq += 1
        tokens = tuple(int(t) for t in tokens)
        bt = self.block_tokens
        n = len(blocks)
        if len(tokens) != n * bt:
            raise ValueError(
                f"insert of {len(tokens)} tokens with {n} blocks at "
                f"{bt} tokens/block — full blocks only"
            )
        cur = self._root
        path: List[_Node] = []
        dupes: list = []
        for j in range(n):
            run = tokens[j * bt : (j + 1) * bt]
            child = cur.children.get(run)
            if child is None:
                child = _Node(run, cur, self._seq)
                cur.children[run] = child
                child.block = int(blocks[j])
                self.device_blocks += 1
            elif child.block is None:
                # host- or disk-resident: adopt the fresh device block
                # (the warm copy is now redundant; a disk copy is KEPT —
                # inclusive retention makes the next spill free and the
                # manifest keeps covering the chain across a restart)
                child.block = int(blocks[j])
                if child.host is not None:
                    child.host = None
                    child.host_crc = None
                    self.host_blocks_in_use -= 1
                self.device_blocks += 1
            else:
                dupes.append(blocks[j])
            child.last_use = self._seq
            path.append(child)
            cur = child
        self._enforce_device(protect=frozenset(id(p) for p in path))
        return dupes

    def covers(self, tokens: Sequence[int], length: int) -> bool:
        """True when the first ``length`` tokens (a block multiple) are
        already DEVICE-resident — the store path's dedup probe."""
        tokens = tuple(int(t) for t in tokens)
        bt = self.block_tokens
        cur = self._root
        for j in range(length // bt):
            cur = cur.children.get(tokens[j * bt : (j + 1) * bt])
            if cur is None or cur.block is None:
                return False
        return True

    def pop_lru(self) -> bool:
        """Evict ONE device-resident node (lowest frequency+recency
        score, deepest-first by construction) — the admission gate's
        block-pressure valve.  Spills to the host tier when it has room;
        True when a device reference was actually dropped."""
        return self._evict_one(protect=frozenset())

    # -- stats -------------------------------------------------------------

    @property
    def device_bytes(self) -> int:
        return self.device_blocks * self.pool.bytes_per_block

    @property
    def host_bytes(self) -> int:
        return self.host_blocks_in_use * self.pool.bytes_per_block

    @property
    def disk_blocks_in_use(self) -> int:
        return 0 if self.disk is None else self.disk.blocks_in_use

    @property
    def disk_bytes(self) -> int:
        return 0 if self.disk is None else self.disk.payload_bytes

    def hottest_chains(self, max_blocks: int):
        """Up to ``max_blocks`` blocks of root-to-leaf device chains,
        hottest leaf first — the autopilot scale-up's warm-start
        shopping list (``cluster/migration.py`` exports each chain and
        imports it into the newcomer)."""
        leaves = [
            n
            for n in self._walk()
            if n.block is not None
            and not any(
                c.block is not None for c in n.children.values()
            )
        ]
        leaves.sort(key=self._score, reverse=True)
        out, seen = [], set()
        for leaf in leaves:
            chain: List[_Node] = []
            cur = leaf
            while cur.run is not None:
                chain.append(cur)
                cur = cur.parent
            chain.reverse()
            # chains must stay contiguous from the root to be importable,
            # so sibling chains repeat shared ancestors — the budget
            # counts DISTINCT blocks, not chain lengths, or shared
            # prefixes would eat it twice
            fresh = [n.block for n in chain if n.block not in seen]
            if len(seen) + len(fresh) > max_blocks:
                continue
            seen.update(fresh)
            out.append(
                (
                    tuple(t for node in chain for t in node.run),
                    tuple(node.block for node in chain),
                )
            )
            if len(seen) >= max_blocks:
                break
        return out

    # -- internals ---------------------------------------------------------

    def _walk(self):
        stack = [self._root]
        while stack:
            node = stack.pop()
            if node.run is not None:
                yield node
            stack.extend(node.children.values())

    def _score(self, node: _Node):
        """Eviction score (higher = hotter): recency in op-sequence
        units plus a per-hit bonus; ``born`` breaks ties
        deterministically."""
        return (
            node.last_use + self.hit_recency_bonus * node.hits,
            node.born,
        )

    def _restore(self, chain, host_nodes, reserve: int = 0) -> int:
        """Restore the leading run of ``host_nodes`` to fresh device
        blocks: checksum-verify each node's spilled bytes, then one
        batched upload + scatter through the pool.  Restores only what
        fits beyond ``reserve`` and the slots' entitlements — a partial
        restore still extends the hit; a zero restore counts one typed
        fallback.  A checksum-failed node is an ``integrity`` refusal:
        its (unreachable-without-it) subtree drops and the lookup falls
        back to the recompute path — corrupted bytes NEVER reach the
        device.  While the breaker has the tier down, restores refuse
        outright until the half-open window opens, and then admit one
        probe.  Returns restored block count."""
        state = self.breaker_state
        if state == 1:
            return 0  # tier down, probe window not open: recompute
        if state == 2:
            host_nodes = host_nodes[:1]  # half-open: ONE probe block
        # the tail may continue past the host run into DISK-resident
        # nodes: hydrate the leading disk run into transient host
        # payloads first (disk -> host; the import below finishes the
        # promotion to device).  Typed hydrate refusals drop the
        # refused subtree — the verified leading run still restores.
        host_nodes, hydrated = self._hydrate_disk_run(host_nodes)
        if not host_nodes:
            # all-disk tail behind an open disk breaker (or a refused
            # first blob): not a host-tier failure — RAM+device serving
            # continues on whatever device prefix the caller matched
            return 0
        try:
            # verify the leading run BEFORE touching the pool: truncate
            # at the first checksum-failed node (everything below it is
            # unreachable without it anyway)
            verified = []
            corrupt = None
            for node in host_nodes:
                if node.host_crc is not None and (
                    block_checksums(node.host, 1)[0] != node.host_crc
                ):
                    corrupt = node
                    break
                verified.append(node)
            if corrupt is not None:
                self.integrity_failures += 1
                self._drop_subtree(corrupt)
                if not verified:
                    self._restore_failed()
                    return 0
            avail = self.pool.blocks_available() - int(reserve)
            k = min(len(verified), max(0, avail))
            if k == 0:
                self._restore_failed()
                return 0
            take = verified[:k]
            rows = [
                np.concatenate([n.host[i] for n in take], axis=0)
                for i in range(len(take[0].host))
            ]
            try:
                blocks = self.pool.import_stored(
                    rows, k,
                    checksums=[
                        n.host_crc for n in take
                    ] if all(
                        n.host_crc is not None for n in take
                    ) else None,
                )
            except KVIntegrityError:
                # belt and braces: the pool's own verify disagreed
                # (bytes rotted between our check and the upload
                # staging).  The whole run drops — take[0]'s subtree
                # contains the rest.
                self.integrity_failures += 1
                self._drop_subtree(take[0])
                self._restore_failed()
                return 0
            if blocks is None:
                self._restore_failed()
                return 0
            for node, blk in zip(take, blocks):
                node.block = int(blk)
                node.host = None
                node.host_crc = None
                self.host_blocks_in_use -= 1
                self.device_blocks += 1
                node.last_use = self._seq
            self.restored_blocks += k
            self._restore_succeeded()
            # restoring may overshoot the device budget: evict cold
            # nodes, never the chain the caller is about to map
            self._enforce_device(
                protect=frozenset(id(n) for n in chain)
            )
            return k
        finally:
            # hydration is TRANSIENT: a hydrated node the import did
            # not reach sheds its host payload again (the disk copy
            # stays — nothing is lost) so a failed restore cannot
            # overflow the host tier's capacity accounting
            for node in hydrated:
                if node.host is not None and node.block is None:
                    node.host = None
                    node.host_crc = None
                    self.host_blocks_in_use -= 1

    def _hydrate_disk_run(self, nodes):
        """Load the leading disk run of ``nodes`` into host payloads.

        Returns ``(usable_run, hydrated)``: the leading nodes that now
        hold host payloads, and the subset hydrated HERE (whose
        payloads are transient until the device import lands).  Every
        refusal is typed into ``disk_failure_reasons`` and drops the
        refused node's subtree — corrupted or unreadable blobs never
        serve, the chain above them still does.  Breaker discipline
        mirrors the host tier: open = no disk reads, half-open =
        exactly one probe blob (a verified load closes the breaker)."""
        run: List[_Node] = []
        hydrated: List[_Node] = []
        probe_spent = False
        for node in nodes:
            if node.host is not None:
                run.append(node)
                continue
            if node.disk is None or self.disk is None:
                break
            state = self.disk_breaker_state
            if state == 1 or (state == 2 and probe_spent):
                break
            probe_spent = True
            from tpu_parallel.serving.kv_disk import (
                DISK_WEIGHTS,
                KVDiskError,
            )

            try:
                export = self.disk.load(node.disk)
            except KVDiskError as err:
                self.disk_restore_failures += 1
                self._disk_failed(err.reason)
                self._drop_subtree(node)
                break
            if export.weights_version != self.weights_version:
                # stale weight set: a typed refusal, not media sickness
                # — no breaker feed
                self.disk_restore_failures += 1
                self.disk_failure_reasons[DISK_WEIGHTS] = (
                    self.disk_failure_reasons.get(DISK_WEIGHTS, 0) + 1
                )
                self._drop_subtree(node)
                break
            self._disk_succeeded()
            node.host = list(export.leaves)
            node.host_crc = int(export.checksums[0])
            self.host_blocks_in_use += 1
            self.disk_restores += 1
            hydrated.append(node)
            run.append(node)
        return run, hydrated

    def _enforce_device(self, protect=frozenset()) -> None:
        while self.device_blocks > self.max_device_blocks:
            if not self._evict_one(protect=protect):
                break  # only protected nodes remain: transient overshoot

    def _evict_one(self, protect) -> bool:
        """Drop one device reference: the coldest node with no
        device-resident child (deepest-first keeps the contiguous-prefix
        invariant).  Spills to the host tier when it has room — making
        room by dropping a strictly colder host entry first — else the
        node (and its unreachable host descendants) drop for good."""
        cands = [
            n
            for n in self._walk()
            if n.block is not None
            and id(n) not in protect
            and not any(
                c.block is not None for c in n.children.values()
            )
        ]
        if not cands:
            return False
        victim = min(cands, key=self._score)
        # only evicted-but-WARM blocks spill: a node nothing ever hit
        # (the typical case — a prompt's one-off suffix blocks) drops
        # outright, so the host tier holds reusable prefixes instead of
        # churning PCIe copies on bytes no lookup will ever want back.
        # An OPEN breaker stops spills too — a tier that cannot restore
        # is pure PCIe waste; the HALF-OPEN state re-admits them so the
        # tier can repopulate and the next lookup's probe can prove it
        # (corrupted host copies were dropped at detection, so the tier
        # may be empty by the time the probe window opens).
        spill = (
            self.host_capacity > 0
            and victim.hits > 0
            and self.breaker_state != 1
        )
        if spill and self.host_blocks_in_use >= self.host_capacity:
            spill = self._evict_host_one(colder_than=victim)
        if spill:
            victim.host = self.pool.export_blocks([victim.block])
            # checksum at spill time: restore verifies against it, so
            # host-RAM rot is a typed refusal, never wrong attention
            victim.host_crc = block_checksums(victim.host, 1)[0]
            self.host_blocks_in_use += 1
            self.offloads += 1
        self.pool.free_stored((victim.block,))
        victim.block = None
        self.device_blocks -= 1
        self.evictions += 1
        if victim.host is None and victim.disk is None:
            self._drop_subtree(victim)
        return True

    def _evict_host_one(self, colder_than: Optional[_Node] = None) -> bool:
        """Evict the coldest leaf-most host node.  With an SSD tier
        attached and healthy its payload SPILLS DOWN (the node stays in
        the tree, disk-resident, and the persisted manifest now covers
        its chain across a restart); otherwise it drops for good.
        Refuses when the victim would be HOTTER than the node about to
        spill into the freed slot."""
        cands = [
            n
            for n in self._walk()
            if n.host is not None
            and not any(
                c.block is not None or c.host is not None
                for c in n.children.values()
            )
        ]
        if not cands:
            return False
        victim = min(cands, key=self._score)
        if colder_than is not None and (
            self._score(victim) > self._score(colder_than)
        ):
            return False
        if self._spill_to_disk(victim):
            victim.host = None
            victim.host_crc = None
            self.host_blocks_in_use -= 1
            self.host_evictions += 1
            return True
        self._drop_subtree(victim)
        return True

    def _chain_of(self, node: _Node) -> List[_Node]:
        """Root-to-``node`` path, root's child first."""
        chain: List[_Node] = []
        cur = node
        while cur.run is not None:
            chain.append(cur)
            cur = cur.parent
        chain.reverse()
        return chain

    def _spill_to_disk(self, node: _Node) -> bool:
        """Persist ``node``'s payload — and any not-yet-persisted
        ancestors, the PREFIX-CLOSURE invariant: every disk chain must
        be restorable from block 0 by a cold process that holds nothing
        but the manifest.  Ancestors already on disk are skipped
        (inclusive retention makes re-spills free).  Typed failures
        feed the disk breaker and return False — the caller then drops
        the node exactly as before this tier existed."""
        store = self.disk
        if store is None or store.wedged or self.disk_breaker_state == 1:
            return False
        chain = self._chain_of(node)
        need = [n for n in chain if n.disk is None]
        if not need:
            return True  # already persisted
        # make room with cold pure-disk leaves; never drop something
        # hotter than what is arriving
        while store.blocks_in_use + len(need) > store.capacity_blocks:
            if not self._evict_disk_one(colder_than=node):
                return False
        from tpu_parallel.serving.kv_disk import KVDiskError

        tokens: List[int] = []
        for n in chain:
            tokens.extend(n.run)
            if n.disk is not None:
                continue
            if n.host is not None:
                rows = list(n.host)
                crc = n.host_crc
                if crc is None:
                    crc = block_checksums(rows, 1)[0]
            elif n.block is not None:
                rows = self.pool.export_blocks([n.block])
                crc = block_checksums(rows, 1)[0]
            else:
                return False  # payload gone: the prefix cannot close
            export = KVPrefixExport(
                tokens=tuple(n.run),
                length=self.block_tokens,
                block_tokens=self.block_tokens,
                weights_version=self.weights_version,
                meta=self.pool.export_meta,
                leaves=tuple(rows),
                checksums=(int(crc),),
            )
            try:
                n.disk = store.put(export, chain_tokens=tuple(tokens))
            except KVDiskError as err:
                self._disk_failed(err.reason)
                return False
            self.disk_spills += 1
        return True

    def _evict_disk_one(self, colder_than: Optional[_Node] = None) -> bool:
        """The SSD tier's capacity valve: drop the coldest childless
        disk-only leaf for good, falling back to shedding an INCLUSIVE
        disk copy (a childless node still resident above — losing only
        restart coverage, not serving).  Refuses rather than drop
        something hotter than ``colder_than``."""
        pure = [
            n
            for n in self._walk()
            if n.disk is not None
            and n.block is None
            and n.host is None
            and not n.children
        ]
        cands = pure or [
            n
            for n in self._walk()
            if n.disk is not None and not n.children
        ]
        if not cands:
            return False
        victim = min(cands, key=self._score)
        if colder_than is not None and (
            self._score(victim) > self._score(colder_than)
        ):
            return False
        if victim.block is None and victim.host is None:
            self._drop_subtree(victim)
        else:
            if self.disk is not None:
                self.disk.delete(victim.disk)
            victim.disk = None
            self.disk_evictions += 1
        return True

    def _drop_subtree(self, node: _Node) -> None:
        """Unlink ``node`` (and any host- or disk-resident descendants
        — they are unreachable without their prefix) from the tree;
        disk blobs are deleted so the manifest keeps mirroring the
        tree's disk-resident set."""
        stack = list(node.children.values())
        while stack:
            sub = stack.pop()
            stack.extend(sub.children.values())
            self._shed_residency(sub)
            # device descendants are impossible here: eviction is
            # deepest-first and the tier invariant keeps device nodes in
            # a contiguous prefix above any host node
            assert sub.block is None, "device node below an evicted one"
        self._shed_residency(node)
        if node.parent is not None:
            node.parent.children.pop(node.run, None)
        node.children.clear()

    def _shed_residency(self, node: _Node) -> None:
        if node.host is not None:
            node.host = None
            node.host_crc = None
            self.host_blocks_in_use -= 1
            self.host_evictions += 1
        if node.disk is not None:
            if self.disk is not None:
                self.disk.delete(node.disk)
            node.disk = None
            self.disk_evictions += 1

    def _seed_from_disk(self) -> None:
        """Cold-boot warm start: fold the persisted manifest back into
        the tree as disk-resident nodes, shortest chain first so a
        parent always folds before its children.  An entry whose prefix
        is missing (its ancestor's blob was swept, superseded, or shed)
        or whose weight set no longer matches is an ORPHAN — dropped
        typed, blob deleted.  Payloads stay on disk: the first lookup
        hydrates and CRC-verifies them, so a rotted blob is a typed
        refusal at restore time, never wrong attention now."""
        from tpu_parallel.serving.kv_disk import DISK_WEIGHTS

        bt = self.block_tokens
        for entry in self.disk.entries():
            tokens = entry.tokens
            if len(tokens) == 0 or len(tokens) % bt != 0:
                self.disk.delete(entry.blob)
                self.disk_orphans_dropped += 1
                continue
            if entry.weights_version != self.weights_version:
                self.disk.delete(entry.blob)
                self.disk_orphans_dropped += 1
                self.disk_failure_reasons[DISK_WEIGHTS] = (
                    self.disk_failure_reasons.get(DISK_WEIGHTS, 0) + 1
                )
                continue
            cur = self._root
            ok = True
            n_runs = len(tokens) // bt
            for j in range(n_runs - 1):
                cur = cur.children.get(tokens[j * bt : (j + 1) * bt])
                if cur is None or cur.disk is None:
                    # a chain is only restorable from block 0 — a hole
                    # in the prefix closure orphans everything below it
                    ok = False
                    break
            if not ok:
                self.disk.delete(entry.blob)
                self.disk_orphans_dropped += 1
                continue
            run = tokens[(n_runs - 1) * bt :]
            child = cur.children.get(run)
            if child is not None:
                if child.disk is not None:
                    # duplicate chain (a crash between put and delete):
                    # first blob wins, this one is garbage
                    self.disk.delete(entry.blob)
                    self.disk_orphans_dropped += 1
                    continue
                child.disk = entry.blob
            else:
                child = _Node(run, cur, 0)
                child.disk = entry.blob
                cur.children[run] = child
            self.disk_seeded_blocks += 1
        self.disk_seeded_chains = sum(
            1 for n in self._walk()
            if n.disk is not None and not n.children
        )
