"""Multi-host correctness: a real 2-process jax.distributed CPU cluster.

The reference never leaves one process (``util.py:31-38``); the framework's
multi-host paths (``runtime.initialize``, per-process loader shards,
``make_global_batch``, cross-process collectives in the DP step) were until
now only exercised on a single-process simulated mesh, where every
multi-host bug is invisible.  These tests spawn two local worker processes
(4 simulated CPU devices each -> one 8-device cluster over gloo) via
``tests/multihost_worker.py`` and compare against single-process ground
truth computed in this process.
"""

import os
import subprocess
import sys

import jax
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

# the 2-process cluster needs a free port; without portpicker the whole
# module SKIPS cleanly instead of erroring at collection
pytest.importorskip("portpicker")

from tpu_parallel.data import DataLoader, TokenDataset, classification_batch

pytestmark = pytest.mark.multihost


@pytest.fixture(scope="module")
def cluster_outputs(tmp_path_factory):
    """Run the 2-process cluster once; yield (outdir, worker0, worker1)."""
    import portpicker

    outdir = tmp_path_factory.mktemp("multihost")
    rng = np.random.default_rng(0)
    tokens = rng.integers(0, 256, size=4000, dtype=np.uint16)
    TokenDataset.write_bin(str(outdir / "corpus.bin"), tokens)

    port = portpicker.pick_unused_port()
    worker = os.path.join(os.path.dirname(__file__), "multihost_worker.py")
    env = dict(os.environ)
    env.update(
        # force the CPU path before interpreter start (sitecustomize may
        # import jax eagerly); 4 local devices per process
        JAX_PLATFORMS="cpu",
        PALLAS_AXON_POOL_IPS="",
        XLA_FLAGS="--xla_force_host_platform_device_count=4",
    )
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(i), "2", str(port), str(outdir)],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=600)
            logs.append(out)
    finally:
        # a hung worker (e.g. peer died mid-collective) must not leak past
        # the fixture — kill both before re-raising
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait()
    assert all(p.returncode == 0 for p in procs), "\n".join(logs)
    return (
        outdir,
        np.load(outdir / "worker0.npz"),
        np.load(outdir / "worker1.npz"),
    )


def _window_ids(ds, rows):
    """Recover window indices from actual token rows (content-matched, so
    assertions on them are not circular with loader internals)."""
    stream = np.asarray(ds.shards[0][: ds.num_windows * ds.seq_len]).astype(
        np.int32
    )
    ids = []
    for row in rows:
        starts = np.flatnonzero(stream[:: ds.seq_len] == row[0])
        ids.append(
            next(
                int(s)
                for s in starts
                if np.array_equal(
                    stream[s * ds.seq_len : (s + 1) * ds.seq_len], row
                )
            )
        )
    return np.asarray(ids)


def test_loader_shards_disjoint_and_deterministic(cluster_outputs, mesh_data8):
    """Process p holds rows p::P of every batch (recovered from the token
    content each worker actually received) — disjoint, and identical to the
    single-process loader's assignment."""
    outdir, w0, w1 = cluster_outputs
    ds = TokenDataset(str(outdir / "corpus.bin"), seq_len=16)
    ref = DataLoader(ds, mesh_data8, global_batch_size=8, seed=7)
    for step in range(3):
        rows0 = _window_ids(ds, w0["local_tokens"][step])
        rows1 = _window_ids(ds, w1["local_tokens"][step])
        assert set(rows0).isdisjoint(rows1)
        epoch, b = divmod(step, ref.batches_per_epoch)
        order = ref._epoch_order(epoch) + ref._window_offset
        expect = order[b * 8 : (b + 1) * 8]
        np.testing.assert_array_equal(np.sort(np.r_[rows0, rows1]), np.sort(expect))
        np.testing.assert_array_equal(rows0, expect[0::2])
        np.testing.assert_array_equal(rows1, expect[1::2])


def test_global_batch_matches_single_process(cluster_outputs, mesh_data8):
    """The stitched 2-process global batch holds exactly the windows the
    single-process loader yields, in the documented process-interleaved
    device order — token content bit-for-bit."""
    outdir, w0, w1 = cluster_outputs
    ds = TokenDataset(str(outdir / "corpus.bin"), seq_len=16)
    ref = DataLoader(ds, mesh_data8, global_batch_size=8, seed=7)
    for step in range(3):
        # both hosts must see the identical global value
        np.testing.assert_array_equal(
            w0["global_tokens"][step], w1["global_tokens"][step]
        )
        epoch, b = divmod(step, ref.batches_per_epoch)
        order = ref._epoch_order(epoch) + ref._window_offset
        rows = order[b * 8 : (b + 1) * 8]
        # device order: process 0's rows occupy devices 0-3, process 1's 4-7
        expect = ds.batch(np.r_[rows[0::2], rows[1::2]]).tokens
        np.testing.assert_array_equal(w0["global_tokens"][step], expect)


def test_dp_step_matches_single_process(cluster_outputs, mesh_data8):
    """One DP step on the cluster == the same step single-process (params
    agree across hosts bitwise, and with local ground truth numerically)."""
    import jax.numpy as jnp

    from tpu_parallel.core import TrainState
    from tpu_parallel.core.losses import make_classification_loss
    from tpu_parallel.models import MLPClassifier, MLPConfig
    from tpu_parallel.parallel import dp

    outdir, w0, w1 = cluster_outputs
    param_keys = [k for k in w0.files if k not in ("local_tokens", "global_tokens", "loss_sum")]
    assert param_keys
    for k in param_keys:  # replicated state must agree across hosts exactly
        np.testing.assert_array_equal(w0[k], w1[k], err_msg=k)
    np.testing.assert_array_equal(w0["loss_sum"], w1["loss_sum"])

    # ground truth: same model, same rows, same per-process row interleave
    cls_batch = classification_batch(jax.random.PRNGKey(0), 16, 32, 10)
    permuted = jax.tree_util.tree_map(
        lambda x: np.r_[np.asarray(x)[0::2], np.asarray(x)[1::2]], cls_batch
    )
    model = MLPClassifier(MLPConfig(hidden_size=32, dtype=jnp.float32))
    tx = optax.sgd(0.1)

    def init(rng, inputs):
        p = model.init({"params": rng}, jnp.zeros_like(inputs), train=False)[
            "params"
        ]
        return TrainState.create(apply_fn=model.apply, params=p, tx=tx, rng=rng)

    state = dp.make_init(init, mesh=mesh_data8)(
        jax.random.PRNGKey(1), permuted.inputs
    )
    step_fn = dp.make_train_step(
        make_classification_loss("data"),
        num_minibatches=2,
        mesh=mesh_data8,
        donate=False,
    )
    state, metrics = step_fn(state, None, permuted)
    flat = {
        "/".join(str(k) for k in path): np.asarray(leaf)
        for path, leaf in jax.tree_util.tree_flatten_with_path(state.params)[0]
    }
    assert set(flat) == set(param_keys)
    for k in param_keys:
        np.testing.assert_allclose(
            w0[k], flat[k], rtol=1e-5, atol=1e-6, err_msg=k
        )
    np.testing.assert_allclose(
        w0["loss_sum"], np.asarray(metrics["loss"][0]), rtol=1e-5
    )
