"""Collective-synced (sum, count) metrics.

Capability parity: the reference's metric convention (``util.py:18``,
``print_metrics`` at ``util.py:170-181``, psum sync at ``data_paral.py:220-228``)
— metrics are pytrees of ``(sum, count)`` pairs, so syncing is one ``psum`` and
accumulation across steps is a tree-add.  The reference's ``metics`` typo bug
(``data_paral.py:231``) is, naturally, not reproduced.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

Metrics = Dict[str, Tuple[jax.Array, jax.Array]]


def vma_of(x) -> Tuple[str, ...]:
    """The mesh axes ``x`` is varying over (empty outside shard_map).

    Single home for the version-sensitive vma introspection — works on
    traced arrays and on ``jax.eval_shape`` results.
    """
    # sorted: .vma is a frozenset, and hash-randomized iteration order would
    # vary the axes tuples baked into jaxprs run-to-run (compile-cache poison)
    return tuple(sorted(getattr(jax.typeof(x), "vma", ()) or ()))


def _cast_varying(x: jax.Array, axis_names: Sequence[str]) -> jax.Array:
    # lax.pcast supersedes the deprecated lax.pvary; keep the fallback while
    # the pinned jax still ships both
    pcast = getattr(lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axis_names), to="varying")
    return lax.pvary(x, tuple(axis_names))


def pvary_missing(x: jax.Array, axis_names: Sequence[str]) -> jax.Array:
    """Promote ``x`` to "varying" over any of ``axis_names`` it isn't yet.

    Under shard_map's replication checker (check_vma=True) a collective may
    only reduce over axes its operand varies on; a metric computed from
    replicated inputs (e.g. an eval loss on a broadcast batch) is *invarying*
    over the data axis and a bare ``psum(x, "data")`` is rejected.  The
    promotion is semantically free — the per-device values are identical, so
    the sum simply multiplies by the axis size exactly as it did with the
    checker off.  Outside shard_map (no vma tracking) this is a no-op.
    """
    vma = getattr(jax.typeof(x), "vma", None)
    if vma is None:
        return x
    missing = tuple(a for a in axis_names if a not in vma)
    return _cast_varying(x, missing) if missing else x


def metric(value: jax.Array, count: Union[int, jax.Array] = 1) -> Tuple[jax.Array, jax.Array]:
    """Build one (sum, count) entry. ``value`` should already be a sum."""
    return (jnp.asarray(value, jnp.float32), jnp.asarray(count, jnp.float32))


def sync_metrics(
    metrics: Metrics,
    axis_names: Union[str, Sequence[str]],
    mean_axes: Union[str, Sequence[str]] = (),
) -> Metrics:
    """All-reduce metric sums and counts over the given mesh axes.

    ``axis_names``: axes whose ranks hold *disjoint* tokens (data, seq, and
    pipe under last-stage masking) — summed.  ``mean_axes``: axes whose ranks
    compute *replicated* metrics (the tensor-parallel axis) — averaged, so
    token counts stay exact instead of multiplying by the axis size.
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    if isinstance(mean_axes, str):
        mean_axes = (mean_axes,)

    def _sync(x):
        if axis_names:
            x = lax.psum(pvary_missing(x, axis_names), axis_names)
        if mean_axes:
            x = lax.pmean(pvary_missing(x, mean_axes), mean_axes)
        return x

    with jax.named_scope("sync_metrics"):
        return jax.tree_util.tree_map(_sync, metrics)


def accumulate_metrics(running: Optional[Metrics], step: Metrics) -> Metrics:
    """Tree-add a step's metrics into the running totals."""
    if running is None:
        return step
    return jax.tree_util.tree_map(jnp.add, running, step)


def zeros_like_metrics(shapes) -> Metrics:
    """Zero-initialized pytree matching an ``eval_shape`` result.

    Works for any pytree of ``ShapeDtypeStruct``s (metrics, gradient
    accumulators, scan carries).
    """
    return jax.tree_util.tree_map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


def compute(metrics: Metrics) -> Dict[str, float]:
    """Device-get and reduce each (sum, count) to a host-side mean."""
    host = jax.device_get(metrics)
    return {k: float(s) / max(float(c), 1e-8) for k, (s, c) in host.items()}


def format_metrics(metrics: Metrics, title: Optional[str] = None) -> str:
    vals = compute(metrics)
    lines = []
    if title:
        lines.append(f" {title} ".center(32, "="))
    for k in sorted(vals):
        lines.append(f"{k}: {vals[k]:.6f}")
    return "\n".join(lines)


def print_metrics(metrics: Metrics, title: Optional[str] = None) -> None:
    print(format_metrics(metrics, title))
