"""Pluggable request routing across serving replicas.

Three policies, one contract: ``route(prompt, candidates)`` returns the
replica to try first (or None when no candidate exists).  ``candidates``
is the frontend's pre-filtered view — alive, accepting, not excluded for
this request — ordered by replica id, so policies stay pure ranking
logic with no health bookkeeping of their own.

- :class:`RoundRobinRouter` — the baseline: cycle the candidate list.
  Ignores load AND locality; every comparison in ``SERVE_r03.json``
  starts here.
- :class:`LeastLoadedRouter` — rank by :meth:`ReplicaHandle.load`
  (queue depth + active slots + discounted pending prefill tokens),
  ties to the lowest replica id.  The right default when prompts share
  nothing.
- :class:`PrefixAffinityRouter` — SGLang-style cache-aware routing:
  consistent-hash the request's BUCKET-ALIGNED prompt prefix onto a
  replica, so repeated prefixes (system prompts, few-shot headers) land
  where that replica's :class:`~tpu_parallel.serving.prefix_cache.
  PrefixCache` already holds their K/V.  Two properties matter and both
  come from the hash RING (not ``hash(prefix) % n``):

  * **Stability under failure** — when a replica dies, only the keys it
    owned move (to their ring successors); every other prefix keeps its
    replica and its warm cache.  Modulo hashing would reshuffle nearly
    everything on any membership change.
  * **Deterministic placement** — positions come from ``sha1``, not
    Python's salted ``hash``, so placement is identical across processes
    and runs (routing tests and multi-frontend deployments see one map).

  Affinity yields to load: when the hash-owner is OVERLOADED (queue
  depth at/over ``overload_queue_depth``), the router falls back to
  least-loaded — a hot prefix must not melt one replica while its peers
  idle.  Fallbacks are counted (``fallbacks``) and surface in the
  frontend's ``cluster_affinity_fallbacks`` gauge.

The prefix key mirrors :meth:`PrefixCache.lookup` alignment: the largest
bucket STRICTLY shorter than the prompt (a full-prompt hit can't exist —
the first sampled token needs the last real token's forward pass), whole
prompt when no bucket is shorter.  Aligning router and cache on the same
boundary is the point: the router's unit of placement is exactly the
cache's unit of reuse.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import List, Optional, Sequence, Tuple

from tpu_parallel.cluster.replica import ReplicaHandle


def prefix_route_key(
    prompt: Sequence[int], buckets: Optional[Sequence[int]]
) -> Tuple[int, ...]:
    """The bucket-aligned placement key for ``prompt``: its largest
    proper bucket-prefix (the longest prefix a :class:`PrefixCache`
    could ever serve), or the whole prompt when every bucket is too
    long / no buckets exist."""
    prompt = tuple(int(t) for t in prompt)
    if buckets:
        for b in sorted(buckets, reverse=True):
            if b < len(prompt):
                return prompt[:b]
    return prompt


def _stable_hash(data: bytes) -> int:
    """Process-stable 64-bit hash (sha1 prefix) — Python's ``hash`` is
    salted per process and would scramble placement every run."""
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


class Router:
    """Routing-policy contract (and registry of the built-in names)."""

    name = "base"

    def route(
        self,
        prompt: Sequence[int],
        candidates: List[ReplicaHandle],
    ) -> Optional[ReplicaHandle]:
        raise NotImplementedError


class RoundRobinRouter(Router):
    """Cycle through candidates in replica-id order, one per decision."""

    name = "rr"

    def __init__(self):
        self._next = 0

    def route(self, prompt, candidates):
        if not candidates:
            return None
        pick = candidates[self._next % len(candidates)]
        self._next += 1
        return pick


def least_loaded(candidates: List[ReplicaHandle]) -> Optional[ReplicaHandle]:
    if not candidates:
        return None
    return min(candidates, key=lambda h: (h.load(), h.replica_id))


class LeastLoadedRouter(Router):
    """Lowest ``load()`` wins; ties break to the lowest replica id so
    placement is deterministic."""

    name = "least"

    def route(self, prompt, candidates):
        return least_loaded(candidates)


class PrefixAffinityRouter(Router):
    """Consistent-hash placement on the bucket-aligned prompt prefix,
    least-loaded fallback on overload (see the module docstring).

    ``replica_ids`` fixes the ring membership up front (every replica the
    cluster was built with, dead or alive — the ring never changes, only
    which owners are currently routable).  ``vnodes`` virtual nodes per
    replica smooth the key distribution; 64 keeps per-replica share
    within a few percent of fair for any realistic replica count.
    """

    name = "prefix"

    def __init__(
        self,
        replica_ids: Sequence[int],
        buckets: Optional[Sequence[int]] = None,
        vnodes: int = 64,
        overload_queue_depth: int = 8,
    ):
        if not replica_ids:
            raise ValueError("PrefixAffinityRouter needs at least 1 replica")
        if vnodes < 1:
            raise ValueError(f"vnodes={vnodes} < 1")
        self.buckets = tuple(buckets) if buckets else None
        self.overload_queue_depth = overload_queue_depth
        self.fallbacks = 0  # affinity target overloaded -> least-loaded
        ring = []
        for rid in replica_ids:
            for v in range(vnodes):
                ring.append((_stable_hash(f"{rid}:{v}".encode()), rid))
        ring.sort()
        self._ring_points = [p for p, _ in ring]
        self._ring_ids = [rid for _, rid in ring]

    def owner(self, prompt: Sequence[int]) -> int:
        """The ring owner of this prompt's prefix key, ignoring health —
        the stable answer to "where does this prefix live?"."""
        key = prefix_route_key(prompt, self.buckets)
        h = _stable_hash(
            b"".join(int(t).to_bytes(8, "big", signed=True) for t in key)
        )
        i = bisect.bisect_right(self._ring_points, h) % len(self._ring_points)
        return self._ring_ids[i]

    def route(self, prompt, candidates):
        if not candidates:
            return None
        key = prefix_route_key(prompt, self.buckets)
        h = _stable_hash(
            b"".join(int(t).to_bytes(8, "big", signed=True) for t in key)
        )
        # walk the ring clockwise; first ROUTABLE owner wins, so keys of
        # dead/excluded replicas slide to their successors while every
        # other key keeps its home
        by_id = {c.replica_id: c for c in candidates}
        start = bisect.bisect_right(self._ring_points, h)
        pick = None
        n = len(self._ring_ids)
        for off in range(n):
            rid = self._ring_ids[(start + off) % n]
            if rid in by_id:
                pick = by_id[rid]
                break
        if pick is None:
            return None
        if pick.queue_depth >= self.overload_queue_depth:
            self.fallbacks += 1
            return least_loaded(candidates)
        return pick


def make_router(
    policy: str,
    replica_ids: Sequence[int],
    buckets: Optional[Sequence[int]] = None,
    **kwargs,
) -> Router:
    """Build a router by policy name (``rr`` / ``least`` / ``prefix``) —
    the string surface ``serve_bench --router`` and the frontend expose."""
    if policy == "rr":
        return RoundRobinRouter()
    if policy == "least":
        return LeastLoadedRouter()
    if policy == "prefix":
        return PrefixAffinityRouter(replica_ids, buckets=buckets, **kwargs)
    raise ValueError(
        f"unknown router policy {policy!r} (want rr | least | prefix)"
    )
