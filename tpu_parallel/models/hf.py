"""HuggingFace GPT-2 weight interop.

``from_hf_gpt2`` converts a ``transformers`` GPT-2 checkpoint (model or
state dict) into this framework's single-device param layout, so pretrained
GPT-2 weights drop into :class:`~tpu_parallel.models.gpt.GPTLM` /
:func:`~tpu_parallel.models.generate.generate`; ``to_hf_gpt2`` goes the
other way for ecosystem hand-off.  The round-trip is exact (no
re-quantization), and logit equivalence against the canonical torch
implementation is pinned in ``tests/test_hf.py`` — which doubles as an
architecture-parity proof for the transformer itself (pre-norm residuals,
tanh-approximate GELU, 1e-5 layernorm epsilon, per-head QKV packing).

Layout notes:
- HF ``Conv1D`` weights are already [in, out] — same as flax kernels, no
  transpose.
- HF packs ``c_attn`` columns as [q(all heads) | k | v]; this model fuses
  QKV per head ([head, 3*head_dim] blocks).  ``_qkv_to_ours`` /
  ``_qkv_to_hf`` permute between the two.
- GPT-2 ties ``lm_head`` to ``wte``; this model keeps a separate lm_head
  kernel, set to ``wte.T`` on import and written back from ``wte`` (the
  framework may untie during fine-tuning — ``to_hf_gpt2`` refuses if the
  two have drifted, rather than silently dropping one).

Reference capability: none (the reference has no model zoo or interop —
SURVEY.md §2.4 covers only its inline MLP).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax.numpy as jnp
import numpy as np

Pytree = Any


def _to_np(x) -> np.ndarray:
    if hasattr(x, "detach"):  # torch tensor
        return x.detach().cpu().numpy()
    return np.asarray(x)


def _qkv_to_ours(w: np.ndarray, n_heads: int) -> np.ndarray:
    """[*, 3*D] HF (q|k|v blocks) -> [*, H, 3, dh] fused-per-head, flattened."""
    lead = w.shape[:-1]
    d3 = w.shape[-1]
    d = d3 // 3
    dh = d // n_heads
    w = w.reshape(*lead, 3, n_heads, dh)  # [., 3, H, dh]
    w = np.moveaxis(w, -3, -2)  # [., H, 3, dh]
    return w.reshape(*lead, d3)


def _qkv_to_hf(w: np.ndarray, n_heads: int) -> np.ndarray:
    lead = w.shape[:-1]
    d3 = w.shape[-1]
    d = d3 // 3
    dh = d // n_heads
    w = w.reshape(*lead, n_heads, 3, dh)  # [., H, 3, dh]
    w = np.moveaxis(w, -2, -3)  # [., 3, H, dh]
    return w.reshape(*lead, d3)


def _state_dict(hf_model_or_dict) -> Dict[str, np.ndarray]:
    sd = (
        hf_model_or_dict.state_dict()
        if hasattr(hf_model_or_dict, "state_dict")
        else hf_model_or_dict
    )
    out = {}
    for k, v in sd.items():
        k = k.removeprefix("transformer.")
        out[k] = _to_np(v)
    return out


def from_hf_gpt2(hf_model_or_dict, config, dtype=jnp.float32) -> Pytree:
    """HF GPT-2 weights -> this framework's (unrolled-layout) params.

    ``config`` must structurally match the checkpoint (n_layers, n_heads,
    d_model, vocab_size, learned positions, gelu MLP, layernorm) — checked
    against tensor shapes as we go.  Returns the layout a mesh-free
    ``GPTLM(config).init`` produces with ``scan_layers=False``; for a
    scan-layers model, stack the per-layer leaves (tests show the recipe).
    """
    if (
        config.positional != "learned"
        or config.mlp != "gelu"
        or config.norm != "layernorm"
    ):
        raise ValueError(
            "GPT-2 interop needs positional='learned', mlp='gelu', "
            "norm='layernorm'"
        )
    if config.scan_layers:
        raise ValueError(
            "from_hf_gpt2 emits the unrolled layout; build the config with "
            "scan_layers=False (stack leaves yourself for a scanned model)"
        )
    hf_config = getattr(hf_model_or_dict, "config", None)
    if hf_config is not None and getattr(hf_config, "n_head", None) not in (
        None,
        config.n_heads,
    ):
        # n_heads is NOT derivable from any tensor shape — a mismatch would
        # silently permute QKV into garbage
        raise ValueError(
            f"checkpoint has n_head={hf_config.n_head}, config.n_heads="
            f"{config.n_heads}"
        )
    sd = _state_dict(hf_model_or_dict)
    ckpt_layers = 1 + max(
        int(k.split(".")[1]) for k in sd if k.startswith("h.")
    )
    if ckpt_layers != config.n_layers:
        raise ValueError(
            f"checkpoint has {ckpt_layers} layers, config.n_layers="
            f"{config.n_layers} — refusing to silently truncate/underfill"
        )
    if sd["wpe.weight"].shape[0] < config.seq_len:
        raise ValueError(
            f"checkpoint position table covers {sd['wpe.weight'].shape[0]} "
            f"positions < config.seq_len={config.seq_len} (longer sequences "
            "would silently reuse clipped rows under jit)"
        )
    h = config.n_heads
    cast = lambda x: jnp.asarray(x, dtype)

    def norm(prefix):
        return {"scale": cast(sd[f"{prefix}.weight"]), "bias": cast(sd[f"{prefix}.bias"])}

    wte = sd["wte.weight"]
    if wte.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"wte {wte.shape} != (vocab={config.vocab_size}, d={config.d_model})"
        )
    params: Dict[str, Any] = {
        "embed": {
            "tok": {"embedding": cast(wte)},
            "pos": {"embedding": cast(sd["wpe.weight"][: config.seq_len])},
        },
        "norm_final": norm("ln_f"),
        # GPT-2 ties the lm_head to wte
        "lm_head": {"shard": {"kernel": cast(wte.T)}},
        "blocks": {},
    }
    for i in range(config.n_layers):
        p = f"h.{i}"
        params["blocks"][f"layer_{i}"] = {
            "norm_attn": norm(f"{p}.ln_1"),
            "norm_mlp": norm(f"{p}.ln_2"),
            "attn": {
                "qkv": {
                    "shard": {
                        "kernel": cast(
                            _qkv_to_ours(sd[f"{p}.attn.c_attn.weight"], h)
                        ),
                        "bias": cast(_qkv_to_ours(sd[f"{p}.attn.c_attn.bias"], h)),
                    }
                },
                "out": {
                    "shard": {"kernel": cast(sd[f"{p}.attn.c_proj.weight"])},
                    "bias": cast(sd[f"{p}.attn.c_proj.bias"]),
                },
            },
            "mlp": {
                "up": {
                    "shard": {
                        "kernel": cast(sd[f"{p}.mlp.c_fc.weight"]),
                        "bias": cast(sd[f"{p}.mlp.c_fc.bias"]),
                    }
                },
                "down": {
                    "shard": {"kernel": cast(sd[f"{p}.mlp.c_proj.weight"])},
                    "bias": cast(sd[f"{p}.mlp.c_proj.bias"]),
                },
            },
        }
    return params


def to_hf_gpt2(
    params: Pytree, config, n_positions: Optional[int] = None
) -> Dict[str, np.ndarray]:
    """This framework's (unrolled, mesh-free) params -> an HF GPT-2 state
    dict (``transformer.``-prefixed keys plus ``lm_head.weight``) loadable
    with ``GPT2LMHeadModel.load_state_dict``.

    ``n_positions``: the target HF model's position-table length.  An import
    with ``seq_len < n_positions`` sliced the wpe table
    (:func:`from_hf_gpt2`), and torch's ``load_state_dict`` rejects shape
    mismatches even with ``strict=False`` — pass the original length to
    zero-pad the table back out (rows beyond ``seq_len`` were never
    trained; they export as zeros, not the discarded originals).
    """
    h = config.n_heads
    g = lambda *path: np.asarray(_dig(params, path), np.float32)
    wte = g("embed", "tok", "embedding")
    head = g("lm_head", "shard", "kernel").T
    if not np.allclose(wte, head, atol=1e-6):
        raise ValueError(
            "lm_head and wte have drifted apart (untied fine-tune?) — "
            "GPT-2's format ties them; refusing to drop one silently"
        )
    wpe = g("embed", "pos", "embedding")
    if n_positions is not None:
        if n_positions < wpe.shape[0]:
            raise ValueError(
                f"n_positions={n_positions} < trained position table "
                f"{wpe.shape[0]} — refusing to truncate trained rows"
            )
        if n_positions > wpe.shape[0]:
            wpe = np.concatenate(
                [wpe, np.zeros((n_positions - wpe.shape[0], wpe.shape[1]),
                               wpe.dtype)]
            )
    sd: Dict[str, np.ndarray] = {
        "transformer.wte.weight": wte,
        "transformer.wpe.weight": wpe,
        "transformer.ln_f.weight": g("norm_final", "scale"),
        "transformer.ln_f.bias": g("norm_final", "bias"),
        "lm_head.weight": wte,
    }
    for i in range(config.n_layers):
        b = ("blocks", f"layer_{i}")
        p = f"transformer.h.{i}"
        sd[f"{p}.ln_1.weight"] = g(*b, "norm_attn", "scale")
        sd[f"{p}.ln_1.bias"] = g(*b, "norm_attn", "bias")
        sd[f"{p}.ln_2.weight"] = g(*b, "norm_mlp", "scale")
        sd[f"{p}.ln_2.bias"] = g(*b, "norm_mlp", "bias")
        sd[f"{p}.attn.c_attn.weight"] = _qkv_to_hf(
            g(*b, "attn", "qkv", "shard", "kernel"), h
        )
        sd[f"{p}.attn.c_attn.bias"] = _qkv_to_hf(
            g(*b, "attn", "qkv", "shard", "bias"), h
        )
        sd[f"{p}.attn.c_proj.weight"] = g(*b, "attn", "out", "shard", "kernel")
        sd[f"{p}.attn.c_proj.bias"] = g(*b, "attn", "out", "bias")
        sd[f"{p}.mlp.c_fc.weight"] = g(*b, "mlp", "up", "shard", "kernel")
        sd[f"{p}.mlp.c_fc.bias"] = g(*b, "mlp", "up", "shard", "bias")
        sd[f"{p}.mlp.c_proj.weight"] = g(*b, "mlp", "down", "shard", "kernel")
        sd[f"{p}.mlp.c_proj.bias"] = g(*b, "mlp", "down", "bias")
    return sd


def _dig(tree, path):
    for k in path:
        tree = tree[k]
    return tree


# --- Llama --------------------------------------------------------------------


def _rope_perm(head_dim: int) -> np.ndarray:
    """HF-Llama -> this framework's RoPE dimension order.

    HF rotates pairs ``(j, j + dh/2)`` (rotate_half); this model rotates
    interleaved pairs ``(2j, 2j+1)`` (models/layers.py apply_rope).  Both
    use the same per-pair frequency ``theta^(-2j/dh)``, so permuting each
    head's q/k output dims with ``perm[2j] = j, perm[2j+1] = j + dh/2``
    makes the two rotations identical.  V and the output projection are
    untouched (no rotation on that path).
    """
    half = head_dim // 2
    perm = np.empty(head_dim, np.int64)
    perm[0::2] = np.arange(half)
    perm[1::2] = np.arange(half) + half
    return perm


def from_hf_llama(hf_model_or_dict, config, dtype=jnp.float32) -> Pytree:
    """HF Llama weights -> this framework's (unrolled-layout) params.

    Handles MHA (fused qkv) and GQA (separate q + fused kv) layouts, the
    rotate_half -> interleaved RoPE permutation, RMSNorm scales, and the
    bias-free SwiGLU MLP.  This model's attention projections carry bias
    parameters Llama lacks; they import as zeros (numerically identical).
    """
    if (
        config.positional != "rope"
        or config.mlp != "swiglu"
        or config.norm != "rmsnorm"
    ):
        raise ValueError(
            "Llama interop needs positional='rope', mlp='swiglu', "
            "norm='rmsnorm'"
        )
    if config.scan_layers:
        raise ValueError("from_hf_llama emits the unrolled layout")
    hf_config = getattr(hf_model_or_dict, "config", None)
    if hf_config is not None:
        if getattr(hf_config, "num_attention_heads", config.n_heads) != config.n_heads:
            raise ValueError(
                f"checkpoint heads {hf_config.num_attention_heads} != "
                f"config.n_heads {config.n_heads}"
            )
        ckpt_kv = getattr(hf_config, "num_key_value_heads", None)
        ours_kv = config.n_kv_heads or config.n_heads
        if ckpt_kv is not None and ckpt_kv != ours_kv:
            raise ValueError(
                f"checkpoint kv heads {ckpt_kv} != config {ours_kv}"
            )
        ckpt_eps = getattr(hf_config, "rms_norm_eps", None)
        if ckpt_eps is not None and abs(ckpt_eps - config.norm_eps) > 1e-12:
            raise ValueError(
                f"checkpoint rms_norm_eps={ckpt_eps} != config.norm_eps="
                f"{config.norm_eps} — logits would drift by ~1e-3; set "
                "norm_eps to match"
            )
        ckpt_theta = getattr(hf_config, "rope_theta", None)
        if ckpt_theta is not None and abs(ckpt_theta - config.rope_theta) > 1e-6:
            raise ValueError(
                f"checkpoint rope_theta={ckpt_theta} != config "
                f"{config.rope_theta}"
            )
    sd = _state_dict(hf_model_or_dict)
    sd = {k.removeprefix("model."): v for k, v in sd.items()}
    ckpt_layers = 1 + max(
        int(k.split(".")[1]) for k in sd if k.startswith("layers.")
    )
    if ckpt_layers != config.n_layers:
        raise ValueError(
            f"checkpoint has {ckpt_layers} layers, config.n_layers="
            f"{config.n_layers}"
        )
    wte = sd["embed_tokens.weight"]
    if wte.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"embed_tokens {wte.shape} != (vocab={config.vocab_size}, "
            f"d={config.d_model})"
        )
    if "lm_head.weight" not in sd:
        # tied-embedding checkpoints omit lm_head (it aliases embed_tokens)
        sd["lm_head.weight"] = wte
    d = config.d_model
    h = config.n_heads
    kv = config.n_kv_heads or config.n_heads
    dh = config.head_dim
    perm = _rope_perm(dh)
    cast = lambda x: jnp.asarray(x, dtype)

    def heads_T(w, n):  # HF [n*dh, D] -> ours [D, n, dh]
        return w.T.reshape(d, n, dh)

    params: Dict[str, Any] = {
        "embed": {"tok": {"embedding": cast(sd["embed_tokens.weight"])}},
        "norm_final": {"scale": cast(sd["norm.weight"])},
        "lm_head": {"shard": {"kernel": cast(sd["lm_head.weight"].T)}},
        "blocks": {},
    }
    for i in range(config.n_layers):
        p = f"layers.{i}"
        q = heads_T(sd[f"{p}.self_attn.q_proj.weight"], h)[:, :, perm]
        k = heads_T(sd[f"{p}.self_attn.k_proj.weight"], kv)[:, :, perm]
        v = heads_T(sd[f"{p}.self_attn.v_proj.weight"], kv)
        if kv == h:
            # MHA: fused qkv, per-head [q | k | v] blocks
            qkv = np.concatenate([q, k, v], axis=-1).reshape(d, 3 * d)
            attn = {
                "qkv": {
                    "shard": {
                        "kernel": cast(qkv),
                        "bias": jnp.zeros((3 * d,), dtype),
                    }
                }
            }
        else:
            # GQA: separate q + fused per-kv-head [k | v]
            kvw = np.concatenate([k, v], axis=-1).reshape(d, kv * 2 * dh)
            attn = {
                "q": {
                    "shard": {
                        "kernel": cast(q.reshape(d, h * dh)),
                        "bias": jnp.zeros((h * dh,), dtype),
                    }
                },
                "kv": {
                    "shard": {
                        "kernel": cast(kvw),
                        "bias": jnp.zeros((kv * 2 * dh,), dtype),
                    }
                },
            }
        attn["out"] = {
            "shard": {"kernel": cast(sd[f"{p}.self_attn.o_proj.weight"].T)},
            "bias": jnp.zeros((d,), dtype),
        }
        params["blocks"][f"layer_{i}"] = {
            "norm_attn": {"scale": cast(sd[f"{p}.input_layernorm.weight"])},
            "norm_mlp": {
                "scale": cast(sd[f"{p}.post_attention_layernorm.weight"])
            },
            "attn": attn,
            "mlp": {
                "gate": {
                    "shard": {"kernel": cast(sd[f"{p}.mlp.gate_proj.weight"].T)}
                },
                "up": {
                    "shard": {"kernel": cast(sd[f"{p}.mlp.up_proj.weight"].T)}
                },
                "down": {
                    "shard": {"kernel": cast(sd[f"{p}.mlp.down_proj.weight"].T)}
                },
            },
        }
    return params


def to_hf_llama(params: Pytree, config) -> Dict[str, np.ndarray]:
    """This framework's (unrolled, mesh-free) Llama params -> an HF Llama
    state dict (``model.``-prefixed keys plus ``lm_head.weight``) loadable
    with ``LlamaForCausalLM.load_state_dict`` — the inverse of
    :func:`from_hf_llama` (interleaved -> rotate_half RoPE permutation,
    per-head de-fusion, kernel transposes).

    Llama has NO attention biases; this model's projections carry them
    (imported as zeros) unless ``dense_bias=False``.  If fine-tuning moved
    them materially off zero the export would silently change the function
    — refuse instead; absent biases (dense_bias=False) export cleanly.
    """
    if (
        config.positional != "rope"
        or config.mlp != "swiglu"
        or config.norm != "rmsnorm"
    ):
        # a learned-positional/layernorm model would export silently wrong
        # (position table dropped, norm biases dropped) — same guard as
        # from_hf_llama
        raise ValueError(
            "Llama interop needs positional='rope', mlp='swiglu', "
            "norm='rmsnorm'"
        )
    d = config.d_model
    h = config.n_heads
    kv = config.n_kv_heads or config.n_heads
    dh = config.head_dim
    perm = _rope_perm(dh)
    inv = np.empty_like(perm)
    inv[perm] = np.arange(dh)
    g = lambda *path: np.asarray(_dig(params, path), np.float32)

    def check_zero_bias(tree, path, name):
        try:
            b = np.asarray(_dig(tree, path))
        except (KeyError, TypeError):
            return  # dense_bias=False: no bias param — nothing to drop
        if np.abs(b).max() > 1e-6:
            raise ValueError(
                f"{name} bias is nonzero (max |b| = {np.abs(b).max():.2e}); "
                "Llama has no bias slots — exporting would drop it. Zero "
                "the biases (or retrain without them: dense_bias=False)"
            )

    sd: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": g("embed", "tok", "embedding"),
        "model.norm.weight": g("norm_final", "scale"),
        "lm_head.weight": g("lm_head", "shard", "kernel").T,
    }
    for i in range(config.n_layers):
        ours = params["blocks"][f"layer_{i}"]
        gl = lambda *path: np.asarray(_dig(ours, path), np.float32)
        p = f"model.layers.{i}"
        sd[f"{p}.input_layernorm.weight"] = gl("norm_attn", "scale")
        sd[f"{p}.post_attention_layernorm.weight"] = gl("norm_mlp", "scale")
        attn = ours["attn"]
        if kv == h:
            check_zero_bias(attn, ("qkv", "shard", "bias"), f"layer {i} qkv")
            qkv = gl("attn", "qkv", "shard", "kernel").reshape(d, h, 3 * dh)
            q, k, v = qkv[..., :dh], qkv[..., dh : 2 * dh], qkv[..., 2 * dh :]
        else:
            check_zero_bias(attn, ("q", "shard", "bias"), f"layer {i} q")
            check_zero_bias(attn, ("kv", "shard", "bias"), f"layer {i} kv")
            q = gl("attn", "q", "shard", "kernel").reshape(d, h, dh)
            kvw = gl("attn", "kv", "shard", "kernel").reshape(d, kv, 2 * dh)
            k, v = kvw[..., :dh], kvw[..., dh:]
        check_zero_bias(attn, ("out", "bias"), f"layer {i} out")
        # undo the interleaved RoPE permutation for q and k (v untouched)
        sd[f"{p}.self_attn.q_proj.weight"] = (
            q[:, :, inv].reshape(d, h * dh).T
        )
        sd[f"{p}.self_attn.k_proj.weight"] = (
            k[:, :, inv].reshape(d, kv * dh).T
        )
        sd[f"{p}.self_attn.v_proj.weight"] = v.reshape(d, kv * dh).T
        sd[f"{p}.self_attn.o_proj.weight"] = gl(
            "attn", "out", "shard", "kernel"
        ).T
        for hf_name, ours_name in (
            ("gate_proj", "gate"),
            ("up_proj", "up"),
            ("down_proj", "down"),
        ):
            sd[f"{p}.mlp.{hf_name}.weight"] = gl(
                "mlp", ours_name, "shard", "kernel"
            ).T
    return sd


def from_hf_bert(hf_model_or_dict, config, dtype=jnp.float32):
    """HF BERT trunk weights -> ``(params, pooler)`` for the encoder family.

    ``params`` is the GPTLM (unrolled, mesh-free) layout for a
    post-norm bidirectional config; ``pooler`` is ``{"kernel", "bias"}``
    for :class:`~tpu_parallel.models.gpt.EncoderClassifier`'s tanh pooler
    (``None`` when the checkpoint has no pooler — e.g. a full
    ``BertForMaskedLM`` state dict, whose ``bert.`` prefix is stripped and
    which carries embeddings + encoder but no pooler).

    ``config`` must be the BERT-faithful variant: ``prenorm=False`` (post-
    norm residuals), ``embed_norm=True`` (embeddings.LayerNorm),
    ``mlp="gelu_exact"`` (erf gelu), ``bidirectional=True``, learned
    positions, no GQA.  Reference checkpoint structure:
    ``encoder.layer.{i}.attention.self.{query,key,value}`` /
    ``attention.output.{dense,LayerNorm}`` / ``intermediate.dense`` /
    ``output.{dense,LayerNorm}``.

    Token-type (segment) embeddings: row 0 is folded into the position
    table — exact for single-segment inputs (``token_type_ids == 0``,
    the universal fine-tuning case); two-segment NSP-style inputs are not
    representable and the fold is documented rather than silent: pass
    ``token_type_ids`` of zeros on the HF side when comparing.

    The MLM prediction head (dense+gelu+LN+decoder) is NOT imported —
    ``lm_head`` is initialized to the TIED word embedding (the decoder's
    weight without its transform), which suits fine-tuning;
    :class:`EncoderClassifier` ignores it entirely.
    """
    if config.prenorm or not config.embed_norm:
        raise ValueError(
            "BERT interop needs the post-norm variant: prenorm=False, "
            "embed_norm=True (see bert_base_hf)"
        )
    if (
        config.positional != "learned"
        or config.mlp != "gelu_exact"
        or config.norm != "layernorm"
        or not config.bidirectional
    ):
        raise ValueError(
            "BERT interop needs positional='learned', mlp='gelu_exact', "
            "norm='layernorm', bidirectional=True"
        )
    if (config.n_kv_heads or config.n_heads) != config.n_heads:
        raise ValueError("BERT has no GQA: n_kv_heads must be None/n_heads")
    if config.scan_layers:
        raise ValueError(
            "from_hf_bert emits the unrolled layout; build the config with "
            "scan_layers=False"
        )
    sd = {}
    for k, v in _state_dict(hf_model_or_dict).items():
        sd[k.removeprefix("bert.")] = v
    hf_config = getattr(hf_model_or_dict, "config", None)
    if hf_config is not None and getattr(
        hf_config, "num_attention_heads", config.n_heads
    ) != config.n_heads:
        raise ValueError(
            f"checkpoint has num_attention_heads="
            f"{hf_config.num_attention_heads}, config.n_heads={config.n_heads}"
        )
    if hf_config is not None:
        hf_eps = getattr(hf_config, "layer_norm_eps", None)
        if hf_eps is not None and abs(hf_eps - config.norm_eps) > 1e-15:
            # not derivable from any tensor — a mismatch (BERT's 1e-12 vs
            # the family default 1e-5) silently drifts every LayerNorm
            raise ValueError(
                f"checkpoint layer_norm_eps={hf_eps}, config.norm_eps="
                f"{config.norm_eps} (bert_base_hf sets 1e-12)"
            )
    ckpt_layers = 1 + max(
        int(k.split(".")[2]) for k in sd if k.startswith("encoder.layer.")
    )
    if ckpt_layers != config.n_layers:
        raise ValueError(
            f"checkpoint has {ckpt_layers} layers, config.n_layers="
            f"{config.n_layers} — refusing to silently truncate/underfill"
        )
    wte = sd["embeddings.word_embeddings.weight"]
    if wte.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"word_embeddings {wte.shape} != (vocab={config.vocab_size}, "
            f"d={config.d_model})"
        )
    wpe = sd["embeddings.position_embeddings.weight"]
    if wpe.shape[0] < config.seq_len:
        raise ValueError(
            f"checkpoint position table covers {wpe.shape[0]} positions < "
            f"config.seq_len={config.seq_len}"
        )
    cast = lambda x: jnp.asarray(x, dtype)
    # fold token-type-0 into the position table (see docstring)
    tt0 = sd["embeddings.token_type_embeddings.weight"][0]
    params: Dict[str, Any] = {
        "embed": {
            "tok": {"embedding": cast(wte)},
            "pos": {"embedding": cast(wpe[: config.seq_len] + tt0[None, :])},
            "norm": {
                "scale": cast(sd["embeddings.LayerNorm.weight"]),
                "bias": cast(sd["embeddings.LayerNorm.bias"]),
            },
        },
        # tied word embedding as a serviceable lm_head (see docstring)
        "lm_head": {"shard": {"kernel": cast(wte.T)}},
        "blocks": {},
    }
    h = config.n_heads
    for i in range(config.n_layers):
        p = f"encoder.layer.{i}"
        # torch Linear stores [out, in]; our kernels are [in, out].  Fuse
        # q|k|v blocks then regroup per-head like the GPT-2 path.
        qkv_w = np.concatenate(
            [
                sd[f"{p}.attention.self.{n}.weight"].T
                for n in ("query", "key", "value")
            ],
            axis=1,
        )
        qkv_b = np.concatenate(
            [
                sd[f"{p}.attention.self.{n}.bias"]
                for n in ("query", "key", "value")
            ]
        )
        params["blocks"][f"layer_{i}"] = {
            # post-norm: norm_attn/norm_mlp normalize the residual SUMS —
            # HF's attention.output.LayerNorm / output.LayerNorm
            "norm_attn": {
                "scale": cast(sd[f"{p}.attention.output.LayerNorm.weight"]),
                "bias": cast(sd[f"{p}.attention.output.LayerNorm.bias"]),
            },
            "norm_mlp": {
                "scale": cast(sd[f"{p}.output.LayerNorm.weight"]),
                "bias": cast(sd[f"{p}.output.LayerNorm.bias"]),
            },
            "attn": {
                "qkv": {
                    "shard": {
                        "kernel": cast(_qkv_to_ours(qkv_w, h)),
                        "bias": cast(_qkv_to_ours(qkv_b, h)),
                    }
                },
                "out": {
                    "shard": {
                        "kernel": cast(sd[f"{p}.attention.output.dense.weight"].T)
                    },
                    "bias": cast(sd[f"{p}.attention.output.dense.bias"]),
                },
            },
            "mlp": {
                "up": {
                    "shard": {
                        "kernel": cast(sd[f"{p}.intermediate.dense.weight"].T),
                        "bias": cast(sd[f"{p}.intermediate.dense.bias"]),
                    }
                },
                "down": {
                    "shard": {"kernel": cast(sd[f"{p}.output.dense.weight"].T)},
                    "bias": cast(sd[f"{p}.output.dense.bias"]),
                },
            },
        }
    pooler = None
    if "pooler.dense.weight" in sd:
        pooler = {
            "kernel": cast(sd["pooler.dense.weight"].T),
            "bias": cast(sd["pooler.dense.bias"]),
        }
    return params, pooler


def to_hf_bert(
    params: Pytree,
    config,
    pooler: Optional[Dict[str, Any]] = None,
    n_positions: Optional[int] = None,
    type_vocab_size: int = 2,
) -> Dict[str, np.ndarray]:
    """This framework's post-norm BERT params -> an HF ``BertModel`` state
    dict — the inverse of :func:`from_hf_bert`.

    The import folded token-type row 0 into the position table (exact for
    single-segment inputs); the fold cannot be split back, so the export
    writes the COMPOSITE table as ``position_embeddings`` and ZEROS for
    ``token_type_embeddings`` — the exported model computes the identical
    function for ``token_type_ids == 0``, which is the only regime the
    import supported in the first place.  ``pooler`` (the dict
    :func:`from_hf_bert` returned, or EncoderClassifier's pooler params)
    exports ``pooler.dense``; omit it for a pooler-free dict.
    """
    if config.prenorm or not config.embed_norm:
        raise ValueError(
            "BERT interop needs the post-norm variant: prenorm=False, "
            "embed_norm=True (see bert_base_hf)"
        )
    if (
        config.positional != "learned"
        or config.mlp != "gelu_exact"
        or config.norm != "layernorm"
        or not config.bidirectional
        or (config.n_kv_heads or config.n_heads) != config.n_heads
    ):
        # same guard as from_hf_bert: a tanh-gelu / causal / GQA model
        # would export silently wrong (drifted or dropped weights)
        raise ValueError(
            "BERT interop needs positional='learned', mlp='gelu_exact', "
            "norm='layernorm', bidirectional=True, no GQA"
        )
    h = config.n_heads
    g = lambda *path: np.asarray(_dig(params, path), np.float32)
    pos = g("embed", "pos", "embedding")
    if n_positions is not None:
        if n_positions < pos.shape[0]:
            raise ValueError(
                f"n_positions={n_positions} < trained position table "
                f"{pos.shape[0]} — refusing to silently truncate"
            )
        if n_positions > pos.shape[0]:
            # the import sliced a longer table (seq_len < n_positions):
            # zero-pad back out so torch accepts the dict (the discarded
            # rows are gone; they export as zeros, like to_hf_gpt2)
            pos = np.concatenate(
                [pos, np.zeros((n_positions - pos.shape[0], pos.shape[1]),
                               np.float32)]
            )
    sd: Dict[str, np.ndarray] = {
        "embeddings.word_embeddings.weight": g("embed", "tok", "embedding"),
        "embeddings.position_embeddings.weight": pos,
        "embeddings.token_type_embeddings.weight": np.zeros(
            (type_vocab_size, config.d_model), np.float32
        ),
        "embeddings.LayerNorm.weight": g("embed", "norm", "scale"),
        "embeddings.LayerNorm.bias": g("embed", "norm", "bias"),
    }
    for i in range(config.n_layers):
        b = ("blocks", f"layer_{i}")
        p = f"encoder.layer.{i}"
        qkv_w = _qkv_to_hf(g(*b, "attn", "qkv", "shard", "kernel"), h)
        qkv_b = _qkv_to_hf(g(*b, "attn", "qkv", "shard", "bias"), h)
        d = config.d_model
        for j, name in enumerate(("query", "key", "value")):
            sd[f"{p}.attention.self.{name}.weight"] = qkv_w[
                :, j * d : (j + 1) * d
            ].T
            sd[f"{p}.attention.self.{name}.bias"] = qkv_b[j * d : (j + 1) * d]
        sd[f"{p}.attention.output.dense.weight"] = g(
            *b, "attn", "out", "shard", "kernel"
        ).T
        sd[f"{p}.attention.output.dense.bias"] = g(*b, "attn", "out", "bias")
        sd[f"{p}.attention.output.LayerNorm.weight"] = g(*b, "norm_attn", "scale")
        sd[f"{p}.attention.output.LayerNorm.bias"] = g(*b, "norm_attn", "bias")
        sd[f"{p}.intermediate.dense.weight"] = g(
            *b, "mlp", "up", "shard", "kernel"
        ).T
        sd[f"{p}.intermediate.dense.bias"] = g(*b, "mlp", "up", "shard", "bias")
        sd[f"{p}.output.dense.weight"] = g(*b, "mlp", "down", "shard", "kernel").T
        sd[f"{p}.output.dense.bias"] = g(*b, "mlp", "down", "bias")
        sd[f"{p}.output.LayerNorm.weight"] = g(*b, "norm_mlp", "scale")
        sd[f"{p}.output.LayerNorm.bias"] = g(*b, "norm_mlp", "bias")
    if pooler is not None:
        sd["pooler.dense.weight"] = np.asarray(pooler["kernel"], np.float32).T
        sd["pooler.dense.bias"] = np.asarray(pooler["bias"], np.float32)
    return sd


def from_hf_t5(hf_model_or_dict, config, dtype=jnp.float32) -> Pytree:
    """HF T5 weights -> :class:`~tpu_parallel.models.seq2seq.EncoderDecoder`
    params (unrolled, mesh-free layout).

    ``config`` must be the T5-faithful variant (``t5_small_hf``):
    ``positional="relative"`` (bucketed per-stack bias),
    ``norm="rmsnorm"`` with eps 1e-6 (T5LayerNorm is RMS), ``prenorm``,
    ``dense_bias=False``, ``mlp="relu"`` (original checkpoints) or
    ``"geglu"`` (v1.1's gated-gelu ``wi_0``/``wi_1``).

    Conversions beyond renaming:

    - **Attention scale fold**: T5 computes UNSCALED ``q·k`` scores; this
      framework scales q by ``1/sqrt(head_dim)``.  Imported q kernels are
      multiplied by ``sqrt(head_dim)`` so the math is identical.
    - torch Linear ``[out, in]`` -> ``[in, out]`` transposes everywhere.
    - Self-attention q|k|v fuse to the per-head layout
      (:func:`_qkv_to_ours`); cross-attention keeps q separate and
      interleaves k|v per head (the CrossAttention ``kv`` layout).
    - Tied checkpoints (no ``lm_head.weight``): the head becomes
      ``shared.T * d_model**-0.5`` — T5's tied-head rescale folded into
      the kernel.
    """
    from tpu_parallel.models.seq2seq import Seq2SeqConfig  # noqa: F401  (doc type)

    if config.positional != "relative" or config.norm != "rmsnorm":
        raise ValueError(
            "T5 interop needs positional='relative', norm='rmsnorm' "
            "(see t5_small_hf)"
        )
    if config.dense_bias or config.mlp not in ("relu", "geglu"):
        raise ValueError(
            "T5 interop needs dense_bias=False and mlp='relu' (original) "
            "or 'geglu' (v1.1)"
        )
    if (config.n_kv_heads or config.n_heads) != config.n_heads:
        raise ValueError("T5 has no GQA: n_kv_heads must be None/n_heads")
    if config.scan_layers:
        raise ValueError(
            "from_hf_t5 emits the unrolled layout; build the config with "
            "scan_layers=False"
        )
    sd = _state_dict(hf_model_or_dict)
    hf_config = getattr(hf_model_or_dict, "config", None)
    if hf_config is not None:
        for hf_name, ours in (
            ("num_heads", config.n_heads),
            ("num_layers", config.encoder_layers),
            ("num_decoder_layers", config.n_layers),
            ("relative_attention_num_buckets", config.rel_num_buckets),
            ("relative_attention_max_distance", config.rel_max_distance),
            # T5 decouples d_kv from d_model/num_heads (t5-v1_1-small:
            # 512/6 heads at d_kv=64; t5-3b: d_kv=128) — this framework
            # fixes head_dim = d_model // n_heads, so a mismatch must be a
            # clear refusal here, not a reshape error deep in the import
            ("d_kv", config.head_dim),
            ("d_ff", config.mlp_ratio * config.d_model),
        ):
            have = getattr(hf_config, hf_name, None)
            if have is not None and have != ours:
                raise ValueError(
                    f"checkpoint {hf_name}={have} != config's {ours}"
                )
        eps = getattr(hf_config, "layer_norm_epsilon", None)
        if eps is not None and abs(eps - config.norm_eps) > 1e-12:
            raise ValueError(
                f"checkpoint layer_norm_epsilon={eps}, config.norm_eps="
                f"{config.norm_eps} (t5_small_hf sets 1e-6)"
            )
    shared = sd["shared.weight"]
    if shared.shape != (config.vocab_size, config.d_model):
        raise ValueError(
            f"shared embedding {shared.shape} != (vocab={config.vocab_size},"
            f" d={config.d_model})"
        )
    cast = lambda x: jnp.asarray(x, dtype)
    h = config.n_heads
    dh = config.head_dim
    qscale = np.sqrt(dh).astype(np.float32)

    def rms(key):
        return {"scale": cast(sd[key])}

    def self_attn(p):
        qkv = np.concatenate(
            [
                sd[f"{p}.q.weight"].T * qscale,
                sd[f"{p}.k.weight"].T,
                sd[f"{p}.v.weight"].T,
            ],
            axis=1,
        )
        return {
            "qkv": {"shard": {"kernel": cast(_qkv_to_ours(qkv, h))}},
            "out": {"shard": {"kernel": cast(sd[f"{p}.o.weight"].T)}},
        }

    def cross_attn(p):
        # k|v interleaved per head: [d, H, 2*dh] -> [d, 2*H*dh]
        k = sd[f"{p}.k.weight"].T.reshape(config.d_model, h, dh)
        v = sd[f"{p}.v.weight"].T.reshape(config.d_model, h, dh)
        kv = np.concatenate([k, v], axis=-1).reshape(config.d_model, 2 * h * dh)
        return {
            "q": {"shard": {"kernel": cast(sd[f"{p}.q.weight"].T * qscale)}},
            "kv": {"shard": {"kernel": cast(kv)}},
            "out": {"shard": {"kernel": cast(sd[f"{p}.o.weight"].T)}},
        }

    def mlp(p):
        if config.mlp == "geglu":
            return {
                "gate": {"shard": {"kernel": cast(sd[f"{p}.wi_0.weight"].T)}},
                "up": {"shard": {"kernel": cast(sd[f"{p}.wi_1.weight"].T)}},
                "down": {"shard": {"kernel": cast(sd[f"{p}.wo.weight"].T)}},
            }
        return {
            "up": {"shard": {"kernel": cast(sd[f"{p}.wi.weight"].T)}},
            "down": {"shard": {"kernel": cast(sd[f"{p}.wo.weight"].T)}},
        }

    # T5 applies a d_model**-0.5 rescale to the decoder output IFF the head
    # is tied — a forward-pass behavior, NOT baked into the stored weights
    # (tied checkpoints still expose lm_head.weight in the state dict,
    # aliasing shared).  Fold the scale into the kernel when tied.
    tied = getattr(hf_config, "tie_word_embeddings", None)
    if "lm_head.weight" in sd:
        head = sd["lm_head.weight"].T
        if tied is None:
            tied = np.array_equal(sd["lm_head.weight"], shared)
        if tied:
            head = head * (config.d_model**-0.5)
    else:
        head = shared.T * (config.d_model**-0.5)

    params: Dict[str, Any] = {
        "embed": {"tok": {"embedding": cast(shared)}},
        "enc_rel_bias": {
            "rel_embedding": cast(
                sd["encoder.block.0.layer.0.SelfAttention"
                   ".relative_attention_bias.weight"]
            )
        },
        "dec_rel_bias": {
            "rel_embedding": cast(
                sd["decoder.block.0.layer.0.SelfAttention"
                   ".relative_attention_bias.weight"]
            )
        },
        "enc_norm": rms("encoder.final_layer_norm.weight"),
        "dec_norm": rms("decoder.final_layer_norm.weight"),
        "lm_head": {"shard": {"kernel": cast(head)}},
        "encoder": {},
        "decoder": {},
    }
    for i in range(config.encoder_layers):
        p = f"encoder.block.{i}"
        params["encoder"][f"layer_{i}"] = {
            "norm_attn": rms(f"{p}.layer.0.layer_norm.weight"),
            "norm_mlp": rms(f"{p}.layer.1.layer_norm.weight"),
            "attn": self_attn(f"{p}.layer.0.SelfAttention"),
            "mlp": mlp(f"{p}.layer.1.DenseReluDense"),
        }
    for i in range(config.n_layers):
        p = f"decoder.block.{i}"
        params["decoder"][f"layer_{i}"] = {
            "norm_self": rms(f"{p}.layer.0.layer_norm.weight"),
            "norm_cross": rms(f"{p}.layer.1.layer_norm.weight"),
            "norm_mlp": rms(f"{p}.layer.2.layer_norm.weight"),
            "self_attn": self_attn(f"{p}.layer.0.SelfAttention"),
            "cross_attn": cross_attn(f"{p}.layer.1.EncDecAttention"),
            "mlp": mlp(f"{p}.layer.2.DenseReluDense"),
        }
    return params


def to_hf_t5(params: Pytree, config) -> Dict[str, np.ndarray]:
    """This framework's T5-faithful seq2seq params -> an HF T5 state dict —
    the inverse of :func:`from_hf_t5`.

    Undoes the two forward-pass folds: the ``sqrt(head_dim)`` scale comes
    OFF the q kernels (T5 attention is unscaled), and the lm_head exports
    UNTIED (``lm_head.weight`` present, no ``d**-0.5`` rescale to strip —
    dividing it back out reconstructs T5's tied forward exactly, and
    untied checkpoints load it verbatim; pass the result to a model with
    ``tie_word_embeddings=False``, or compare against a tied model with
    the shared embedding).  Emits the mapping HF's
    ``T5ForConditionalGeneration`` loads: ``shared`` + per-stack
    ``relative_attention_bias`` on block 0 + self/cross attention blocks.
    """
    if config.positional != "relative" or config.norm != "rmsnorm":
        raise ValueError(
            "T5 interop needs positional='relative', norm='rmsnorm' "
            "(see t5_small_hf)"
        )
    if config.dense_bias or config.mlp not in ("relu", "geglu"):
        raise ValueError(
            "T5 interop needs dense_bias=False and mlp='relu' or 'geglu'"
        )
    if (config.n_kv_heads or config.n_heads) != config.n_heads:
        raise ValueError("T5 has no GQA: n_kv_heads must be None/n_heads")
    if config.scan_layers:
        raise ValueError(
            "to_hf_t5 reads the unrolled layout; build the config with "
            "scan_layers=False"
        )
    d = config.d_model
    h = config.n_heads
    dh = config.head_dim
    qscale = np.float32(1.0 / np.sqrt(dh))
    g = lambda *path: np.asarray(_dig(params, path), np.float32)

    shared = g("embed", "tok", "embedding")
    sd: Dict[str, np.ndarray] = {
        "shared.weight": shared,
        # T5 stores the per-stack embed_tokens as (tied) aliases of shared
        "encoder.embed_tokens.weight": shared,
        "decoder.embed_tokens.weight": shared,
        "encoder.block.0.layer.0.SelfAttention.relative_attention_bias"
        ".weight": g("enc_rel_bias", "rel_embedding"),
        "decoder.block.0.layer.0.SelfAttention.relative_attention_bias"
        ".weight": g("dec_rel_bias", "rel_embedding"),
        "encoder.final_layer_norm.weight": g("enc_norm", "scale"),
        "decoder.final_layer_norm.weight": g("dec_norm", "scale"),
        # exported untied: T5's tied forward rescales by d**-0.5 at run
        # time, which the import folded INTO this kernel — dividing it out
        # here would only be correct for tied checkpoints, so emit the
        # kernel as-is and load with tie_word_embeddings=False
        "lm_head.weight": g("lm_head", "shard", "kernel").T,
    }

    def sub(tree):
        return lambda *path: np.asarray(_dig(tree, path), np.float32)

    def split_self(attn):
        qkv = _qkv_to_hf(sub(attn)("qkv", "shard", "kernel"), h)
        q, k, v = (qkv[:, j * d : (j + 1) * d] for j in range(3))
        return (q * qscale).T, k.T, v.T

    def split_cross(attn):
        q = sub(attn)("q", "shard", "kernel")
        kvw = sub(attn)("kv", "shard", "kernel").reshape(d, h, 2 * dh)
        k = kvw[..., :dh].reshape(d, h * dh)
        v = kvw[..., dh:].reshape(d, h * dh)
        return (q * qscale).T, k.T, v.T

    def mlp_keys(ours, p):
        gm = sub(ours)
        out = {}
        if config.mlp == "geglu":
            out[f"{p}.wi_0.weight"] = gm("gate", "shard", "kernel").T
            out[f"{p}.wi_1.weight"] = gm("up", "shard", "kernel").T
        else:
            out[f"{p}.wi.weight"] = gm("up", "shard", "kernel").T
        out[f"{p}.wo.weight"] = gm("down", "shard", "kernel").T
        return out

    for i in range(config.encoder_layers):
        ours = params["encoder"][f"layer_{i}"]
        p = f"encoder.block.{i}"
        q, k, v = split_self(ours["attn"])
        sd[f"{p}.layer.0.SelfAttention.q.weight"] = q
        sd[f"{p}.layer.0.SelfAttention.k.weight"] = k
        sd[f"{p}.layer.0.SelfAttention.v.weight"] = v
        go = sub(ours)
        sd[f"{p}.layer.0.SelfAttention.o.weight"] = go(
            "attn", "out", "shard", "kernel"
        ).T
        sd[f"{p}.layer.0.layer_norm.weight"] = go("norm_attn", "scale")
        sd[f"{p}.layer.1.layer_norm.weight"] = go("norm_mlp", "scale")
        sd.update(mlp_keys(ours["mlp"], f"{p}.layer.1.DenseReluDense"))
    for i in range(config.n_layers):
        ours = params["decoder"][f"layer_{i}"]
        p = f"decoder.block.{i}"
        q, k, v = split_self(ours["self_attn"])
        sd[f"{p}.layer.0.SelfAttention.q.weight"] = q
        sd[f"{p}.layer.0.SelfAttention.k.weight"] = k
        sd[f"{p}.layer.0.SelfAttention.v.weight"] = v
        go = sub(ours)
        sd[f"{p}.layer.0.SelfAttention.o.weight"] = go(
            "self_attn", "out", "shard", "kernel"
        ).T
        cq, ck, cv = split_cross(ours["cross_attn"])
        sd[f"{p}.layer.1.EncDecAttention.q.weight"] = cq
        sd[f"{p}.layer.1.EncDecAttention.k.weight"] = ck
        sd[f"{p}.layer.1.EncDecAttention.v.weight"] = cv
        sd[f"{p}.layer.1.EncDecAttention.o.weight"] = go(
            "cross_attn", "out", "shard", "kernel"
        ).T
        for j, name in ((0, "norm_self"), (1, "norm_cross"), (2, "norm_mlp")):
            sd[f"{p}.layer.{j}.layer_norm.weight"] = go(name, "scale")
        sd.update(mlp_keys(ours["mlp"], f"{p}.layer.2.DenseReluDense"))
    return sd
