from tpu_parallel.data.loader import DataLoader, TokenDataset, make_global_batch
from tpu_parallel.data.packed import PackedDataset
from tpu_parallel.data.synthetic import classification_batch, lm_batch, seq2seq_batch

__all__ = [
    "DataLoader",
    "TokenDataset",
    "PackedDataset",
    "make_global_batch",
    "classification_batch",
    "lm_batch",
    "seq2seq_batch",
]
