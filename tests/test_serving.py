"""Continuous-batching engine tests: greedy parity with the static path,
slot reuse across staggered arrivals, scheduler policies, per-request
sampling isolation."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.serving import (
    EXPIRED,
    FINISHED,
    REJECTED,
    FIFOScheduler,
    Request,
    RequestOutput,
    SamplingParams,
    SchedulerConfig,
    ServingEngine,
    percentile,
)


def _build(rng, n_rows=3, prompt_len=5, **overrides):
    cfg = tiny_test(dtype=jnp.float32, remat=False, **overrides)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (n_rows, prompt_len), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, prompt, train=False
    )["params"]
    return cfg, model, prompt, params


def _req(prompt_row, n_new, **kwargs):
    return Request(
        prompt=[int(t) for t in np.asarray(prompt_row)],
        max_new_tokens=n_new,
        **kwargs,
    )


@pytest.mark.parametrize("variant", ["gpt", "rope"])
def test_engine_greedy_parity_simultaneous(rng, variant):
    """Acceptance: N simultaneously-arriving greedy requests through the
    engine are token-identical to static generate() on the same prompts —
    learned-pos (GPT-2) and RoPE variants."""
    overrides = dict(
        gpt={}, llama={}, rope=dict(positional="rope", norm="rmsnorm")
    )[variant]
    cfg, model, prompt, params = _build(rng, n_rows=3, **overrides)
    want = np.asarray(generate(model, params, prompt, max_new_tokens=8))
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=3),
    )
    outs = [eng.add_request(_req(prompt[i], 8)) for i in range(3)]
    eng.run()
    for i, out in enumerate(outs):
        assert out.status == FINISHED and out.finish_reason == "length"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), want[i], err_msg=f"request {i}"
        )


def test_engine_staggered_arrivals_match_reference(rng):
    """Acceptance: requests joining mid-flight into freed slots (pool of 2,
    4 requests of different prompt lengths and budgets, arrivals spread
    over ticks) each match a one-request-at-a-time reference decode."""
    cfg, model, _, params = _build(rng)
    lens, budgets = [3, 5, 4, 6], [6, 4, 8, 5]
    rows = [
        jax.random.randint(
            jax.random.fold_in(rng, i), (1, L), 1, cfg.vocab_size
        )
        for i, L in enumerate(lens)
    ]
    refs = [
        np.asarray(generate(model, params, r, max_new_tokens=n))
        for r, n in zip(rows, budgets)
    ]
    eng = ServingEngine(model, params, n_slots=2)
    outs = [eng.add_request(_req(rows[0][0], budgets[0]))]
    outs.append(eng.add_request(_req(rows[1][0], budgets[1])))
    eng.step(), eng.step()
    outs.append(eng.add_request(_req(rows[2][0], budgets[2])))
    eng.step(), eng.step()
    outs.append(eng.add_request(_req(rows[3][0], budgets[3])))
    eng.run()
    for i, (out, ref) in enumerate(zip(outs, refs)):
        assert out.status == FINISHED, f"request {i}: {out.status}"
        np.testing.assert_array_equal(
            np.asarray(out.tokens), ref[0], err_msg=f"request {i}"
        )
    # four requests through two slots => slots were reused
    assert eng.metrics.finished == 4 and eng.pool.n_free == 2


def test_slot_reuse_after_completion(rng):
    """A single-slot pool serves requests strictly in sequence: the second
    runs only after the first retires and reuses its slot, with outputs
    unpolluted by the slot's previous occupant."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    refs = [
        np.asarray(generate(model, params, prompt[i : i + 1], max_new_tokens=5))
        for i in range(2)
    ]
    eng = ServingEngine(model, params, n_slots=1)
    a = eng.add_request(_req(prompt[0], 5))
    b = eng.add_request(_req(prompt[1], 5))
    # first tick admits only request a (one slot)
    eng.step()
    assert a.status == "running" and b.status == "queued"
    eng.run()
    np.testing.assert_array_equal(np.asarray(a.tokens), refs[0][0])
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1][0])
    assert eng.pool.n_free == 1


def test_eos_retires_before_max_new_tokens(rng):
    """EOS stop: the engine retires the slot at the first EOS (included in
    the output) instead of decoding to the length budget."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    ref = list(
        np.asarray(generate(model, params, prompt, max_new_tokens=8))[0]
    )
    eos = int(ref[2])
    stop = ref.index(eos)  # first occurrence (<= 2, well before 8)
    eng = ServingEngine(model, params, n_slots=2)
    out = eng.add_request(_req(prompt[0], 8, eos_token_id=eos))
    eng.run()
    assert out.finish_reason == "eos"
    assert out.tokens == ref[: stop + 1]
    assert eng.pool.n_free == 2  # slot returned


def test_admission_control_rejects_when_full(rng):
    """max_queue admission control: submissions beyond the queue bound are
    REJECTED at submit time while the pool is busy."""
    cfg, model, prompt, params = _build(rng, n_rows=3)
    eng = ServingEngine(
        model, params, n_slots=1,
        scheduler=SchedulerConfig(max_queue=1),
    )
    a = eng.add_request(_req(prompt[0], 6))
    eng.step()  # a occupies the only slot; queue is empty again
    b = eng.add_request(_req(prompt[1], 6))
    c = eng.add_request(_req(prompt[2], 6))
    assert b.status == "queued"
    assert c.status == REJECTED and c.finish_reason == "queue full"
    eng.run()
    assert a.status == FINISHED and b.status == FINISHED
    assert c.tokens == []


def test_queue_timeout_expires_requests(rng):
    """max_wait: a queued request whose wait exceeds the budget EXPIRES
    instead of serving a long-abandoned client (deterministic via an
    injected clock)."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    t = [0.0]
    eng = ServingEngine(
        model, params, n_slots=1,
        scheduler=SchedulerConfig(max_wait=10.0),
        clock=lambda: t[0],
    )
    seen = []
    a = eng.add_request(_req(prompt[0], 6))
    b = eng.add_request(
        _req(prompt[1], 6, on_token=lambda ev: seen.append(ev))
    )
    eng.step()  # a takes the slot, b queued at t=0
    t[0] = 11.0
    events = eng.run()
    assert a.status == FINISHED
    assert b.status == EXPIRED and b.tokens == []
    assert b.finish_reason == "max_wait"
    # expiry is asynchronous: the stream gets a tokenless terminal event
    assert len(seen) == 1 and seen[0].finished and seen[0].token == -1
    assert seen[0].finish_reason == "max_wait"
    assert any(
        ev.request_id == b.request.request_id and ev.finished
        for ev in events
    )
    assert eng.metrics.expired == 1
    assert eng.metrics.tokens_out == 6  # a's tokens only, not the notification


def test_per_request_sampling_isolation(rng):
    """Per-slot sampling knobs: a greedy request, a temp-with-top_k=1
    request (deterministically argmax — proves the per-row filter applies
    to ITS row), and a hot-temperature request share ticks; the two
    deterministic rows must match the static greedy reference exactly."""
    cfg, model, prompt, params = _build(rng, n_rows=1)
    ref = np.asarray(generate(model, params, prompt, max_new_tokens=6))[0]
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
        rng=jax.random.PRNGKey(3),
    )
    greedy = eng.add_request(_req(prompt[0], 6))
    topk1 = eng.add_request(
        _req(prompt[0], 6, sampling=SamplingParams(temperature=1.0, top_k=1))
    )
    hot = eng.add_request(
        _req(prompt[0], 6, sampling=SamplingParams(temperature=4.0))
    )
    eng.run()
    np.testing.assert_array_equal(np.asarray(greedy.tokens), ref)
    np.testing.assert_array_equal(np.asarray(topk1.tokens), ref)
    assert len(hot.tokens) == 6
    assert all(0 <= tok < cfg.vocab_size for tok in hot.tokens)


def test_engine_int8_cache_matches_static_int8(rng):
    """The engine's slot pool composes with kv_cache_dtype="int8": both
    paths quantize identically, so engine greedy tokens equal static
    generate() on the same int8-cache model."""
    cfg, model, prompt, params = _build(rng, n_rows=2, kv_cache_dtype="int8")
    want = np.asarray(generate(model, params, prompt, max_new_tokens=6))
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = [eng.add_request(_req(prompt[i], 6)) for i in range(2)]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])


def test_streaming_events_and_metrics(rng):
    """Incremental delivery + observability: on_token fires once per token
    in order, and the summary's counters/latency stats are coherent."""
    cfg, model, prompt, params = _build(rng, n_rows=2)
    seen = []
    eng = ServingEngine(model, params, n_slots=2)
    out = eng.add_request(
        _req(prompt[0], 5, on_token=lambda ev: seen.append(ev))
    )
    eng.run()
    assert [ev.token for ev in seen] == out.tokens
    assert [ev.index for ev in seen] == list(range(5))
    assert seen[-1].finished and seen[-1].finish_reason == "length"
    s = eng.metrics.summary()
    assert s["finished"] == 1 and s["tokens_out"] == 5
    assert s["ttft_ms_p50"] is not None and s["ttft_ms_p50"] >= 0
    assert 0.0 < s["slot_occupancy_mean"] <= 1.0
    assert s["tokens_per_sec"] is None or s["tokens_per_sec"] > 0


def test_capacity_rejected_at_submit(rng):
    cfg, model, prompt, params = _build(rng, n_rows=1)
    eng = ServingEngine(model, params, n_slots=1)
    out = eng.add_request(_req(prompt[0], cfg.seq_len))
    assert out.status == REJECTED and "seq_len" in out.finish_reason


def test_scheduler_policies_host_only():
    """Pure host-side scheduler behavior: FIFO order, prefill budget,
    expiry — no device work."""
    sched = FIFOScheduler(SchedulerConfig(max_prefills_per_tick=2))
    outs = [
        RequestOutput(Request(prompt=[1]), arrival_time=float(i))
        for i in range(5)
    ]
    for out in outs:
        assert sched.submit(out)
    assert sched.depth == 5
    first = sched.schedule(n_free=4, now=10.0)
    assert first == outs[:2]  # prefill budget caps below free slots
    second = sched.schedule(n_free=1, now=10.0)
    assert second == outs[2:3]  # free slots cap below the budget
    timed = FIFOScheduler(SchedulerConfig(max_wait=5.0))
    old = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    new = RequestOutput(Request(prompt=[1]), arrival_time=8.0)
    timed.submit(old), timed.submit(new)
    dropped = timed.expire(now=9.0)
    assert dropped == [old] and old.status == EXPIRED
    assert timed.schedule(4, 9.0) == [new]


def test_percentile_helper():
    assert percentile([], 50) is None
    assert percentile([3.0], 95) == 3.0
    assert percentile([1.0, 2.0, 3.0, 4.0], 50) == 2.5
    assert percentile([1.0, 2.0, 3.0, 4.0], 100) == 4.0


@pytest.mark.skipif(
    not hasattr(jax, "shard_map"),
    reason="jax.shard_map unavailable (the repo's sharded paths need it)",
)
def test_engine_sharded_tp_matches_static(mesh_data4_model2, rng):
    """TP serving through the engine: mesh-sharded weights, head-sharded
    cache pool, greedy tokens identical to generate_sharded on the same
    mesh."""
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import generate_sharded

    mesh = mesh_data4_model2
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 5), 1, cfg.vocab_size)

    def init(r, p):
        return model.init({"params": r}, p, train=False)["params"]

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, prompt))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, prompt)

    want = np.asarray(
        generate_sharded(model, params, prompt, mesh, max_new_tokens=6)
    )
    eng = ServingEngine(
        model, params, n_slots=2, mesh=mesh, param_specs=specs,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = [eng.add_request(_req(prompt[i], 6)) for i in range(2)]
    eng.run()
    for i, out in enumerate(outs):
        np.testing.assert_array_equal(np.asarray(out.tokens), want[i])
