"""Pipeline-parallel tests: GPipe schedule correctness and end-to-end training.

The reference has zero pipeline logic to mirror (its pipeline_parallel.py is
an import-only stub), so these tests define the contract from scratch:
(1) the pipelined forward equals sequentially composing the per-stage modules,
(2) a PP classifier trains end-to-end on a pipe x data mesh.
"""

import functools

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

from tpu_parallel.core import compute
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.parallel import pp
from tpu_parallel.parallel.spmd import build_train_functions, make_model_init
from tpu_parallel.core.state import Batch, TrainState
from tpu_parallel.data import classification_batch

DIM = 16


class _Block(nn.Module):
    """A residual stage block (shape-preserving, as pipeline stages must be)."""

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.Dense(DIM)(x)
        h = nn.silu(h)
        return x + h


def test_pipeline_forward_equals_sequential(mesh_pipe4_data2, rng):
    """Pipelined forward == applying the 4 stage modules one after another."""
    x = jax.random.normal(jax.random.PRNGKey(0), (8, DIM))
    model = pp.PipelineModule(
        stage_fn=_Block, num_microbatches=4, axis_name="pipe", broadcast_outputs=True
    )

    def body(rng, x):
        variables = model.init({"params": rng}, x)
        out = model.apply(variables, x)
        return variables["params"], out

    probe = jax.shard_map(
        body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
        out_specs=P(), check_vma=False,
    )
    shapes = jax.eval_shape(probe, rng, x)
    specs = nn.get_partition_spec(shapes)[0]
    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
            out_specs=(specs, P("data", None)), check_vma=False,
        )
    )
    params, out = f(rng, x)

    # Assemble per-stage weights ([4, DIM, DIM] kernels) and compose manually.
    stage_params = params["stage"]["sharded"]
    kernel = np.asarray(stage_params["Dense_0"]["kernel"].value)  # [4, DIM, DIM]
    bias = np.asarray(stage_params["Dense_0"]["bias"].value)  # [4, DIM]
    ref = np.asarray(x)
    for s in range(4):
        ref = ref + np.asarray(jax.nn.silu(jnp.asarray(ref @ kernel[s] + bias[s])))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-5)


def test_pipeline_stage_params_differ(mesh_pipe4_data2, rng):
    """RNG folding must give each pipe rank independent stage weights."""
    x = jnp.zeros((8, DIM))
    model = pp.PipelineModule(stage_fn=_Block, num_microbatches=2)

    def body(rng, x):
        return model.init({"params": rng}, x)["params"]

    probe = jax.shard_map(
        body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
        out_specs=P(), check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, x))
    f = jax.jit(
        jax.shard_map(
            body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
            out_specs=specs, check_vma=False,
        )
    )
    params = f(rng, x)
    kernel = np.asarray(params["stage"]["sharded"]["Dense_0"]["kernel"].value)
    for a in range(4):
        for b in range(a + 1, 4):
            assert not np.allclose(kernel[a], kernel[b]), f"stages {a},{b} identical"


class _DropoutBlock(nn.Module):
    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.Dense(DIM)(x)
        h = nn.Dropout(rate=0.5, deterministic=not train)(h)
        return x + h


def test_pipeline_forwards_kwargs_to_stages(mesh_pipe4_data2, rng):
    """train=False must reach the stage modules: eval is deterministic."""
    x = jax.random.normal(jax.random.PRNGKey(0), (8, DIM))
    model = pp.PipelineModule(
        stage_fn=_DropoutBlock, num_microbatches=4, broadcast_outputs=True
    )

    def body(rng, drng, x):
        variables = model.init({"params": rng}, x, train=False)
        return model.apply(variables, x, train=False, rngs={"dropout": drng})

    f = jax.jit(
        jax.shard_map(
            body,
            mesh=mesh_pipe4_data2,
            in_specs=(P(), P(), P("data", None)),
            out_specs=P("data", None),
            check_vma=False,
        )
    )
    out1 = f(rng, jax.random.PRNGKey(1), x)
    out2 = f(rng, jax.random.PRNGKey(2), x)
    # different dropout rngs, identical outputs <=> dropout actually disabled
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))


def test_indivisible_microbatches_raise(mesh_pipe4_data2, rng):
    model = pp.PipelineModule(stage_fn=_Block, num_microbatches=3)
    x = jnp.zeros((8, DIM))  # 8 % 3 != 0

    def body(rng, x):
        return model.init({"params": rng}, x)["params"]

    with pytest.raises(ValueError, match="not divisible"):
        jax.eval_shape(
            jax.shard_map(
                body, mesh=mesh_pipe4_data2, in_specs=(P(), P("data", None)),
                out_specs=P(), check_vma=False,
            ),
            rng,
            x,
        )


class _PPClassifier(nn.Module):
    """Embed -> pipelined residual blocks -> head, loss valid on last rank."""

    num_classes: int = 10
    num_microbatches: int = 4

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = nn.Dense(DIM, name="embed")(x)
        x = pp.PipelineModule(
            stage_fn=_Block, num_microbatches=self.num_microbatches, name="pipeline"
        )(x, train=train)
        return nn.Dense(self.num_classes, name="head")(x).astype(jnp.float32)


def _pp_loss(params, apply_fn, batch, rng):
    dropout_rng = fold_rng_over_axis(rng, ("data", "pipe"))
    logits = apply_fn({"params": params}, batch.inputs, rngs={"dropout": dropout_rng})
    loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch.labels)
    mask = pp.last_stage_mask("pipe")
    correct = (logits.argmax(-1) == batch.labels).astype(jnp.float32)
    bs = jnp.float32(batch.labels.size)
    metrics = {
        "loss": ((loss * mask).sum(), bs * mask),
        "accuracy": ((correct * mask).sum(), bs * mask),
    }
    return (loss * mask).mean(), metrics


def test_pp_replicated_params_stay_consistent(mesh_pipe4_data2, rng):
    """Embed/head params are replicated over pipe but only one rank produces
    their gradient; grad_psum_axes=('pipe',) must keep all ranks bit-identical
    (without it they silently diverge)."""
    batch = classification_batch(jax.random.PRNGKey(5), 32, DIM, 10)
    model = _PPClassifier()
    init = make_model_init(model, optax.adamw(1e-3), train_arg=True)
    funcs = build_train_functions(
        init,
        _pp_loss,
        mesh_pipe4_data2,
        batch,
        grad_sync_axes=("data",),
        grad_psum_axes=("pipe",),
        metric_axes=("data", "pipe"),
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    for _ in range(5):
        state, _ = funcs.step_fn(state, None, batch)
    read = jax.jit(
        jax.shard_map(
            lambda s: s.params["embed"]["kernel"][None],
            mesh=mesh_pipe4_data2,
            in_specs=(funcs.state_specs,),
            out_specs=P("pipe"),
            check_vma=False,
        )
    )
    per_rank = np.asarray(read(state))
    for i in range(1, 4):
        np.testing.assert_array_equal(per_rank[i], per_rank[0])


def test_pp_training_loss_decreases(mesh_pipe4_data2, rng):
    batch = classification_batch(jax.random.PRNGKey(3), 32, DIM, 10)
    model = _PPClassifier()
    init = make_model_init(model, optax.adamw(1e-3), train_arg=True)
    funcs = build_train_functions(
        init,
        _pp_loss,
        mesh_pipe4_data2,
        batch,
        batch_spec=P("data"),
        grad_sync_axes=("data",),
        grad_psum_axes=("pipe",),
        metric_axes=("data", "pipe"),
        num_minibatches=1,
        donate=False,
    )
    state = funcs.init_fn(rng, batch)
    state, m0 = funcs.step_fn(state, None, batch)
    first = compute(m0)["loss"]
    for _ in range(15):
        state, m = funcs.step_fn(state, None, batch)
    last = compute(m)["loss"]
    assert last < first, f"PP loss did not decrease: {first} -> {last}"
    # metric counts: 32-sample global batch, only last pipe rank contributes
    assert float(m["loss"][1]) == 32.0
