"""Tiny GPT on a simulated 2x2x2 DP x TP x PP mesh — CPU smoke test."""

from ml_collections import ConfigDict

from configs.common import model_overrides


def get_config():
    c = ConfigDict()
    c.simulate_cpu_devices = 8
    c.model = "tiny"
    c.model_overrides = model_overrides(num_microbatches=2)
    c.mesh = ConfigDict(dict(data=2, model=2, pipe=2, seq=1))
    c.global_batch_size = 16
    c.num_minibatches = 1
    c.steps = 20
    c.optimizer = "adamw"  # adamw | lion | sgd
    c.lr_schedule = "cosine"  # cosine | linear | constant
    c.ema_decay = 0.0  # >0 keeps an EMA shadow of params (eval prefers it)
    c.learning_rate = 3e-3
    c.warmup_steps = 5
    c.weight_decay = 0.1
    c.grad_clip = 1.0
    c.seed = 0
    c.log_every = 5
    c.donate = True
    # optional run plumbing (empty = disabled)
    c.checkpoint_dir = ""
    c.checkpoint_every = 10
    c.data_path = ""
    c.data_format = "flat"  # flat | packed (EOS-delimited docs + segment_ids)
    c.eos_id = 50256
    c.eval_steps = 0
    c.eval_every = 0  # >0: periodic eval during fit (uses the held-out split)
    c.keep_best = False  # snapshot lowest-eval-loss state to {checkpoint_dir}/best
    return c
