"""Packed-sequence dataset: variable-length documents packed into fixed rows.

The flash kernels mask same-segment attention in-kernel
(``ops/flash_attention.py`` ``segment_ids``); this supplies the loader side:
a token stream of EOS-terminated documents becomes fixed ``seq_len`` rows
holding several whole documents each, with

- ``segment_ids``: 1, 2, ... per document within the row, 0 on padding —
  attention never crosses documents,
- ``positions``: restarting at 0 per document (correct RoPE / learned
  embeddings per document),
- ``loss_mask``: 0 on padding and on each document's final token (the
  next-token target would cross into the neighbouring document).

Same duck interface as :class:`~tpu_parallel.data.loader.TokenDataset`
(``num_windows`` + ``batch(order)``), so :class:`DataLoader` — including its
holdout split, multi-host sharding, and prefetch — works unchanged.

Packing is deterministic first-fit in stream order (documents longer than
``seq_len`` are split); shuffling happens at the row level in the loader, so
resumed runs replay identical batches.
"""

from __future__ import annotations

import numpy as np

from tpu_parallel.core.state import TextBatch


class PackedDataset:
    """Rows of whole documents packed from an EOS-delimited token stream."""

    # EOS scan block size: bounds the transient bool array on huge memmaps
    _SCAN_BLOCK = 1 << 24

    def __init__(self, tokens, seq_len: int, eos_id: int):
        if isinstance(tokens, str):
            tokens = np.memmap(tokens, dtype=np.uint16, mode="r")
        self.tokens = tokens
        self.seq_len = seq_len
        self.eos_id = eos_id
        n_tokens = len(tokens)

        # document ends (exclusive, INCLUDING the trailing EOS), scanned in
        # blocks so the corpus never materializes in RAM; a final partial
        # document (no trailing EOS) is kept too
        end_blocks = []
        for off in range(0, n_tokens, self._SCAN_BLOCK):
            blk = np.asarray(tokens[off : off + self._SCAN_BLOCK])
            end_blocks.append(np.flatnonzero(blk == eos_id).astype(np.int64) + off + 1)
        ends = (
            np.concatenate(end_blocks) if end_blocks else np.zeros(0, np.int64)
        )
        if len(ends) == 0:
            raise ValueError(
                f"no eos_id={eos_id} found in the {n_tokens}-token stream — "
                "packing needs document boundaries (wrong eos_id for this "
                "corpus/vocab?)"
            )
        if ends[-1] != n_tokens:
            ends = np.append(ends, n_tokens)
        starts = np.concatenate([[0], ends[:-1]])
        keep = ends > starts
        starts, ends = starts[keep], ends[keep]

        # split oversize documents into seq_len chunks — vectorized
        lens = ends - starts
        n_chunks = -(-lens // seq_len)  # ceil
        rep_starts = np.repeat(starts, n_chunks)
        # grouped arange (0..n_chunks[d]-1 per doc) without a Python loop
        grp_first = np.concatenate([[0], np.cumsum(n_chunks)[:-1]])
        within = np.arange(int(n_chunks.sum())) - np.repeat(grp_first, n_chunks)
        chunk_starts = rep_starts + within * seq_len
        chunk_ends = np.minimum(chunk_starts + seq_len, np.repeat(ends, n_chunks))
        self._chunk_starts = chunk_starts
        self._chunk_ends = chunk_ends

        # first-fit in stream order: row r covers the longest chunk run
        # whose total length fits seq_len — O(rows log chunks) via
        # searchsorted over the cumulative chunk lengths.  Deterministic,
        # so row i is stable across runs (resume replay).
        cum = np.concatenate([[0], np.cumsum(chunk_ends - chunk_starts)])
        bounds = [0]
        while bounds[-1] < len(chunk_starts):
            start = bounds[-1]
            # furthest chunk with cum[j] - cum[start] <= seq_len
            j = int(np.searchsorted(cum, cum[start] + seq_len, side="right")) - 1
            bounds.append(max(j, start + 1))
        self._row_bounds = np.asarray(bounds, np.int64)
        self.num_windows = len(bounds) - 1

    def row(self, i: int):
        seq = self.seq_len
        tokens = np.full(seq, self.eos_id, np.int32)
        targets = np.full(seq, self.eos_id, np.int32)
        segment_ids = np.zeros(seq, np.int32)
        positions = np.zeros(seq, np.int32)
        loss_mask = np.zeros(seq, np.float32)
        off = 0
        lo, hi = self._row_bounds[i], self._row_bounds[i + 1]
        for seg, ci in enumerate(range(lo, hi), start=1):
            s, e = int(self._chunk_starts[ci]), int(self._chunk_ends[ci])
            n = e - s
            doc = np.asarray(self.tokens[s:e], np.int32)
            tokens[off : off + n] = doc
            # next-token targets within the document; the final position's
            # target would cross into the next document — mask it
            targets[off : off + n - 1] = doc[1:]
            loss_mask[off : off + n - 1] = 1.0
            segment_ids[off : off + n] = seg
            positions[off : off + n] = np.arange(n)
            off += n
        return tokens, targets, segment_ids, positions, loss_mask

    def batch(self, order: np.ndarray) -> TextBatch:
        rows = [self.row(int(i)) for i in order]
        stack = lambda j: np.stack([r[j] for r in rows])
        return TextBatch(
            tokens=stack(0),
            targets=stack(1),
            segment_ids=stack(2),
            positions=stack(3),
            loss_mask=stack(4),
        )
