"""Find which model wrapper ruins the flash kernel's standalone speed.

Standalone, the Pallas flash kernel is ~8x faster than XLA attention at
bench shapes, but inside the full train step it measures *slower*.  This
wraps the bare attention call in each suspect layer — remat(policy),
lax.scan over layers, shard_map — one at a time and times fwd+bwd.

Usage: python scripts/attn_wrap_bisect.py
"""

import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
from jax import lax

B, S, H, D = 16, 1024, 12, 64
LAYERS = 12


def time_fn(name, step, *args, **kw):
    try:
        out = step(*args)
        jax.block_until_ready(out)
        n = 10
        t0 = time.perf_counter()
        for _ in range(n):
            out = step(*args)
        jax.block_until_ready(out)
        float(jnp.sum(jax.tree.leaves(out)[0].astype(jnp.float32)))
        dt = (time.perf_counter() - t0) / n
        print(json.dumps({"variant": name, **kw, "ms": round(dt * 1e3, 2)}), flush=True)
    except Exception as e:
        print(json.dumps({"variant": name, **kw, "error": repr(e)[:140]}), flush=True)


def main():
    from tpu_parallel.models.layers import causal_attention
    from tpu_parallel.ops.flash_attention import flash_attention

    key = jax.random.PRNGKey(0)
    kq, kk, kv = jax.random.split(key, 3)
    q = jax.random.normal(kq, (B, S, H, D), jnp.bfloat16)
    k = jax.random.normal(kk, (B, S, H, D), jnp.bfloat16)
    v = jax.random.normal(kv, (B, S, H, D), jnp.bfloat16)

    proj_policy = jax.checkpoint_policies.save_only_these_names("proj", "attn")

    for impl_name, fn in [("xla", causal_attention), ("flash", flash_attention)]:

        def plain_loss(q, k, v, fn=fn):
            # LAYERS sequential attentions, python-unrolled
            x = q
            for _ in range(LAYERS):
                x = fn(x, k, v)
            return jnp.sum(x.astype(jnp.float32))

        time_fn(f"{impl_name}:unrolled", jax.jit(jax.grad(plain_loss)), q, k, v)

        def remat_loss(q, k, v, fn=fn):
            x = q
            body = jax.checkpoint(lambda x, k, v: fn(x, k, v), policy=proj_policy)
            for _ in range(LAYERS):
                x = body(x, k, v)
            return jnp.sum(x.astype(jnp.float32))

        time_fn(f"{impl_name}:remat-proj", jax.jit(jax.grad(remat_loss)), q, k, v)

        def scan_loss(q, k, v, fn=fn):
            def body(x, _):
                return fn(x, k, v), None

            x, _ = lax.scan(body, q, None, length=LAYERS)
            return jnp.sum(x.astype(jnp.float32))

        time_fn(f"{impl_name}:scan", jax.jit(jax.grad(scan_loss)), q, k, v)

        def scan_remat_loss(q, k, v, fn=fn):
            def body(x, _):
                return jax.checkpoint(
                    lambda x: fn(x, k, v), policy=proj_policy
                )(x), None

            x, _ = lax.scan(body, q, None, length=LAYERS)
            return jnp.sum(x.astype(jnp.float32))

        time_fn(
            f"{impl_name}:scan+remat", jax.jit(jax.grad(scan_remat_loss)), q, k, v
        )

        # shard_map over a 1-device data mesh, like the Trainer's step
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(jax.devices()[:1], ("data",))
        smapped = jax.shard_map(
            jax.grad(scan_remat_loss),
            mesh=mesh,
            in_specs=(P("data"), P("data"), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )
        time_fn(f"{impl_name}:shmap+scan+remat", jax.jit(smapped), q, k, v)


if __name__ == "__main__":
    main()
