"""Benchmark: GPT-2 125M training throughput on the available hardware.

Prints ONE JSON line:
    {"metric": "tokens/sec/chip", "value": N, "unit": "tokens/sec/chip",
     "vs_baseline": M, ...}

``vs_baseline`` is measured MFU divided by the 0.40 north-star target from
BASELINE.json (the reference publishes no numbers of its own — BASELINE.md).
Runs on whatever ``jax.devices()`` offers: the real TPU chip under the
driver, or CPU (with a tiny model) when no accelerator is present.
"""

import json
import os
import threading
import time

import jax
import jax.numpy as jnp


def _arm_watchdog(seconds: float) -> threading.Timer:
    """Hard-exit if the benchmark wedges (e.g. a dead TPU transport hangs
    jax.devices() in C++ before any Python timeout can fire).  A failed
    bench run must be an error, not an eternal hang.  The caller cancels
    the returned timer once the result is printed."""

    def bite():
        print(
            json.dumps(
                {
                    "metric": "tokens/sec/chip",
                    "value": 0,
                    "unit": "tokens/sec/chip",
                    "vs_baseline": 0,
                    "error": f"watchdog: no result within {seconds:.0f}s "
                    "(wedged transport?)",
                }
            ),
            flush=True,
        )
        os._exit(3)

    t = threading.Timer(seconds, bite)
    t.daemon = True
    t.start()
    return t


def main():
    watchdog = _arm_watchdog(float(os.environ.get("BENCH_WATCHDOG_SECS", "900")))
    from tpu_parallel.runtime import enable_compilation_cache

    # warm re-runs skip the first compile; a no-op on remote-compile
    # transports, where persisting large executables stalls (see
    # enable_compilation_cache)
    enable_compilation_cache()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    n_chips = jax.device_count()

    from tpu_parallel.core import compute as compute_metrics
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig
    from tpu_parallel.utils.profiling import (
        peak_flops,
        sync,
        transformer_flops_per_token,
    )

    if on_tpu:
        # Defaults from the round-3 sweep (SWEEP_r03.json, scripts/
        # sweep_bench.py): 0.4344 MFU on v5e-1 vs 0.2852 for the previous
        # batch-16/proj/XLA-attn/scan config.  The three levers, measured by
        # substitution (scripts/bisect_step.py, scripts/attn_wrap_bisect.py):
        # the Pallas flash kernel at 512x512 tiles (XLA attention costs ~2x
        # more inside shard_map than standalone; flash is immune), the
        # "proj_attn" remat policy (saves flash's out+lse so the backward
        # never re-runs the forward kernel), and unrolled layers (the layer
        # scan cost ~25ms/step at this depth).
        model, batch, steps, minib = "gpt2_125m", 16 * n_chips, 20, 1
        overrides = dict(
            dropout_rate=0.0,
            remat=True,
            remat_policy="proj_attn",
            attn_impl="flash",
            scan_layers=False,
        )
    else:
        model, batch, steps, minib = "tiny", 8 * n_chips, 10, 1
        overrides = dict(num_microbatches=1)

    config = TrainerConfig(
        model=model,
        model_overrides=overrides,
        mesh=MeshConfig(data=-1),
        global_batch_size=batch,
        num_minibatches=minib,
        steps=steps,
        log_every=10_000,  # no intermediate logging inside the timed loop
        donate=True,
    )
    trainer = Trainer(config)
    trainer.init()

    tokens_per_step = batch * trainer.model_config.seq_len

    # warmup (compile + first steps).  Sync via a device->host scalar read:
    # on some transports block_until_ready returns before execution finishes,
    # which would inflate throughput; a value fetch cannot lie.
    state, metrics = trainer.state, None
    for _ in range(3):
        state, metrics = trainer.funcs.step_fn(state, metrics, trainer.example_batch)
    sync((state, metrics))

    metrics = None  # drop warmup-step sums so final_loss covers timed steps only
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.funcs.step_fn(state, metrics, trainer.example_batch)
    sync((state, metrics))
    dt = time.perf_counter() - t0
    final_loss = compute_metrics(metrics)["loss"]

    tokens_per_sec = tokens_per_step * steps / dt
    tokens_per_sec_chip = tokens_per_sec / n_chips
    flops_per_token = transformer_flops_per_token(trainer.model_config)
    peak = peak_flops(device) or 197e12  # CPU: nominal, MFU not meaningful
    mfu = tokens_per_sec_chip * flops_per_token / peak

    print(
        json.dumps(
            {
                "metric": "tokens/sec/chip",
                "value": round(tokens_per_sec_chip, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(mfu / 0.40, 4),
                "mfu": round(mfu, 4),
                "model": model,
                "params_m": round(trainer.num_params / 1e6, 1),
                "n_chips": n_chips,
                "device": getattr(device, "device_kind", device.platform),
                "global_batch": batch,
                "seq_len": trainer.model_config.seq_len,
                "steps_timed": steps,
                "final_loss": round(final_loss, 4),
            }
        )
    )
    watchdog.cancel()


if __name__ == "__main__":
    main()
