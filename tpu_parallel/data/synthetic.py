"""Synthetic data generators for tests and benchmarks.

Capability parity: the reference's inline synthetic batches
(``data_paral.py:113-124``, ``param_sharding.py:276-287``) — with the intent
implemented correctly: integer labels come from ``jax.random.randint`` (the
reference drew them from ``normal`` with the wrong signature, bug #4 in
SURVEY.md §2.4).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from tpu_parallel.core.state import Batch, TextBatch


def classification_batch(
    rng: jax.Array, batch_size: int, input_size: int, num_classes: int
) -> Batch:
    k_in, k_lbl = jax.random.split(rng)
    return Batch(
        inputs=jax.random.normal(k_in, (batch_size, input_size)),
        labels=jax.random.randint(k_lbl, (batch_size,), 0, num_classes),
    )


def lm_batch(
    rng: jax.Array, batch_size: int, seq_len: int, vocab_size: int
) -> TextBatch:
    """Next-token-prediction batch from a random token stream."""
    tokens = jax.random.randint(rng, (batch_size, seq_len + 1), 0, vocab_size)
    return TextBatch(
        tokens=tokens[:, :-1],
        targets=tokens[:, 1:],
        loss_mask=jnp.ones((batch_size, seq_len), jnp.float32),
        positions=jnp.broadcast_to(jnp.arange(seq_len), (batch_size, seq_len)),
    )


def seq2seq_batch(
    rng: jax.Array,
    batch_size: int,
    src_len: int,
    dst_len: int,
    vocab_size: int,
    bos_id: int = 1,
):
    """Teacher-forced seq2seq batch: random source, target = the source
    cycled to the target length (a learnable copy task, like
    :func:`lm_batch`'s random stream — and shape-exact for EVERY
    ``dst_len``: a bare ``src[:, :dst_len]`` would silently clamp when
    ``dst_len > src_len``)."""
    from tpu_parallel.models.seq2seq import Seq2SeqBatch

    src = jax.random.randint(rng, (batch_size, src_len), 2, vocab_size)
    reps = -(-dst_len // src_len)  # ceil
    tgt = jnp.tile(src, (1, reps))[:, :dst_len]
    bos = jnp.full((batch_size, 1), bos_id, jnp.int32)
    return Seq2SeqBatch(
        src_tokens=src,
        tokens=jnp.concatenate([bos, tgt[:, :-1]], axis=1),
        targets=tgt,
        src_mask=jnp.ones_like(src, bool),
    )
