"""Cross-process trace stitching: fake-clock fleets through the REAL
span pipeline.

Every test here drives the production path end to end — two
:class:`~tpu_parallel.obs.tracer.Tracer` instances on deliberately
skewed fake clocks, spans spooled through :class:`SpanSpool` (CRC'd
JSONL, iofaults IO), read back with :func:`read_span_log`, stitched by
:mod:`tpu_parallel.obs.stitch` — because the contract under test is the
COMPOSITION: the router's forked span id must thread through the spool
round-trip and come out the other side as a single-rooted tree with a
flow arrow, and a seeded clock skew must cancel to within the sync
sample's RTT.  The damage tests corrupt real spool bytes (garbage line,
checksum tamper) and assert the reader skips them TYPED, never fatally.
"""

import importlib.util
import json
import os
import random
from unittest import mock

import pytest

from tpu_parallel.obs.spool import SpanSpool, read_span_log
from tpu_parallel.obs.stitch import (
    clock_offsets,
    phase_breakdown,
    stitch_traces,
    trace_summary,
)
from tpu_parallel.obs.tracer import TraceContext, Tracer

ADDR = "127.0.0.1:9101"
RID = "req-stitch-1"

# the seeded cross-host skew every fixture injects: the daemon's clock
# reads 1000s ahead of the router's.  Any stitched daemon timestamp
# that is not rebased by ~-1000s is wildly, visibly wrong.
SKEW = 1000.0

# the sync sample's send/recv window (symmetric here, so the offset
# estimate is exact; its RTT is still the honest error bound)
T_SEND, T_RECV = 0.01, 0.05
SYNC_RTT = T_RECV - T_SEND


class FakeClock:
    """A settable monotonic clock: ``base`` + whatever the test adds."""

    def __init__(self, base=0.0):
        self.now = base

    def __call__(self):
        return self.now


def _fleet_processes(tmp_path):
    """One traced request crossing router -> daemon, through the REAL
    pipeline: two Tracers on skewed fake clocks, spans stamped via
    bind_trace, spooled to disk, read back.  Returns
    ``(processes, ctx, wire_ctx)`` in stitch_traces' input shape.

    Router timeline (its own clock): route [0.0, 0.5] owning
    wire:submit [0.01, 0.05] and wire:kv_import [0.30, 0.34]; one
    clock_sync sample around the submit.  Daemon timeline (router time
    + SKEW): queue [0.06, 0.08], prefill [0.08, 0.20],
    decode [0.20, 0.45].
    """
    router = Tracer(FakeClock())
    ctx = TraceContext.new()
    router.bind_trace(RID, ctx)

    root = router.record("route", "fleet", 0.0, 0.5, rid=RID)
    # the router's root discipline: the root span IS the minted
    # context — its own id, no parent (fleet/router.py does exactly
    # this, so receiver spans have a resolvable ancestor)
    root.span_id = ctx.span_id
    root.parent_id = None

    wire_ctx = ctx.fork()
    wire = router.record(
        "wire:submit", "fleet", T_SEND, T_RECV, rid=RID, peer=ADDR
    )
    wire.span_id = wire_ctx.span_id  # receiver spans parent HERE

    kv_ctx = ctx.fork()
    kv = router.record(
        "wire:kv_import", "fleet", 0.30, 0.34, rid=RID, peer=ADDR,
        bytes=2048,
    )
    kv.span_id = kv_ctx.span_id

    router.instant(
        "clock_sync", track="fleet", peer=ADDR,
        t_send=T_SEND, t_recv=T_RECV, peer_ts=SKEW + (T_SEND + T_RECV) / 2,
    )
    router.release_trace(RID)

    daemon = Tracer(FakeClock(SKEW))
    daemon.bind_trace(RID, wire_ctx)
    daemon.record(
        "queue", "scheduler", SKEW + 0.06, SKEW + 0.08, request_id=RID
    )
    daemon.record(
        "prefill", "slot 0", SKEW + 0.08, SKEW + 0.20, request_id=RID
    )
    daemon.record(
        "decode", "slot 0", SKEW + 0.20, SKEW + 0.45, request_id=RID
    )
    daemon.release_trace(RID)

    processes = []
    for name, pid, tracer, extra in (
        ("router", 101, router, {}),
        ("daemon:serve", 202, daemon, {"addr": ADDR}),
    ):
        path = os.path.join(str(tmp_path), f"{name.replace(':', '_')}.jsonl")
        # both "processes" live in this one test process; stamp the
        # fleet pids a real deployment would have (SpanSpool captures
        # the pid at construction)
        with mock.patch("os.getpid", return_value=pid):
            spool = SpanSpool(path, proc=name)
        assert spool.drain(tracer) > 0
        spool.close()
        records, skipped = read_span_log(path)
        assert skipped == {"garbage": 0, "crc": 0}
        proc = {"name": name, "pid": pid, "records": records,
                "skipped": skipped}
        proc.update(extra)
        processes.append(proc)
    return processes, ctx, wire_ctx


# -- the stitched verdict ---------------------------------------------------


def test_fleet_trace_is_single_rooted_across_processes(tmp_path):
    processes, ctx, _wire_ctx = _fleet_processes(tmp_path)
    summary = trace_summary(processes)
    assert list(summary) == [ctx.trace_id]
    verdict = summary[ctx.trace_id]
    assert verdict["spans"] == 6  # route, 2x wire, queue, prefill, decode
    assert verdict["pids"] == [101, 202]
    assert verdict["roots"] == 1
    assert verdict["single_rooted"] is True
    # queue, prefill and decode all parent to the router's wire span
    assert verdict["cross_process_links"] == 3


def test_stitch_recovers_seeded_skew_within_sync_rtt(tmp_path):
    processes, _ctx, _wire_ctx = _fleet_processes(tmp_path)
    trace = stitch_traces(processes)
    meta = {p["name"]: p for p in trace["metadata"]["processes"]}
    # the daemon's 1000s skew cancels to within the sync sample's RTT
    assert abs(meta["daemon:serve"]["clock_offset_seconds"] + SKEW) \
        <= SYNC_RTT
    queue = next(
        ev for ev in trace["traceEvents"]
        if ev.get("ph") == "X" and ev.get("name") == "queue"
    )
    # true router-frame start is 0.06s; the stitched microsecond
    # timestamp must land within the RTT error bound, not 1000s away
    assert abs(queue["ts"] - 0.06e6) <= SYNC_RTT * 1e6
    assert queue["pid"] == 202


def test_stitch_draws_flow_arrow_across_the_wire(tmp_path):
    processes, ctx, wire_ctx = _fleet_processes(tmp_path)
    trace = stitch_traces(processes)
    assert trace["metadata"]["flow_arrows"] == 1
    starts = [ev for ev in trace["traceEvents"] if ev.get("ph") == "s"]
    ends = [ev for ev in trace["traceEvents"] if ev.get("ph") == "f"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"]
    assert ctx.trace_id in starts[0]["id"]
    # the arrow leaves the router's wire span and lands on the
    # daemon's first span — distinct pids, or it proved nothing
    assert starts[0]["pid"] == 101
    assert ends[0]["pid"] == 202
    assert ends[0]["bp"] == "e"


def test_dropped_context_shows_up_as_second_root():
    """The failure mode the check_trace gate exists for: a crossing
    that forgot the trace kwarg leaves the receiver's spans parented to
    an id nobody recorded — the summary must call that out as a second
    root, not quietly report a healthy tree."""
    tid = "f" * 32
    router_rec = {"kind": "span", "name": "route", "track": "fleet",
                  "start": 0.0, "end": 0.5, "trace_id": tid,
                  "span_id": "a" * 16, "parent_id": None, "attrs": {}}
    orphan_rec = {"kind": "span", "name": "queue", "track": "scheduler",
                  "start": 0.1, "end": 0.2, "trace_id": tid,
                  "span_id": "b" * 16, "parent_id": "c" * 16, "attrs": {}}
    summary = trace_summary([
        {"name": "router", "pid": 1, "records": [router_rec]},
        {"name": "daemon", "pid": 2, "records": [orphan_rec]},
    ])
    verdict = summary[tid]
    assert verdict["roots"] == 2
    assert verdict["single_rooted"] is False
    assert verdict["cross_process_links"] == 0


def test_phase_breakdown_attributes_the_fleet_trace(tmp_path):
    processes, _ctx, _wire_ctx = _fleet_processes(tmp_path)
    spans = [r for p in processes for r in p["records"]
             if r.get("kind") == "span"]
    breakdown = phase_breakdown(spans)
    assert breakdown["spans"] == 6
    phases = breakdown["phases"]
    assert phases["queue"]["seconds"] == pytest.approx(0.02)
    assert phases["prefill"]["seconds"] == pytest.approx(0.12)
    assert phases["decode"]["seconds"] == pytest.approx(0.25)
    assert phases["wire"]["seconds"] == pytest.approx(SYNC_RTT)
    assert phases["kv_wire"]["count"] == 1
    assert breakdown["kv_wire_bytes"] == 2048


# -- clock-offset estimation ------------------------------------------------


def test_clock_offsets_min_rtt_discipline():
    """Seeded noisy sync samples with asymmetric one-way delays: the
    estimator must keep the minimum-RTT sample, whose error is bounded
    by half ITS OWN rtt — not an average polluted by the slow ones."""
    rnd = random.Random(1234)
    true_offset = -567.89  # router ~= peer + offset
    records = []
    min_rtt = None
    for i in range(24):
        t_send = float(i)
        rtt = 0.002 + rnd.random() * 0.08
        d_out = rnd.uniform(0.0, rtt)  # asymmetric split of the rtt
        peer_ts = (t_send + d_out) - true_offset
        records.append({
            "kind": "instant", "name": "clock_sync",
            "attrs": {"peer": ADDR, "t_send": t_send,
                      "t_recv": t_send + rtt, "peer_ts": peer_ts},
        })
        min_rtt = rtt if min_rtt is None else min(min_rtt, rtt)
    offsets = clock_offsets(records)
    est = offsets[ADDR]
    assert est["samples"] == 24
    assert est["rtt"] == pytest.approx(min_rtt)
    assert abs(est["offset"] - true_offset) <= min_rtt / 2 + 1e-9


def test_clock_offsets_ignores_malformed_samples():
    good = {"kind": "instant", "name": "clock_sync",
            "attrs": {"peer": ADDR, "t_send": 1.0, "t_recv": 1.1,
                      "peer_ts": 5.0}}
    bad = [
        {"kind": "instant", "name": "clock_sync", "attrs": {}},
        {"kind": "instant", "name": "clock_sync",
         "attrs": {"peer": ADDR, "t_send": "x", "t_recv": 1.0,
                   "peer_ts": 1.0}},
        {"kind": "instant", "name": "clock_sync",  # negative rtt
         "attrs": {"peer": ADDR, "t_send": 2.0, "t_recv": 1.0,
                   "peer_ts": 1.0}},
        {"kind": "span", "name": "clock_sync"},
    ]
    offsets = clock_offsets([good] + bad)
    assert list(offsets) == [ADDR]
    assert offsets[ADDR]["samples"] == 1


# -- damaged span logs ------------------------------------------------------


def _spooled_log(tmp_path, n_spans=4):
    tracer = Tracer(FakeClock())
    for i in range(n_spans):
        tracer.record(f"span{i}", "main", float(i), float(i) + 0.5)
    path = os.path.join(str(tmp_path), "damaged.jsonl")
    spool = SpanSpool(path, proc="victim")
    spool.drain(tracer)
    spool.close()
    return path


def test_damaged_lines_skipped_typed_not_fatal(tmp_path):
    path = _spooled_log(tmp_path)
    with open(path) as fh:
        lines = fh.read().splitlines()
    # tamper a MID-FILE span record without recomputing its checksum:
    # parseable JSON, checksum disagrees -> the "crc" bucket
    tampered = json.loads(lines[2])
    tampered["name"] = "tampered"
    lines[2] = json.dumps(tampered)
    # and splice in an unparseable line -> the "garbage" bucket
    lines.insert(3, "not json {{{")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")

    records, skipped = read_span_log(path)
    assert skipped == {"garbage": 1, "crc": 1}
    names = [r.get("name") for r in records if r.get("kind") == "span"]
    assert "tampered" not in names
    assert len(names) == 3  # the other three spans all survived
    assert records[0]["kind"] == "meta"  # meta record intact


def test_trace_filter_keeps_clock_sync_and_meta(tmp_path):
    processes, ctx, _wire_ctx = _fleet_processes(tmp_path)
    router_path = os.path.join(str(tmp_path), "router.jsonl")
    # filter to a trace id that matches NOTHING: spans drop, but the
    # alignment-critical records (meta, clock_sync) must survive
    records, _skipped = read_span_log(router_path, trace_id="0" * 32)
    kinds = sorted(r["kind"] for r in records)
    assert kinds == ["instant", "meta"]
    assert records[1]["name"] == "clock_sync"
    # and the REAL trace id keeps every stamped span
    records, _skipped = read_span_log(router_path, trace_id=ctx.trace_id)
    assert sum(1 for r in records if r["kind"] == "span") == 3


# -- the CLI ----------------------------------------------------------------


def _load_cli():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "trace_stitch", os.path.join(repo, "scripts", "trace_stitch.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_stitch_cli_writes_one_perfetto_file(tmp_path, capsys):
    _fleet_processes(tmp_path)
    cli = _load_cli()
    out = os.path.join(str(tmp_path), "stitched.json")
    rc = cli.main([
        "trace_stitch", out,
        os.path.join(str(tmp_path), "router.jsonl"),
        os.path.join(str(tmp_path), "daemon_serve.jsonl") + f"={ADDR}",
        "--summary",
    ])
    assert rc == 0
    with open(out) as fh:
        trace = json.load(fh)
    assert trace["metadata"]["flow_arrows"] == 1
    assert any(ev.get("ph") == "X" for ev in trace["traceEvents"])
    summary = json.loads(capsys.readouterr().out)
    assert len(summary) == 1
    (verdict,) = summary.values()
    assert verdict["single_rooted"] is True
    assert len(verdict["pids"]) == 2


def test_trace_stitch_cli_rejects_an_empty_stitch(tmp_path):
    cli = _load_cli()
    out = os.path.join(str(tmp_path), "empty.json")
    missing = os.path.join(str(tmp_path), "no_such_log.jsonl")
    assert cli.main(["trace_stitch", out, missing]) == 1
    assert not os.path.exists(out)
