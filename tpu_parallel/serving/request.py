"""Request/result types for the continuous-batching serving engine.

A :class:`Request` is one user generation call: a prompt, per-request
sampling knobs, a stopping contract (``max_new_tokens`` and an optional
EOS id), and an optional streaming callback.  The engine wraps every
submitted request in a :class:`RequestOutput` — the mutable record that
accumulates tokens and timing as the request moves through QUEUED ->
RUNNING -> FINISHED (or is REJECTED / EXPIRED by the scheduler).

Incremental delivery: every engine tick yields :class:`StreamEvent`s, one
per token produced that tick; ``Request.on_token`` (when set) receives the
same events synchronously as they are produced.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Callable, List, Optional, Sequence

_request_counter = itertools.count()


@dataclasses.dataclass(frozen=True)
class SamplingParams:
    """Per-request sampling knobs — the same contract as
    :func:`tpu_parallel.models.generate.generate`: ``temperature == 0`` is
    greedy; ``top_k``/``top_p`` compose by intersection after the
    temperature scale, and the argmax token always survives the nucleus
    cut.  Unlike the static path these are per-REQUEST: two requests with
    different knobs decode in the same engine tick (the sampler is
    vectorized over traced per-slot knob arrays, so no recompile per
    combination)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 0.0


# request lifecycle states
QUEUED = "queued"  # accepted, waiting for a free slot
RUNNING = "running"  # occupies a cache slot, decoding
FINISHED = "finished"  # completed (see finish_reason)
REJECTED = "rejected"  # refused at submission (queue full / capacity)
EXPIRED = "expired"  # timed out in the queue (scheduler max_wait)
CANCELLED = "cancelled"  # cancelled mid-flight (deadline / client cancel)
FAILED = "failed"  # the cluster gave up (retry limit, no live replica)

# typed rejection reasons — machine-readable ``finish_reason`` values a
# front end can switch on (human detail, when any, rides in
# ``RequestOutput.detail``).  The engine and the cluster frontend use the
# SAME vocabulary so a client sees identical reporting regardless of
# which layer refused.
REJECT_QUEUE_FULL = "queue_full"  # scheduler admission control
REJECT_DRAINING = "draining"  # drain gate: no new work accepted
REJECT_CAPACITY = "capacity"  # prompt + budget exceed seq_len
REJECT_TOKEN_BUDGET = "token_budget"  # cluster-wide token backpressure
REJECT_CLIENT_LIMIT = "client_limit"  # per-client concurrency cap
# overload shedding (cluster autopilot): a NEW lowest-effective-priority
# submission rejected — or a queued request whose deadline is provably
# unmeetable cancelled — while the fleet is past its SLO targets.  Shed
# early and loudly beats missing every deadline silently.
REJECT_SHED = "shed"
# device-side integrity sentinel (engine ``sample_tokens``): a request's
# logits went non-finite (NaN/Inf — corrupted weights, a numerics bug,
# bad hardware).  The request FAILS typed instead of streaming garbage
# tokens, and the replica escalates to DEGRADED health.
FAIL_INTEGRITY = "integrity"


@dataclasses.dataclass
class Request:
    """One generation request.

    ``prompt`` is a token-id sequence (list/tuple/1-D array).  ``prompt``
    plus ``max_new_tokens`` must fit the model's ``seq_len`` — the same
    capacity contract as the static ``generate()`` path, because each cache
    slot is one ``seq_len``-long row of the pool.
    """

    prompt: Sequence[int]
    max_new_tokens: int = 32
    sampling: SamplingParams = dataclasses.field(default_factory=SamplingParams)
    eos_token_id: Optional[int] = None
    request_id: Optional[str] = None
    # speculative decoding: None inherits the engine's draft_tokens
    # setting, 0 disables drafting for THIS request (it still shares
    # verify ticks, as a one-token block), >0 caps the request's draft
    # length (clamped to the engine's compiled width).  Output stays
    # exact either way — the knob trades wasted verify positions against
    # multi-token ticks per request.
    draft_tokens: Optional[int] = None
    # cluster-frontend fields (tpu_parallel/cluster/ — the engine itself
    # ignores all three): per-client concurrency caps key off client_id;
    # priority reorders frontend admission (higher first, aged so lower
    # classes never starve); deadline is a per-request completion budget
    # in SECONDS FROM ARRIVAL — past it the frontend cancels the request
    # wherever it is, including in-engine work.
    client_id: Optional[str] = None
    priority: int = 0
    deadline: Optional[float] = None
    # daemon-layer idempotence key (tpu_parallel/daemon/): a client
    # retrying an acknowledged submission — across network failures or
    # a daemon crash+recovery — reuses its dedupe token and gets the
    # SAME request record back instead of a duplicate admission.  The
    # engine and cluster frontend carry it untouched.
    dedupe_token: Optional[str] = None
    # called synchronously with each StreamEvent for this request
    on_token: Optional[Callable[["StreamEvent"], None]] = None

    def __post_init__(self):
        if self.request_id is None:
            self.request_id = f"req-{next(_request_counter)}"
        if len(self.prompt) < 1:
            raise ValueError("empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(f"max_new_tokens={self.max_new_tokens} < 1")
        if self.draft_tokens is not None and self.draft_tokens < 0:
            raise ValueError(f"draft_tokens={self.draft_tokens} < 0")


@dataclasses.dataclass(frozen=True)
class StreamEvent:
    """One incrementally-delivered token — or a terminal notification.

    Queue expiry and cancellation deliver a tokenless terminal event
    (``token == -1``, ``index == -1``, ``finish_reason`` naming the cause)
    so stream consumers learn the request died; every other event carries
    a real token.
    """

    request_id: str
    token: int
    index: int  # 0-based position among the request's generated tokens
    finished: bool = False
    # "eos" | "length" | "max_wait" | "cancelled" | "deadline" |
    # "retry_limit" | "no_replica" when finished
    finish_reason: Optional[str] = None


@dataclasses.dataclass
class RequestOutput:
    """The engine's mutable per-request record (returned by
    ``ServingEngine.add_request``; also the scheduler's queue entry)."""

    request: Request
    status: str = QUEUED
    tokens: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None
    # human-readable detail behind a TYPED finish_reason (e.g. the exact
    # capacity arithmetic behind "capacity") — never switch on this
    detail: Optional[str] = None
    # timing (engine clock; None until the event happens)
    arrival_time: Optional[float] = None
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None
    token_times: List[float] = dataclasses.field(default_factory=list)

    @property
    def done(self) -> bool:
        return self.status in (FINISHED, REJECTED, EXPIRED, CANCELLED, FAILED)

    @property
    def ttft(self) -> Optional[float]:
        """Time-to-first-token (seconds), None until the first token."""
        if self.first_token_time is None or self.arrival_time is None:
            return None
        return self.first_token_time - self.arrival_time

    def inter_token_latencies(self) -> List[float]:
        """Gaps between consecutive token deliveries (seconds)."""
        return [
            b - a for a, b in zip(self.token_times, self.token_times[1:])
        ]
