"""Serving observability: queue depth, TTFT, inter-token latency, slot
occupancy, throughput.

Two consumers: (1) live per-tick export through
:class:`~tpu_parallel.utils.logging_utils.MetricLogger` (stdout +
machine-readable JSONL, process-0-only on multi-host — the same sink the
trainer uses), and (2) an end-of-run :meth:`ServingMetrics.summary` dict
(the record ``scripts/serve_bench.py`` emits next to the ``DECODE_r*``
decode-bench lines).
"""

from __future__ import annotations

from collections import deque
from typing import Dict, Optional, Sequence

from tpu_parallel.utils.logging_utils import MetricLogger


def percentile(values: Sequence[float], p: float) -> Optional[float]:
    """Linear-interpolated percentile (``p`` clamped into [0, 100]); None
    on empty — the empty-safe wrapper every summary stat here needs (a run
    with ZERO finished requests must still produce a serializable summary,
    not an IndexError/NaN in the JSONL sink)."""
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    import numpy as np

    return float(np.percentile(vals, min(max(p, 0.0), 100.0)))


class ServingMetrics:
    """Accumulates per-tick and per-request serving statistics.

    The engine calls :meth:`record_tick` once per ``step()`` and
    :meth:`record_finished` as requests retire; everything else derives.
    ``logger``/``log_every`` stream tick metrics through the shared
    :class:`MetricLogger` (queue depth, occupancy, cumulative tokens/sec).

    Sample collections are BOUNDED (``max_samples`` most-recent entries,
    sliding window) so a long-lived engine's memory stays flat — counters
    and throughput remain exact over the whole lifetime; percentiles and
    means in :meth:`summary` cover the window.
    """

    def __init__(
        self,
        logger: Optional[MetricLogger] = None,
        log_every: int = 0,
        max_samples: int = 100_000,
    ):
        self.logger = logger
        self.log_every = log_every
        self.ticks = 0
        self.decode_ticks = 0
        self.tokens_out = 0
        self.prefills = 0
        self.queue_depths: deque = deque(maxlen=max_samples)
        self.occupancies: deque = deque(maxlen=max_samples)
        self.ttfts: deque = deque(maxlen=max_samples)
        self.inter_token: deque = deque(maxlen=max_samples)
        self.finished = 0
        self.rejected = 0
        self.expired = 0
        # prefill fast path: batched prefill device calls (vs. `prefills`,
        # which counts admitted REQUESTS), chunk continuations, and the
        # prefix cache's hit/miss/eviction tallies
        self.prefill_calls = 0
        self.prefill_chunks = 0
        self.prefix_hits = 0
        self.prefix_misses = 0
        self.prefix_evictions = 0
        # speculative decode: drafted vs accepted tokens (acceptance rate
        # = the drafter's hit quality), and verify positions computed but
        # not delivered (pads + rejected drafts + post-finish surplus —
        # the FLOP overhead speculative decode pays for its win)
        self.spec_slot_ticks = 0
        self.tokens_drafted = 0
        self.tokens_accepted = 0
        self.spec_wasted_positions = 0
        self._t_start: Optional[float] = None
        self._t_last: Optional[float] = None

    def record_tick(
        self,
        now: float,
        queue_depth: int,
        occupancy: float,
        new_tokens: int,
        prefills: int,
        decoded: bool,
    ) -> None:
        if self._t_start is None:
            self._t_start = now
        self._t_last = now
        self.ticks += 1
        self.decode_ticks += int(decoded)
        self.tokens_out += new_tokens
        self.prefills += prefills
        self.queue_depths.append(queue_depth)
        self.occupancies.append(occupancy)
        if (
            self.logger is not None
            and self.log_every > 0
            and self.ticks % self.log_every == 0
        ):
            self.logger.log(
                self.ticks,
                {
                    "queue_depth": float(queue_depth),
                    "slot_occupancy": float(occupancy),
                    "tokens_out": float(self.tokens_out),
                    "tokens_per_sec": float(self.throughput() or 0.0),
                },
            )

    def record_finished(self, out) -> None:
        """Fold one retired RequestOutput's latencies in."""
        self.finished += 1
        if out.ttft is not None:
            self.ttfts.append(out.ttft)
        self.inter_token.extend(out.inter_token_latencies())

    def record_rejected(self) -> None:
        self.rejected += 1

    def record_expired(self) -> None:
        self.expired += 1

    def record_prefill_call(self, chunks: int = 0) -> None:
        """One batched prefill device call (``chunks`` counts any chunk
        continuations it was split into)."""
        self.prefill_calls += 1
        self.prefill_chunks += chunks

    def record_spec(self, drafted: int, accepted: int, wasted: int) -> None:
        """One active slot's share of a speculative verify tick: how many
        draft tokens it proposed, how many the verify accepted, and how
        many of its compiled verify positions went undelivered."""
        self.spec_slot_ticks += 1
        self.tokens_drafted += drafted
        self.tokens_accepted += accepted
        self.spec_wasted_positions += wasted

    def sync_prefix_cache(self, prefix_cache) -> None:
        """Mirror a :class:`~tpu_parallel.serving.prefix_cache.PrefixCache`'s
        cumulative counters (the cache owns the tallies; metrics snapshots
        them so ``summary()`` is self-contained)."""
        self.prefix_hits = prefix_cache.hits
        self.prefix_misses = prefix_cache.misses
        self.prefix_evictions = prefix_cache.evictions

    def throughput(self) -> Optional[float]:
        """Generated tokens per wall-second over the ticks observed."""
        if self._t_start is None or self._t_last is None:
            return None
        dt = self._t_last - self._t_start
        if dt <= 0:
            return None
        return self.tokens_out / dt

    def summary(self) -> Dict[str, float]:
        def ms(x):
            return None if x is None else round(x * 1000.0, 3)

        mean = lambda xs: (sum(xs) / len(xs)) if xs else None
        probes = self.prefix_hits + self.prefix_misses
        return {
            "ticks": self.ticks,
            "decode_ticks": self.decode_ticks,
            "prefills": self.prefills,
            "prefill_calls": self.prefill_calls,
            "prefill_chunks": self.prefill_chunks,
            "prefix_hits": self.prefix_hits,
            "prefix_misses": self.prefix_misses,
            "prefix_evictions": self.prefix_evictions,
            "prefix_hit_rate": (
                round(self.prefix_hits / probes, 4) if probes else None
            ),
            "finished": self.finished,
            "rejected": self.rejected,
            "expired": self.expired,
            "tokens_out": self.tokens_out,
            "tokens_drafted": self.tokens_drafted,
            "tokens_accepted": self.tokens_accepted,
            "spec_acceptance_rate": (
                round(self.tokens_accepted / self.tokens_drafted, 4)
                if self.tokens_drafted
                else None
            ),
            "spec_wasted_positions": self.spec_wasted_positions,
            "tokens_per_decode_tick": (
                round(self.tokens_out / self.decode_ticks, 3)
                if self.decode_ticks
                else None
            ),
            "tokens_per_sec": (
                round(self.throughput(), 1)
                if self.throughput() is not None
                else None
            ),
            "ttft_ms_p50": ms(percentile(self.ttfts, 50)),
            "ttft_ms_p95": ms(percentile(self.ttfts, 95)),
            "itl_ms_p50": ms(percentile(self.inter_token, 50)),
            "itl_ms_p95": ms(percentile(self.inter_token, 95)),
            "slot_occupancy_mean": (
                round(mean(self.occupancies), 4)
                if self.occupancies
                else None
            ),
            "queue_depth_mean": (
                round(mean(self.queue_depths), 2)
                if self.queue_depths
                else None
            ),
            "queue_depth_max": (
                max(self.queue_depths) if self.queue_depths else None
            ),
        }
