"""Shared reference-lookup drafters for the speculative-decoding suites
(ONE definition for tests/test_spec_decode.py and tests/test_serving.py —
a fix to the prefix-match or index logic must not silently miss a copy).

Both drafters key the request off its prompt prefix in a
``{prompt tuple -> greedy continuation}`` map built from static
``generate()`` references (:func:`ref_map`).
"""


def ref_map(prompts, refs):
    return {
        tuple(int(t) for t in p): [int(t) for t in ref]
        for p, ref in zip(prompts, refs)
    }


class AntiOracleDrafter:
    """Adversarial: knowing each context's true greedy next token, always
    drafts something ELSE — guaranteed 0 acceptance, and the output must
    STILL be exact (the no-rollback story under pure rejection)."""

    def __init__(self, refs, vocab):
        self.refs = refs
        self.vocab = vocab

    def draft(self, context, k):
        for prompt, ref in self.refs.items():
            if tuple(context[: len(prompt)]) == prompt:
                idx = len(context) - len(prompt)
                truth = ref[idx] if idx < len(ref) else 0
                return [(int(truth) + 1) % self.vocab] * k
        return [0] * k


class OracleDrafter:
    """Drafts the true greedy continuation — maximal acceptance, used to
    pin multi-token progress and EOS-mid-block behavior
    deterministically."""

    def __init__(self, refs):
        self.refs = refs

    def draft(self, context, k):
        for prompt, ref in self.refs.items():
            if tuple(context[: len(prompt)]) == prompt:
                idx = len(context) - len(prompt)
                return [int(t) for t in ref[idx: idx + k]]
        return []
