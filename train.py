"""Training entrypoint: one script for every parallelism strategy.

Usage:
    python train.py --config=configs/mlp_dp_cpu.py            # reference parity
    python train.py --config=configs/gpt2_125m_dp.py          # pure DP
    python train.py --config=configs/gpt2_125m_tp.py          # 1-D tensor parallel
    python train.py --config=configs/gpt2_350m_pp.py          # 4-stage GPipe
    python train.py --config=configs/llama_1b_3d.py           # DP x TP x PP
    python train.py --config=configs/tiny_3d_cpu.py --config.steps=5

Any config field can be overridden on the CLI (``--config.steps=100``,
``--config.mesh.model=2`` ...) — the flag system the reference imported but
never wired up (SURVEY.md §5, config/flag row).

Telemetry (docs/11_observability.md): ``--trace-out PATH`` records
per-step ``data_wait``/``compute`` spans and writes a Perfetto-openable
Chrome trace at exit; ``--metrics-out PATH`` writes a Prometheus
text-exposition snapshot of the trainer's metric registry (MFU,
tokens/sec, loss gauges).
"""

import os
import sys

from absl import app, flags, logging
from ml_collections import config_flags

_CONFIG = config_flags.DEFINE_config_file("config", None, "Training config file.")
_TRACE_OUT = flags.DEFINE_string(
    "trace_out", "",
    "write a Chrome trace-event JSON of per-step data_wait/compute spans "
    "here (opens in Perfetto; forces a per-step device fence)",
)
_METRICS_OUT = flags.DEFINE_string(
    "metrics_out", "",
    "write a Prometheus text-exposition snapshot of the trainer's metric "
    "registry here at exit",
)


def main(argv):
    del argv
    cd = _CONFIG.value
    from tpu_parallel.runtime import initialize, process_info, simulate_cpu_devices
    from tpu_parallel.train_lib import Trainer, TrainerConfig

    # Distributed bootstrap first: jax.distributed.initialize must run before
    # the first backend touch (simulate_cpu_devices initializes the backend to
    # validate its post-condition).
    initialize()
    from tpu_parallel.runtime import enable_compilation_cache

    # no-op on remote-compile transports / with TPU_PARALLEL_NO_COMPILE_CACHE=1
    enable_compilation_cache()
    sim = cd.get("simulate_cpu_devices", 0)
    if sim:
        simulate_cpu_devices(sim)
    logging.info("topology: %s", process_info())

    trainer_cd = dict(cd)
    trainer_cd.pop("simulate_cpu_devices", None)
    checkpoint_dir = trainer_cd.pop("checkpoint_dir", "")
    checkpoint_every = trainer_cd.pop("checkpoint_every", 100)
    data_path = trainer_cd.pop("data_path", "")
    # "flat": contiguous seq_len windows; "packed": EOS-delimited documents
    # packed whole into rows with segment_ids (in-kernel attention masking)
    data_format = trainer_cd.pop("data_format", "flat")
    eos_id = trainer_cd.pop("eos_id", 50256)  # GPT-2's <|endoftext|>
    eval_steps = trainer_cd.pop("eval_steps", 0)
    # >0: evaluate on the held-out split every N steps during fit;
    # keep_best then also snapshots the lowest-eval-loss state to
    # {checkpoint_dir}/best
    eval_every = trainer_cd.pop("eval_every", 0)
    keep_best = trainer_cd.pop("keep_best", False)
    # fraction of the token stream held out for eval (never trained on);
    # defaults on whenever eval is requested over a real dataset
    eval_fraction = trainer_cd.pop(
        "eval_fraction", 0.1 if (eval_steps or eval_every) else 0.0
    )
    config = TrainerConfig.from_config_dict(trainer_cd)
    tracer = None
    if _TRACE_OUT.value:
        from tpu_parallel.obs import Tracer

        tracer = Tracer()
    trainer = Trainer(config, tracer=tracer)
    logging.info(
        "model=%s params=%.1fM mesh=%s",
        config.model,
        trainer.num_params / 1e6,
        dict(trainer.mesh.shape),
    )

    data_loader = None
    if data_path:
        from tpu_parallel.data import DataLoader, PackedDataset, TokenDataset

        paths = data_path.split(",") if "," in data_path else data_path
        if data_format == "packed":
            if isinstance(paths, list):
                raise NotImplementedError(
                    "packed datasets read a single .bin stream "
                    "(concatenate shards at prepare time)"
                )
            dataset = PackedDataset(
                paths, trainer.model_config.seq_len, eos_id=eos_id
            )
        elif data_format == "flat":
            dataset = TokenDataset(paths, trainer.model_config.seq_len)
        else:
            raise ValueError(f"data_format={data_format!r} (flat | packed)")
        data_loader = DataLoader(
            dataset,
            trainer.mesh,
            config.global_batch_size,
            seed=config.seed,
            holdout_fraction=eval_fraction,
            batch_spec=trainer.batch_spec,
        )
        if eval_steps:
            # fail fast: an eval split smaller than one batch (or
            # eval_fraction=0) should abort before training, not after it
            data_loader.eval_view()

    def log_fn(step, metrics):
        parts = " ".join(f"{k}={v:.4f}" for k, v in sorted(metrics.items()))
        logging.info("step %d: %s", step, parts)

    if (eval_every or keep_best) and not checkpoint_dir:
        raise ValueError(
            "eval_every/keep_best run inside the fault-tolerant fit loop — "
            "set checkpoint_dir too"
        )
    if checkpoint_dir:
        # fault-tolerant path: auto-resume + periodic saves + exact data replay
        final = trainer.fit(
            checkpoint_dir,
            data_loader=data_loader,
            checkpoint_every=checkpoint_every,
            log_fn=log_fn,
            eval_every=eval_every,
            eval_steps=eval_steps or 10,
            keep_best=keep_best,
        )
    else:
        final = trainer.train(
            # prefetch overlaps batch assembly + H2D with the device step
            batch_iter=data_loader.prefetch() if data_loader else None,
            log_fn=log_fn,
        )
    logging.info("final: %s", final)
    if eval_steps:
        # held-out split: windows the train loader can never sample
        eval_iter = iter(data_loader.eval_view()) if data_loader else None
        ev = trainer.evaluate(batch_iter=eval_iter, steps=eval_steps)
        logging.info("eval: %s", ev)
    if tracer is not None:
        from tpu_parallel.obs import write_chrome_trace

        logging.info("trace: %s", write_chrome_trace(tracer, _TRACE_OUT.value))
    if _METRICS_OUT.value:
        from tpu_parallel.obs import write_prometheus

        logging.info(
            "metrics: %s",
            write_prometheus(trainer.registry, _METRICS_OUT.value),
        )


if __name__ == "__main__":
    # absl flags spell underscores; accept the GNU-style dashed forms the
    # docs advertise (--trace-out / --metrics-out) too
    sys.argv = [
        a.replace("--trace-out", "--trace_out").replace(
            "--metrics-out", "--metrics_out"
        )
        for a in sys.argv
    ]
    app.run(main)
