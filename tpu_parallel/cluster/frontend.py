"""The cluster frontend: one submit/step/drain surface over N replicas.

This is the piece that turns a pile of :class:`~tpu_parallel.serving.
engine.ServingEngine` replicas into something a service can sit behind.
``submit()`` is the cluster's ONE admission gate; everything past it is
accepted work the frontend is responsible for finishing — on whichever
replica, after however many failures:

- **Admission control** (all typed, same ``finish_reason`` vocabulary as
  the engine): global token-budget backpressure (``token_budget`` — the
  sum of every open request's ``prompt + max_new_tokens`` reservation is
  capped, the scale-out generalization of the scheduler's ``max_queue``),
  per-client concurrency caps (``client_limit``), capacity (``capacity``)
  and the drain gate (``draining``).
- **Priority with aging**: dispatch order is effective priority =
  ``priority + waited / aging_seconds`` — higher classes go first, but
  every pending request gains one priority class per ``aging_seconds``
  waited, so a starving low-priority request provably overtakes any
  fixed-priority flood (the no-starvation test pins this).
- **Deadlines**: a request past ``deadline`` seconds from arrival is
  cancelled WHEREVER it is — pending here, queued in a replica, or
  holding a cache slot mid-decode (``ServingEngine.cancel`` releases the
  slot) — with a tokenless terminal event, because a reply the client
  stopped waiting for is pure wasted compute.
- **Fault-tolerant lifecycle**: a replica death (fault plan or real
  exception) orphans its queued AND running requests; each is re-routed
  with the dead replica excluded and its prompt FORCED-PREFIXED with the
  tokens already streamed (``prompt + delivered``), so the retry re-
  prefills exactly the context the dead replica had and greedy output is
  bitwise identical to a never-failed run — the stream just continues.
  Tokens are never re-streamed and never lost.  ``retry_limit`` bounds
  the replay of a request that keeps landing on dying replicas
  (``failed``/``retry_limit``), and a cluster dead BEYOND RECOVERY fails
  pending work loudly (``no_replica``) instead of queueing forever —
  while any restart is pending, pending work holds here instead, so a
  full-fleet flap doesn't fail every request.
- **Self-healing** (docs/12_cluster.md draws the state machine): a
  progress WATCHDOG marks a replica that has work but delivers nothing
  for ``watchdog_ticks`` cluster ticks DEGRADED, and after
  ``watchdog_kill_ticks`` declares it DEAD with its work orphaned
  through the normal forced-prefix replay — stalls are detected from
  observed behavior, never from the injection side.  Dead replicas with
  an ``engine_factory`` are rebuilt under a :class:`~tpu_parallel.
  cluster.replica.RestartPolicy` circuit breaker: exponential backoff
  on the injectable clock (BACKOFF), then a half-open PROBATION window
  (bounded concurrent requests; ``probation_ticks`` clean ticks promote
  to HEALTHY; a probation death trips the breaker and doubles the
  backoff) until the budget (``max_restarts``) runs out.
- **Graceful drain**: ``drain()`` closes the admission gate, pulls every
  replica's QUEUED remainder back and re-routes it across live replicas
  (the queue stuck behind one busy engine redistributes), then ticks
  until all in-flight work finishes.  Every cache slot comes back free —
  the acceptance suite asserts slot counts and table alignment.

Observability: the frontend owns its own ``cluster_*`` metric namespace
(per-replica load/health gauges labeled by replica, typed rejection and
dispatch-reject counters, retry/requeue/cancel counters, a route-
imbalance histogram, TTFT/E2E latency histograms) and traces routing
decisions, deaths, retries and drains on a dedicated ``router`` tracer
track alongside the engines' per-slot tracks.  Engine registries stay
per-replica — their unlabeled ``serving_*`` series would collide across
replicas in one store.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Callable, Dict, List, Optional, Sequence, Union

from tpu_parallel.cluster.autopilot import (
    Autopilot,
    AutopilotPolicy,
)
from tpu_parallel.cluster.replica import (
    BACKOFF,
    DEAD,
    DEGRADED,
    HEALTHY,
    PROBATION,
    RETIRED,
    ReplicaDead,
    ReplicaHandle,
    RestartPolicy,
)
from tpu_parallel.cluster.migration import (
    MIGRATE_IMPORTED,
    MIGRATION_STATUSES,
    capture_kv,
    install_kv,
    warm_start,
)
from tpu_parallel.cluster.router import (
    PrefixAffinityRouter,
    Router,
    make_router,
)
from tpu_parallel.cluster.swap import (
    SWAP_REFUSED_DRAINING,
    SWAP_REFUSED_FINGERPRINT,
    SWAP_REFUSED_IN_PROGRESS,
    SWAP_REFUSED_SHAPE,
    SWAP_REFUSED_VERSION,
    SWAP_TRACK,
    SwapController,
    SwapPolicy,
)
from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.obs.tracer import NULL_TRACER, Tracer
from tpu_parallel.serving.engine import ServingEngine, validate_same_shapes
from tpu_parallel.serving.request import (
    CANCELLED,
    EXPIRED,
    FAILED,
    FINISHED,
    REJECT_CAPACITY,
    REJECT_CLIENT_LIMIT,
    REJECT_DRAINING,
    REJECT_SHED,
    REJECT_TOKEN_BUDGET,
    REJECTED,
    RUNNING,
    Request,
    RequestOutput,
    StreamEvent,
)

_HEALTH_CODE = {
    HEALTHY: 0.0,
    DEGRADED: 1.0,
    DEAD: 2.0,
    BACKOFF: 3.0,
    PROBATION: 4.0,
    RETIRED: 5.0,
}
# circuit-breaker state per replica: 0 = closed (serving), 1 = half-open
# (probation trickle), 2 = open (no traffic flows — dead / waiting out
# backoff / retired by the autopilot, which is benign but equally closed
# to traffic)
_BREAKER_CODE = {
    HEALTHY: 0.0,
    DEGRADED: 0.0,
    PROBATION: 1.0,
    BACKOFF: 2.0,
    DEAD: 2.0,
    RETIRED: 2.0,
}


@dataclasses.dataclass(frozen=True)
class FrontendConfig:
    """Admission-control and retry policy knobs.

    - ``max_inflight_tokens``: global token-budget backpressure — the sum
      of ``len(prompt) + max_new_tokens`` over every OPEN request (from
      accept to terminal) may not exceed this; beyond it ``submit``
      rejects typed ``token_budget``.  None = unbounded.
    - ``max_per_client``: per-``client_id`` cap on open requests
      (requests without a ``client_id`` are uncapped).
    - ``aging_seconds``: a pending request gains one effective priority
      class per this many seconds waited — the anti-starvation dial
      (must be > 0; infinity-like values approximate strict priority).
    - ``retry_limit``: replica-death replays allowed per request before
      it fails with ``retry_limit``.
    - ``dispatch_queue_depth``: how deep a replica's engine queue the
      frontend will dispatch into (None = the replica's slot count).
      This is LATE BINDING, and priority depends on it: a request handed
      to an engine joins a FIFO the frontend can no longer reorder, so
      the frontend keeps just enough queued per replica to refill every
      slot and holds the rest HERE, where effective priority (with
      aging) re-sorts the backlog every tick.
    - ``watchdog_ticks`` / ``watchdog_kill_ticks``: the progress
      watchdog.  A replica that ``has_work()`` but makes NO observable
      progress (no stream events, no prefill advance) for
      ``watchdog_ticks`` consecutive cluster ticks is marked DEGRADED
      (drained of new routing while anything healthy exists); at
      ``watchdog_kill_ticks`` it is declared DEAD and its work replays
      elsewhere through the forced-prefix path — stall DETECTION from
      behavior alone, with zero help from the injection side.  None
      disables that threshold.  Progress clears the counter and restores
      a DEGRADED replica to HEALTHY.
    - ``restart``: the :class:`~tpu_parallel.cluster.replica.
      RestartPolicy` circuit breaker (None = dead replicas stay dead).
      Only replicas carrying an ``engine_factory`` are ever restarted;
      backoff timing flows through the frontend's injectable clock.
    - ``warm_start_blocks``: KV blocks to pre-seed into a scale-up
      newcomer's prefix cache from the hottest radix chains of a live
      donor (``cluster/migration.py``; 0 disables).  A no-op unless the
      engines run the radix KV hierarchy — a cold cache is slow, not
      wrong, so warm start is always best-effort.
    """

    max_inflight_tokens: Optional[int] = None
    max_per_client: Optional[int] = None
    aging_seconds: float = 10.0
    retry_limit: int = 3
    dispatch_queue_depth: Optional[int] = None
    watchdog_ticks: Optional[int] = 10
    watchdog_kill_ticks: Optional[int] = 40
    restart: Optional[RestartPolicy] = dataclasses.field(
        default_factory=RestartPolicy
    )
    warm_start_blocks: int = 16

    def __post_init__(self):
        if self.aging_seconds <= 0:
            raise ValueError(f"aging_seconds={self.aging_seconds} <= 0")
        if self.retry_limit < 0:
            raise ValueError(f"retry_limit={self.retry_limit} < 0")
        if self.dispatch_queue_depth is not None and (
            self.dispatch_queue_depth < 1
        ):
            raise ValueError(
                f"dispatch_queue_depth={self.dispatch_queue_depth} < 1"
            )
        if self.watchdog_ticks is not None and self.watchdog_ticks < 1:
            raise ValueError(f"watchdog_ticks={self.watchdog_ticks} < 1")
        if self.watchdog_kill_ticks is not None:
            if self.watchdog_kill_ticks < 1:
                raise ValueError(
                    f"watchdog_kill_ticks={self.watchdog_kill_ticks} < 1"
                )
            if (
                self.watchdog_ticks is not None
                and self.watchdog_kill_ticks <= self.watchdog_ticks
            ):
                raise ValueError(
                    f"watchdog_kill_ticks={self.watchdog_kill_ticks} must "
                    f"exceed watchdog_ticks={self.watchdog_ticks} — a "
                    "replica must degrade before it is killed"
                )


@dataclasses.dataclass
class ClusterOutput(RequestOutput):
    """The client-visible record: a :class:`RequestOutput` whose tokens
    accumulate ACROSS replica attempts, plus the attempt history."""

    replicas: List[int] = dataclasses.field(default_factory=list)
    retries: int = 0


class _Recovery:
    """Frontend-internal self-healing state for one replica: the
    watchdog's stall counter, the circuit breaker's failure/attempt
    tallies, the pending restart deadline, and probation progress."""

    __slots__ = (
        "stall_ticks", "failures", "attempts", "clean_ticks",
        "restart_at", "probation",
    )

    def __init__(self):
        self.stall_ticks = 0  # consecutive no-progress ticks with work
        self.failures = 0  # consecutive deaths since the last promotion
        self.attempts = 0  # lifetime restart attempts (breaker budget)
        self.clean_ticks = 0  # exception-free ticks this probation
        self.restart_at: Optional[float] = None  # frontend-clock deadline
        self.probation = False  # currently half-open


class _ClientState:
    """Frontend-internal bookkeeping for one accepted request."""

    __slots__ = (
        "out", "seq", "budget", "excluded", "handle", "engine_rid", "base",
        "pinned_version", "kv_export",
    )

    def __init__(self, out: ClusterOutput, seq: int, budget: int):
        self.out = out
        self.seq = seq
        self.budget = budget  # reserved tokens (prompt + max_new)
        self.excluded: set = set()  # replica ids this request must avoid
        self.handle: Optional[ReplicaHandle] = None  # current attempt
        self.engine_rid: Optional[str] = None
        self.base = 0  # tokens delivered before the current attempt
        # the weight version that produced this request's FIRST token: a
        # stream must not straddle weight versions, so replays prefer
        # same-version replicas while any exist (rolling-swap hygiene)
        self.pinned_version: Optional[str] = None
        # KV blocks captured from the last relocation's source replica
        # (cluster/migration.py): installed into the next placement's
        # engine so the forced-prefix replay HITS instead of recomputing;
        # one-shot, cleared at the install attempt
        self.kv_export = None


class Frontend:
    """Replicated serving frontend (see the module docstring).

    ``replicas`` is a sequence of :class:`ReplicaHandle` (or bare
    :class:`ServingEngine`, wrapped with ids 0..N-1 and no fault plan).
    ``router`` is a policy name (``rr`` / ``least`` / ``prefix``) or a
    ready :class:`Router`; the prefix policy reads its bucket alignment
    from replica 0's engine.  ``clock`` is injectable — every timestamp
    in the frontend flows through it (``scripts/check_clock.py`` enforces
    that no cluster/serving module reads wall time directly).
    """

    def __init__(
        self,
        replicas: Sequence[Union[ReplicaHandle, ServingEngine]],
        router: Union[str, Router] = "least",
        config: Optional[FrontendConfig] = None,
        clock: Callable[[], float] = time.monotonic,
        tracer: Optional[Tracer] = None,
        registry: Optional[MetricRegistry] = None,
    ):
        if not replicas:
            raise ValueError("Frontend needs at least one replica")
        handles: List[ReplicaHandle] = []
        for i, rep in enumerate(replicas):
            if isinstance(rep, ReplicaHandle):
                handles.append(rep)
            else:
                handles.append(ReplicaHandle(i, rep))
        ids = [h.replica_id for h in handles]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate replica ids {ids}")
        self.replicas = sorted(handles, key=lambda h: h.replica_id)
        self.config = config or FrontendConfig()
        self.clock = clock
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.registry = registry if registry is not None else MetricRegistry()
        if isinstance(router, str):
            buckets = self.replicas[0].engine.prefill_buckets
            router = make_router(router, ids, buckets=buckets)
        self.router = router
        self.draining = False
        self._seq = itertools.count()
        self._pending: List[_ClientState] = []
        self._by_attempt: Dict[str, _ClientState] = {}
        self._reserved = 0  # open token-budget reservations
        self._events: List[StreamEvent] = []
        r = self.registry
        self._submitted = r.counter("cluster_submitted_total")
        self._finished = r.counter("cluster_finished_total")
        self._retries = r.counter("cluster_retries_total")
        self._requeued = r.counter("cluster_requeued_total")
        self._cancelled = r.counter("cluster_cancelled_total")
        # the ONE deadline-shed counter: every typed ``deadline``
        # terminal — tick-top sweep or pre-dispatch check alike — goes
        # through _shed_deadline and lands here exactly once
        self._deadline_sheds = r.counter("cluster_deadline_sheds_total")
        self._failed = r.counter("cluster_failed_total")
        self._deaths = r.counter("cluster_replica_deaths_total")
        self._watchdog_degraded = r.counter(
            "cluster_watchdog_degraded_total"
        )
        self._watchdog_kills = r.counter("cluster_watchdog_kills_total")
        self._restarts = r.counter("cluster_restarts_total")
        self._restart_failures = r.counter("cluster_restart_failures_total")
        self._promotions = r.counter("cluster_probation_promotions_total")
        self._demotions = r.counter("cluster_probation_demotions_total")
        self._recovery: Dict[int, _Recovery] = {
            h.replica_id: _Recovery() for h in self.replicas
        }
        self._imbalance = r.histogram("cluster_route_imbalance")
        self._ttft = r.histogram("cluster_ttft_seconds")
        self._e2e = r.histogram("cluster_e2e_seconds")
        self._by_id: Dict[int, ReplicaHandle] = {
            h.replica_id: h for h in self.replicas
        }
        # rolling weight hot-swap (cluster/swap.py): the in-flight (or
        # last finished) rollout, the fleet's post-swap standard weights
        # (restarting replicas rebind to them), and version ordinals for
        # the per-replica cluster_swap_version gauge
        self._swap: Optional[SwapController] = None
        self._fleet_weights: Optional[tuple] = None
        self._version_ordinals: Dict[str, int] = {"initial": 0}
        self._swap_seq = itertools.count(1)
        # SLO autopilot (cluster/autopilot.py): the closed overload-
        # control loop, plus the replicas it has scaled down (kept for
        # observability — a retired handle owns no work and never ticks)
        self._autopilot: Optional[Autopilot] = None
        self.retired: List[ReplicaHandle] = []
        # monotone id source for scale-ups: never reuse an id — not even
        # a retiree's, whose terminal gauge row and trace history a new
        # engine must not inherit
        self._next_replica_id = max(self._by_id) + 1
        # write-ahead journal hook (tpu_parallel/daemon/): when set, the
        # frontend notifies it at the durability-relevant points —
        # accepted submissions, terminal events, drain begin, swap
        # begin, autopilot actions — so a daemon shell can journal every
        # state change it must survive.  None costs nothing.
        self._journal: Optional[Callable[[str, dict], None]] = None
        self._journal_ap_seen = 0  # autopilot actions already notified

    # -- journal hook ------------------------------------------------------

    def set_journal(self, sink: Optional[Callable[[str, dict], None]]) -> None:
        """Attach (or clear) the write-ahead journal hook: ``sink(kind,
        payload)`` fires at submit-accept / terminal / drain-begin /
        swap-begin and once per autopilot action.  The daemon shell is
        the intended consumer; the frontend never depends on it."""
        self._journal = sink

    def _journal_note(self, kind: str, **payload) -> None:
        if self._journal is not None:
            self._journal(kind, payload)

    # -- admission ---------------------------------------------------------

    @property
    def seq_len(self) -> int:
        return self.replicas[0].engine.model.config.seq_len

    def _open_states(self) -> List[_ClientState]:
        return self._pending + list(self._by_attempt.values())

    def submit(self, request: Request) -> ClusterOutput:
        """The cluster's admission gate.  Returns the live record; a
        REJECTED status carries the typed reason (``draining`` /
        ``capacity`` / ``client_limit`` / ``token_budget``)."""
        now = self.clock()
        out = ClusterOutput(request=request, arrival_time=now)
        self._submitted.inc()

        def reject(reason: str, detail: Optional[str] = None):
            out.status = REJECTED
            out.finish_reason = reason
            out.detail = detail
            self.registry.counter(
                "cluster_rejected_total", reason=reason
            ).inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "reject", track="router",
                    request_id=request.request_id, reason=reason,
                )
            return out

        if self.draining:
            return reject(REJECT_DRAINING)
        need = len(request.prompt) + request.max_new_tokens
        if need > self.seq_len:
            return reject(
                REJECT_CAPACITY,
                detail=(
                    f"prompt ({len(request.prompt)}) + max_new_tokens "
                    f"({request.max_new_tokens}) exceeds seq_len "
                    f"({self.seq_len})"
                ),
            )
        cfg = self.config
        if cfg.max_per_client is not None and request.client_id is not None:
            open_for_client = sum(
                1
                for st in self._open_states()
                if st.out.request.client_id == request.client_id
            )
            if open_for_client >= cfg.max_per_client:
                return reject(REJECT_CLIENT_LIMIT)
        if (
            cfg.max_inflight_tokens is not None
            and self._reserved + need > cfg.max_inflight_tokens
        ):
            return reject(REJECT_TOKEN_BUDGET)
        if self._autopilot is not None:
            # overload shedding: while the autopilot is past its SLO
            # targets, NEW lowest-effective-priority submissions are
            # refused typed (bounded by the policy's shed fraction)
            veto = self._autopilot.admission_veto(request, now)
            if veto is not None:
                return reject(REJECT_SHED)
        self._reserved += need
        self._pending.append(_ClientState(out, next(self._seq), need))
        self._journal_note(
            "submit_accepted", request_id=request.request_id,
            reserved_tokens=need,
        )
        return out

    # -- the tick ----------------------------------------------------------

    def step(self) -> List[StreamEvent]:
        """One cluster tick: fire due restarts, enforce deadlines,
        dispatch pending work through the router, tick every live
        replica (deaths collected and their work re-routed THIS tick,
        the progress watchdog fed from each replica's observed output),
        publish per-replica telemetry.  Returns the tick's cluster-level
        StreamEvents (client request ids, cluster token indices)."""
        now = self.clock()
        self._events = []
        self._service_restarts(now)
        if self._swap is not None and self._swap.active:
            # the rolling swap advances BEFORE dispatch so exclusions,
            # rebinds and canary promotions shape this tick's placement
            self._swap.tick(now)
        if self._autopilot is not None:
            # the autopilot senses and actuates before dispatch too, so
            # shed state, fleet size and retuned budgets shape this tick
            self._autopilot.tick(now)
            if self._journal is not None:
                acts = self._autopilot.actions
                for act in acts[self._journal_ap_seen:]:
                    self._journal_note(
                        "autopilot_action", kind=act.kind,
                        reason=act.reason, tick=act.tick,
                        detail=dict(act.detail),
                    )
                self._journal_ap_seen = len(acts)
        self._enforce_deadlines(now)
        self._dispatch(now)
        for handle in self.replicas:
            if handle.health in (DEAD, BACKOFF):
                continue
            # progress is judged from OBSERVED behavior only: stream
            # events out, or prefill work consumed (a mid-chunk tick
            # delivers no token yet clearly advances)
            had_work = handle.has_work()
            prefill_before = handle.pending_prefill_tokens
            try:
                events = handle.step()
            except ReplicaDead:
                self._on_death(handle)
                continue
            progressed = bool(events) or (
                handle.pending_prefill_tokens < prefill_before
            )
            if handle.health == PROBATION:
                self._probation_tick(handle, had_work, progressed)
            self._watchdog(handle, had_work, progressed)
        # re-place retries and bounced attempts without losing a tick
        self._dispatch(self.clock())
        # loud failure ONLY with the whole fleet dead beyond recovery: a
        # replica in backoff/probation (or rescheduled for restart) means
        # capacity is coming back, so pending work HOLDS in the frontend
        # queue instead of failing a full-fleet flap's every request
        if all(h.health == DEAD for h in self.replicas):
            for st in list(self._pending):
                self._pending.remove(st)
                self._finalize(st, FAILED, "no_replica", self.clock())
                self._failed.inc()
                self._emit_terminal(st, "no_replica")
        self._publish()
        events, self._events = self._events, []
        return events

    # -- self-healing ------------------------------------------------------

    def _handle(self, replica_id: int) -> ReplicaHandle:
        return self._by_id[replica_id]

    def _restartable(self, handle: ReplicaHandle) -> bool:
        """Whether the circuit breaker could ever revive this replica —
        a restart policy exists, the handle carries a factory, and the
        lifetime attempt budget is not exhausted."""
        policy = self.config.restart
        return (
            policy is not None
            and handle.engine_factory is not None
            and self._recovery[handle.replica_id].attempts
            < policy.max_restarts
        )

    def _service_restarts(self, now: float) -> None:
        """Fire every due restart: rebuild the engine through the
        handle's factory and enter PROBATION.  A factory failure counts
        against the breaker budget and doubles the backoff; an exhausted
        budget leaves the replica DEAD (breaker open for good)."""
        policy = self.config.restart
        if policy is None:
            return
        for handle in self.replicas:
            if handle.health != BACKOFF:
                continue
            rec = self._recovery[handle.replica_id]
            if rec.restart_at is None or now < rec.restart_at:
                continue
            rec.restart_at = None
            rec.attempts += 1
            try:
                handle.restart()
            except Exception as exc:
                self._restart_failures.inc()
                rec.failures += 1
                if self.tracer.enabled:
                    self.tracer.instant(
                        "restart_failed", track="router",
                        replica=handle.replica_id, error=repr(exc),
                    )
                if rec.attempts < policy.max_restarts:
                    rec.restart_at = now + policy.delay(rec.failures)
                else:
                    handle.health = DEAD  # breaker open for good
                continue
            # version reconciliation: the factory rebuilds with the
            # weights the cluster was BORN with, but a completed hot
            # swap made a newer set the fleet standard — rebind the
            # fresh (idle) engine before it takes probation traffic, so
            # a post-swap restart can never resurrect the old version
            if self._fleet_weights is not None:
                ver, params = self._fleet_weights
                if handle.weights_version != ver:
                    handle.engine.rebind_params(params, version=ver)
                    if self.tracer.enabled:
                        self.tracer.instant(
                            "swap_rebind_on_restart", track=SWAP_TRACK,
                            replica=handle.replica_id, version=ver,
                        )
            rec.clean_ticks = 0
            rec.stall_ticks = 0
            rec.probation = True
            self._restarts.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "restart", track="router", replica=handle.replica_id,
                    attempt=rec.attempts,
                )
            # the fresh engine owes nothing to old exclusions: requests
            # orphaned by the PREVIOUS incarnation may run here again
            # (without this, a 1-replica cluster could never self-heal)
            for st in self._open_states():
                st.excluded.discard(handle.replica_id)

    def _probation_tick(
        self, handle: ReplicaHandle, had_work: bool, progressed: bool
    ) -> None:
        policy = self.config.restart
        rec = self._recovery[handle.replica_id]
        if had_work and not progressed:
            # a stall-suspect tick proves nothing: freeze the clean
            # count and let the watchdog judge the replica — a wedged
            # restart must never be promoted (which would also reset
            # the breaker's failure count and defeat backoff escalation)
            return
        rec.clean_ticks += 1
        if self._swap is not None and self._swap.gates_probation(handle):
            # the swap canary (and any replica awaiting rollback) is
            # promoted by the SwapPolicy, not the generic probation
            # clock — clean ticks still accrue for the canary gate
            return
        if policy is not None and rec.clean_ticks >= policy.probation_ticks:
            handle.health = HEALTHY
            rec.probation = False
            rec.failures = 0  # proved itself: earn back fast restarts
            self._promotions.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "probation_promote", track="router",
                    replica=handle.replica_id,
                    clean_ticks=rec.clean_ticks,
                )

    def _watchdog(
        self, handle: ReplicaHandle, had_work: bool, progressed: bool
    ) -> None:
        """Observed-progress stall detection: a replica with work that
        produced nothing this tick accrues stall ticks; enough of them
        degrade it (drained of new routing) and then kill it (work
        orphaned through the normal death path).  Any progress clears
        the counter and restores a DEGRADED replica."""
        cfg = self.config
        if cfg.watchdog_ticks is None and cfg.watchdog_kill_ticks is None:
            return
        rec = self._recovery[handle.replica_id]
        if progressed or not had_work:
            rec.stall_ticks = 0
            if handle.health == DEGRADED:
                handle.health = HEALTHY
                if self.tracer.enabled:
                    self.tracer.instant(
                        "watchdog_recovered", track="router",
                        replica=handle.replica_id,
                    )
            return
        rec.stall_ticks += 1
        kill = cfg.watchdog_kill_ticks
        warn = cfg.watchdog_ticks
        if kill is not None and rec.stall_ticks >= kill:
            self._watchdog_kills.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "watchdog_kill", track="router",
                    replica=handle.replica_id,
                    stalled_ticks=rec.stall_ticks,
                )
            handle.kill(
                f"watchdog: no progress for {rec.stall_ticks} ticks"
            )
            self._on_death(handle)
        elif (
            warn is not None
            and rec.stall_ticks >= warn
            and handle.health == HEALTHY
        ):
            handle.health = DEGRADED
            self._watchdog_degraded.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "watchdog_degraded", track="router",
                    replica=handle.replica_id,
                    stalled_ticks=rec.stall_ticks,
                )

    def has_work(self) -> bool:
        return bool(self._pending) or bool(self._by_attempt)

    def run(self, max_ticks: Optional[int] = None) -> List[StreamEvent]:
        """Tick until every accepted request is terminal (or ``max_ticks``)."""
        events: List[StreamEvent] = []
        ticks = 0
        while self.has_work() and (max_ticks is None or ticks < max_ticks):
            events.extend(self.step())
            ticks += 1
        return events

    def drain(self, max_ticks: Optional[int] = None) -> List[StreamEvent]:
        """Graceful shutdown: stop admitting (typed ``draining``
        rejections), gate every live engine, pull the engines' queued
        remainders back and re-route them across live replicas, then run
        to completion.  On return every accepted request is terminal and
        every replica's cache pool is fully released."""
        self.draining = True
        self._journal_note("drain_begin")
        span = (
            self.tracer.span("drain", track="router")
            if self.tracer.enabled
            else None
        )
        for handle in self.replicas:
            if handle.health in (DEAD, BACKOFF):
                continue
            handle.engine.begin_drain()
        for handle in self.replicas:
            if handle.health in (DEAD, BACKOFF):
                continue
            self._pull_back_queued(handle)
        events = self.run(max_ticks)
        if span is not None:
            span.finish(requeued=int(self._requeued.value))
        return events

    # -- rolling weight hot-swap -------------------------------------------

    def begin_swap(
        self,
        checkpoint_dir: Optional[str] = None,
        step: Optional[int] = None,
        *,
        params=None,
        version: Optional[str] = None,
        policy: Optional[SwapPolicy] = None,
    ) -> dict:
        """Start a zero-downtime rolling weight swap (cluster/swap.py —
        the module docstring and docs/12 describe the state machine).

        Pass either a ``checkpoint_dir`` (+ optional ``step``) written by
        :func:`~tpu_parallel.checkpoint.io.save_serving_weights` — the
        manifest supplies the version and the load is fingerprint-
        verified — or an in-memory ``params`` tree with a ``version``
        string.  Returns the swap status dict (see :meth:`swap_status`);
        a REFUSED swap carries the typed reason in ``verdict``:
        ``draining`` (mid-drain fleets don't take new weights),
        ``swap_in_progress`` (one rollout at a time),
        ``fingerprint_mismatch`` (checkpoint failed its manifest audit),
        ``shape_mismatch`` (not a same-shape weight set) or
        ``version_in_service`` (the version id is already live — a
        rollback could never tell old from new).
        """

        def refuse(reason: str, detail: Optional[str] = None) -> dict:
            self.registry.counter(
                "cluster_swap_refused_total", reason=reason
            ).inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "swap_refused", track=SWAP_TRACK, reason=reason,
                )
            return {"state": "refused", "verdict": reason, "detail": detail}

        if self.draining:
            return refuse(SWAP_REFUSED_DRAINING)
        if self._swap is not None and self._swap.active:
            return refuse(SWAP_REFUSED_IN_PROGRESS)
        if checkpoint_dir is not None:
            from tpu_parallel.checkpoint.io import (
                WeightsCorrupt,
                load_serving_weights,
            )

            try:
                params, manifest = load_serving_weights(
                    checkpoint_dir, step=step,
                    like=self.replicas[0].engine.params,
                )
            except WeightsCorrupt as exc:
                return refuse(SWAP_REFUSED_FINGERPRINT, detail=str(exc))
            if version is None:
                version = manifest.version
        if params is None:
            raise ValueError(
                "begin_swap needs params=... or a checkpoint_dir"
            )
        if version is None:
            version = f"swap-{next(self._swap_seq)}"
        if any(h.weights_version == version for h in self.replicas):
            return refuse(
                SWAP_REFUSED_VERSION,
                detail=f"version {version!r} is already serving",
            )
        try:
            validate_same_shapes(self.replicas[0].engine.params, params)
        except ValueError as exc:
            return refuse(SWAP_REFUSED_SHAPE, detail=str(exc))
        self._version_ordinals.setdefault(
            version, len(self._version_ordinals)
        )
        self._swap = SwapController(
            self, params, version, policy or SwapPolicy()
        )
        self._journal_note(
            "swap_begin", version=version, replicas=len(self.replicas)
        )
        if self.tracer.enabled:
            self.tracer.instant(
                "swap_begin", track=SWAP_TRACK, version=version,
                replicas=len(self.replicas),
            )
        return self._swap.status_dict()

    def swap_status(self) -> dict:
        """The current (or last finished) rollout's typed status:
        ``state`` (``idle`` / ``rolling`` / ``rolling_back`` /
        ``completed`` / ``rolled_back``), the typed ``verdict``
        (``completed``, or the rollback reason — ``canary_death`` /
        ``slo_ttft`` / ``slo_e2e`` / ``logit_fingerprint``), per-replica
        phases and weight versions, and the canary-vs-baseline latency
        window means."""
        if self._swap is None:
            return {
                "state": "idle",
                "verdict": None,
                "replica_versions": {
                    h.replica_id: h.weights_version for h in self.replicas
                },
            }
        return self._swap.status_dict()

    # -- SLO autopilot (cluster/autopilot.py) -------------------------------

    def enable_autopilot(
        self,
        policy: Optional[AutopilotPolicy] = None,
        engine_factory=None,
        role_controller=None,
    ) -> Autopilot:
        """Arm the closed-loop overload controller: once per ``step()``
        it senses the queue-age/TTFT windows and actuates bounded shed /
        scale / retune moves (the module docstring of ``cluster/
        autopilot.py`` is the full story).  ``engine_factory`` builds
        the engines scale-up adds (default: the first replica's own
        factory).  Returns the controller; ``autopilot_status()`` and
        ``summary()`` expose its state.

        The default policy (``policy=None``) is SHED-ONLY, anchored to
        the current fleet: ``max_replicas == min_replicas == len(
        replicas)`` and scale-down disabled — arming the controller for
        graceful degradation must never quietly resize a fleet the
        operator sized by hand.  Scaling is opt-in via an explicit
        policy.  ``role_controller`` (a FleetRouter, or anything with
        its role surface) arms the re-role lever — None leaves the
        fleet's prefill:decode ratio alone."""
        if self._autopilot is not None:
            raise RuntimeError("autopilot already enabled")
        if policy is None:
            policy = AutopilotPolicy(
                max_replicas=len(self.replicas),
                min_replicas=len(self.replicas),
                scale_down_idle_ticks=None,
            )
        self._autopilot = Autopilot(
            self, policy, engine_factory, role_controller=role_controller,
        )
        return self._autopilot

    def autopilot_status(self) -> dict:
        """The controller's typed state (``{"enabled": False}`` when no
        autopilot is armed)."""
        if self._autopilot is None:
            return {"enabled": False}
        return self._autopilot.status()

    def _add_replica(self, engine_factory) -> ReplicaHandle:
        """Scale-up actuator: build a fresh engine, wrap it under the
        next free replica id, and enter it through the SAME half-open
        probation gate a restarted replica uses — a new replica proves
        itself on a bounded trickle before taking full traffic.  After
        a completed swap the newcomer is rebound to the fleet-standard
        weights first, so scale-up can never resurrect an old version."""
        rid = self._next_replica_id
        self._next_replica_id += 1
        handle = ReplicaHandle(
            rid, engine_factory(), engine_factory=engine_factory
        )
        if self._fleet_weights is not None:
            ver, params = self._fleet_weights
            if handle.weights_version != ver:
                handle.engine.rebind_params(params, version=ver)
        rec = _Recovery()
        if self.config.restart is not None:
            handle.health = PROBATION
            rec.probation = True
        else:
            # no RestartPolicy = no probation machinery to promote out
            # of — enter HEALTHY rather than strand the newcomer
            # half-open forever (it could then never idle-retire either)
            handle.health = HEALTHY
        if self.config.warm_start_blocks > 0:
            # pre-seed the newcomer's prefix cache from the hottest
            # radix chains of the busiest live donor: rebalanced traffic
            # then hits immediately instead of re-prefilling every hot
            # tenant header (no-op without the radix hierarchy)
            donor, best = None, 0
            for h in self.replicas:
                if h.health in (DEAD, BACKOFF):
                    continue
                radix = getattr(h.engine, "_radix", None)
                if radix is not None and radix.device_blocks > best:
                    donor, best = h, radix.device_blocks
            if donor is not None:
                handle.kv_warm_blocks = warm_start(
                    donor, handle, self.config.warm_start_blocks
                )
                if handle.kv_warm_blocks:
                    self.registry.counter(
                        "cluster_kv_warm_start_blocks_total"
                    ).inc(handle.kv_warm_blocks)
                    if self.tracer.enabled:
                        self.tracer.instant(
                            "kv_warm_start", track="router", replica=rid,
                            donor=donor.replica_id,
                            blocks=handle.kv_warm_blocks,
                        )
        self.replicas.append(handle)
        self.replicas.sort(key=lambda h: h.replica_id)
        self._by_id[rid] = handle
        self._recovery[rid] = rec
        if isinstance(self.router, PrefixAffinityRouter):
            self.router.add_replica(rid)
        self.registry.counter("cluster_scale_ups_total").inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "scale_up", track="router", replica=rid,
                replicas=len(self.replicas),
            )
        return handle

    def _retire_replica(self, handle: ReplicaHandle) -> None:
        """Scale-down actuator: retire one IDLE replica through the
        existing drain machinery — the engine's gate closes, the (empty)
        queued remainder relocates, and the handle leaves the fleet for
        the ``retired`` list.  Nothing orphans and nothing replays: the
        idle precondition is the whole point of ``scale_down_idle_ticks``."""
        self._pull_back_queued(handle)  # belt and braces: idle = empty
        handle.retire()
        rid = handle.replica_id
        self.replicas = [h for h in self.replicas if h.replica_id != rid]
        self._by_id.pop(rid, None)
        self._recovery.pop(rid, None)
        self.retired.append(handle)
        if isinstance(self.router, PrefixAffinityRouter):
            self.router.remove_replica(rid)
        # final gauge row: the retired replica stops publishing, so pin
        # its last health/load values to the terminal state
        lab = {"replica": rid}
        self.registry.gauge("cluster_replica_health", **lab).set(
            _HEALTH_CODE[RETIRED]
        )
        self.registry.gauge("cluster_breaker_state", **lab).set(
            _BREAKER_CODE[RETIRED]
        )
        self.registry.gauge("cluster_replica_load", **lab).set(0.0)
        self.registry.counter("cluster_scale_downs_total").inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "scale_down", track="router", replica=rid,
                replicas=len(self.replicas),
            )

    def _capture_relocation_kv(
        self, st: "_ClientState", handle: ReplicaHandle, engine_rid: str
    ) -> None:
        """Capture an attempt's written KV blocks from a LIVE source
        replica before a relocation cancels its slot (the cancel frees
        the blocks) — the export half of cross-replica KV migration.
        Best effort: None leaves the replay on the proven recompute
        path.  Crash replay never reaches here by design — a dead
        engine's state must not be read."""
        export = capture_kv(handle, engine_rid)
        if export is None:
            return
        st.kv_export = export
        self.registry.counter("cluster_kv_exports_total").inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "kv_export", track="router",
                request_id=st.out.request.request_id,
                replica=handle.replica_id, blocks=export.n_blocks,
            )

    def _pull_back_queued(self, handle: ReplicaHandle) -> int:
        """Pull ``handle``'s engine-queued remainder back into the
        frontend backlog — the ONE relocation-of-queued-work move drain
        and the swap rollout's exclusion/revert phases all share (queued
        work has no replica or weight-version stake yet).  Returns how
        many requests moved."""
        moved = 0
        for eout in handle.take_queued():
            st = self._by_attempt.pop(eout.request.request_id, None)
            self.tracer.release_trace(eout.request.request_id)
            if st is None or st.out.done:
                continue
            st.handle = None
            st.engine_rid = None
            self._requeued.inc()
            self._pending.append(st)
            moved += 1
        return moved

    def cancel(self, request_id: str, reason: str = "cancelled") -> bool:
        """Client-initiated cancellation by CLUSTER request id — pending,
        queued-in-replica, or mid-decode alike.  False if unknown/done."""
        for st in self._open_states():
            if st.out.request.request_id == request_id and not st.out.done:
                self._cancel_state(st, reason, self.clock())
                return True
        return False

    def export_request_kv(self, request_id: str):
        """Export ONE live request's written KV prefix from whichever
        replica currently decodes it (by CLUSTER request id) — the donor
        half of the fleet's prefill→decode handoff.  Only the frontend
        can translate the client id into the attempt-scoped engine id
        (``rid@attempt``), so this is the one seam the daemon shell gets.
        None when the request is unknown, finished, still pending, or
        its engine holds no full exportable block."""
        for st in self._by_attempt.values():
            if st.out.request.request_id != request_id or st.out.done:
                continue
            if st.handle is None or st.engine_rid is None:
                return None
            exporter = getattr(st.handle.engine, "export_prefix", None)
            if exporter is None:
                return None
            return exporter(st.engine_rid)
        return None

    # -- dispatch ----------------------------------------------------------

    def _dispatch_depth(self, handle: ReplicaHandle) -> int:
        """Per-replica dispatch bound (see ``dispatch_queue_depth``)."""
        if self.config.dispatch_queue_depth is not None:
            return self.config.dispatch_queue_depth
        return handle.engine.pool.n_slots

    def _probation_headroom(self, handle: ReplicaHandle) -> bool:
        """A half-open replica takes at most ``probation_requests``
        concurrent open requests — enough traffic to prove the rebuilt
        engine, little enough that a relapse orphans almost nothing."""
        if handle.health != PROBATION:
            return True
        policy = self.config.restart
        if policy is None:
            return True
        return handle.open_requests < policy.probation_requests

    def _effective_priority(self, st: _ClientState, now: float) -> float:
        arrival = st.out.arrival_time
        waited = max(0.0, now - arrival) if arrival is not None else 0.0
        return st.out.request.priority + waited / self.config.aging_seconds

    def _dispatch(self, now: float) -> None:
        if not self._pending:
            return
        order = sorted(
            self._pending,
            key=lambda st: (-self._effective_priority(st, now), st.seq),
        )
        leftover = []
        for st in order:
            # pre-dispatch deadline shed: a request whose deadline
            # expired while it waited here must not be handed to an
            # engine — the prefill would be pure waste, and the engine
            # would only hand it back for the in-flight cancel next
            # tick.  (The tick-top _enforce_deadlines pass runs on the
            # tick's FIRST clock read; the post-step re-dispatch reads a
            # fresh clock, so a deadline can expire between the two.)
            deadline = st.out.request.deadline
            if (
                deadline is not None
                and st.out.arrival_time is not None
                and now - st.out.arrival_time > deadline
            ):
                self._shed_deadline(st, now)
                continue
            if not self._try_place(st, now):
                leftover.append(st)
        self._pending = leftover

    def _try_place(self, st: _ClientState, now: float) -> bool:
        """Route one pending request: the policy picks among routable
        candidates (healthy preferred over degraded, exclusions and
        non-accepting replicas filtered), synchronous engine rejections
        (queue_full) exclude that replica FOR THIS PASS and re-route.
        False leaves the request pending for the next tick."""
        req = st.out.request
        tried: set = set()
        swap = self._swap
        while True:
            cands = [
                h
                for h in self.replicas
                if h.routable
                and h.queue_depth < self._dispatch_depth(h)
                and h.replica_id not in st.excluded
                and h.replica_id not in tried
                and self._probation_headroom(h)
                # rolling swap: the current target is drained of NEW
                # placement; during a rollback every replica still on
                # the abandoned version is off limits
                and not h.swap_excluded
                and (swap is None or not swap.blocked(h))
            ]
            if st.pinned_version is not None:
                # a stream must finish on the weight version that
                # started it: prefer same-version replicas, fall back
                # only when none exist anywhere (counted at the actual
                # dispatch below, once per placement, not per pass)
                same = [
                    h for h in cands
                    if h.weights_version == st.pinned_version
                ]
                if same:
                    cands = same
            # healthy first; a PROBATION replica takes its half-open
            # trickle alongside them (that's how it proves itself);
            # DEGRADED only when nothing else is placeable
            preferred = [
                h for h in cands if h.health in (HEALTHY, PROBATION)
            ]
            cands = preferred or cands
            pick = self.router.route(req.prompt, cands)
            if pick is None:
                return False
            loads = [h.load() for h in cands]
            self._imbalance.observe(pick.load() - min(loads))
            ereq = self._attempt_request(st)
            # engine spans carry the ATTEMPT id (rid@N), not the cluster
            # rid the daemon bound its trace under — alias the attempt
            # to the same context BEFORE the engine admission records
            # its queue span, and release wherever the attempt retires
            ctx = self.tracer.trace_of(req.request_id)
            if ctx is not None:
                self.tracer.bind_trace(ereq.request_id, ctx)
            # requeue=True: frontend-accepted work being PLACED is not a
            # new admission from the engine's point of view — the drain
            # gate guards direct engine submissions, the frontend's gate
            # already guarded this one
            eout = pick.submit(
                ereq, requeue=True, arrival_time=st.out.arrival_time
            )
            if eout.done:  # synchronous engine rejection (queue_full)
                self.tracer.release_trace(ereq.request_id)
                self.registry.counter(
                    "cluster_dispatch_rejects_total",
                    reason=eout.finish_reason or "unknown",
                ).inc()
                if self.tracer.enabled:
                    self.tracer.instant(
                        "dispatch_reject", track="router",
                        request_id=req.request_id,
                        replica=pick.replica_id,
                        reason=eout.finish_reason,
                    )
                tried.add(pick.replica_id)
                continue
            if isinstance(self.router, PrefixAffinityRouter):
                # the router counts overload fallbacks it decides itself;
                # spills it never SAW — the hash-owner filtered out of
                # the candidate list by the dispatch bound, an exclusion
                # or death — are counted here, so the fallback gauge is
                # meaningful under the frontend's pre-filtering too
                owner = self.router.owner(req.prompt)
                if owner != pick.replica_id and owner not in {
                    c.replica_id for c in cands
                }:
                    self.router.fallbacks += 1
            if (
                st.pinned_version is not None
                and st.out.tokens
                and pick.weights_version != st.pinned_version
            ):
                # the one case a stream crosses weight versions: a
                # mid-stream replay found NO replica on its pinned
                # version — counted per actual placement
                self.registry.counter(
                    "cluster_swap_version_fallbacks_total"
                ).inc()
            st.handle = pick
            st.engine_rid = ereq.request_id
            st.out.replicas.append(pick.replica_id)
            self._by_attempt[ereq.request_id] = st
            if st.kv_export is not None:
                # relocated KV rides along: land the captured blocks in
                # the target's prefix cache BEFORE the engine's admission
                # tick, so the forced-prefix replay hits and ships blocks
                # instead of recomputing; every verdict is typed and
                # counted — recompute survives only as observable fallback
                verdict = install_kv(pick, st.kv_export)
                self.registry.counter(
                    "cluster_kv_migrations_total", status=verdict
                ).inc()
                if verdict == MIGRATE_IMPORTED:
                    self.registry.counter(
                        "cluster_kv_migrated_blocks_total"
                    ).inc(st.kv_export.n_blocks)
                if self.tracer.enabled:
                    self.tracer.instant(
                        "kv_migrate", track="router",
                        request_id=req.request_id,
                        replica=pick.replica_id, status=verdict,
                        blocks=st.kv_export.n_blocks,
                    )
                st.kv_export = None
            self.registry.counter(
                "cluster_dispatched_total", replica=pick.replica_id
            ).inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "route", track="router", request_id=req.request_id,
                    replica=pick.replica_id, policy=self.router.name,
                    attempt=len(st.out.replicas),
                )
            return True

    def _attempt_request(self, st: _ClientState) -> Request:
        """Build the engine-level request for the next attempt: the
        prompt is FORCED-PREFIXED with every token already delivered, so
        a replay re-prefills exactly the context the previous replica
        held and the stream continues bit-for-bit (greedy) where it
        stopped.  The attempt's budget is the REMAINDER, so engine-side
        length retirement equals cluster-side length retirement."""
        req = st.out.request
        st.base = len(st.out.tokens)
        return Request(
            prompt=list(req.prompt) + list(st.out.tokens),
            max_new_tokens=req.max_new_tokens - st.base,
            sampling=req.sampling,
            eos_token_id=req.eos_token_id,
            request_id=f"{req.request_id}@{len(st.out.replicas)}",
            draft_tokens=req.draft_tokens,
            on_token=self._make_on_token(st),
        )

    def _make_on_token(self, st: _ClientState):
        def on_token(ev: StreamEvent) -> None:
            if st.out.done:
                return  # frontend already finalized (cancel/deadline)
            if ev.token < 0:
                # attempt-level terminal notification without a token
                # (engine queue expiry): the attempt died before
                # producing.  Each bounce COUNTS AGAINST retry_limit —
                # the retry preserves the original arrival, so on an
                # engine whose max_wait the request has already blown it
                # would expire again every tick, forever.  Past the
                # limit the request terminates EXPIRED instead of
                # livelocking run()/drain().
                if st.handle is None:
                    return
                self._by_attempt.pop(st.engine_rid, None)
                if st.engine_rid is not None:
                    self.tracer.release_trace(st.engine_rid)
                st.handle = None
                st.engine_rid = None
                st.out.retries += 1
                self._retries.inc()
                if st.out.retries > self.config.retry_limit:
                    self._finalize(st, EXPIRED, "max_wait", self.clock())
                    self._emit_terminal(st, "max_wait")
                    return
                self._requeued.inc()
                self._pending.append(st)
                return
            now = self.clock()
            index = st.base + ev.index
            if st.out.first_token_time is None:
                st.out.first_token_time = now
            if st.pinned_version is None and st.handle is not None:
                # first token: the stream is now committed to this
                # weight version (replays prefer same-version replicas)
                st.pinned_version = st.handle.weights_version
            st.out.status = RUNNING
            st.out.tokens.append(ev.token)
            st.out.token_times.append(now)
            cev = StreamEvent(
                request_id=st.out.request.request_id,
                token=ev.token,
                index=index,
                finished=ev.finished,
                finish_reason=ev.finish_reason,
            )
            if ev.finished:
                if self._swap is not None and self._swap.active:
                    # canary-window accounting + spot-check candidate
                    # capture (needs st.handle, so before _finalize)
                    self._swap.note_finish(st, now)
                self._finalize(st, FINISHED, ev.finish_reason, now)
                self._finished.inc()
                if st.out.ttft is not None:
                    self._ttft.observe(st.out.ttft)
                self._e2e.observe(now - st.out.arrival_time)
            self._events.append(cev)
            if st.out.request.on_token is not None:
                st.out.request.on_token(cev)

        return on_token

    # -- failure / cancellation -------------------------------------------

    def _on_death(self, handle: ReplicaHandle) -> None:
        """A replica died mid-tick (engine exception, fault plan, or
        watchdog kill — they all count against the same retry budget):
        exclude it for every orphaned request and replay each
        (forced-prefix) elsewhere; requests out of retries fail loudly.
        Each orphan is also FORGOTTEN from the handle's ledger — the
        replay is now the frontend's responsibility, and a later restart
        of this replica must not find stale orphans to double-replay.
        Finally the circuit breaker decides whether a restart is
        scheduled (BACKOFF) or the replica stays DEAD."""
        now = self.clock()
        self._deaths.inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "replica_death", track="router", replica=handle.replica_id,
                orphans=len(handle.orphans()),
            )
        for eout in handle.orphans():
            handle.forget(eout.request.request_id)
            st = self._by_attempt.pop(eout.request.request_id, None)
            self.tracer.release_trace(eout.request.request_id)
            if st is None or st.out.done:
                continue
            st.excluded.add(handle.replica_id)
            st.handle = None
            st.engine_rid = None
            st.out.retries += 1
            self._retries.inc()
            if st.out.retries > self.config.retry_limit:
                self._finalize(st, FAILED, "retry_limit", now)
                self._failed.inc()
                self._emit_terminal(st, "retry_limit")
                continue
            if self.tracer.enabled:
                self.tracer.instant(
                    "retry", track="router",
                    request_id=st.out.request.request_id,
                    from_replica=handle.replica_id,
                    delivered=len(st.out.tokens),
                )
            self._pending.append(st)
        # circuit breaker: consecutive failures stretch the backoff; a
        # death during probation is the classic breaker trip (the replica
        # failed its audition) and doubles the next wait
        rec = self._recovery[handle.replica_id]
        rec.failures += 1
        rec.clean_ticks = 0
        rec.stall_ticks = 0
        if rec.probation:
            rec.probation = False
            self._demotions.inc()
            if self.tracer.enabled:
                self.tracer.instant(
                    "probation_demote", track="router",
                    replica=handle.replica_id,
                )
        policy = self.config.restart
        if self._restartable(handle):
            delay = policy.delay(rec.failures)
            handle.health = BACKOFF
            rec.restart_at = now + delay
            if self.tracer.enabled:
                self.tracer.instant(
                    "restart_scheduled", track="router",
                    replica=handle.replica_id, delay=delay,
                    failures=rec.failures,
                )
        if self._swap is not None and self._swap.active:
            # the rollout reacts AFTER the breaker decided: a dead
            # canary triggers rollback, a dead target defers, a dead
            # promoted replica re-queues (its restart resurrects the
            # old weights and must be swapped again)
            self._swap.on_death(handle.replica_id)

    def _enforce_deadlines(self, now: float) -> None:
        for st in self._open_states():
            deadline = st.out.request.deadline
            if deadline is None or st.out.done:
                continue
            if now - st.out.arrival_time > deadline:
                self._shed_deadline(st, now)

    def _shed_deadline(self, st: _ClientState, now: float) -> None:
        """The ONE deadline-expiry terminal: both sweeps — the tick-top
        ``_enforce_deadlines`` pass and the pre-dispatch check (whose
        fresh clock read can observe an expiry BETWEEN the two passes) —
        shed through here, so every deadline miss is one typed
        ``deadline`` cancel counted once on one counter, wherever in the
        tick it was caught."""
        self._deadline_sheds.inc()
        self._cancel_state(st, "deadline", now)

    def _cancel_state(self, st: _ClientState, reason: str, now: float) -> None:
        """Cancel wherever the request is.  Finalizes the cluster record
        FIRST so the engine's own cancel notification no-ops in the
        attempt callback, then releases any in-engine work (slot freed)."""
        handle, engine_rid = st.handle, st.engine_rid
        if st in self._pending:
            self._pending.remove(st)
        self._finalize(st, CANCELLED, reason, now)
        if handle is not None and handle.health not in (DEAD, BACKOFF):
            handle.engine.cancel(engine_rid, reason=reason)
            handle.forget(engine_rid)
        self._cancelled.inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "cancel", track="router",
                request_id=st.out.request.request_id, reason=reason,
            )
        self._emit_terminal(st, reason)

    def _finalize(
        self, st: _ClientState, status: str, reason: Optional[str], now: float
    ) -> None:
        st.out.status = status
        st.out.finish_reason = reason
        st.out.finish_time = now
        if st.engine_rid is not None:
            self._by_attempt.pop(st.engine_rid, None)
            self.tracer.release_trace(st.engine_rid)
        st.handle = None
        st.engine_rid = None
        self._reserved -= st.budget
        self._journal_note(
            "terminal", request_id=st.out.request.request_id,
            status=status, reason=reason, n_tokens=len(st.out.tokens),
        )

    def _emit_terminal(self, st: _ClientState, reason: str) -> None:
        event = StreamEvent(
            request_id=st.out.request.request_id,
            token=-1,
            index=-1,
            finished=True,
            finish_reason=reason,
        )
        self._events.append(event)
        if st.out.request.on_token is not None:
            st.out.request.on_token(event)

    # -- telemetry ---------------------------------------------------------

    def _publish(self) -> None:
        r = self.registry
        for h in self.replicas:
            lab = {"replica": h.replica_id}
            r.gauge("cluster_replica_health", **lab).set(
                _HEALTH_CODE[h.health]
            )
            r.gauge("cluster_breaker_state", **lab).set(
                _BREAKER_CODE[h.health]
            )
            r.gauge("cluster_replica_restarts", **lab).set(h.restarts)
            r.gauge("cluster_swap_version", **lab).set(
                self._version_ordinals.setdefault(
                    h.weights_version, len(self._version_ordinals)
                )
            )
            r.gauge("cluster_replica_load", **lab).set(
                0.0 if h.health in (DEAD, BACKOFF) else h.load()
            )
            r.gauge("cluster_replica_queue_depth", **lab).set(h.queue_depth)
            r.gauge("cluster_replica_active_slots", **lab).set(h.active_slots)
        r.gauge("cluster_inflight_tokens").set(self._reserved)
        r.gauge("cluster_pending_requests").set(len(self._pending))
        if isinstance(self.router, PrefixAffinityRouter):
            r.gauge("cluster_affinity_fallbacks").set(self.router.fallbacks)

    def recovery_summary(self) -> Dict[int, dict]:
        """Per-replica self-healing state for tooling and the chaos
        harness: breaker attempts/budget, consecutive failures, whether a
        restart is pending, and probation progress."""
        policy = self.config.restart
        out = {}
        for h in self.replicas:
            rec = self._recovery[h.replica_id]
            out[h.replica_id] = {
                "health": h.health,
                "restarts": h.restarts,
                "attempts": rec.attempts,
                "budget_left": (
                    0 if policy is None or h.engine_factory is None
                    else max(0, policy.max_restarts - rec.attempts)
                ),
                "failures": rec.failures,
                "restart_pending": rec.restart_at is not None,
                "restart_at": rec.restart_at,
                "probation": rec.probation,
                "clean_ticks": rec.clean_ticks,
                "stall_ticks": rec.stall_ticks,
            }
        return out

    def prefix_hit_rate(self) -> Optional[float]:
        """Aggregate prefix-cache hit rate across every replica whose
        engine runs a prefix cache (None when none do or nothing probed) —
        the number prefix-affinity routing exists to maximize."""
        hits = misses = 0
        for h in self.replicas:
            pc = h.engine._prefix
            if pc is not None:
                hits += pc.hits
                misses += pc.misses
        probes = hits + misses
        if probes == 0:
            return None
        return hits / probes

    def summary(self) -> dict:
        hit_rate = self.prefix_hit_rate()
        return {
            "replicas": [h.summary() for h in self.replicas],
            "router": self.router.name,
            "submitted": int(self._submitted.value),
            "finished": int(self._finished.value),
            "retries": int(self._retries.value),
            "requeued": int(self._requeued.value),
            "cancelled": int(self._cancelled.value),
            "deadline_sheds": int(self._deadline_sheds.value),
            "failed": int(self._failed.value),
            "replica_deaths": int(self._deaths.value),
            "watchdog_degraded": int(self._watchdog_degraded.value),
            "watchdog_kills": int(self._watchdog_kills.value),
            "restarts": int(self._restarts.value),
            "restart_failures": int(self._restart_failures.value),
            "probation_promotions": int(self._promotions.value),
            "probation_demotions": int(self._demotions.value),
            "swap_state": self.swap_status()["state"],
            "swaps": int(
                self.registry.counter("cluster_swaps_total").value
            ),
            "swap_rollbacks": int(
                self.registry.counter(
                    "cluster_swap_rollbacks_total"
                ).value
            ),
            "autopilot": (
                None if self._autopilot is None
                else {
                    "shedding": self._autopilot.shedding,
                    "shed_rejects": int(
                        self._autopilot._shed_rejects.value
                    ),
                    "shed_cancels": int(
                        self._autopilot._shed_cancels.value
                    ),
                    "actions": len(self._autopilot.actions),
                }
            ),
            "scale_ups": int(
                self.registry.counter("cluster_scale_ups_total").value
            ),
            "scale_downs": int(
                self.registry.counter("cluster_scale_downs_total").value
            ),
            "inflight_tokens": self._reserved,
            "kv_exports": int(
                self.registry.counter("cluster_kv_exports_total").value
            ),
            "kv_migrations": {
                status: int(
                    self.registry.counter(
                        "cluster_kv_migrations_total", status=status
                    ).value
                )
                for status in MIGRATION_STATUSES
            },
            "kv_migrated_blocks": int(
                self.registry.counter(
                    "cluster_kv_migrated_blocks_total"
                ).value
            ),
            "kv_warm_start_blocks": int(
                self.registry.counter(
                    "cluster_kv_warm_start_blocks_total"
                ).value
            ),
            "prefix_hit_rate": (
                None if hit_rate is None else round(hit_rate, 4)
            ),
            "ttft_ms_p50": _ms(self._ttft.percentile(50)),
            "ttft_ms_p95": _ms(self._ttft.percentile(95)),
            "e2e_ms_p95": _ms(self._e2e.percentile(95)),
        }


def _ms(x: Optional[float]) -> Optional[float]:
    return None if x is None else round(x * 1000.0, 3)
