"""Length-prefixed binary wire codec for :class:`KVPrefixExport`.

PR 15 made the export a self-verifying exchange unit (one CRC32 per
block, recomputed before any import lands).  This module makes it a
WIRE format: ``encode_export`` flattens one export into a single frame
of bytes, ``decode_export`` rebuilds it bitwise, and concatenated
frames (``encode_exports`` / ``decode_exports``) are the body of the
fleet's ``/v1/kv/export`` → ``/v1/kv/import`` exchange
(docs/14_fleet.md).

Frame layout (all integers big-endian)::

    magic   b"KVW1"                       4 bytes
    hlen    uint32  header length         4 bytes
    hcrc    uint32  CRC32 of header       4 bytes
    header  canonical JSON (utf-8)        hlen bytes
    payload leaf arrays, C-order bytes    sum(leaf nbytes)

The header carries everything except the raw K/V bytes — tokens,
block geometry, ``weights_version``, the exporter's ``meta`` shape
signature, the per-block checksums, and each leaf's dtype/shape (which
is what makes the payload self-describing: leaf byte extents are
derived, never trusted from a length field that could disagree).

Decoding REFUSES, never guesses: every way a frame can be damaged maps
to a typed :class:`WireFormatError` reason (``truncated``, ``magic``,
``header_crc``, ``header_schema``, ``integrity``).  A bit flipped in
the payload trips the per-block CRC (``integrity``); a bit flipped in
the header trips ``hcrc`` before the JSON is even parsed — so version
skew and shape compatibility are still judged by
:meth:`ServingEngine.import_prefix` on exactly the values the exporter
wrote, and corrupt bytes never serve (the importer recomputes from
tokens instead).

The codec is pure bytes-in/bytes-out; only the file helpers at the
bottom touch the filesystem, and they go through the
``daemon.iofaults`` read gate so the seeded-rot soak covers blobs at
rest the same way it covers the journal.
"""

from __future__ import annotations

import json
import math
import struct
import zlib
from typing import List, Tuple

import numpy as np

from tpu_parallel.serving.kv_hierarchy import KVPrefixExport

MAGIC = b"KVW1"
_HEADER_STRUCT = struct.Struct(">II")  # hlen, hcrc
_FRAME_OVERHEAD = len(MAGIC) + _HEADER_STRUCT.size

# a header is small (tokens + shapes); anything claiming more is damage,
# not data — refuse before allocating
MAX_HEADER_BYTES = 1 << 24

WIRE_TRUNCATED = "truncated"
WIRE_MAGIC = "magic"
WIRE_HEADER_CRC = "header_crc"
WIRE_HEADER_SCHEMA = "header_schema"
WIRE_INTEGRITY = "integrity"

WIRE_REASONS = (
    WIRE_TRUNCATED,
    WIRE_MAGIC,
    WIRE_HEADER_CRC,
    WIRE_HEADER_SCHEMA,
    WIRE_INTEGRITY,
)


class WireFormatError(ValueError):
    """A frame that cannot be decoded — carries the typed ``reason``
    (one of :data:`WIRE_REASONS`) the refusing side reports, so the
    import endpoint's 400 and the fleet's ``fleet_kv_wire_refusals``
    counter speak the same vocabulary as the migration verdicts."""

    def __init__(self, reason: str, detail: str):
        assert reason in WIRE_REASONS, reason
        super().__init__(f"{reason}: {detail}")
        self.reason = reason
        self.detail = detail


def _dtype(name: str) -> np.dtype:
    """Resolve a dtype name recorded at encode time.  Plain numpy names
    resolve directly; the ml_dtypes extensions jax caches use
    (bfloat16, float8 variants) resolve through the registered scalar
    types."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        try:
            return np.dtype(getattr(ml_dtypes, name))
        except (AttributeError, TypeError):
            raise WireFormatError(
                WIRE_HEADER_SCHEMA, f"unknown leaf dtype {name!r}"
            ) from None


def _tuplize(obj):
    """JSON loses tuple-ness; ``meta`` equality at import compares
    against the pool's tuple-of-tuples signature, so rebuild it."""
    if isinstance(obj, list):
        return tuple(_tuplize(x) for x in obj)
    return obj


def encode_export(export: KVPrefixExport) -> bytes:
    """One export → one frame of bytes (see the module docstring for
    the layout).  Leaves are shipped as C-order raw bytes; the header's
    per-leaf dtype/shape entries are what decode uses to carve the
    payload back up, and the canonical-JSON header keeps equal exports
    byte-identical on the wire."""
    leaves = [np.ascontiguousarray(leaf) for leaf in export.leaves]
    header = {
        "tokens": [int(t) for t in export.tokens],
        "length": int(export.length),
        "block_tokens": int(export.block_tokens),
        "weights_version": str(export.weights_version),
        "meta": export.meta,
        "checksums": [int(c) for c in export.checksums],
        "leaves": [
            {"dtype": str(leaf.dtype), "shape": list(leaf.shape)}
            for leaf in leaves
        ],
    }
    hbytes = json.dumps(
        header, sort_keys=True, separators=(",", ":"), default=list
    ).encode("utf-8")
    frame = [
        MAGIC,
        _HEADER_STRUCT.pack(len(hbytes), zlib.crc32(hbytes) & 0xFFFFFFFF),
        hbytes,
    ]
    frame.extend(leaf.tobytes(order="C") for leaf in leaves)
    return b"".join(frame)


def _decode_frame(
    buf: bytes, off: int, verify: bool
) -> Tuple[KVPrefixExport, int]:
    """Decode one frame starting at ``off``; returns the export and the
    offset just past it.  Raises :class:`WireFormatError` — typed,
    never a stray struct/json/numpy exception."""
    if len(buf) - off < _FRAME_OVERHEAD:
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"{len(buf) - off} bytes at offset {off}, "
            f"frame prelude needs {_FRAME_OVERHEAD}",
        )
    if buf[off:off + len(MAGIC)] != MAGIC:
        raise WireFormatError(
            WIRE_MAGIC,
            f"bad magic {buf[off:off + len(MAGIC)]!r} at offset {off}",
        )
    hlen, hcrc = _HEADER_STRUCT.unpack_from(buf, off + len(MAGIC))
    if hlen > MAX_HEADER_BYTES:
        raise WireFormatError(
            WIRE_HEADER_SCHEMA, f"header claims {hlen} bytes"
        )
    hstart = off + _FRAME_OVERHEAD
    if len(buf) - hstart < hlen:
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"header needs {hlen} bytes, {len(buf) - hstart} remain",
        )
    hbytes = buf[hstart:hstart + hlen]
    if (zlib.crc32(hbytes) & 0xFFFFFFFF) != hcrc:
        raise WireFormatError(
            WIRE_HEADER_CRC, "header CRC mismatch (damaged in transit)"
        )
    try:
        header = json.loads(hbytes.decode("utf-8"))
        tokens = tuple(int(t) for t in header["tokens"])
        length = int(header["length"])
        block_tokens = int(header["block_tokens"])
        weights_version = str(header["weights_version"])
        meta = _tuplize(header["meta"])
        checksums = tuple(int(c) for c in header["checksums"])
        leaf_specs = []
        for spec in header["leaves"]:
            shape = tuple(int(d) for d in spec["shape"])
            if any(d < 0 for d in shape):
                # a negative dim would make the extent arithmetic lie
                # (count<0 reads the whole buffer, pos walks backwards)
                raise WireFormatError(
                    WIRE_HEADER_SCHEMA, f"negative leaf dim in {shape}"
                )
            leaf_specs.append((_dtype(spec["dtype"]), shape))
    except WireFormatError:
        raise
    except (KeyError, TypeError, ValueError) as exc:
        raise WireFormatError(
            WIRE_HEADER_SCHEMA, f"malformed header: {exc}"
        ) from None
    pos = hstart + hlen
    leaves = []
    for dtype, shape in leaf_specs:
        # Python-int arithmetic: a huge claimed dim must overflow into
        # "bigger than the buffer" (truncated), never wrap negative
        count = math.prod(shape)
        nbytes = dtype.itemsize * count
        if nbytes > len(buf) - pos:
            raise WireFormatError(
                WIRE_TRUNCATED,
                f"leaf needs {nbytes} bytes, {len(buf) - pos} remain",
            )
        try:
            arr = np.frombuffer(buf, dtype=dtype, count=count, offset=pos)
            leaves.append(arr.reshape(shape).copy())
        except (ValueError, TypeError) as exc:
            raise WireFormatError(
                WIRE_HEADER_SCHEMA, f"leaf does not carve: {exc}"
            ) from None
        pos += nbytes
    export = KVPrefixExport(
        tokens=tokens,
        length=length,
        block_tokens=block_tokens,
        weights_version=weights_version,
        meta=meta,
        leaves=tuple(leaves),
        checksums=checksums,
    )
    if verify and not export.verified():
        raise WireFormatError(
            WIRE_INTEGRITY,
            "per-block CRC mismatch — payload damaged in transit",
        )
    return export, pos


def decode_export(buf: bytes, *, verify: bool = True) -> KVPrefixExport:
    """Decode exactly one frame; trailing bytes are damage, not data.
    ``verify=True`` (the default) recomputes the per-block CRCs so
    corrupt payloads refuse HERE — importers may pass ``verify=False``
    when they run the same check themselves via
    :meth:`ServingEngine.import_prefix`."""
    export, end = _decode_frame(buf, 0, verify)
    if end != len(buf):
        raise WireFormatError(
            WIRE_TRUNCATED,
            f"{len(buf) - end} trailing bytes after one frame",
        )
    return export


def encode_exports(exports) -> bytes:
    """Concatenated frames — the ``/v1/kv/export`` response body.  An
    empty list is an empty body (a donor with nothing hot is a valid
    answer, not an error)."""
    return b"".join(encode_export(e) for e in exports)


def decode_exports(
    buf: bytes, *, verify: bool = True
) -> List[KVPrefixExport]:
    """Decode a stream of concatenated frames until the buffer is
    exactly consumed.  Any damage — mid-frame truncation included —
    refuses the WHOLE stream: a partial import would leave the receiver
    believing it warm-started chains it only half holds."""
    out: List[KVPrefixExport] = []
    off = 0
    while off < len(buf):
        export, off = _decode_frame(buf, off, verify)
        out.append(export)
    return out


def write_export_file(path: str, exports) -> str:
    """Spill a stream of exports to ``path`` (the bench's corpus /
    corrupt-injection legs).  Plain binary write — durability barriers
    are the journal's business, not a bench artifact's."""
    from tpu_parallel.daemon import iofaults

    with iofaults.open_file(path, "wb") as fh:
        fh.write(encode_exports(exports))
    return path


def read_export_file(
    path: str, *, verify: bool = True
) -> List[KVPrefixExport]:
    """Read a spilled stream back through the ``iofaults`` read gate —
    an armed flip plan rots the blob exactly as it would the journal,
    and the typed refusal surfaces here instead of garbage K/V."""
    from tpu_parallel.daemon import iofaults

    return decode_exports(iofaults.read_bytes(path), verify=verify)
