"""Decoder-only transformer LM, composable over DP x FSDP x TP x PP meshes.

The flagship model family for the BASELINE.json matrix: GPT-2 125M/350M
(learned positions, LayerNorm, gelu) and Llama-style (RoPE, RMSNorm, SwiGLU)
via :class:`~tpu_parallel.models.layers.TransformerConfig` switches.  No
reference model exists to mirror (the reference trains 2-layer MLPs only);
the parallelism semantics follow the framework's strategy modules:

- TP: structural (TPDense everywhere; identity on tp=1 meshes).
- FSDP: ``config.fsdp`` wraps each Block / embedding in
  ``fsdp.shard_module_params`` over the data axis — gathers are per-block,
  so peak HBM holds one block's full weights, not the model's.
- PP: ``pipe_size > 1`` runs the block stack as GPipe stages over the pipe
  axis.  Logits are then valid on the **last** pipe rank only — train with
  :func:`make_gpt_loss`, which masks by :func:`pp.last_stage_mask`.
  Under PP, ``positions``/``segment_ids`` must be ``None`` (unpacked
  sequences; blocks regenerate default positions per microbatch).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import lax

from tpu_parallel.core.metrics import Metrics
from tpu_parallel.core.rng import fold_rng_over_axis
from tpu_parallel.models.layers import (
    Attention,
    Block,
    BlockStack,
    Embedding,
    TransformerConfig,
    make_norm,
)
from tpu_parallel.parallel import fsdp, pp
from tpu_parallel.parallel.tp import TPDense


@dataclasses.dataclass(frozen=True)
class GPTConfig(TransformerConfig):
    """TransformerConfig plus pipeline degree (static model knobs only)."""

    pipe_size: int = 1  # number of pipeline stages the block stack is cut into


class GPTLM(nn.Module):
    """tokens [B, S] -> logits [B, S, vocab]."""

    config: GPTConfig

    @nn.compact
    def __call__(
        self,
        tokens: jax.Array,
        positions: Optional[jax.Array] = None,
        segment_ids: Optional[jax.Array] = None,
        train: bool = True,
        decode: bool = False,
    ) -> jax.Array:
        cfg = self.config
        if decode and cfg.pipe_size > 1:
            raise NotImplementedError("incremental decoding under pipeline parallelism")
        if decode and positions is None:
            # default decode positions from a model-level step counter, so
            # learned positional embeddings see global positions (Attention
            # keeps its own per-layer cache index for the K/V mask — both
            # advance by the same token count and stay consistent)
            counter = self.variable(
                "cache", "decode_pos", lambda: jnp.zeros((), jnp.int32)
            )
            positions = jnp.broadcast_to(
                counter.value + jnp.arange(tokens.shape[1])[None, :], tokens.shape
            )
            counter.value = counter.value + tokens.shape[1]
        embed_cls = Embedding
        if cfg.fsdp:
            embed_cls = fsdp.shard_module_params(
                Embedding, cfg.data_axis, cfg.fsdp_min_size
            )
        x = embed_cls(cfg, name="embed")(tokens, positions=positions)

        if cfg.pipe_size > 1:
            # positions are consumed by the (pre-pipeline) embedding; inside
            # the pipeline, RoPE blocks fall back to default arange positions.
            # Packed sequences can't ride the activation ppermute yet:
            if segment_ids is not None:
                raise NotImplementedError(
                    "pipeline parallelism currently requires unpacked sequences "
                    "(segment_ids must be None)"
                )
            if cfg.n_layers % cfg.pipe_size != 0:
                raise ValueError(
                    f"n_layers={cfg.n_layers} not divisible by pipe_size={cfg.pipe_size}"
                )
            layers_per_stage = cfg.n_layers // cfg.pipe_size
            x = pp.PipelineModule(
                stage_fn=functools.partial(BlockStack, cfg, layers_per_stage),
                num_microbatches=cfg.num_microbatches,
                axis_name=cfg.pipe_axis,
                # BlockStack accepts aux_scale: bubble ticks contribute
                # exactly zero to sown losses (MoE balance)
                pass_validity=True,
                name="pipeline",
            )(x, train=train)
        else:
            x = BlockStack(cfg, cfg.n_layers, name="blocks")(
                x,
                positions=positions,
                segment_ids=segment_ids,
                train=train,
                decode=decode,
            )

        x = make_norm(cfg, "norm_final")(x).astype(cfg.dtype)
        logits = TPDense(
            features=cfg.vocab_size,
            axis_name=cfg.model_axis,
            style="column",
            gather_output=True,
            use_bias=False,
            dtype=cfg.dtype,
            name="lm_head",
        )(x)
        return logits.astype(jnp.float32)


def make_gpt_loss(config: GPTConfig, train: bool = True):
    """Next-token CE in the accumulate_gradients loss shape, PP/TP-aware.

    Dropout RNG folds over every parallel axis; under PP the loss and metric
    counts are masked to the last pipe rank (the only rank with real logits).
    ``train=False`` builds the evaluation variant (dropout off).
    """
    fold_axes = (config.data_axis, config.model_axis, config.pipe_axis)

    def loss_fn(params, apply_fn, batch, rng):
        dropout_rng = fold_rng_over_axis(rng, fold_axes)
        apply_kwargs = dict(
            positions=batch.positions,
            segment_ids=None if config.pipe_size > 1 else batch.segment_ids,
            train=train,
            rngs={"dropout": dropout_rng},
        )
        aux_loss = 0.0
        if config.moe_experts > 0:
            logits, mods = apply_fn(
                {"params": params}, batch.tokens, mutable=["losses"], **apply_kwargs
            )
            sown = jax.tree_util.tree_leaves(mods.get("losses", {}))
            if sown:
                # Normalize the tick/layer-stacked sum to a per-layer mean so
                # the aux weight is depth- and schedule-invariant.  Without PP
                # each of this rank's n_layers blocks sows once.  Under PP this
                # rank's layers_per_stage blocks each sow once per REAL tick
                # (bubble ticks are zeroed via aux_scale — pp.py), i.e.
                # num_microbatches times.
                if config.pipe_size > 1:
                    denom = (
                        config.n_layers // config.pipe_size
                    ) * config.num_microbatches
                else:
                    denom = config.n_layers
                aux_loss = sum(jnp.sum(leaf) for leaf in sown) / denom
        else:
            logits = apply_fn({"params": params}, batch.tokens, **apply_kwargs)
        loss = optax.softmax_cross_entropy_with_integer_labels(logits, batch.targets)
        mask = (
            batch.loss_mask
            if batch.loss_mask is not None
            else jnp.ones_like(loss, jnp.float32)
        )
        if config.pipe_size > 1:
            mask = mask * pp.last_stage_mask(config.pipe_axis)
        loss = loss * mask
        n_tok = mask.sum()
        correct = ((logits.argmax(-1) == batch.targets) * mask).sum()
        metrics: Metrics = {
            "loss": (loss.sum(), n_tok),
            "accuracy": (correct.astype(jnp.float32), n_tok),
        }
        total = loss.sum() / jnp.maximum(n_tok, 1.0)
        if config.moe_experts > 0:
            metrics["moe_balance"] = (aux_loss * n_tok, n_tok)
            total = total + config.moe_balance_weight * aux_loss
        return total, metrics

    return loss_fn


# --- Named configurations (BASELINE.md matrix) --------------------------------


def gpt2_125m(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=50304, d_model=768, n_layers=12, n_heads=12, seq_len=1024
            ),
            **overrides,
        }
    )


def gpt2_350m(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=50304, d_model=1024, n_layers=24, n_heads=16, seq_len=1024
            ),
            **overrides,
        }
    )


def llama_1b(**overrides) -> GPTConfig:
    return GPTConfig(
        **{
            **dict(
                vocab_size=32000,
                d_model=2048,
                n_layers=16,
                n_heads=16,
                seq_len=2048,
                positional="rope",
                norm="rmsnorm",
                mlp="swiglu",
            ),
            **overrides,
        }
    )


def tiny_test(**overrides) -> GPTConfig:
    """Small config for CPU-mesh tests: real structure, toy sizes."""
    return GPTConfig(
        **{
            **dict(
                vocab_size=256,
                d_model=32,
                n_layers=4,
                n_heads=4,
                seq_len=32,
                dtype=jnp.float32,
                num_microbatches=2,
            ),
            **overrides,
        }
    )
