"""Benchmark: GPT-2 125M training throughput on the available hardware.

Prints ONE JSON line:
    {"metric": "tokens/sec/chip", "value": N, "unit": "tokens/sec/chip",
     "vs_baseline": M, ...}

``vs_baseline`` is measured MFU divided by the 0.40 north-star target from
BASELINE.json (the reference publishes no numbers of its own — BASELINE.md).
Runs on whatever ``jax.devices()`` offers: the real TPU chip under the
driver, or CPU (with a tiny model) when no accelerator is present.

Process architecture (hardened after BENCH_r03, a watchdog zero caused by a
wedged TPU transport, not by the code):

    parent (this file, no jax import — importing jax dials the TPU relay
    and can itself hang on a wedged transport)
      ├─ phase "probe": tiny matmul in a subprocess, short timeout.
      │    A healthy first touch takes seconds; a hang means the transport
      │    is wedged *before* we spend the full watchdog on it.
      ├─ phase "bench": the real measurement (BENCH_CHILD=1) under the
      │    watchdog; ONE respawn on wedge/crash (the persistent compile
      │    cache makes the retry far cheaper than the first attempt).
      └─ on success: result echoed + saved to BENCH_LAST_GOOD.json.
         on final failure: error JSON says which phase died and carries the
         last good in-round result so a flaky transport can't erase the
         round's measurement entirely.

Watchdog budget: BENCH_WATCHDOG_SECS (default 1800 — the old 900s default
equalled the worst measured fresh-compile time for the unrolled config, so a
legitimate cold run could be killed right at the boundary).
BENCH_RETRY_PAUSE_SECS (default 60) sets the probe-retry pause (the respawn
settle pause is min(30, this)); BENCH_LAST_GOOD_PATH relocates the last-good
record (tests point it at a tmp dir).
"""

import json
import os
import subprocess
import sys
import time

_SELF = os.path.abspath(__file__)
_REPO = os.path.dirname(_SELF)
_LAST_GOOD = os.environ.get(
    "BENCH_LAST_GOOD_PATH", os.path.join(_REPO, "BENCH_LAST_GOOD.json")
)


# --------------------------------------------------------------------------
# Child: the actual measurement.  Runs with BENCH_CHILD=1 in a subprocess so
# the parent can kill/respawn it without wedging its own interpreter.
# --------------------------------------------------------------------------


def child_main():
    import jax

    from tpu_parallel.runtime import enable_compilation_cache

    # warm re-runs skip the first compile; a no-op on remote-compile
    # transports, where persisting large executables stalls (see
    # enable_compilation_cache)
    enable_compilation_cache()
    device = jax.devices()[0]
    on_tpu = device.platform == "tpu"
    n_chips = jax.device_count()

    from tpu_parallel.core import compute as compute_metrics
    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig
    from tpu_parallel.utils.profiling import (
        peak_flops,
        sync,
        transformer_flops_per_token,
    )

    if on_tpu:
        # Defaults from the round-5 sweep (SWEEP_r05.json, scripts/
        # sweep_bench.py): 0.4735 MFU on v5e-1 at batch 256 with 16
        # accumulation minibatches (per-pass batch 16), up from round 4's
        # 0.4468 at batch 16/minib 1 (ladder: 0.4689 at 128/8, 0.4719 at
        # 192/12 — gains taper but stay monotone).  The earlier levers stand (flash
        # 512x512 tiles, "proj_attn" remat, unrolled layers — see
        # SWEEP_r03/r04); round 5 added the batch ladder: throughput climbs
        # with accumulated batch while the per-pass shape stays at the
        # compile-friendly 16.  The scan-layers alternative was bisected
        # (fwd +6.6%, bwd +15.7% — the lax.scan transpose) and tuned
        # (scan_group / _split_transpose / in-scan unroll / batch ladder):
        # best 0.4278 at the same 128/8 shape, an ~9% structural tax the
        # sweeps could not close — the bench stays unrolled, deep configs
        # (350M/1B) keep scan for compile budget (docs/05).
        model, batch, steps, minib = "gpt2_125m", 256 * n_chips, 12, 16
        overrides = dict(
            dropout_rate=0.0,
            remat=True,
            remat_policy="proj_attn",
            attn_impl="flash",
            scan_layers=False,
        )
    else:
        model, batch, steps, minib = "tiny", 8 * n_chips, 10, 1
        overrides = dict(num_microbatches=1)

    config = TrainerConfig(
        model=model,
        model_overrides=overrides,
        mesh=MeshConfig(data=-1),
        global_batch_size=batch,
        num_minibatches=minib,
        steps=steps,
        log_every=10_000,  # no intermediate logging inside the timed loop
        donate=True,
    )
    trainer = Trainer(config)
    trainer.init()

    tokens_per_step = batch * trainer.model_config.seq_len

    # warmup (compile + first steps).  Sync via a device->host scalar read:
    # on some transports block_until_ready returns before execution finishes,
    # which would inflate throughput; a value fetch cannot lie.
    state, metrics = trainer.state, None
    for _ in range(3):
        state, metrics = trainer.funcs.step_fn(state, metrics, trainer.example_batch)
    sync((state, metrics))

    metrics = None  # drop warmup-step sums so final_loss covers timed steps only
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = trainer.funcs.step_fn(state, metrics, trainer.example_batch)
    sync((state, metrics))
    dt = time.perf_counter() - t0
    final_loss = compute_metrics(metrics)["loss"]

    tokens_per_sec = tokens_per_step * steps / dt
    tokens_per_sec_chip = tokens_per_sec / n_chips
    flops_per_token = transformer_flops_per_token(trainer.model_config)
    peak = peak_flops(device) or 197e12  # CPU: nominal, MFU not meaningful
    mfu = tokens_per_sec_chip * flops_per_token / peak

    print(
        json.dumps(
            {
                "metric": "tokens/sec/chip",
                "value": round(tokens_per_sec_chip, 1),
                "unit": "tokens/sec/chip",
                "vs_baseline": round(mfu / 0.40, 4),
                "mfu": round(mfu, 4),
                "model": model,
                "params_m": round(trainer.num_params / 1e6, 1),
                "n_chips": n_chips,
                "device": getattr(device, "device_kind", device.platform),
                "global_batch": batch,
                "seq_len": trainer.model_config.seq_len,
                "steps_timed": steps,
                "final_loss": round(final_loss, 4),
            }
        ),
        flush=True,
    )


# --------------------------------------------------------------------------
# Parent: probe → bench (with one respawn) → report.  Pure stdlib.
# --------------------------------------------------------------------------

_PROBE_SRC = """
import jax, jax.numpy as jnp
x = jnp.ones((256, 256))
(x @ x).block_until_ready()
print("BENCH-PROBE-OK", jax.devices()[0].platform, flush=True)
"""


def _run(cmd, timeout, env=None):
    """Run ``cmd``; return (rc, stdout, wedged).  rc is None on timeout."""
    try:
        proc = subprocess.run(
            cmd,
            stdout=subprocess.PIPE,
            stderr=None,  # compile noise goes straight to our stderr
            timeout=timeout,
            env=env,
            text=True,
        )
        return proc.returncode, proc.stdout, False
    except subprocess.TimeoutExpired as e:
        out = e.stdout
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        return None, out or "", True


def _git_head():
    try:
        return subprocess.run(
            ["git", "-C", _REPO, "rev-parse", "--short", "HEAD"],
            stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL,
            timeout=10,
            text=True,
        ).stdout.strip() or "unknown"
    except (OSError, subprocess.TimeoutExpired):
        return "unknown"


def _fail(phase, detail, elapsed, last_good_path=None):
    payload = {
        "metric": "tokens/sec/chip",
        "value": 0,
        "unit": "tokens/sec/chip",
        "vs_baseline": 0,
        "error": f"{phase}: {detail} (elapsed {elapsed:.0f}s)",
        "phase": phase,
    }
    # A flaky transport must not erase the record entirely: carry the last
    # successful TPU measurement by this benchmark.  Its "ts" and "commit"
    # fields say when/what was measured — it may predate the current code
    # state, so it documents hardware reachability, not current throughput.
    try:
        with open(last_good_path or _LAST_GOOD) as f:
            payload["last_good"] = json.load(f)
    except (OSError, ValueError):
        pass
    print(json.dumps(payload), flush=True)
    sys.exit(3)


def parent_main(run=_run, monotonic=time.monotonic, sleep=time.sleep,
                last_good_path=None):
    """Probe → bench → report.  ``run``/``monotonic``/``sleep`` are
    injectable so the wedge paths are testable WITHOUT racing a wall
    clock: the old subprocess test assumed a 1s probe timeout could
    never be met, which a warm page cache disproves.  Production callers
    pass nothing and get real time and real subprocesses."""
    budget = float(os.environ.get("BENCH_WATCHDOG_SECS", "1800"))
    t_start = monotonic()
    py = sys.executable

    # Phase 1: probe.  Healthy first touch is seconds; 300s of silence means
    # the transport is wedged — killing the probe then leaks no claim a
    # working run would need (the claim is already orphaned).
    probe_timeout = min(300.0, budget / 3)
    retry_pause = float(os.environ.get("BENCH_RETRY_PAUSE_SECS", "60"))
    rc, out, wedged = run([py, "-c", _PROBE_SRC], probe_timeout)
    if wedged or rc != 0 or "BENCH-PROBE-OK" not in (out or ""):
        # One retry after a pause: transient relay hiccups (mid-handoff
        # claims) clear in under a minute; a real wedge does not.
        sleep(retry_pause)
        rc, out, wedged = run([py, "-c", _PROBE_SRC], probe_timeout)
        if wedged or rc != 0 or "BENCH-PROBE-OK" not in (out or ""):
            detail = (
                "transport wedged (probe hung)"
                if wedged
                else f"probe failed rc={rc}: {(out or '').strip()[-200:]}"
            )
            _fail("probe", detail, monotonic() - t_start, last_good_path)

    # Phase 2: the measurement, with one respawn.  Attempt 1 gets the bulk
    # of the budget (covers a fresh compile); the retry runs against a warm
    # persistent compile cache and needs far less.
    env = dict(os.environ, BENCH_CHILD="1")
    for attempt in (1, 2):
        remaining = budget - (monotonic() - t_start)
        if remaining < 60:
            _fail("bench", "budget exhausted before attempt "
                  f"{attempt}", monotonic() - t_start, last_good_path)
        timeout = remaining * (0.7 if attempt == 1 else 1.0)
        rc, out, wedged = run([py, _SELF], timeout, env=env)
        # Honor a result even when the child wedged AFTER printing it
        # (interpreter teardown can hang on the dead relay) — the
        # measurement itself is complete and valid.
        line = next(
            (l for l in reversed((out or "").splitlines()) if l.startswith("{")),
            None,
        )
        if (rc == 0 or wedged) and line:
            try:
                result = json.loads(line)
            except ValueError:
                result = None
            if result and result.get("value"):
                if result.get("device", "").lower() != "cpu":
                    # only TPU runs are worth carrying into a wedge report —
                    # a CPU number would misrepresent what the hardware did
                    result["ts"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
                    result["commit"] = _git_head()
                    try:
                        with open(last_good_path or _LAST_GOOD, "w") as f:
                            json.dump(result, f, indent=1)
                    except OSError:
                        pass
                print(line, flush=True)
                return
        if attempt == 1:
            # let a killed child's claim settle before respawn
            sleep(min(30.0, retry_pause))
    if wedged:
        detail = "child wedged (watchdog)"
    elif rc == 0:
        detail = f"child exited 0 but printed no usable result JSON: {(out or '').strip()[-200:]}"
    else:
        detail = f"child failed rc={rc}: {(out or '').strip()[-200:]}"
    _fail("bench", detail, monotonic() - t_start, last_good_path)


if __name__ == "__main__":
    if os.environ.get("BENCH_CHILD"):
        child_main()
    else:
        parent_main()
