"""Replicated serving-cluster tests: routing policies, typed admission
control, priority aging (no starvation), deadlines/cancellation, drain,
and the headline guarantee — greedy output through the cluster is BITWISE
identical to a single no-fault engine even when a replica crashes
mid-request (exact, bucketed, chunked and speculative paths)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import (
    BACKOFF,
    DEAD,
    DEGRADED,
    HEALTHY,
    PROBATION,
    FaultPlan,
    Frontend,
    FrontendConfig,
    PrefixAffinityRouter,
    ReplicaHandle,
    ReplicaDead,
    RestartPolicy,
    RoundRobinRouter,
    least_loaded,
    make_router,
    prefix_route_key,
)
from tpu_parallel.cluster.replica import logic_error, xla_like_error
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.serving import (
    CANCELLED,
    EXPIRED,
    FAILED,
    FINISHED,
    REJECT_CLIENT_LIMIT,
    REJECT_DRAINING,
    REJECT_QUEUE_FULL,
    REJECT_TOKEN_BUDGET,
    REJECTED,
    FIFOScheduler,
    Request,
    RequestOutput,
    SchedulerConfig,
    ServingEngine,
)


@pytest.fixture(scope="module")
def env():
    """One tiny model + a mixed-length prompt set + greedy references,
    shared by every device-driving test in this file."""
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(7)
    lens = [3, 9, 6, 12, 5, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    probe = jax.random.randint(rng, (1, max(lens)), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=8,
        ))[0]
        for p in prompts
    ]
    return cfg, model, params, prompts, refs


def _engine(env, clock=None, **kw):
    cfg, model, params, _, _ = env
    # per-step decode tick by default: the fault-injection choreography
    # in this file (crash_at_tick / stall windows / retry counts) is
    # pinned at one-token-per-tick granularity so crashes land
    # mid-request; the FUSED default is covered by
    # test_crash_midflight_exact_fused_tick and the serving parity suite
    kwargs = dict(
        n_slots=2, scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=1,
    )
    kwargs.update(kw)
    if clock is not None:
        kwargs["clock"] = clock
    return ServingEngine(model, params, **kwargs)


# -- typed scheduler rejections (satellite regression) ----------------------


def test_submit_result_typed_reasons():
    """FIFOScheduler.submit reports WHY it refused — queue_full vs
    draining — through a result that still behaves like the old bool."""
    sched = FIFOScheduler(SchedulerConfig(max_queue=1))
    a = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    b = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    ok = sched.submit(a)
    assert ok and bool(ok) and ok.reason is None
    full = sched.submit(b)
    assert not full and full.reason == REJECT_QUEUE_FULL
    sched.begin_drain()
    sched.take_queued()
    draining = sched.submit(b)
    assert not draining and draining.reason == REJECT_DRAINING
    # relocation of accepted work bypasses the drain gate, not the bound
    assert sched.submit(b, requeue=True)
    assert sched.depth == 1
    c = RequestOutput(Request(prompt=[1]), arrival_time=0.0)
    assert sched.submit(c, requeue=True).reason == REJECT_QUEUE_FULL


def test_engine_surfaces_typed_reject(env):
    """Engine rejections carry the SAME typed vocabulary the frontend
    uses (satellite: identical reporting across layers)."""
    eng = _engine(env, scheduler=SchedulerConfig(max_queue=0))
    out = eng.add_request(Request(prompt=[1, 2], max_new_tokens=2))
    assert out.status == REJECTED and out.finish_reason == REJECT_QUEUE_FULL
    eng2 = _engine(env)
    eng2.begin_drain()
    out2 = eng2.add_request(Request(prompt=[1, 2], max_new_tokens=2))
    assert out2.status == REJECTED and out2.finish_reason == REJECT_DRAINING
    assert eng2.draining


def test_scheduler_take_queued_and_remove():
    sched = FIFOScheduler()
    outs = [
        RequestOutput(Request(prompt=[1] * (i + 1)), arrival_time=0.0)
        for i in range(3)
    ]
    for out in outs:
        sched.submit(out)
    assert sched.pending_prefill_tokens == 1 + 2 + 3
    assert sched.queued() == outs
    gone = sched.remove(outs[1].request.request_id)
    assert gone is outs[1] and sched.depth == 2
    assert sched.remove("nope") is None
    taken = sched.take_queued()
    assert taken == [outs[0], outs[2]] and sched.depth == 0


def test_expire_retry_wait_accounting():
    """Satellite: an expired-then-retried request is observed ONCE in
    serving_queue_wait_seconds — at its eventual admission, carrying the
    CUMULATIVE wait across replicas (expiry itself never observes)."""
    reg = MetricRegistry()
    t = [0.0]
    a = FIFOScheduler(
        SchedulerConfig(max_wait=10.0), clock=lambda: t[0], registry=reg
    )
    out = RequestOutput(Request(prompt=[1, 2]), arrival_time=0.0)
    assert a.submit(out)
    t[0] = 11.0
    assert a.expire() == [out] and out.status == EXPIRED
    # the retry carries the ORIGINAL arrival to a different replica's
    # scheduler sharing the registry (the frontend passes arrival_time
    # through engine.add_request the same way)
    retry = RequestOutput(out.request, arrival_time=out.arrival_time)
    b = FIFOScheduler(clock=lambda: t[0], registry=reg)
    assert b.submit(retry)
    t[0] = 15.0
    assert b.schedule(1) == [retry]
    rows = [
        row for row in reg.snapshot()["histograms"]
        if row["name"] == "serving_queue_wait_seconds"
    ]
    assert len(rows) == 1
    assert rows[0]["count"] == 1  # not double-counted across schedulers
    assert rows[0]["sum"] == pytest.approx(15.0)  # cumulative, not 4.0


def test_engine_arrival_time_passthrough(env):
    """engine.add_request(arrival_time=) pins the record to the CLIENT's
    arrival instead of the local clock — the hook the cluster retry path
    uses to keep queue-wait telemetry cumulative across replicas."""
    _, _, _, prompts, _ = env
    eng = _engine(env, clock=lambda: 5.0)
    out = eng.add_request(
        Request(prompt=prompts[0], max_new_tokens=2), arrival_time=1.5
    )
    assert out.arrival_time == 1.5
    fresh = eng.add_request(Request(prompt=prompts[1], max_new_tokens=2))
    assert fresh.arrival_time == 5.0


# -- fault plan + replica handle -------------------------------------------


def test_fault_plan_windows():
    fp = FaultPlan(stall_at_tick=3, stall_ticks=2, reject_at_tick=1,
                   reject_ticks=1)
    assert not fp.stalled(2) and fp.stalled(3) and fp.stalled(4)
    assert not fp.stalled(5)
    assert fp.rejecting(1) and not fp.rejecting(2)


def test_watchdog_detects_stall_by_observation(env):
    """Acceptance (satellite regression): an injected stall is caught by
    the frontend's progress WATCHDOG alone — ``FaultPlan.stalled`` ticks
    are pure behavior (no-op, no events) and never touch health.  The
    watchdog degrades the replica from observed no-progress and restores
    it when tokens flow again."""
    _, _, _, prompts, refs = env
    h = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(stall_at_tick=1, stall_ticks=2)
    )
    fe = Frontend(
        [h], config=FrontendConfig(watchdog_ticks=1, watchdog_kill_ticks=50)
    )
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.step()  # tick 0: admitted + prefilled (progress)
    assert h.health == HEALTHY
    fe.step()  # tick 1: stalled -> watchdog observes no progress
    assert h.health == DEGRADED
    n_before = len(out.tokens)
    fe.step()  # tick 2: still stalled
    assert len(out.tokens) == n_before  # no progress while stalled
    fe.run(max_ticks=50)
    assert h.health == HEALTHY  # watchdog restored it on progress
    assert out.status == FINISHED
    np.testing.assert_array_equal(np.asarray(out.tokens), refs[0])
    assert fe.summary()["watchdog_degraded"] >= 1


def test_fault_stall_never_mutates_health(env):
    """Satellite pin: stepping a stalled replica DIRECTLY (no frontend,
    no watchdog) leaves health untouched — injection causes behavior
    only.  Detection lives in the observer."""
    _, _, _, prompts, _ = env
    h = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(stall_at_tick=0, stall_ticks=3)
    )
    h.submit(Request(prompt=prompts[0], max_new_tokens=4))
    for _ in range(3):
        assert h.step() == []  # stalled no-op ticks
        assert h.health == HEALTHY
    assert h.has_work()


def test_reject_window_routes_to_peer(env):
    """A replica inside a FaultPlan admission-reject window is simply not
    routable — everything lands on the peer, nothing is lost."""
    _, _, _, prompts, refs = env
    h0 = ReplicaHandle(
        0, _engine(env),
        fault_plan=FaultPlan(reject_at_tick=0, reject_ticks=1000),
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=4)) for p in prompts]
    fe.run(max_ticks=100)
    assert all(out.status == FINISHED for out in outs)
    assert h0.engine.metrics.finished == 0
    assert h1.engine.metrics.finished == len(prompts)


# -- routers ----------------------------------------------------------------


class _FakeReplica:
    def __init__(self, rid, load=0.0, queue_depth=0):
        self.replica_id = rid
        self._load = load
        self.queue_depth = queue_depth

    def load(self):
        return self._load


def test_round_robin_cycles():
    r = RoundRobinRouter()
    reps = [_FakeReplica(i) for i in range(3)]
    picks = [r.route([1], reps).replica_id for _ in range(6)]
    assert picks == [0, 1, 2, 0, 1, 2]
    assert r.route([1], []) is None


def test_least_loaded_ranks():
    reps = [
        _FakeReplica(0, load=3.0),
        _FakeReplica(1, load=1.0),
        _FakeReplica(2, load=1.0),
    ]
    assert least_loaded(reps).replica_id == 1  # tie -> lowest id
    assert least_loaded([]) is None


def test_prefix_route_key_alignment():
    assert prefix_route_key([1, 2, 3, 4, 5], (4, 8)) == (1, 2, 3, 4)
    # bucket == len is NOT a proper prefix (mirrors PrefixCache.lookup)
    assert prefix_route_key([1, 2, 3, 4], (4, 8)) == (1, 2, 3, 4)
    assert prefix_route_key([1, 2, 3], (4, 8)) == (1, 2, 3)
    assert prefix_route_key([1, 2, 3], None) == (1, 2, 3)


def test_prefix_router_stable_placement():
    """Consistent hashing: placement is deterministic, same-prefix
    prompts share an owner, and removing a replica moves ONLY the keys
    it owned (every other key keeps its warm cache)."""
    ids = [0, 1, 2, 3]
    r1 = PrefixAffinityRouter(ids, buckets=(4, 8))
    r2 = PrefixAffinityRouter(ids, buckets=(4, 8))
    prompts = [
        [i, i + 1, i + 2, i + 3, i + 4, i + 5] for i in range(0, 120, 3)
    ]
    owners = [r1.owner(p) for p in prompts]
    assert owners == [r2.owner(p) for p in prompts]  # deterministic
    assert len(set(owners)) > 1  # keys actually spread
    # same bucket-aligned prefix, different suffix -> same owner
    assert r1.owner([5, 6, 7, 8, 99, 98]) == r1.owner([5, 6, 7, 8, 1, 2])
    # kill replica `dead`: its keys move, every other key stays put
    dead = owners[0]
    reps = {i: _FakeReplica(i) for i in ids}
    alive = [reps[i] for i in ids if i != dead]
    for p, owner in zip(prompts, owners):
        new = r1.route(p, alive).replica_id
        if owner != dead:
            assert new == owner, "surviving replica's keys must not move"
        else:
            assert new != dead


def test_prefix_router_overload_falls_back():
    reps = [
        _FakeReplica(0, load=9.0, queue_depth=9),
        _FakeReplica(1, load=0.0, queue_depth=0),
    ]
    r = PrefixAffinityRouter([0, 1], buckets=(4,), overload_queue_depth=8)
    # find a prompt whose owner is replica 0, then overload it
    prompt = next(
        p for p in ([i, i + 1, i + 2, i + 3, i + 4] for i in range(200))
        if r.owner(p) == 0
    )
    assert r.route(prompt, reps).replica_id == 1
    assert r.fallbacks == 1


def test_make_router_unknown_policy():
    with pytest.raises(ValueError, match="unknown router"):
        make_router("zigzag", [0, 1])


# -- engine cancel / drain --------------------------------------------------


def test_engine_cancel_running_and_queued(env):
    """cancel() frees the slot mid-decode (alignment preserved), pulls
    queued requests before they ever run, and streams a terminal event."""
    _, _, _, prompts, _ = env
    eng = _engine(env, n_slots=1)
    seen = []
    a = eng.add_request(Request(prompt=prompts[0], max_new_tokens=20))
    b = eng.add_request(
        Request(prompt=prompts[1], max_new_tokens=4,
                on_token=lambda ev: seen.append(ev))
    )
    eng.step()  # a running, b queued
    assert a.status == "running"
    assert eng.cancel(b.request.request_id)  # queued cancel
    assert b.status == CANCELLED and b.finish_reason == "cancelled"
    assert seen and seen[0].token == -1 and seen[0].finish_reason == "cancelled"
    eng.step()
    assert eng.cancel(a.request.request_id, reason="deadline")  # running
    assert a.status == CANCELLED and a.finish_reason == "deadline"
    assert eng.pool.n_free == 1  # slot came back
    eng.pool.assert_slot_aligned(0)
    assert eng.metrics.cancelled == 2
    assert not eng.cancel("unknown")
    assert not eng.cancel(a.request.request_id)  # already terminal
    # the engine still serves correctly after cancels
    c = eng.add_request(Request(prompt=prompts[2], max_new_tokens=3))
    eng.run()
    assert c.status == FINISHED


# -- frontend admission control --------------------------------------------


def test_token_budget_backpressure(env):
    """Global token-budget: typed rejection past the cap, capacity
    released as requests finish."""
    _, _, _, prompts, _ = env
    fe = Frontend(
        [_engine(env)],
        config=FrontendConfig(max_inflight_tokens=20),
    )
    a = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))  # 3+8=11
    b = fe.submit(Request(prompt=prompts[4], max_new_tokens=4))  # 5+4=9
    c = fe.submit(Request(prompt=prompts[2], max_new_tokens=4))
    assert a.status != REJECTED and b.status != REJECTED
    assert c.status == REJECTED and c.finish_reason == REJECT_TOKEN_BUDGET
    fe.run(max_ticks=100)
    assert a.status == FINISHED and b.status == FINISHED
    d = fe.submit(Request(prompt=prompts[2], max_new_tokens=4))
    assert d.status != REJECTED  # reservations released
    fe.run(max_ticks=100)
    assert d.status == FINISHED


def test_per_client_concurrency_cap(env):
    _, _, _, prompts, _ = env
    fe = Frontend([_engine(env)], config=FrontendConfig(max_per_client=2))
    a = fe.submit(Request(prompt=prompts[0], max_new_tokens=4,
                          client_id="alice"))
    b = fe.submit(Request(prompt=prompts[1], max_new_tokens=4,
                          client_id="alice"))
    c = fe.submit(Request(prompt=prompts[2], max_new_tokens=4,
                          client_id="alice"))
    d = fe.submit(Request(prompt=prompts[3], max_new_tokens=4,
                          client_id="bob"))
    anon = fe.submit(Request(prompt=prompts[4], max_new_tokens=4))
    assert c.status == REJECTED and c.finish_reason == REJECT_CLIENT_LIMIT
    assert d.status != REJECTED  # other clients unaffected
    assert anon.status != REJECTED  # no client_id -> uncapped
    fe.run(max_ticks=200)
    assert all(o.status == FINISHED for o in (a, b, d, anon))
    # capacity freed: alice can submit again
    e = fe.submit(Request(prompt=prompts[2], max_new_tokens=2,
                          client_id="alice"))
    assert e.status != REJECTED


def test_priority_aging_prevents_starvation(env):
    """Priority reorders admission but never starves: under a continuous
    flood of fresh high-priority arrivals that outpaces one slot, an aged
    low-priority request still finishes; the strict-priority control
    (effectively no aging) starves it."""
    _, _, _, prompts, _ = env

    def drive(aging_seconds, ticks=60):
        t = [0.0]
        eng = _engine(env, clock=lambda: t[0], n_slots=1)
        fe = Frontend(
            [eng], clock=lambda: t[0],
            config=FrontendConfig(aging_seconds=aging_seconds),
        )
        low = fe.submit(
            Request(prompt=prompts[0], max_new_tokens=2, priority=0)
        )
        for k in range(ticks):
            t[0] += 1.0
            # two fresh priority-5 arrivals per tick >> service rate
            fe.submit(
                Request(prompt=prompts[2], max_new_tokens=2, priority=5)
            )
            fe.submit(
                Request(prompt=prompts[2], max_new_tokens=2, priority=5)
            )
            fe.step()
            if low.status == FINISHED:
                return k
        return None

    aged = drive(aging_seconds=2.0)
    assert aged is not None, "aging must rescue the low-priority request"
    starved = drive(aging_seconds=1e9)
    assert starved is None, (
        "strict priority should starve it — otherwise this test proves "
        "nothing about aging"
    )


def test_deadline_cancels_in_engine_work(env):
    """A request past its deadline is cancelled mid-decode: slot
    released, typed terminal event streamed, neighbours unharmed."""
    _, _, _, prompts, refs = env
    t = [0.0]
    eng = _engine(env, clock=lambda: t[0], n_slots=2)
    fe = Frontend([eng], clock=lambda: t[0])
    seen = []
    a = fe.submit(
        Request(prompt=prompts[0], max_new_tokens=20, deadline=5.0,
                on_token=lambda ev: seen.append(ev))
    )
    b = fe.submit(Request(prompt=prompts[1], max_new_tokens=8))
    t[0] = 1.0
    fe.step()
    assert a.status == "running"
    t[0] = 6.0
    fe.step()
    assert a.status == CANCELLED and a.finish_reason == "deadline"
    assert seen[-1].token == -1 and seen[-1].finish_reason == "deadline"
    fe.run(max_ticks=100)
    assert b.status == FINISHED
    np.testing.assert_array_equal(np.asarray(b.tokens), refs[1])
    assert eng.pool.n_free == 2
    assert fe.summary()["cancelled"] == 1
    # a pending (never-dispatched) request past deadline cancels too
    t2 = [0.0]
    eng2 = _engine(env, clock=lambda: t2[0], n_slots=1)
    fe2 = Frontend([eng2], clock=lambda: t2[0])
    busy = fe2.submit(Request(prompt=prompts[0], max_new_tokens=8))
    lazy = fe2.submit(
        Request(prompt=prompts[1], max_new_tokens=8, deadline=2.0)
    )
    t2[0] = 1.0
    fe2.step()
    t2[0] = 3.0
    fe2.step()
    assert lazy.status == CANCELLED and lazy.finish_reason == "deadline"
    fe2.run(max_ticks=100)
    assert busy.status == FINISHED


# -- exactness under failure (the headline acceptance) ----------------------


_MODES = {
    "exact": dict(prefill_buckets=None),
    "bucketed": dict(prefill_buckets=(4, 8, 16)),
    "chunked": dict(prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4),
    "spec": dict(prefill_buckets=(4, 8, 16), draft_tokens=3),
}


@pytest.mark.parametrize("mode", sorted(_MODES))
def test_crash_midflight_bitwise_exact(env, mode):
    """Acceptance: with a FaultPlan killing one replica mid-decode, every
    request completes and greedy tokens are BITWISE identical to a
    single-engine no-fault baseline — per prefill/decode mode."""
    _, _, _, prompts, _ = env
    kw = _MODES[mode]

    baseline_eng = _engine(env, **kw)
    base_outs = [
        baseline_eng.add_request(Request(prompt=p, max_new_tokens=8))
        for p in prompts
    ]
    baseline_eng.run()
    assert all(o.status == FINISHED for o in base_outs)

    h0 = ReplicaHandle(
        0, _engine(env, **kw), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env, **kw))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    fe.run(max_ticks=400)
    assert h0.health == DEAD
    s = fe.summary()
    assert s["replica_deaths"] == 1 and s["retries"] > 0
    for i, (out, base) in enumerate(zip(outs, base_outs)):
        assert out.status == FINISHED, (
            f"request {i}: {out.status} ({out.finish_reason})"
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(base.tokens),
            err_msg=f"request {i} diverged after failover ({mode})",
        )


def test_crash_midflight_exact_fused_tick(env):
    """The headline crash guarantee holds under the FUSED decode tick
    (the engine default): a replica dying between multi-token ticks is
    replayed forced-prefix on the survivor, greedy output bitwise equal
    to a no-fault fused baseline — which itself equals the per-step
    engine (serving parity suite)."""
    _, _, _, prompts, _ = env
    kw = dict(prefill_buckets=(4, 8, 16), decode_steps_per_tick=4)

    baseline_eng = _engine(env, **kw)
    base_outs = [
        baseline_eng.add_request(Request(prompt=p, max_new_tokens=16))
        for p in prompts
    ]
    baseline_eng.run()
    assert all(o.status == FINISHED for o in base_outs)

    h0 = ReplicaHandle(
        0, _engine(env, **kw), fault_plan=FaultPlan(crash_at_tick=2)
    )
    h1 = ReplicaHandle(1, _engine(env, **kw))
    fe = Frontend([h0, h1], router="rr")
    outs = [fe.submit(Request(prompt=p, max_new_tokens=16)) for p in prompts]
    fe.run(max_ticks=400)
    assert h0.health == DEAD
    s = fe.summary()
    assert s["replica_deaths"] == 1 and s["retries"] > 0
    for i, (out, base) in enumerate(zip(outs, base_outs)):
        assert out.status == FINISHED, (
            f"request {i}: {out.status} ({out.finish_reason})"
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(base.tokens),
            err_msg=f"request {i} diverged after fused-tick failover",
        )


def test_crash_midflight_exact_unified_tick(env):
    """The crash guarantee under the UNIFIED ragged tick (chunked
    prefill + fused decode in one dispatch, fused speculative verify on
    the spec leg): a replica dying mid-flight — possibly mid-chunk — is
    replayed forced-prefix on the survivor, greedy output bitwise equal
    to a no-fault unified baseline."""
    _, _, _, prompts, _ = env
    for kw in (
        dict(
            prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
            decode_steps_per_tick=4,
        ),
        dict(
            prefill_buckets=(4, 8, 16), prefill_chunk_tokens=4,
            decode_steps_per_tick=4, draft_tokens=2,
        ),
    ):
        baseline_eng = _engine(env, **kw)
        assert baseline_eng.unified_tick
        base_outs = [
            baseline_eng.add_request(Request(prompt=p, max_new_tokens=12))
            for p in prompts
        ]
        baseline_eng.run()
        assert all(o.status == FINISHED for o in base_outs)

        h0 = ReplicaHandle(
            0, _engine(env, **kw), fault_plan=FaultPlan(crash_at_tick=2)
        )
        h1 = ReplicaHandle(1, _engine(env, **kw))
        fe = Frontend([h0, h1], router="rr")
        outs = [
            fe.submit(Request(prompt=p, max_new_tokens=12))
            for p in prompts
        ]
        fe.run(max_ticks=400)
        assert h0.health == DEAD
        assert fe.summary()["replica_deaths"] == 1
        for i, (out, base) in enumerate(zip(outs, base_outs)):
            assert out.status == FINISHED, (
                f"request {i}: {out.status} ({out.finish_reason})"
            )
            np.testing.assert_array_equal(
                np.asarray(out.tokens), np.asarray(base.tokens),
                err_msg=(
                    f"request {i} diverged after unified-tick failover "
                    f"({kw})"
                ),
            )


def test_crash_stream_indices_stay_contiguous(env):
    """Across a failover the client stream never re-delivers or skips:
    every request's event indices are exactly 0..n-1 in order."""
    _, _, _, prompts, refs = env
    streams = {}

    def track(ev):
        streams.setdefault(ev.request_id, []).append(ev)

    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr")
    outs = [
        fe.submit(
            Request(prompt=p, max_new_tokens=8, on_token=track)
        )
        for p in prompts
    ]
    fe.run(max_ticks=400)
    assert fe.summary()["retries"] > 0
    for out, ref in zip(outs, refs):
        events = streams[out.request.request_id]
        assert [ev.index for ev in events] == list(range(8))
        assert [ev.token for ev in events] == list(ref)
        assert events[-1].finished and not any(
            ev.finished for ev in events[:-1]
        )


def test_expiry_bounce_terminates_instead_of_livelocking(env):
    """Regression: a request whose CUMULATIVE wait already exceeds an
    engine's max_wait would expire at every re-dispatch forever (the
    retry preserves the original arrival).  Bounces count against
    retry_limit, so the request terminates EXPIRED and run()/drain()
    still halt."""
    _, _, _, prompts, _ = env
    t = [0.0]
    eng = _engine(
        env, clock=lambda: t[0], n_slots=1,
        scheduler=SchedulerConfig(max_wait=1.0),
    )
    fe = Frontend(
        [eng], clock=lambda: t[0], config=FrontendConfig(retry_limit=2)
    )
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=2))
    t[0] = 5.0  # past the engine's max_wait before first dispatch
    fe.run(max_ticks=20)
    assert out.status == EXPIRED and out.finish_reason == "max_wait"
    assert not fe.has_work()
    assert out.retries == 3  # retry_limit + the terminal bounce


def test_retry_limit_fails_loudly(env):
    _, _, _, prompts, _ = env
    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=1)
    )
    fe = Frontend([h0], config=FrontendConfig(retry_limit=0))
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.run(max_ticks=20)
    assert out.status == FAILED and out.finish_reason == "retry_limit"
    assert not fe.has_work()


def test_all_replicas_dead_fails_pending(env):
    _, _, _, prompts, _ = env
    handles = [
        ReplicaHandle(
            i, _engine(env, n_slots=1),
            fault_plan=FaultPlan(crash_at_tick=i + 1),
        )
        for i in range(2)
    ]
    fe = Frontend(handles, config=FrontendConfig(retry_limit=5))
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    fe.run(max_ticks=50)
    assert all(h.health == DEAD for h in handles)
    assert not fe.has_work()
    assert all(out.done for out in outs)
    assert any(
        out.status == FAILED
        and out.finish_reason in ("no_replica", "retry_limit")
        for out in outs
    )


# -- drain ------------------------------------------------------------------


def test_drain_terminates_and_releases(env):
    """Acceptance: drain() finishes in-flight work, re-routes the queued
    remainder, admits nothing new, and leaves every replica's CachePool
    fully released with aligned position tables."""
    _, _, _, prompts, refs = env
    engines = [_engine(env, n_slots=1) for _ in range(2)]
    fe = Frontend(
        engines, router="least",
        # deep dispatch so engine queues actually hold a remainder
        config=FrontendConfig(dispatch_queue_depth=4),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts]
    fe.step()  # fills slots and engine queues
    assert any(eng.scheduler.depth > 0 for eng in engines)
    events = fe.drain(max_ticks=300)
    assert not fe.has_work()
    assert all(out.status == FINISHED for out in outs)
    s = fe.summary()
    assert s["requeued"] > 0  # the queued remainder really re-routed
    late = fe.submit(Request(prompt=prompts[0], max_new_tokens=2))
    assert late.status == REJECTED and late.finish_reason == REJECT_DRAINING
    for eng in engines:
        assert eng.draining
        assert eng.pool.n_free == eng.pool.n_slots
        for slot in range(eng.pool.n_slots):
            eng.pool.assert_slot_aligned(slot)
    # drained output is still exact
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(ref)[: len(out.tokens)]
        )
        assert len(out.tokens) == 6
    assert any(ev.finished for ev in events)


# -- self-healing: fault-plan extensions ------------------------------------


def test_fault_plan_from_seed_deterministic():
    """Satellite: the chaos constructor is a pure function of the rng
    state — same seed, same schedule, every run; seeds actually vary the
    schedule; pinned kinds appear (and only they do) with a stall that
    ends before the crash begins."""
    import random

    a = FaultPlan.from_seed(random.Random(42), 64)
    b = FaultPlan.from_seed(random.Random(42), 64)
    assert a == b
    plans = [FaultPlan.from_seed(random.Random(s), 64) for s in range(24)]
    assert len(set(plans)) > 1  # schedules genuinely vary by seed
    p = FaultPlan.from_seed(random.Random(7), 64, kinds=("crash", "stall"))
    assert p.crash_at_tick is not None and p.stall_at_tick is not None
    assert p.crash_every is None and p.reject_at_tick is None
    # the stall window closes before the crash: the stall is observable
    assert p.stall_at_tick + p.stall_ticks < p.crash_at_tick
    flap = FaultPlan.from_seed(random.Random(7), 64, kinds=("flap",))
    assert flap.crash_every is not None and flap.crash_at_tick is None
    with pytest.raises(ValueError, match="unknown fault kinds"):
        FaultPlan.from_seed(random.Random(0), 64, kinds=("meteor",))
    with pytest.raises(ValueError, match="ticks"):
        FaultPlan.from_seed(random.Random(0), 4)


def test_flap_crash_loop_and_one_shot_crash(env):
    """crash_every keys on INCARNATION ticks (every life dies on its
    K-th step); crash_at_tick is one-shot (a restarted replica does not
    re-crash on the stale schedule)."""
    mk = lambda: _engine(env)  # noqa: E731
    h = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_every=3), engine_factory=mk
    )
    h.step(), h.step()
    with pytest.raises(ReplicaDead):
        h.step()
    assert h.health == DEAD
    h.restart()
    assert h.health == PROBATION and h.incarnation_ticks == 0
    h.step(), h.step()
    with pytest.raises(ReplicaDead):
        h.step()  # every incarnation flaps on schedule
    one_shot = ReplicaHandle(
        1, mk(), fault_plan=FaultPlan(crash_at_tick=1), engine_factory=mk
    )
    one_shot.step()
    with pytest.raises(ReplicaDead):
        one_shot.step()
    one_shot.restart()
    for _ in range(5):
        one_shot.step()  # the stale crash schedule never refires
    assert one_shot.health == PROBATION


def test_exception_factory_preserves_cause(env):
    """Satellite: injected error TYPES ride the ReplicaDead cause chain
    — an XLA-shaped RuntimeError and a host-logic ValueError stay
    distinguishable at the frontend."""
    h = ReplicaHandle(
        0, _engine(env),
        fault_plan=FaultPlan(crash_at_tick=0,
                             exception_factory=xla_like_error),
    )
    with pytest.raises(ReplicaDead) as ei:
        h.step()
    assert isinstance(ei.value.__cause__, RuntimeError)
    assert "XLA" in str(ei.value)
    h2 = ReplicaHandle(
        1, _engine(env),
        fault_plan=FaultPlan(crash_at_tick=0,
                             exception_factory=logic_error),
    )
    with pytest.raises(ReplicaDead) as ei2:
        h2.step()
    assert isinstance(ei2.value.__cause__, ValueError)


# -- self-healing: watchdog kill, restart, breaker --------------------------


def test_watchdog_kill_orphans_and_replays_exact(env):
    """A permanently stalled replica is degraded, then KILLED by the
    watchdog — from observation alone — and its orphans replay
    forced-prefix on the survivor: every request finishes bitwise equal
    to the no-fault reference."""
    _, _, _, prompts, refs = env
    h0 = ReplicaHandle(
        0, _engine(env),
        fault_plan=FaultPlan(stall_at_tick=2, stall_ticks=10 ** 9),
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend(
        [h0, h1], router="rr",
        config=FrontendConfig(watchdog_ticks=2, watchdog_kill_ticks=4),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    fe.run(max_ticks=400)
    assert h0.health == DEAD  # no engine_factory: stays dead
    s = fe.summary()
    assert s["watchdog_kills"] == 1 and s["watchdog_degraded"] >= 1
    assert s["replica_deaths"] == 1 and s["retries"] > 0
    for out, ref in zip(outs, refs):
        assert out.status == FINISHED, (out.status, out.finish_reason)
        np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_retry_limit_counts_watchdog_and_crash_kills(env):
    """Satellite corner: watchdog kills and crash kills draw on the SAME
    per-request retry budget — one of each exhausts retry_limit=1."""
    _, _, _, prompts, _ = env
    h0 = ReplicaHandle(
        0, _engine(env),
        fault_plan=FaultPlan(stall_at_tick=1, stall_ticks=10 ** 9),
    )
    h1 = ReplicaHandle(1, _engine(env), fault_plan=FaultPlan(crash_at_tick=6))
    fe = Frontend(
        [h0, h1], router="least",
        config=FrontendConfig(
            retry_limit=1, watchdog_ticks=1, watchdog_kill_ticks=3
        ),
    )
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.run(max_ticks=100)
    assert out.status == FAILED and out.finish_reason == "retry_limit"
    assert out.retries == 2  # watchdog kill + crash kill
    s = fe.summary()
    assert s["watchdog_kills"] == 1 and s["replica_deaths"] == 2
    assert not fe.has_work()


def test_restart_heals_and_serves(env):
    """Tentpole acceptance: a crashed replica backs off, restarts
    through half-open probation (bounded concurrent requests), promotes
    to HEALTHY, and serves fresh traffic — with every request, including
    the failover replays, bitwise equal to the no-fault reference."""
    _, _, _, prompts, refs = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock)  # noqa: E731
    h0 = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_at_tick=2), engine_factory=mk
    )
    h1 = ReplicaHandle(1, mk())
    policy = RestartPolicy(
        max_restarts=2, backoff_seconds=1.0, probation_ticks=3,
        probation_requests=1,
    )
    fe = Frontend(
        [h0, h1], router="rr", clock=clock,
        config=FrontendConfig(restart=policy),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    saw_backoff = saw_probation = False
    cap_respected = True
    for _ in range(400):
        if not fe.has_work():
            break
        t[0] += 0.25
        fe.step()
        if h0.health == BACKOFF:
            saw_backoff = True
        if h0.health == PROBATION:
            saw_probation = True
            cap_respected &= (
                h0.open_requests <= policy.probation_requests
            )
    assert saw_backoff and saw_probation and cap_respected
    assert h0.restarts == 1 and h0.health == HEALTHY
    for out, ref in zip(outs, refs):
        assert out.status == FINISHED, (out.status, out.finish_reason)
        np.testing.assert_array_equal(np.asarray(out.tokens), ref)
    s = fe.summary()
    assert s["restarts"] == 1 and s["probation_promotions"] == 1
    # the healed replica carries fresh traffic (acceptance: "serves
    # completed requests afterward")
    extra = [
        fe.submit(Request(prompt=prompts[i], max_new_tokens=4))
        for i in range(4)
    ]
    for _ in range(200):
        if not fe.has_work():
            break
        t[0] += 0.25
        fe.step()
    assert all(o.status == FINISHED for o in extra)
    assert h0.engine.metrics.finished > 0  # post-restart incarnation
    # breaker gauge closed again for everyone
    snap = fe.registry.snapshot()
    breaker = {
        row["labels"]["replica"]: row["value"]
        for row in snap["gauges"]
        if row["name"] == "cluster_breaker_state"
    }
    assert breaker == {"0": 0.0, "1": 0.0}


def test_breaker_backoff_on_injectable_clock_doubles_then_opens(env):
    """Acceptance: backoff flows through the INJECTABLE clock (a frozen
    clock never restarts, no matter how many ticks pass), a probation
    death trips the breaker and DOUBLES the wait, and an exhausted
    budget leaves the replica dead for good."""
    _, _, _, prompts, _ = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock, n_slots=1)  # noqa: E731
    h0 = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_every=1), engine_factory=mk
    )
    h1 = ReplicaHandle(1, mk())
    policy = RestartPolicy(
        max_restarts=2, backoff_seconds=1.0, backoff_factor=2.0,
        probation_ticks=5, probation_requests=1,
    )
    fe = Frontend(
        [h0, h1], router="least", clock=clock,
        config=FrontendConfig(restart=policy, retry_limit=10),
    )
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=4))
    fe.step()  # ties route to replica 0, which dies on its first step
    rs = fe.recovery_summary()[0]
    assert h0.health == BACKOFF and rs["restart_pending"]
    assert rs["restart_at"] == pytest.approx(t[0] + 1.0)
    for _ in range(5):  # frozen clock: the restart must NOT fire
        fe.step()
    assert h0.health == BACKOFF and h0.restarts == 0
    t[0] += 1.01
    fe.step()  # restart fires -> probation -> flap kills it same tick
    assert h0.restarts == 1
    s = fe.summary()
    assert s["probation_demotions"] == 1
    rs = fe.recovery_summary()[0]
    assert h0.health == BACKOFF
    assert rs["restart_at"] == pytest.approx(t[0] + 2.0)  # doubled
    t[0] += 2.01
    fe.step()  # second (last) attempt burns the budget
    assert h0.restarts == 2 and h0.health == DEAD
    assert fe.recovery_summary()[0]["budget_left"] == 0
    fe.run(max_ticks=100)
    assert out.status == FINISHED  # the survivor finished the work
    assert h0.health == DEAD  # breaker open for good


def test_wedged_probation_never_promotes_and_backoff_escalates(env):
    """Regression: a replica that restarts into a WEDGED engine (has
    work, shows no observable progress) must not accrue probation clean
    ticks — promotion would reset the breaker's failure count and every
    stall-loop iteration would restart at the base backoff.  Instead the
    clean count freezes, the watchdog kills it, and the next backoff is
    DOUBLED (the demotion counted as a consecutive failure)."""
    _, _, _, prompts, _ = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock)  # noqa: E731
    # lifetime ticks: progress at 0-1, crash at 2, and every later tick
    # (the whole post-restart incarnation) inside a stall window — the
    # restarted engine is permanently wedged while holding retried work
    h = ReplicaHandle(
        0, mk(),
        fault_plan=FaultPlan(
            crash_at_tick=2, stall_at_tick=3, stall_ticks=1000
        ),
        engine_factory=mk,
    )
    fe = Frontend(
        [h], clock=clock,
        config=FrontendConfig(
            retry_limit=8, watchdog_ticks=2, watchdog_kill_ticks=4,
            restart=RestartPolicy(
                max_restarts=3, backoff_seconds=1.0, backoff_factor=2.0,
                probation_ticks=2, probation_requests=2,
            ),
        ),
    )
    fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    for _ in range(3):
        fe.step()  # progress, progress, crash
    assert h.health == BACKOFF
    t[0] += 1.01
    fe.step()  # restart fires -> PROBATION; first wedged tick
    assert h.health == PROBATION and h.restarts == 1
    fe.step()  # wedged with work: clean_ticks must stay frozen
    assert h.health == PROBATION  # probation_ticks=2 would have promoted
    assert fe.recovery_summary()[0]["clean_ticks"] == 0
    fe.step()
    fe.step()  # 4th no-progress tick: watchdog kills the wedged replica
    s = fe.summary()
    assert s["watchdog_kills"] == 1
    assert s["probation_promotions"] == 0
    assert s["probation_demotions"] == 1
    rs = fe.recovery_summary()[0]
    assert h.health == BACKOFF
    # failures were NOT reset by a bogus promotion: backoff doubled
    assert rs["restart_at"] == pytest.approx(t[0] + 2.0)


def test_pending_holds_while_restart_pending(env):
    """Tentpole acceptance: a single-replica cluster whose only replica
    crashes does NOT fail pending work ``no_replica`` while a restart is
    pending — the frontend holds the queue through the flap and the
    healed replica finishes everything, bitwise exact."""
    _, _, _, prompts, refs = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock)  # noqa: E731
    h = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_at_tick=3), engine_factory=mk
    )
    fe = Frontend(
        [h], clock=clock,
        config=FrontendConfig(
            retry_limit=5,
            restart=RestartPolicy(
                backoff_seconds=1.0, probation_ticks=2,
                probation_requests=2,
            ),
        ),
    )
    outs = [
        fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts[:3]
    ]
    for _ in range(6):
        t[0] += 0.25
        fe.step()
    assert h.health in (BACKOFF, PROBATION)
    assert not any(o.status == FAILED for o in outs)  # held, not failed
    for _ in range(400):
        if not fe.has_work():
            break
        t[0] += 0.25
        fe.step()
    assert h.restarts == 1
    assert fe.summary()["failed"] == 0
    for out, ref in zip(outs, refs):
        assert out.status == FINISHED, (out.status, out.finish_reason)
        np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_drain_while_replica_in_probation(env):
    """Satellite corner: drain() with a replica mid-probation completes
    every request and releases every live pool — the half-open replica
    participates in the drain like any routable peer."""
    _, _, _, prompts, refs = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock)  # noqa: E731
    h0 = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_at_tick=2), engine_factory=mk
    )
    h1 = ReplicaHandle(1, mk())
    fe = Frontend(
        [h0, h1], router="rr", clock=clock,
        config=FrontendConfig(
            restart=RestartPolicy(
                backoff_seconds=0.5, probation_ticks=50,
                probation_requests=2,
            )
        ),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts]
    for _ in range(60):
        if h0.health == PROBATION:
            break
        t[0] += 0.25
        fe.step()
    assert h0.health == PROBATION
    fe.drain(max_ticks=400)
    assert not fe.has_work()
    assert all(out.status == FINISHED for out in outs)
    for out, ref in zip(outs, refs):
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(ref)[: len(out.tokens)]
        )
        assert len(out.tokens) == 6
    assert h0.health in (PROBATION, HEALTHY)
    for h in (h0, h1):
        assert h.engine.pool.n_free == h.engine.pool.n_slots
        for slot in range(h.engine.pool.n_slots):
            h.engine.pool.assert_slot_aligned(slot)
    late = fe.submit(Request(prompt=prompts[0], max_new_tokens=2))
    assert late.status == REJECTED and late.finish_reason == REJECT_DRAINING


def test_no_double_replay_after_restart(env):
    """Satellite corner: death replays each orphan exactly once — the
    handle's ledger is forgotten at death and cleared by restart, so a
    flapping replica's LATER deaths never re-retry requests that already
    finished elsewhere (their retry counts freeze at finish)."""
    _, _, _, prompts, refs = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    mk = lambda: _engine(env, clock=clock)  # noqa: E731
    h0 = ReplicaHandle(
        0, mk(), fault_plan=FaultPlan(crash_every=6), engine_factory=mk
    )
    h1 = ReplicaHandle(1, mk())
    fe = Frontend(
        [h0, h1], router="rr", clock=clock,
        config=FrontendConfig(
            retry_limit=6,
            restart=RestartPolicy(
                max_restarts=2, backoff_seconds=0.5, probation_ticks=2,
                probation_requests=2,
            ),
        ),
    )
    outs = [fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts]
    frozen_retries = {}
    deaths_seen = 0
    for _ in range(500):
        if not fe.has_work():
            break
        t[0] += 0.25
        fe.step()
        if h0.health in (DEAD, BACKOFF):
            # the frontend forgot every orphan at death: nothing left
            # in the ledger for a restarted incarnation to double-replay
            assert h0.orphans() == []
        d = int(fe.summary()["replica_deaths"])
        if d > deaths_seen:
            deaths_seen = d
        for i, out in enumerate(outs):
            if out.done and i not in frozen_retries:
                frozen_retries[i] = out.retries
    assert deaths_seen >= 2  # the flap really killed it repeatedly
    assert h0.restarts >= 1
    for i, out in enumerate(outs):
        assert out.status == FINISHED, (out.status, out.finish_reason)
        assert out.retries == frozen_retries[i], (
            f"request {i} re-retried after finishing"
        )
        np.testing.assert_array_equal(np.asarray(out.tokens), refs[i])


# -- chaos soak (tentpole acceptance) ----------------------------------------


def _chaos_bench():
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import chaos_bench
    finally:
        sys.path.pop(0)
    return chaos_bench


def test_chaos_smoke_seeded(env):
    """Tier-1 acceptance smoke: a seeded 2-replica fault storm (crash +
    observed stall + flap across the fleet) — every request terminal and
    FINISHED, greedy streams bitwise identical to the no-fault baseline,
    no leaked slots or reservations, and a killed replica restarts,
    passes probation and serves again.  Deterministic: same seed, same
    storm."""
    import random

    chaos_bench = _chaos_bench()
    cfg, model, params, _, _ = env
    rnd = random.Random(0)
    prompts = chaos_bench.make_prompts(cfg, rnd, 12, 3, 12)
    refs = chaos_bench.baseline_tokens(model, params, prompts, 6, 2)
    record, violations = chaos_bench.run_soak(
        model, params, cfg, prompts, refs, seed=0, n_replicas=2,
        n_slots=2, new_tokens=6, horizon=48, max_ticks=2500,
    )
    assert violations == [], violations
    assert record["all_terminal"] and record["bitwise_exact"]
    assert record["replica_deaths"] >= 1
    assert record["watchdog_degraded"] >= 1  # the stall was OBSERVED
    assert record["restarts"] >= 1
    assert record["probation_promotions"] >= 1
    # determinism: the record's storm shape is a pure function of seed
    record2, violations2 = chaos_bench.run_soak(
        model, params, cfg, prompts, refs, seed=0, n_replicas=2,
        n_slots=2, new_tokens=6, horizon=48, max_ticks=2500,
    )
    assert violations2 == []
    for key in ("ticks", "replica_deaths", "restarts", "retries",
                "fault_plans", "final_health"):
        assert record[key] == record2[key], key


@pytest.mark.slow
def test_chaos_soak_multi_seed(env):
    """Slow lane: longer storms, 3 replicas, several seeds — the
    invariants hold across schedule shapes, not just the pinned smoke.
    (Seeds are pinned to storms whose stall windows overlap traffic —
    a stall scheduled while its replica idles is simply unobservable,
    which the harness counts as a too-tame storm.)"""
    import random

    chaos_bench = _chaos_bench()
    cfg, model, params, _, _ = env
    for seed in (2, 3, 5):
        rnd = random.Random(seed)
        prompts = chaos_bench.make_prompts(cfg, rnd, 24, 3, 12)
        refs = chaos_bench.baseline_tokens(model, params, prompts, 8, 2)
        record, violations = chaos_bench.run_soak(
            model, params, cfg, prompts, refs, seed=seed, n_replicas=3,
            n_slots=2, new_tokens=8, horizon=64, max_ticks=4000,
        )
        assert violations == [], (seed, violations)


def test_queue_age_cumulative_across_requeue_after_death(env):
    """serving_queue_age_seconds — the autopilot's queue-age feed —
    stays CUMULATIVE through a frontend requeue: work orphaned by a
    replica death carries its ORIGINAL arrival into the surviving
    engine's scheduler, so that replica's queue-age gauge reports the
    full client wait, not the seconds since failover."""
    _, _, _, prompts, _ = env
    t = [0.0]
    clock = lambda: t[0]  # noqa: E731
    h0 = ReplicaHandle(
        0, _engine(env, clock=clock, n_slots=1),
        fault_plan=FaultPlan(crash_at_tick=1),
    )
    h1 = ReplicaHandle(1, _engine(env, clock=clock, n_slots=1))
    fe = Frontend(
        [h0, h1], router="least", clock=clock,
        config=FrontendConfig(restart=None),
    )
    outs = [
        fe.submit(Request(prompt=prompts[i], max_new_tokens=8))
        for i in range(3)
    ]
    for _ in range(2):  # r0 runs on h0, r1 on h1, r2 queues on h0; crash
        t[0] += 0.1
        fe.step()
    assert h0.health == DEAD
    for _ in range(3):  # orphans requeue; one waits in h1's queue
        t[0] += 0.1
        fe.step()
    assert h1.engine.scheduler.depth >= 1
    gauge = h1.engine.registry.gauge("serving_queue_age_seconds")
    # cumulative: now - ORIGINAL arrival (t=0), not now - failover time
    assert gauge.value == pytest.approx(t[0])
    fe.run(max_ticks=200)
    assert all(o.status == FINISHED for o in outs)


# -- telemetry wiring -------------------------------------------------------


def test_cluster_metrics_and_router_track(env):
    """cluster_* registry series and router-track trace events appear end
    to end; the snapshot passes the exporter schema gate."""
    from tpu_parallel.obs import Tracer, validate_snapshot

    _, _, _, prompts, _ = env
    tracer = Tracer()
    h0 = ReplicaHandle(
        0, _engine(env), fault_plan=FaultPlan(crash_at_tick=3)
    )
    h1 = ReplicaHandle(1, _engine(env))
    fe = Frontend([h0, h1], router="rr", tracer=tracer)
    outs = [fe.submit(Request(prompt=p, max_new_tokens=6)) for p in prompts]
    fe.run(max_ticks=300)
    assert all(out.status == FINISHED for out in outs)
    snap = fe.registry.snapshot()
    assert validate_snapshot(snap) == []
    gauges = {
        (row["name"], row["labels"].get("replica")): row["value"]
        for row in snap["gauges"]
    }
    assert ("cluster_replica_health", "0") in gauges
    assert gauges[("cluster_replica_health", "0")] == 2.0  # dead
    assert gauges[("cluster_replica_health", "1")] == 0.0  # healthy
    counters = {
        row["name"]: row["value"]
        for row in snap["counters"]
        if not row["labels"]
    }
    assert counters["cluster_replica_deaths_total"] == 1
    assert counters["cluster_retries_total"] >= 1
    names = {ev["name"] for ev in tracer.instants}
    assert {"route", "replica_death", "retry"} <= names
    assert all(
        ev["track"] == "router" for ev in tracer.instants
        if ev["name"] in ("route", "replica_death", "retry")
    )
    imb = [
        row for row in snap["histograms"]
        if row["name"] == "cluster_route_imbalance"
    ]
    assert imb and imb[0]["count"] > 0


# -- prefix affinity wins (slow) -------------------------------------------


@pytest.mark.slow
def test_prefix_affinity_beats_round_robin(env):
    """Acceptance (slow lane): on a repeated-prefix workload, prefix-
    affinity routing's aggregate prefix-cache hit rate beats round-robin
    (group placement is sticky instead of scattered)."""
    import random

    cfg, model, params, _, _ = env
    rng = jax.random.PRNGKey(11)
    rnd = random.Random(0)
    groups = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, g), (8,), 1, cfg.vocab_size
            )
        )]
        for g in range(3)
    ]
    prompts = []
    for i in range(18):
        hdr = groups[rnd.randrange(3)]
        sfx = [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 100 + i), (3 + i % 4,), 1,
                cfg.vocab_size,
            )
        )]
        prompts.append(hdr + sfx)

    def drive(policy):
        engines = [
            ServingEngine(
                model, params, n_slots=2,
                scheduler=SchedulerConfig(max_prefills_per_tick=1),
                prefill_buckets=(8, 16), prefix_cache_size=4,
            )
            for _ in range(3)
        ]
        fe = Frontend(engines, router=policy)
        outs = []
        for p in prompts:  # one arrival per tick: queues stay shallow
            outs.append(fe.submit(Request(prompt=p, max_new_tokens=4)))
            fe.step()
        fe.run(max_ticks=400)
        assert all(out.status == FINISHED for out in outs)
        return fe.prefix_hit_rate()

    affinity = drive("prefix")
    rr = drive("rr")
    assert affinity is not None and rr is not None
    assert affinity > rr, (affinity, rr)


# -- deadline double-check race: ONE typed terminal, ONE counter ------------


def test_deadline_between_passes_unified_counter(env):
    """A deadline expiring BETWEEN the tick-top ``_enforce_deadlines``
    pass and the post-step dispatch pass (which reads a FRESH clock) is
    shed on the same unified path as the tick-top sweep: exactly one
    typed ``deadline`` terminal, one count on
    ``cluster_deadline_sheds_total``, and the engine never sees the
    request."""
    _, _, _, prompts, _ = env
    dt = 0.3
    box = {"t": 0.0}

    def clock():  # every read advances: time moves WITHIN a tick
        box["t"] += dt
        return box["t"]

    eng = _engine(env, clock=clock, n_slots=1)
    fe = Frontend(
        [eng], clock=clock,
        config=FrontendConfig(dispatch_queue_depth=1),
    )
    filler = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    seen = []
    victim = fe.submit(Request(
        prompt=prompts[1], max_new_tokens=8, deadline=2 * dt,
        on_token=lambda ev: seen.append(ev),
    ))
    # tick 1: at the tick-top read the victim has waited exactly dt
    # (inside deadline); the filler fills the only dispatch slot, the
    # replica's step advances the clock, and the post-step dispatch
    # pass reads a fresh clock past the deadline — the race window
    events = fe.step()
    assert victim.status == CANCELLED
    assert victim.finish_reason == "deadline"
    assert victim.replicas == []  # never handed to an engine
    terms = [
        ev for ev in events
        if ev.request_id == victim.request.request_id
    ]
    assert len(terms) == 1 and terms[0].finish_reason == "deadline"
    assert len([ev for ev in seen if ev.finished]) == 1
    assert fe.summary()["deadline_sheds"] == 1
    # the tick-top sweep rides the SAME counter (no second path)
    lazy = fe.submit(Request(
        prompt=prompts[2], max_new_tokens=8, deadline=dt / 2,
    ))
    fe.step()
    assert lazy.status == CANCELLED and lazy.finish_reason == "deadline"
    assert fe.summary()["deadline_sheds"] == 2
    assert fe.summary()["cancelled"] == 2
    fe.run(max_ticks=200)
    assert filler.status == FINISHED
    assert fe.summary()["inflight_tokens"] == 0
    assert eng.pool.n_free == 1


# -- cancel racing drain and migration (PR 14 satellite) --------------------


def test_cancel_pending_during_drain_one_terminal_no_leaks(env):
    """Client cancel of a request PENDING at the frontend mid-drain:
    exactly one terminal event, the drain still completes, and nothing
    leaks — reservations zero, the pool fully free and aligned."""
    _, _, _, prompts, refs = env
    t = [0.0]
    eng = _engine(env, clock=lambda: t[0], n_slots=1)
    fe = Frontend(
        [eng], clock=lambda: t[0],
        config=FrontendConfig(dispatch_queue_depth=1),
    )
    a = fe.submit(Request(prompt=prompts[0], max_new_tokens=8))
    fe.step()
    assert a.status == "running"
    seen = []
    # b is accepted but still PENDING at the frontend when drain begins
    b = fe.submit(Request(
        prompt=prompts[1], max_new_tokens=8,
        on_token=lambda ev: seen.append(ev),
    ))
    assert b.replicas == []
    fe.drain(max_ticks=0)  # gate closed + queued remainder pulled back
    assert fe.draining
    assert fe.cancel(b.request.request_id) is True
    assert b.status == CANCELLED
    assert fe.cancel(b.request.request_id) is False  # already terminal
    fe.run(max_ticks=200)  # the drain's remainder
    assert a.status == FINISHED
    np.testing.assert_array_equal(np.asarray(a.tokens), refs[0])
    assert len([ev for ev in seen if ev.finished]) == 1
    assert fe.summary()["inflight_tokens"] == 0
    assert eng.pool.n_free == 1
    eng.pool.assert_slot_aligned(0)


def test_cancel_midrelocation_with_kv_export_one_terminal_no_leaks(env):
    """Client cancel of a request caught MID-RELOCATION — pulled back
    to pending with its KV export captured (cluster/migration.py), the
    swap drain-timeout state — terminates once and leaks nothing: the
    export's host blocks drop with the state, both engines' allocators
    audit clean, reservations end zero."""
    _, _, _, prompts, _ = env
    t = [0.0]
    kw = dict(kv_block_tokens=4, prefix_cache_size=16,
              kv_radix_cache=True)
    eng_a = _engine(env, clock=lambda: t[0], n_slots=2, **kw)
    eng_b = _engine(env, clock=lambda: t[0], n_slots=2, **kw)
    fe = Frontend([eng_a, eng_b], clock=lambda: t[0])
    seen = []
    a = fe.submit(Request(
        prompt=prompts[1], max_new_tokens=8,
        on_token=lambda ev: seen.append(ev),
    ))
    for _ in range(30):  # run until at least one full block is written
        fe.step()
        if len(a.tokens) >= 5:
            break
    assert 0 < len(a.tokens) < 8
    st = next(s for s in fe._by_attempt.values() if s.out is a)
    handle, erid = st.handle, st.engine_rid
    # mirror SwapController._relocate_open exactly: forget, detach,
    # capture BEFORE the cancel frees the blocks, requeue pending
    handle.forget(erid)
    fe._by_attempt.pop(erid)
    fe._capture_relocation_kv(st, handle, erid)
    st.handle = None
    st.engine_rid = None
    handle.engine.cancel(erid, reason="swap_relocate")
    fe._pending.append(st)
    assert st.kv_export is not None  # genuinely mid-migration
    assert fe.summary()["kv_exports"] == 1
    # the race: the client cancels while the relocation is in flight
    assert fe.cancel(a.request.request_id) is True
    assert a.status == CANCELLED
    assert fe.cancel(a.request.request_id) is False
    assert len([ev for ev in seen if ev.finished]) == 1
    assert fe.summary()["inflight_tokens"] == 0
    # no KV install ever ran — the export died with the cancel, typed
    assert all(
        v == 0 for v in fe.summary()["kv_migrations"].values()
    )
    fe.drain(max_ticks=50)
    for eng in (eng_a, eng_b):
        eng.pool.allocator.check()
        assert eng.in_flight == 0
