"""GPT-2 350M, 4-stage GPipe pipeline (BASELINE config 4: v5e-16)."""

from ml_collections import ConfigDict

from configs.common import model_overrides


def get_config():
    c = ConfigDict()
    c.simulate_cpu_devices = 0
    c.model = "gpt2_350m"
    # interleave=2: 24 layers as 4 ranks x 2 virtual stages of 3 layers —
    # bubble (4-1)/(8*2+3) = 16% vs GPipe's (4-1)/(8+3) = 27%
    c.model_overrides = model_overrides(
        num_microbatches=8, pipe_interleave=2,
        attn_impl="flash", remat_policy="proj_attn",
    )
    c.mesh = ConfigDict(dict(data=-1, model=1, pipe=4, seq=1))
    c.global_batch_size = 64
    c.num_minibatches = 1
    c.steps = 100
    c.optimizer = "adamw"  # adamw | lion | sgd
    c.lr_schedule = "cosine"  # cosine | linear | constant
    c.ema_decay = 0.0  # >0 keeps an EMA shadow of params (eval prefers it)
    c.learning_rate = 3e-4
    c.warmup_steps = 20
    c.weight_decay = 0.1
    c.grad_clip = 1.0
    c.seed = 0
    c.log_every = 10
    c.donate = True
    # optional run plumbing (empty = disabled)
    c.checkpoint_dir = ""
    c.checkpoint_every = 100
    c.data_path = ""
    c.data_format = "flat"  # flat | packed (EOS-delimited docs + segment_ids)
    c.eos_id = 50256
    c.eval_steps = 0
    c.eval_every = 0  # >0: periodic eval during fit (uses the held-out split)
    c.keep_best = False  # snapshot lowest-eval-loss state to {checkpoint_dir}/best
    return c