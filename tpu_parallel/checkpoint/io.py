"""Checkpoint / resume on top of orbax — the TPU-native answer.

The reference has no persistence at all (SURVEY.md §5: "no orbax/flax
serialization anywhere"; its ``TrainState`` is checkpointable-by-construction
but nothing saves it).  This module supplies the capability: sharded
``TrainState`` pytrees (including ``nn.Partitioned``-boxed leaves) saved with
orbax and restored *onto the same mesh layout* via an abstract target derived
from the trainer's init function — every leaf comes back with its
NamedSharding, so restore never materializes a full replica on one host.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Any, Callable, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

Pytree = Any


def _open_file(path, mode="r", **kwargs):
    """Manifest reads/writes go through the injectable IO fault shim:
    the weight-set integrity story (fingerprint verify on load) is only
    as strong as the IO it reads through, and routing it here lets the
    seeded disk-fault soak corrupt a manifest deterministically
    (``scripts/check_io.py`` fences raw opens under this package).
    Imported lazily so the checkpoint layer does not pull the daemon
    package's import graph at module-import time."""
    from tpu_parallel.daemon.iofaults import open_file

    return open_file(path, mode, **kwargs)


class Checkpointer:
    """Thin orbax wrapper bound to one run directory.

    ``abstract_state``: pytree of ShapeDtypeStruct (with shardings) matching
    the live state — build it with :func:`abstract_state_of`.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Pytree, *, wait: bool = False) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def restore(self, abstract_state: Pytree, step: Optional[int] = None) -> Pytree:
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        try:
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        except ValueError as e:
            # possibly structure drift (optional state fields added/removed
            # since the checkpoint was written).  The drift path re-raises
            # for anything it cannot soundly absorb, so chain back to the
            # original error when it fails too — no message parsing.
            try:
                return self._restore_with_drift(abstract_state, step)
            except Exception as drift_exc:
                # chain so BOTH failures surface: the original Standard
                # Restore mismatch and whatever broke the drift path
                raise e from drift_exc

    def _restore_with_drift(self, abstract_state: Pytree, step: int) -> Pytree:
        """Restore a checkpoint whose structure drifted from the live state:
        optional fields added since it was written (e.g. a pre-``ema_params``
        checkpoint into the current ``TrainState``) or written with fields
        the current config no longer carries (EMA turned off on resume).

        Orbax keys the saved tree by dataclass field name; each overlapping
        field restores through its own dict-shaped ``PyTreeRestore`` with
        ``partial_restore=True`` (so the on-disk tree may hold more than the
        target), and fields absent on disk keep their template defaults.
        """
        import dataclasses

        if not dataclasses.is_dataclass(abstract_state):
            raise ValueError(
                f"cannot drift-restore a non-dataclass state "
                f"({type(abstract_state).__name__})"
            )
        # the manager's registered handler is StandardCheckpointHandler and
        # refuses PyTreeRestore args; a bare PyTreeCheckpointer on the step
        # directory accepts partial_restore (the on-disk layout is the same)
        step_dir = os.path.join(self.directory, str(step), "default")
        restored = {}
        for f in dataclasses.fields(abstract_state):
            if not f.metadata.get("pytree_node", True):
                continue  # apply_fn/tx: functions, never serialized
            value = getattr(abstract_state, f.name)
            if value is None:
                continue  # disabled optional field: ignore any on-disk copy
            item = {f.name: value}
            try:
                with ocp.PyTreeCheckpointer() as ptc:
                    out = ptc.restore(
                        step_dir,
                        args=ocp.args.PyTreeRestore(
                            item=item,
                            restore_args=(
                                ocp.checkpoint_utils.construct_restore_args(item)
                            ),
                            partial_restore=True,
                        ),
                    )
            except (ValueError, KeyError, TypeError):
                # Only fields that are optional *by construction* (dataclass
                # default None, like ema_params) may degrade to None —
                # TypeError covers the on-disk None marker saved while the
                # feature was off.  A restore failure on a required field
                # (params, opt_state, ...) is corruption or intra-field
                # drift and must surface, not silently null the state.
                if f.default is not None:
                    raise
                import warnings

                warnings.warn(
                    f"checkpoint at step {step} has no usable {f.name!r}; "
                    "restoring it as None",
                    stacklevel=2,
                )
                restored[f.name] = None
                continue
            restored[f.name] = out[f.name]
        if all(v is None for v in restored.values()):
            raise ValueError(
                f"checkpoint at step {step} shares no fields with the "
                "restore target — structure drift too large"
            )
        return abstract_state.replace(**restored)

    @property
    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()


# -- serving weight sets (the hot-swap unit) --------------------------------
#
# A training checkpoint is a TrainState (params + optimizer + step); the
# serving fleet hot-swaps PARAMS ONLY, and it needs two things a bare
# orbax tree does not give it: a VERSION identity (what the fleet's
# `cluster_swap_version` gauge and `swap_status()` report) and a content
# FINGERPRINT (so a corrupted or wrong-file load is refused BEFORE a
# replica starts serving garbage — the cheap half of the canary's
# logit-fingerprint spot check).  `save_serving_weights` writes the param
# pytree via orbax plus a small JSON manifest next to it;
# `load_serving_weights` restores and verifies the fingerprint, raising
# :class:`WeightsCorrupt` on any mismatch.


class WeightsCorrupt(ValueError):
    """Loaded weights do not match their manifest fingerprint — the file
    set was truncated, tampered with, or mixed from two saves.  Serving
    such weights would be silent garbage; refuse loudly instead."""


@dataclasses.dataclass(frozen=True)
class WeightManifest:
    """Sidecar identity record of one saved serving weight set."""

    version: str
    step: int
    fingerprint: str
    n_leaves: int
    n_params: int

    def to_json(self) -> str:
        return json.dumps(dataclasses.asdict(self), indent=2, sort_keys=True)

    @classmethod
    def from_json(cls, text: str) -> "WeightManifest":
        return cls(**json.loads(text))


def params_fingerprint(params: Pytree) -> str:
    """Deterministic content hash of a param pytree: sha256 over every
    leaf's path, shape, dtype and raw bytes (host transfer — call at save
    / load / audit points, not per tick).  Identical trees hash
    identically across processes; any flipped bit, reshaped leaf, or
    renamed module changes the digest."""
    h = hashlib.sha256()
    leaves = jax.tree_util.tree_flatten_with_path(params)[0]
    for path, leaf in leaves:
        arr = np.asarray(leaf)
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.shape).encode())
        h.update(str(arr.dtype).encode())
        h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def _manifest_path(directory: str, step: int) -> str:
    return os.path.join(
        os.path.abspath(directory), f"weights_manifest_{step}.json"
    )


def _weights_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"weights_{step}")


def save_serving_weights(
    directory: str, step: int, params: Pytree, version: Optional[str] = None
) -> WeightManifest:
    """Write a hot-swappable weight set: the param pytree (orbax) plus its
    :class:`WeightManifest` sidecar.  ``version`` defaults to
    ``"step-<step>"`` — the identity the cluster's rolling swap reports
    per replica."""
    leaves = jax.tree_util.tree_leaves(params)
    manifest = WeightManifest(
        version=version if version is not None else f"step-{step}",
        step=step,
        fingerprint=params_fingerprint(params),
        n_leaves=len(leaves),
        n_params=int(sum(np.asarray(x).size for x in leaves)),
    )
    path = _weights_dir(directory, step)
    with ocp.PyTreeCheckpointer() as ptc:
        ptc.save(path, args=ocp.args.PyTreeSave(params), force=True)
    with _open_file(_manifest_path(directory, step), "w") as fh:
        fh.write(manifest.to_json())
        fh.write("\n")
    return manifest


def latest_weights_step(directory: str) -> Optional[int]:
    """Largest step with a manifest in ``directory`` (None when empty)."""
    steps = []
    if os.path.isdir(directory):
        for name in os.listdir(directory):
            if name.startswith("weights_manifest_") and name.endswith(".json"):
                steps.append(int(name[len("weights_manifest_"):-len(".json")]))
    return max(steps) if steps else None


def load_serving_weights(
    directory: str,
    step: Optional[int] = None,
    like: Optional[Pytree] = None,
) -> tuple:
    """Restore a weight set saved by :func:`save_serving_weights` and
    VERIFY it against its manifest.  ``like`` (the live params the loaded
    set will replace) restores each leaf with its template's dtype/
    sharding; without it leaves come back as saved.  Returns ``(params,
    manifest)``; raises FileNotFoundError when nothing is saved and
    :class:`WeightsCorrupt` when the content hash disagrees with the
    manifest — never hand unverified weights to a serving fleet."""
    if step is None:
        step = latest_weights_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no serving weights saved under {directory}"
            )
    mpath = _manifest_path(directory, step)
    if not os.path.exists(mpath):
        raise FileNotFoundError(f"no weight manifest at {mpath}")
    with _open_file(mpath) as fh:
        manifest = WeightManifest.from_json(fh.read())
    path = _weights_dir(directory, step)
    with ocp.PyTreeCheckpointer() as ptc:
        if like is not None:
            restored = ptc.restore(
                path,
                args=ocp.args.PyTreeRestore(
                    item=like,
                    restore_args=(
                        ocp.checkpoint_utils.construct_restore_args(like)
                    ),
                ),
            )
        else:
            restored = ptc.restore(path)
    digest = params_fingerprint(restored)
    if digest != manifest.fingerprint:
        raise WeightsCorrupt(
            f"weights at {path} hash {digest[:12]}… but the manifest "
            f"records {manifest.fingerprint[:12]}… (version "
            f"{manifest.version!r}, step {step}) — refusing to serve a "
            "corrupted or mismatched weight set"
        )
    return restored, manifest


def abstract_state_of(init_fn: Callable, *example_args) -> Pytree:
    """Abstract (shape/dtype/sharding) twin of ``init_fn(*example_args)``.

    ``init_fn`` should be the jitted sharded init from
    ``build_train_functions`` — its output shardings become the restore
    layout.
    """
    return jax.eval_shape(init_fn, *example_args)
