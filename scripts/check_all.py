"""One runner for every AST contract gate.

The repo grew four static checkers, one per PR, each wired into tier-1
through its own copy of the same plumbing (import-from-scripts, run
``check_paths``, assert empty, self-test the catch path):

- ``check_clock``  — serving/cluster/daemon/fleet code never reads wall
  time directly (the injectable-clock contract).
- ``check_scopes`` — every collective in parallel/ + ops/ sits inside a
  ``jax.named_scope`` (labelable accelerator traces).
- ``check_host_sync`` — no per-slot device sync inside a host loop under
  serving/ (the dispatch tax the fused tick exists to kill).
- ``check_blocks`` — block-table mutation stays inside ``cache_pool.py``
  (the single table-mutation authority).

This module is the registry: each checker contributes its module name
(they all expose ``check_paths(paths=DEFAULT_PATHS) -> [problems]`` and
a ``main(argv)``), and both CI surfaces — ``python scripts/check_all.py``
and the single tier-1 test ``tests/test_checkers.py::test_all_ast_gates``
— iterate it.  Adding the next checker is ONE line here plus its module,
not a fifth copy of the wiring.

A second registry, ``RUNTIME_CHECKS``, holds gates that RUN the product
instead of parsing it — today ``check_daemon``, the serving-daemon
start/submit/SIGTERM-drain smoke.  The CLI runs both registries; the
AST-only ``run_all()`` default keeps ``test_all_ast_gates`` instant,
and each runtime gate carries its own tier-1 test entry
(``tests/test_daemon.py`` for the daemon smoke).

Usage: ``python scripts/check_all.py [names...]`` — runs every gate (or
just the named ones) over its own default paths, prints each problem,
exits nonzero on any.  ``--ast-only`` skips the runtime gates.
"""

from __future__ import annotations

import importlib.util
import os
import sys
from typing import Dict, List, Sequence

# the registry: module name -> one-line contract (the order is the
# historical order the gates landed in; output follows it)
CHECKERS: Dict[str, str] = {
    "check_clock": "serving/cluster time flows through the injectable clock",
    "check_scopes": "collectives sit inside jax.named_scope",
    "check_host_sync": (
        "no device sync in serving host loops (per-slot tax) or in "
        "launch bodies (the overlap-killing pattern)"
    ),
    "check_blocks": (
        "block-table mutation AND allocator reference minting stay "
        "inside cache_pool.py (radix/offload/migration layers only "
        "hold references)"
    ),
    "check_io": (
        "durability-critical file IO under daemon/, checkpoint/ and "
        "fleet/ routes through the iofaults shim (seeded disk-fault "
        "coverage)"
    ),
    "check_trace": (
        "every FleetTransport call site under fleet/ passes the trace "
        "kwarg explicitly (no silently-untraced wire crossings)"
    ),
}

# gates that RUN the product rather than parse it (slower; spawn
# subprocesses).  Kept out of CHECKERS so run_all()'s default stays the
# instant AST sweep; the CLI and their own tier-1 tests run them.
RUNTIME_CHECKS: Dict[str, str] = {
    "check_daemon": (
        "the serving daemon starts, serves over HTTP, drains on "
        "SIGTERM and exits 0 with a clean journal — and recovers a "
        "seeded disk-fault trial (tail corruption typed-detected, "
        "streams bitwise)"
    ),
    "check_fleet": (
        "a fleet (router + 2 daemon processes) serves the daemon's "
        "client contract, survives one seeded SIGKILL with bitwise "
        "handoff, and lands at least one remote KV import"
    ),
}

SCRIPTS_DIR = os.path.dirname(os.path.abspath(__file__))


def load_checker(name: str):
    """Import one checker module from the scripts directory by path (no
    sys.path mutation — safe from tests and other tools)."""
    if name not in CHECKERS and name not in RUNTIME_CHECKS:
        raise ValueError(
            f"unknown checker {name!r} (registered: "
            f"{sorted(CHECKERS) + sorted(RUNTIME_CHECKS)})"
        )
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(SCRIPTS_DIR, f"{name}.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def run_all(names: Sequence[str] = ()) -> Dict[str, List[str]]:
    """Run every registered gate (or just ``names``) over its own
    DEFAULT_PATHS, from the repo root.  Returns name -> problem list;
    an all-empty dict of lists is a passing tree."""
    repo_root = os.path.dirname(SCRIPTS_DIR)
    cwd = os.getcwd()
    os.chdir(repo_root)  # every checker's DEFAULT_PATHS are repo-relative
    try:
        results: Dict[str, List[str]] = {}
        for name in names or CHECKERS:
            results[name] = load_checker(name).check_paths()
        return results
    finally:
        os.chdir(cwd)


def main(argv: List[str]) -> int:
    args = [a for a in argv[1:] if a != "--ast-only"]
    ast_only = "--ast-only" in argv[1:]
    names = args or (
        list(CHECKERS) + ([] if ast_only else list(RUNTIME_CHECKS))
    )
    results = run_all(names)
    contracts = {**CHECKERS, **RUNTIME_CHECKS}
    failed = 0
    for name, problems in results.items():
        for problem in problems:
            print(problem, file=sys.stderr)
        if problems:
            failed += 1
            print(
                f"{name}: {len(problems)} violation(s) — "
                f"{contracts[name]}",
                file=sys.stderr,
            )
        else:
            print(f"{name}: OK")
    if failed:
        print(f"check_all: {failed} gate(s) failed", file=sys.stderr)
        return 1
    print(f"check_all: {len(results)} gates OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
