"""Process-local metric registry: labeled counters, gauges, and
log-bucketed histograms.

The one metric store every subsystem shares.  ``ServingMetrics`` derives
its TTFT/ITL/queue-depth percentiles from histograms registered here (the
PR-1 unbounded-window deques are gone), the trainer publishes MFU and
throughput gauges into the same registry, and the exporters
(:mod:`tpu_parallel.obs.exporters`) serialize one :meth:`snapshot` in
Prometheus text / JSONL form — the instrument API is the only write path,
so every consumer sees the same numbers.

Design constraints, in order:

- **Bounded memory.**  A long-lived engine must not grow state per
  observation.  Counters and gauges are O(1); histograms are LOG-bucketed
  (geometric bucket edges ``growth**i``), so a histogram's size is
  O(log(max/min) / log(growth)) regardless of observation count — ~290
  buckets span 1 ns..1000 s at the default 10% growth — while bucket
  COUNTS, ``sum``, ``count``, ``min`` and ``max`` stay exact.
- **Bounded error.**  A percentile estimate is the geometric midpoint of
  the bucket holding the target rank: always within one bucket width
  (±5% relative at the default growth) of the true order statistic.
  Means are exact (``sum / count``), unlike the sliding-window deques
  this replaces, whose "mean" silently covered only the newest samples.
- **Labels without cardinality surprises.**  Instruments are keyed by
  ``(name, sorted label items)``; asking for the same pair returns the
  same object, so hot paths can hold the instrument and skip the dict
  lookup entirely.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: Dict[str, object]) -> _LabelKey:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class Counter:
    """Monotone accumulator.  ``inc`` only — a counter that can go down is
    a gauge and would break rate() math in any downstream scraper."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter increment {amount} < 0 (use a gauge)")
        self.value += amount


class Gauge:
    """Last-write-wins instantaneous value."""

    __slots__ = ("value",)

    def __init__(self):
        self.value = 0.0

    def set(self, value: float) -> None:
        self.value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        self.value += amount


class Histogram:
    """Log-bucketed distribution: exact count/sum/min/max, bucket counts
    keyed by ``floor(log(v) / log(growth))`` in a sparse dict (only hit
    buckets exist), non-positive observations pooled in a dedicated zero
    bucket.  ``percentile`` answers from bucket boundaries — within one
    bucket width of the true value by construction."""

    __slots__ = ("growth", "count", "sum", "min", "max", "buckets",
                 "zero_count", "_log_growth")

    def __init__(self, growth: float = 1.1):
        if growth <= 1.0:
            raise ValueError(f"growth={growth} must be > 1")
        self.growth = growth
        self._log_growth = math.log(growth)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self.buckets: Dict[int, int] = {}
        self.zero_count = 0

    def observe(self, value: float) -> None:
        self.count += 1
        self.sum += value
        if self.min is None or value < self.min:
            self.min = value
        if self.max is None or value > self.max:
            self.max = value
        if value <= 0.0:
            self.zero_count += 1
            return
        idx = math.floor(math.log(value) / self._log_growth)
        # float edge case: log/floor can land one bucket low when value
        # sits exactly on an edge — nudge up so value < growth**(idx+1)
        if value >= self.growth ** (idx + 1):
            idx += 1
        self.buckets[idx] = self.buckets.get(idx, 0) + 1

    def mean(self) -> Optional[float]:
        return (self.sum / self.count) if self.count else None

    def bucket_bounds(self, idx: int) -> Tuple[float, float]:
        """[lower, upper) value bounds of bucket ``idx``."""
        return self.growth ** idx, self.growth ** (idx + 1)

    def percentile(self, p: float) -> Optional[float]:
        """Geometric midpoint of the bucket containing the rank-``p``
        observation (p clamped into [0, 100]); None when empty."""
        if self.count == 0:
            return None
        p = min(max(p, 0.0), 100.0)
        # rank of the order statistic numpy's linear interpolation pivots
        # on; ceil'd to a whole observation since buckets hold counts
        rank = min(self.count, max(1, math.ceil(p / 100.0 * self.count)))
        seen = self.zero_count
        if rank <= seen:
            return 0.0
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            if rank <= seen:
                lo, hi = self.bucket_bounds(idx)
                return math.sqrt(lo * hi)
        return self.max  # unreachable unless float drift; max is safe

    def cumulative(self) -> List[Tuple[float, int]]:
        """Ascending ``(upper_edge, cumulative_count)`` pairs — the
        Prometheus ``le`` view.  The zero bucket reports at edge 0.0."""
        out: List[Tuple[float, int]] = []
        seen = 0
        if self.zero_count:
            seen = self.zero_count
            out.append((0.0, seen))
        for idx in sorted(self.buckets):
            seen += self.buckets[idx]
            out.append((self.growth ** (idx + 1), seen))
        return out


class HistogramWindow:
    """Point-in-time capture of a :class:`Histogram` for WINDOWED reads.

    Registry histograms are monotone — they can never be reset without
    lying to their other writers — so any consumer that needs "what
    happened since T" (the swap controller's pre-swap latency baseline
    and per-canary windows, a bench's measure-after-warmup read) captures
    a window at T and reads deltas against the live instrument:

    - :meth:`base_count` / :meth:`base_mean`: the distribution AT capture
      (the swap controller's "before" side).
    - :meth:`delta_count` / :meth:`delta_mean`: observations landed SINCE
      capture (the "after" side).  Exact, like the histogram's own
      count/sum.

    The window holds only two floats — capturing is free and windows can
    be re-captured per phase (one monotone canary histogram serves every
    rollout step through a fresh window each time).
    """

    __slots__ = ("hist", "count0", "sum0")

    def __init__(self, hist: Histogram):
        self.hist = hist
        self.count0 = hist.count
        self.sum0 = hist.sum

    def base_count(self) -> int:
        return self.count0

    def base_mean(self) -> Optional[float]:
        """Mean of everything observed BEFORE capture; None when empty."""
        return (self.sum0 / self.count0) if self.count0 else None

    def delta_count(self) -> int:
        return self.hist.count - self.count0

    def delta_mean(self) -> Optional[float]:
        """Mean of everything observed SINCE capture; None when empty."""
        dc = self.delta_count()
        return ((self.hist.sum - self.sum0) / dc) if dc else None


class PercentileWindow(HistogramWindow):
    """A :class:`HistogramWindow` that also snapshots the BUCKET counts,
    so windowed PERCENTILES — not just means — read as deltas.

    The base window's two-float capture answers "what is the mean since
    T"; an SLO controller needs "what is the p95 since T" (a mean hides
    exactly the tail an overload fattens).  Capturing the sparse bucket
    dict costs O(hit buckets) — fine for a consumer that re-captures once
    per decision window (the cluster autopilot), wasteful for one that
    captures per observation.  The swap controller keeps the cheap base
    class; the autopilot uses this one.

    Counter-reset hygiene matters here too: a window holds a reference to
    the HISTOGRAM OBJECT, not to a registry name, so an
    ``engine.reset_metrics()`` mid-window (which installs a fresh
    registry and fresh instruments) leaves the window reading the old,
    now-unwritten instrument — deltas freeze at their last value and can
    never go negative (pinned in ``tests/test_obs.py``).
    """

    __slots__ = ("buckets0", "zero0")

    def __init__(self, hist: Histogram):
        super().__init__(hist)
        self.buckets0 = dict(hist.buckets)
        self.zero0 = hist.zero_count

    def delta_percentile(self, p: float) -> Optional[float]:
        """Percentile over observations landed SINCE capture — the same
        bucket-midpoint estimate as :meth:`Histogram.percentile`, on the
        bucket-count deltas; None when the window is empty."""
        h = self.hist
        dc = self.delta_count()
        if dc <= 0:
            return None
        p = min(max(p, 0.0), 100.0)
        rank = min(dc, max(1, math.ceil(p / 100.0 * dc)))
        seen = h.zero_count - self.zero0
        if rank <= seen:
            return 0.0
        for idx in sorted(h.buckets):
            seen += h.buckets[idx] - self.buckets0.get(idx, 0)
            if rank <= seen:
                lo, hi = h.bucket_bounds(idx)
                return math.sqrt(lo * hi)
        return h.max  # unreachable unless float drift; max is safe


class MetricRegistry:
    """Get-or-create store of labeled instruments.

    ``counter("requests_total", status="finished")`` returns THE counter
    for that (name, labels) pair — hold the reference on hot paths.  One
    name maps to one instrument kind; reusing a name across kinds raises
    (it would silently fork the metric in every exporter).
    """

    def __init__(self):
        self._instruments: Dict[str, Dict[_LabelKey, object]] = {}
        self._kinds: Dict[str, str] = {}
        self._hist_growth: Dict[str, float] = {}

    def _get(self, kind: str, name: str, labels: Dict[str, object],
             factory):
        have = self._kinds.setdefault(name, kind)
        if have != kind:
            raise ValueError(
                f"metric {name!r} already registered as a {have}, "
                f"requested as a {kind}"
            )
        by_label = self._instruments.setdefault(name, {})
        key = _label_key(labels)
        inst = by_label.get(key)
        if inst is None:
            inst = by_label[key] = factory()
        return inst

    def counter(self, name: str, **labels) -> Counter:
        return self._get("counter", name, labels, Counter)

    def gauge(self, name: str, **labels) -> Gauge:
        return self._get("gauge", name, labels, Gauge)

    def histogram(self, name: str, growth: float = 1.1, **labels) -> Histogram:
        prior = self._hist_growth.setdefault(name, growth)
        if prior != growth:
            raise ValueError(
                f"histogram {name!r} growth {growth} != first-registered "
                f"{prior} (label series must share buckets)"
            )
        return self._get(
            "histogram", name, labels, lambda: Histogram(growth)
        )

    def snapshot(self) -> Dict[str, list]:
        """JSON-serializable dump of every instrument: the one structure
        the exporters (Prometheus text, JSONL sink) and the serve_bench
        ``--smoke`` schema gate consume."""
        out: Dict[str, list] = {"counters": [], "gauges": [], "histograms": []}
        for name, by_label in sorted(self._instruments.items()):
            kind = self._kinds[name]
            for key, inst in sorted(by_label.items()):
                labels = dict(key)
                if kind == "counter":
                    out["counters"].append(
                        {"name": name, "labels": labels, "value": inst.value}
                    )
                elif kind == "gauge":
                    out["gauges"].append(
                        {"name": name, "labels": labels, "value": inst.value}
                    )
                else:
                    out["histograms"].append(
                        {
                            "name": name,
                            "labels": labels,
                            "count": inst.count,
                            "sum": inst.sum,
                            "min": inst.min,
                            "max": inst.max,
                            "buckets": [
                                [edge, c] for edge, c in inst.cumulative()
                            ],
                        }
                    )
        return out


def validate_snapshot(snap: Dict) -> List[str]:
    """Schema check for :meth:`MetricRegistry.snapshot` output; returns a
    list of problems (empty = valid).  The serve_bench ``--smoke`` gate
    fails nonzero on any entry, so exporter consumers can rely on the
    shape without defensive parsing."""
    problems: List[str] = []
    if not isinstance(snap, dict):
        return [f"snapshot is {type(snap).__name__}, not dict"]
    for section in ("counters", "gauges", "histograms"):
        rows = snap.get(section)
        if not isinstance(rows, list):
            problems.append(f"missing/invalid section {section!r}")
            continue
        for row in rows:
            name = row.get("name")
            if not isinstance(name, str) or not name:
                problems.append(f"{section}: unnamed entry {row!r}")
                continue
            if not isinstance(row.get("labels"), dict):
                problems.append(f"{section}/{name}: labels not a dict")
            if section in ("counters", "gauges"):
                if not isinstance(row.get("value"), (int, float)):
                    problems.append(f"{section}/{name}: non-numeric value")
                continue
            for field in ("count", "sum"):
                if not isinstance(row.get(field), (int, float)):
                    problems.append(f"histograms/{name}: bad {field!r}")
            buckets = row.get("buckets")
            if not isinstance(buckets, list) or not all(
                isinstance(b, (list, tuple))
                and len(b) == 2
                and all(isinstance(x, (int, float)) for x in b)
                for b in buckets
            ):
                problems.append(f"histograms/{name}: malformed buckets")
                continue
            edges = [b[0] for b in buckets]
            counts = [b[1] for b in buckets]
            if edges != sorted(edges):
                problems.append(f"histograms/{name}: edges not ascending")
            if counts != sorted(counts):
                problems.append(
                    f"histograms/{name}: cumulative counts not monotone"
                )
            if buckets and counts[-1] != row.get("count"):
                problems.append(
                    f"histograms/{name}: cumulative tail != count"
                )
    return problems
