"""Unified telemetry: labeled metric registry, request-lifecycle span
tracer, and pluggable exporters (Chrome trace / Prometheus text / JSONL).

Shared by the serving engine and the trainer (docs/11_observability.md):
``MetricRegistry`` is the one store every counter/gauge/histogram lives
in, ``Tracer`` records lifecycle spans on per-slot tracks, and the
exporters serialize both without touching instrumentation.

Since the fleet-tracing PR the layer also crosses processes:
``TraceContext`` travels in the ``X-TP-Trace`` header, ``SpanSpool``
appends each process's finished spans to a bounded JSONL span log, and
``stitch_traces`` rebases N processes' logs onto one clock and emits a
single Perfetto timeline with flow arrows across the wire crossings.
"""

from tpu_parallel.obs.exporters import (
    chrome_trace_events,
    export_snapshot_jsonl,
    parse_prometheus_text,
    prometheus_lines,
    prometheus_text,
    write_chrome_trace,
    write_prometheus,
)
from tpu_parallel.obs.registry import (
    Counter,
    Gauge,
    Histogram,
    HistogramWindow,
    MetricRegistry,
    PercentileWindow,
    validate_snapshot,
)
from tpu_parallel.obs.spool import SpanSpool, read_span_log
from tpu_parallel.obs.stitch import (
    clock_offsets,
    phase_breakdown,
    stitch_traces,
    trace_summary,
)
from tpu_parallel.obs.tracer import (
    NULL_SPAN,
    NULL_TRACER,
    TRACE_HEADER,
    NullTracer,
    Span,
    TraceContext,
    Tracer,
)

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "HistogramWindow",
    "PercentileWindow",
    "MetricRegistry",
    "validate_snapshot",
    "Span",
    "Tracer",
    "TraceContext",
    "TRACE_HEADER",
    "NullTracer",
    "NULL_SPAN",
    "NULL_TRACER",
    "SpanSpool",
    "read_span_log",
    "clock_offsets",
    "stitch_traces",
    "trace_summary",
    "phase_breakdown",
    "chrome_trace_events",
    "write_chrome_trace",
    "prometheus_lines",
    "prometheus_text",
    "parse_prometheus_text",
    "write_prometheus",
    "export_snapshot_jsonl",
]
