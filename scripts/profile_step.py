"""Profile one training-step workload and print where the time goes.

Runs a few steps of the bench config under ``jax.profiler.trace``, then
parses the captured ``.xplane.pb`` with ``tensorboard_plugin_profile`` and
prints the top ops by self time — the evidence needed to close the MFU gap
(BASELINE.md north star) instead of guessing at configs.

Usage:
    python scripts/profile_step.py [batch] [remat] [attn] [chunk] [scan] [k=v...]
e.g.
    python scripts/profile_step.py 16 proj xla 0 1 scan_group=2
"""

import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_and_trace(batch, remat, attn, chunk, logdir, scan=None, extra=None):
    import jax

    from tpu_parallel.runtime import MeshConfig
    from tpu_parallel.train_lib import Trainer, TrainerConfig
    from tpu_parallel.utils.profiling import sync, trace

    overrides = dict(
        dropout_rate=0.0, attn_impl=attn, loss_chunk=chunk, **(extra or {}),
    )
    if scan is not None:
        overrides["scan_layers"] = scan
    if remat in ("dots", "proj", "proj_attn"):
        overrides.update(remat=True, remat_policy=remat)
    else:
        overrides.update(remat=remat in ("1", "full"))
    on_tpu = jax.devices()[0].platform == "tpu"
    config = TrainerConfig(
        model="gpt2_125m" if on_tpu else "tiny",
        model_overrides=overrides,
        mesh=MeshConfig(data=-1),
        global_batch_size=batch,
        steps=5,
        log_every=10_000,
        donate=False,  # donation confuses repeated stepping here
    )
    trainer = Trainer(config)
    trainer.init()
    state, metrics = trainer.state, None
    for _ in range(3):  # compile + settle outside the trace
        state, metrics = trainer.funcs.step_fn(state, metrics, trainer.example_batch)
    sync((state, metrics))
    with trace(logdir):
        for _ in range(3):
            state, metrics = trainer.funcs.step_fn(
                state, metrics, trainer.example_batch
            )
        sync((state, metrics))


def summarize(logdir, top=30):
    """Aggregate per-op device time from the newest xplane.pb.

    Parses the trace with a locally-compiled mirror of the XSpace proto
    (scripts/xplane.proto) — the image's tensorboard_plugin_profile build
    can't read xplane files, protoc can.
    """
    import subprocess
    import tempfile

    here = os.path.dirname(os.path.abspath(__file__))
    with tempfile.TemporaryDirectory() as tmp:
        subprocess.run(
            ["protoc", f"--python_out={tmp}", "--proto_path", here, "xplane.proto"],
            check=True,
        )
        sys.path.insert(0, tmp)
        os.environ.setdefault("PROTOCOL_BUFFERS_PYTHON_IMPLEMENTATION", "python")
        import xplane_pb2  # noqa: E402

        xplanes = sorted(
            glob.glob(os.path.join(logdir, "**", "*.xplane.pb"), recursive=True),
            key=os.path.getmtime,
        )
        if not xplanes:
            print("no xplane.pb captured", file=sys.stderr)
            return
        space = xplane_pb2.XSpace()
        with open(xplanes[-1], "rb") as f:
            space.ParseFromString(f.read())

    printed = False
    for plane in space.planes:
        is_device = plane.name.startswith("/device:") or "TPU" in plane.name
        if not is_device:
            continue
        printed = True
        print(f"\n=== plane: {plane.name} ===")
        totals = {}
        for line in plane.lines:
            # only the per-op schedule lines: device planes also carry
            # "XLA Modules" / "Steps" lines whose whole-step spans would
            # double-count every op into the totals
            if "Modules" in line.name or "Steps" in line.name:
                continue
            for ev in line.events:
                name = plane.event_metadata[ev.metadata_id].name
                totals[name] = totals.get(name, 0) + ev.duration_ps
        if not totals:
            continue
        grand = sum(totals.values())
        print(f"{'time%':>7}  {'ms':>9}  op")
        for name, ps in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
            print(f"{ps / grand * 100:7.2f}  {ps / 1e9:9.3f}  {name[:90]}")
        print(f"total attributed: {grand / 1e9:.3f} ms across {len(totals)} ops")
    if not printed:
        # CPU traces carry no per-op device lines — list what was captured
        names = ", ".join(p.name for p in space.planes)
        print(f"no device plane with op events (planes: {names})")


def main():
    args = sys.argv[1:]
    batch = int(args[0]) if len(args) > 0 else 16
    remat = args[1] if len(args) > 1 else "proj"
    attn = args[2] if len(args) > 2 else "xla"
    chunk = int(args[3]) if len(args) > 3 else 0
    scan = (args[4] != "0") if len(args) > 4 else None
    extra = {}
    for kv in args[5:]:
        key, val = kv.split("=", 1)
        try:
            val = int(val)
        except ValueError:
            pass
        extra[key] = val
    logdir = os.environ.get("PROFILE_DIR", "/tmp/tpu_parallel_profile")
    run_and_trace(batch, remat, attn, chunk, logdir, scan=scan, extra=extra)
    summarize(logdir)


if __name__ == "__main__":
    main()
