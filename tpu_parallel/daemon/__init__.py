"""Durable serving daemon: the long-lived wall-clock process around the
cluster frontend (docs/13_daemon.md).

``daemon/journal.py`` is the write-ahead request journal (append-only
JSONL, sequence numbers, batched fsync) and its crash-recovery replay;
``daemon/daemon.py`` is the shell — recovery, dedupe-token idempotence,
the SIGTERM/SIGHUP signal contract and the tick pump; ``daemon/http.py``
is the stdlib HTTP + SSE network face; ``daemon/wallclock.py`` is the
ONE place in the serving stack allowed to read real time
(``scripts/check_clock.py`` enforces it).
"""

from tpu_parallel.daemon.daemon import (
    DAEMON_TRACK,
    EXIT_CLEAN,
    EXIT_FORCED,
    REJECT_DEGRADED,
    REJECT_JOURNAL,
    DaemonConfig,
    ServingDaemon,
)
from tpu_parallel.daemon.http import DaemonHTTPServer, build_request
from tpu_parallel.daemon.iofaults import (
    IOFaultInjector,
    IOFaultPlan,
)
from tpu_parallel.daemon.journal import (
    CORRUPT_CRC,
    CORRUPT_GARBAGE,
    CORRUPT_SEQ,
    JOURNAL_VERSION,
    REC_DECISION,
    REC_META,
    REC_RECOVERY,
    REC_SHUTDOWN,
    REC_SUBMIT,
    REC_TERMINAL,
    REC_TOKENS,
    JournalCorrupt,
    JournalEntry,
    JournalWriter,
    RecoveryState,
    drop_torn_tail,
    encode_record,
    load_state,
    read_journal,
    record_crc_ok,
    replay_state,
)
from tpu_parallel.daemon.wallclock import WallClock

__all__ = [
    "CORRUPT_CRC",
    "CORRUPT_GARBAGE",
    "CORRUPT_SEQ",
    "DAEMON_TRACK",
    "EXIT_CLEAN",
    "EXIT_FORCED",
    "DaemonConfig",
    "DaemonHTTPServer",
    "IOFaultInjector",
    "IOFaultPlan",
    "JOURNAL_VERSION",
    "JournalCorrupt",
    "JournalEntry",
    "JournalWriter",
    "REC_DECISION",
    "REC_META",
    "REC_RECOVERY",
    "REC_SHUTDOWN",
    "REC_SUBMIT",
    "REC_TERMINAL",
    "REC_TOKENS",
    "REJECT_DEGRADED",
    "REJECT_JOURNAL",
    "RecoveryState",
    "ServingDaemon",
    "WallClock",
    "build_request",
    "drop_torn_tail",
    "encode_record",
    "load_state",
    "read_journal",
    "record_crc_ok",
    "replay_state",
]
