"""Runtime bootstrap: device discovery, multi-host init, CPU device simulation.

Capability parity target: ``util.py:31-38`` (``sim_multiCPU_dev``) in the
reference, which fakes an N-device machine by appending
``--xla_force_host_platform_device_count`` to ``XLA_FLAGS``.  The reference
version is broken (uses ``os`` without importing it) and fragile (mutates the
env *after* ``import jax``).  This module makes the ordering explicit and adds
the two things the reference never had: a real multi-host bootstrap
(``jax.distributed.initialize``) and introspection helpers for the process
topology.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

logger = logging.getLogger("tpu_parallel")

_SIMULATED = False


def simulate_cpu_devices(num_devices: int = 8) -> None:
    """Present ``num_devices`` virtual CPU devices to JAX in this process.

    Every collective, ``shard_map``, and mesh then behaves exactly as on a real
    multi-chip slice, single-process — the canonical JAX trick for testing
    parallelism without hardware.

    Must run before the first touch of the JAX CPU backend (first
    ``jax.devices()`` / compilation).  Works both before and after
    ``import jax``:

    - ``XLA_FLAGS`` is read by the CPU PJRT client at *backend* init, not at
      import, so setting it here is safe as long as no backend exists yet.
    - If ``jax`` is already imported with another platform selected (e.g. a
      TPU plugin chose itself via ``JAX_PLATFORMS``), we also flip
      ``jax_platforms`` to ``cpu`` through the config system, which — unlike
      mutating ``os.environ`` — still takes effect post-import.
    """
    global _SIMULATED
    flag = f"--xla_force_host_platform_device_count={num_devices}"
    prev = os.environ.get("XLA_FLAGS", "")
    # Replace any stale device-count flag rather than deferring to it.
    kept = [
        f for f in prev.split() if "xla_force_host_platform_device_count" not in f
    ]
    os.environ["XLA_FLAGS"] = " ".join(kept + [flag])
    os.environ["JAX_PLATFORMS"] = "cpu"

    import jax

    jax.config.update("jax_platforms", "cpu")

    # Post-condition, not an assert (must survive `python -O`): if another
    # backend was already initialized, the config update above silently has
    # no effect and every later mesh/reshape error would be obscure.  Local
    # devices, so the check is also correct under multi-process fakes
    # (jax.devices() is global across processes).
    devices = jax.local_devices()
    if devices[0].platform != "cpu" or len(devices) != num_devices:
        raise RuntimeError(
            f"simulate_cpu_devices({num_devices}) failed: backend is "
            f"{len(devices)} x {devices[0].platform!r} — a JAX backend was "
            "initialized before this call (it must run before the first "
            "jax.devices()/compilation in the process)"
        )
    _SIMULATED = True


def is_simulated() -> bool:
    return _SIMULATED


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Bootstrap the distributed runtime.

    - Single-process (one host, any number of local chips): no-op.
    - TPU pod / multi-host: calls ``jax.distributed.initialize``.  On Cloud TPU
      VMs all three arguments are auto-detected from the metadata server, so
      ``initialize()`` with no arguments is the common path; the explicit
      arguments cover manual (e.g. DCN-spanning) launches.

    The reference has no equivalent — it never leaves one process
    (``util.py:31-38`` is its whole runtime layer).
    """
    env_procs = os.environ.get("TPU_PROCESS_COUNT") or os.environ.get("JAX_NUM_PROCESSES")
    multi = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
        or (env_procs is not None and int(env_procs) > 1)
    )
    if not multi:
        logger.debug("single-process runtime; skipping jax.distributed.initialize")
        return
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def enable_compilation_cache(cache_dir: str = "~/.cache/tpu_parallel_xla") -> str:
    """Persist XLA compilations across processes (first TPU compile of the
    125M step is 20-40s; a warm cache makes re-runs near-instant).

    Safe to call any time before the first compilation; returns the resolved
    cache path, or "" when the cache stays off.  Off in two cases:
    ``TPU_PARALLEL_NO_COMPILE_CACHE=1`` (manual escape hatch), and
    remote-compile transports (``PALLAS_AXON_REMOTE_COMPILE=1``), where
    persisting the large unrolled-layer gpt2_125m executable was observed to
    stall the process indefinitely before the first step — on those, a
    ~2-minute cold compile is the reliable price.  Normal TPU VMs (local
    XLA compile) keep the cache.
    """
    import jax

    if os.environ.get("TPU_PARALLEL_NO_COMPILE_CACHE", "") == "1":
        return ""
    if os.environ.get("PALLAS_AXON_REMOTE_COMPILE", "") == "1":
        return ""
    path = os.path.expanduser(cache_dir)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    # cache every program that took meaningful compile time, however small
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    return path


def process_info() -> dict:
    """Topology snapshot for logging: process index/count, device counts."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
        "platform": jax.devices()[0].platform,
    }
