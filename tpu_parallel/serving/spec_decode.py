"""Speculative decoding: draft-verify multi-token decode, exactly.

Decode is the serving hot path (DECODE_r05: ~95% of e2e after the prefill
fast path) and a single-token step is memory-bound — the whole model's
weights stream through HBM to produce ONE token per row.  Speculative
decoding (Leviathan et al. 2023) buys back that bandwidth: a cheap DRAFTER
proposes K tokens per row, one forward scores all K (+1 bonus position)
through the multi-token ``write_index`` scatter the chunked prefill
already built (:func:`~tpu_parallel.models.generate.verify_step`), and an
acceptance rule keeps the longest exact prefix — the output token stream
is PROVABLY identical to non-speculative decoding:

- greedy: accept drafts while they equal the verify argmax chain, then
  append the argmax at the first mismatch (the "bonus" token).  Every
  emitted token is the argmax the sequential loop would have produced —
  bitwise parity (:func:`greedy_verify`).
- sampled: the Leviathan rejection rule (:func:`rejection_verify`).  The
  drafter here is DETERMINISTIC (a point mass q), so draft ``d`` is
  accepted with probability ``p(d)`` under the target distribution ``p``
  (temperature / top-k / top-p filtered), and a rejection resamples from
  the residual ``p`` with ``d`` zeroed out, renormalized — the marginal
  of every emitted token is exactly ``p`` (unit-pinned in
  ``tests/test_spec_decode.py``), though the realized sequence differs
  from the non-spec engine's (different RNG consumption).

Rejection needs NO cache rollback: rejected drafts' K/V sit at columns
beyond the accepted frontier, where the engine's aligned layout
(column == stored position; ``CachePool.assert_slot_aligned``) keeps them
masked until the next verify overwrites them.

The drafter is pluggable (:class:`Drafter`); the default
:class:`NGramDrafter` is MODEL-FREE prompt-lookup drafting (Saxena 2023):
propose the continuation of the most recent earlier occurrence of the
context's longest matching suffix n-gram.  Zero extra FLOPs/HBM, exact by
construction (a bad draft only wastes verify positions), and strongest
exactly where decode is longest — repetitive/structured continuations
(code, extraction, summaries quoting the prompt, greedy cycles).

:func:`generate_speculative` is the standalone batch loop (host-side
drafting around jitted verify ticks) so ``scripts/decode_bench.py`` can
measure the path without the serving engine; the engine's spec tick
(``ServingEngine`` with ``draft_tokens > 0``) shares every device
function with it.
"""

from __future__ import annotations

from typing import List, Optional, Protocol, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class Drafter(Protocol):
    """Anything that proposes draft tokens for one request.

    ``draft(context, k)`` sees the request's full token history (prompt +
    everything generated so far, INCLUDING the current token whose K/V is
    not yet written) and returns up to ``k`` proposed continuation tokens
    (possibly none).  Host-side and per-slot — drafters may be stateful.
    A wrong draft can never corrupt output (the verify rule rejects it);
    it only wastes verify positions.
    """

    def draft(self, context: Sequence[int], k: int) -> List[int]:
        ...


class NGramDrafter:
    """Model-free prompt-lookup drafting: find the most recent earlier
    occurrence of the context's suffix n-gram (longest n first, down to
    ``min_ngram``) and propose the tokens that followed it.

    Deterministic and CPU-only — no second model, no device work.  On
    repetitive continuations (greedy cycles, code, quote-heavy answers)
    acceptance approaches 1 and decode emits ~K+1 tokens per forward; on
    novel text it proposes nothing (or garbage that verify rejects) and
    decode degenerates gracefully to the single-token path.
    """

    def __init__(self, max_ngram: int = 3, min_ngram: int = 1):
        if not 1 <= min_ngram <= max_ngram:
            raise ValueError(
                f"need 1 <= min_ngram ({min_ngram}) <= max_ngram ({max_ngram})"
            )
        self.max_ngram = max_ngram
        self.min_ngram = min_ngram

    def draft(self, context: Sequence[int], k: int) -> List[int]:
        if k <= 0:
            return []
        ctx = list(context)
        length = len(ctx)
        for n in range(min(self.max_ngram, length - 1), self.min_ngram - 1, -1):
            pattern = ctx[length - n:]
            # most recent earlier occurrence wins (locality: recent
            # repetition predicts the continuation better than old)
            for s in range(length - n - 1, -1, -1):
                if ctx[s:s + n] == pattern:
                    cont = ctx[s + n: s + n + k]
                    if cont:
                        return cont
        return []


def filter_logits(
    logits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Per-row sampling filters over [rows, vocab] fp32 logits with traced
    per-row knobs — the shared filter core of ``engine.sample_tokens`` and
    the rejection rule's target distribution.  Semantics identical to the
    static ``models.generate._sample``: temperature scale first, top-k and
    top-p compose by intersection, the argmax always survives the nucleus
    cut; ``top_k <= 0`` / ``top_p`` outside (0, 1) disable that filter.
    Greedy rows (``temperature <= 0``) get a guarded divide — callers take
    the argmax branch and never read their filtered values.
    """
    lf = logits.astype(jnp.float32)
    t = jnp.where(temperature > 0.0, temperature, 1.0)[:, None]
    x = lf / t
    vocab = x.shape[-1]
    # per-row top-k with traced k: the kth-largest value via one sort
    k = jnp.clip(top_k.astype(jnp.int32), 0, vocab)
    asc = jnp.sort(x, axis=-1)
    kth = jnp.take_along_axis(
        asc, jnp.clip(vocab - k, 0, vocab - 1)[:, None], axis=-1
    )
    x = jnp.where((k > 0)[:, None] & (x < kth), -jnp.inf, x)
    # per-row nucleus on the (already top-k-filtered) distribution
    desc = jnp.sort(x, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_p[:, None]  # mass BEFORE the token < p
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    use_p = ((top_p > 0.0) & (top_p < 1.0))[:, None]
    return jnp.where(use_p & (x < cutoff), -jnp.inf, x)


def target_probs(
    logits: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """The verify target distribution p at every offset: [n, T, vocab]
    logits + per-ROW knobs -> filtered, normalized probabilities (fp32).
    Row knobs broadcast over the row's T offsets (one request, one knob
    set, K+1 scored positions)."""
    n, t, vocab = logits.shape
    flat = filter_logits(
        logits.astype(jnp.float32).reshape(n * t, vocab),
        jnp.repeat(temperature, t),
        jnp.repeat(top_k, t),
        jnp.repeat(top_p, t),
    )
    return jax.nn.softmax(flat, axis=-1).reshape(n, t, vocab)


def _leading_accepts(ok: jax.Array) -> jax.Array:
    """Length of the leading all-True prefix per row of a [n, K] bool
    mask — the accepted-draft count (acceptance stops at the first
    rejection; later lucky matches must not count)."""
    return jnp.sum(jnp.cumprod(ok.astype(jnp.int32), axis=1), axis=1)


def _emit(drafts: jax.Array, accepted: jax.Array, bonus: jax.Array):
    """Assemble the emitted-token block [n, K+1]: offsets < accepted carry
    the accepted drafts, offset ``accepted`` the bonus token; later
    offsets repeat the bonus (unread — callers deliver accepted+1)."""
    n, k = drafts.shape
    ext = jnp.concatenate(
        [drafts, jnp.zeros((n, 1), drafts.dtype)], axis=1
    )
    iota = jnp.arange(k + 1, dtype=jnp.int32)[None, :]
    return jnp.where(iota < accepted[:, None], ext, bonus[:, None])


def greedy_verify(
    drafts: jax.Array, draft_len: jax.Array, targets: jax.Array
) -> Tuple[jax.Array, jax.Array]:
    """Greedy acceptance: longest draft prefix matching the verify argmax
    chain, plus the argmax at the first mismatch as the bonus token.

    ``drafts`` [n, K] (pads beyond ``draft_len`` ignored), ``targets``
    [n, K+1] = argmax of the verify logits at each offset (``targets[:,i]``
    is the token that FOLLOWS offset ``i``'s input token).  Returns
    ``(tokens [n, K+1], accepted [n])`` — ``accepted + 1`` tokens emit per
    row, every one bitwise equal to what sequential greedy decode would
    have produced (accepted drafts equal their targets by construction;
    the bonus IS the target at the cut).
    """
    n, k = drafts.shape
    iota = jnp.arange(k, dtype=jnp.int32)[None, :]
    ok = (drafts == targets[:, :k]) & (iota < draft_len[:, None])
    accepted = _leading_accepts(ok)
    bonus = jnp.take_along_axis(targets, accepted[:, None], axis=1)[:, 0]
    return _emit(drafts, accepted, bonus), accepted


def rejection_verify(
    drafts: jax.Array,
    draft_len: jax.Array,
    probs: jax.Array,
    rng: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Leviathan rejection sampling specialized to a DETERMINISTIC drafter
    (q = point mass on the draft): accept ``d_i`` with probability
    ``p_i(d_i)``; at the first rejection sample the bonus from the
    residual ``p_i`` with ``d_i`` zeroed, renormalized; with every draft
    accepted, sample the bonus from the next distribution unmodified.

    ``probs`` [n, K+1, vocab] are the filtered target distributions
    (:func:`target_probs`).  Marginal of each emitted token is exactly the
    target distribution — speculative sampling changes WHEN tokens are
    produced, never their law.  Returns ``(tokens [n, K+1], accepted [n])``.
    """
    n, k = drafts.shape
    r_accept, r_bonus = jax.random.split(rng)
    if k > 0:
        u = jax.random.uniform(r_accept, (n, k))
        p_draft = jnp.take_along_axis(
            probs[:, :k, :], drafts[..., None], axis=-1
        )[..., 0]
        iota = jnp.arange(k, dtype=jnp.int32)[None, :]
        ok = (u < p_draft) & (iota < draft_len[:, None])
        accepted = _leading_accepts(ok)
    else:
        accepted = jnp.zeros((n,), jnp.int32)
    row_p = jnp.take_along_axis(
        probs, accepted[:, None, None], axis=1
    )[:, 0]  # [n, vocab]: the distribution at the cut
    if k > 0:
        # zero the rejected draft out of the residual — only when the cut
        # IS a rejection (accepted < draft_len), not a fully-accepted
        # block whose bonus draws from the next distribution whole
        rejected = jnp.take_along_axis(
            drafts, jnp.clip(accepted, 0, k - 1)[:, None], axis=1
        )[:, 0]
        cut_is_rejection = accepted < draft_len
        mask = jax.nn.one_hot(rejected, probs.shape[-1], dtype=row_p.dtype)
        resid = row_p * (1.0 - mask * cut_is_rejection[:, None])
        norm = resid.sum(axis=-1, keepdims=True)
        # p(d) ~ 1 makes rejection near-impossible; if fp still lands here
        # with an empty residual, falling back to row_p keeps the sample
        # valid (measure-zero event)
        row_p = jnp.where(norm > 0, resid / jnp.maximum(norm, 1e-30), row_p)
    bonus_logits = jnp.where(row_p > 0, jnp.log(jnp.maximum(row_p, 1e-30)),
                             -jnp.inf)
    bonus = jax.random.categorical(r_bonus, bonus_logits, axis=-1).astype(
        jnp.int32
    )
    return _emit(drafts, accepted, bonus), accepted


def verify_tokens(
    drafts: jax.Array,
    draft_len: jax.Array,
    logits: jax.Array,
    rng: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Per-row acceptance over one verify forward's logits [n, K+1, vocab]
    with per-row sampling knobs: greedy rows (``temperature <= 0``) take
    :func:`greedy_verify` on the raw argmax chain (bitwise parity with
    sequential decode), sampled rows :func:`rejection_verify` on the
    filtered target distributions.  Returns ``(tokens [n, K+1],
    accepted [n])``; callers emit ``accepted + 1`` tokens per row.
    """
    lf = logits.astype(jnp.float32)
    g_tokens, g_acc = greedy_verify(drafts, draft_len,
                                    jnp.argmax(lf, axis=-1).astype(jnp.int32))
    greedy = temperature <= 0.0

    def sampled(_):
        probs = target_probs(lf, temperature, top_k, top_p)
        s_tokens, s_acc = rejection_verify(drafts, draft_len, probs, rng)
        return (
            jnp.where(greedy[:, None], g_tokens, s_tokens),
            jnp.where(greedy, g_acc, s_acc),
        )

    # an all-greedy pool (the common serving case) skips the rejection
    # path's [n*(K+1), vocab] sorts entirely at runtime — on CPU they cost
    # more than the verify forward itself
    tokens, accepted = lax.cond(
        jnp.any(~greedy), sampled, lambda _: (g_tokens, g_acc), None
    )
    return tokens.astype(jnp.int32), accepted


def ngram_draft_tokens(
    history: jax.Array,
    length: jax.Array,
    cap: jax.Array,
    k: int,
    max_ngram: int = 3,
    min_ngram: int = 1,
) -> Tuple[jax.Array, jax.Array]:
    """DEVICE prompt-lookup drafting — the traceable twin of
    :class:`NGramDrafter`, token-for-token identical by construction
    (pinned in ``tests/test_spec_decode.py``): longest suffix n-gram
    first (``max_ngram`` down to ``min_ngram``), most recent earlier
    occurrence wins, propose up to ``cap`` following tokens.

    This is what lets the FUSED speculative tick run ``T`` draft-verify
    blocks inside one ``lax.scan``: block ``t+1``'s context includes
    block ``t``'s accepted tokens, which live only on device mid-scan —
    a host drafter would force one dispatch + one sync per block, the
    exact per-step tax the fused tick exists to kill.  The token
    ``history`` [rows, L] rides the scan carry (the engine re-uploads it
    only on admission, like the rest of the slot state).

    ``length`` [rows] is each row's live context length (prompt +
    generated, INCLUDING the current unwritten token — the same context
    :meth:`NGramDrafter.draft` sees); ``cap`` [rows] the per-row draft
    budget (:func:`draft_for_row`'s clamp, computed by the caller;
    ``<= 0`` drafts nothing).  Entries of ``history`` at or beyond
    ``length`` are never read.  Returns ``(drafts [rows, k], dlen
    [rows])`` with drafts zero-padded beyond ``dlen`` — byte-identical
    to the engine's host-side draft block layout.
    """
    if k < 1:
        raise ValueError(f"k={k} < 1")
    if not 1 <= min_ngram <= max_ngram:
        raise ValueError(
            f"need 1 <= min_ngram ({min_ngram}) <= max_ngram ({max_ngram})"
        )
    L = history.shape[-1]

    def one_row(hist, hlen, kcap):
        iota = jnp.arange(L, dtype=jnp.int32)
        drafts = jnp.zeros((k,), jnp.int32)
        dlen = jnp.zeros((), jnp.int32)
        found = jnp.zeros((), bool)
        # static unroll over the (tiny) n-gram size ladder: largest g
        # with any match wins, exactly like the host drafter's outer loop
        for g in range(max_ngram, min_ngram - 1, -1):
            ok_g = (kcap > 0) & (g <= hlen - 1)
            sfx = hist[jnp.clip(hlen - g + jnp.arange(g), 0, L - 1)]
            match = jnp.ones((L,), bool)
            for j in range(g):
                at = jnp.clip(iota + j, 0, L - 1)
                match = match & (hist[at] == sfx[j]) & (iota + j < L)
            # s <= hlen - g - 1 keeps the continuation nonempty (the host
            # drafter's `if cont` can only be empty at s == hlen - g,
            # which its range already excludes)
            match = match & (iota <= hlen - g - 1) & ok_g
            s = jnp.max(jnp.where(match, iota, -1))
            hit = s >= 0
            cont = hist[jnp.clip(s + g + jnp.arange(k), 0, L - 1)]
            take = jnp.where(hit, jnp.minimum(kcap, hlen - (s + g)), 0)
            use = hit & ~found
            drafts = jnp.where(use, cont, drafts)
            dlen = jnp.where(use, take, dlen)
            found = found | hit
        # zero-pad beyond dlen — the host block layout (np.zeros + fill)
        drafts = jnp.where(jnp.arange(k) < dlen, drafts, 0)
        return drafts, dlen

    return jax.vmap(one_row)(
        history,
        jnp.asarray(length, jnp.int32),
        jnp.asarray(cap, jnp.int32),
    )


def adapt_draft_len_traced(
    k: jax.Array, drafted: jax.Array, accepted: jax.Array, k_max: jax.Array,
) -> jax.Array:
    """Traceable :func:`adapt_draft_len` (k_min fixed at 1) — the fused
    spec tick's in-scan per-slot adaptation, same grow/shrink law so the
    fused and per-step engines ride identical draft-length trajectories."""
    grown = jnp.minimum(k + 1, k_max)
    shrunk = jnp.maximum(1, accepted + 1)
    adapted = jnp.where(accepted >= drafted, grown, shrunk)
    return jnp.where(drafted <= 0, k, adapted)


def draft_for_row(
    drafter: Drafter,
    context: Sequence[int],
    k_eff: int,
    write_index: int,
    seq_len: int,
    remaining: int,
) -> List[int]:
    """One row's draft block, safety-capped — THE shared clamp of the
    engine's spec tick and :func:`generate_speculative` (two hand-synced
    copies would let the paths silently diverge).

    The cap is correctness-critical on two sides: ``seq_len - 1 - widx``
    keeps every REAL draft's cache write in range (a dropped write would
    silently lose a scored position), and ``remaining - 1`` keeps a block
    (accepted + bonus) from overshooting the request's token budget.
    Returns at most ``k_eff`` drafted tokens, possibly none.
    """
    cap = min(int(k_eff), seq_len - 1 - int(write_index), remaining - 1)
    if cap <= 0:
        return []
    return list(drafter.draft(context, cap))[:cap]


def adapt_draft_len(k: int, drafted: int, accepted: int, k_max: int,
                    k_min: int = 1) -> int:
    """Acceptance-adaptive draft length: grow by one after a fully-accepted
    block, shrink to just past the acceptance point otherwise.  Bounded in
    [k_min, k_max]; a tick that drafted nothing teaches nothing.  The
    VERIFY program shape never changes (the engine pads every block to its
    compiled K_max width) — adaptation only trims how many real drafts
    ride it, trading wasted verify positions against capture of long runs.
    """
    if drafted <= 0:
        return k
    if accepted >= drafted:
        return min(k + 1, k_max)
    return max(k_min, accepted + 1)


def generate_speculative(
    model,
    params,
    prompt: jax.Array,
    rng: Optional[jax.Array] = None,
    *,
    max_new_tokens: int = 32,
    draft_tokens: int = 4,
    drafter: Optional[Drafter] = None,
    adaptive: bool = True,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    return_stats: bool = False,
    registry=None,
):
    """Standalone batch speculative decoding — ``generate()``'s contract
    (returns [batch, max_new_tokens]; greedy output is token-identical,
    pinned in tests) through draft-verify ticks instead of a single-token
    scan.

    The loop is HOST-side (the drafter reads token histories Python-side),
    one jitted :func:`~tpu_parallel.models.generate.verify_step` + accept
    per tick, sharing the engine's compiled functions
    (``serving.engine._engine_fns``) — so ``scripts/decode_bench.py`` can
    measure speculative decode without standing up the engine, and
    ``draft_tokens=0`` degenerates to the engine-style per-token host loop
    (the honest non-spec baseline: the engine cannot use ``generate()``'s
    fused scan, requests join and leave between ticks).  Rows finish at
    their own tick (variable acceptance); finished rows park their cache
    writes out of range exactly like the engine's freed slots.

    With ``return_stats`` also returns ``{"ticks", "drafted", "accepted",
    "acceptance_rate", "tokens_per_tick"}``.  ``registry`` (a
    :class:`~tpu_parallel.obs.registry.MetricRegistry`) additionally
    observes each row-tick's acceptance fraction into the SAME
    ``serving_spec_acceptance_ratio`` histogram the engine's spec tick
    feeds, so standalone decode-bench runs and engine runs export
    comparable acceptance distributions.
    """
    from tpu_parallel.serving.engine import _engine_fns

    cfg = model.config
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds seq_len ({cfg.seq_len})"
        )
    if draft_tokens < 0:
        raise ValueError(f"draft_tokens={draft_tokens} < 0")
    if rng is None:
        rng = jax.random.PRNGKey(0)
    if drafter is None:
        drafter = NGramDrafter()
    prefill_fn, _, _, verify_fn, sample_fn, _, _ = _engine_fns(model)

    def split():
        nonlocal rng
        rng, sub = jax.random.split(rng)
        return sub

    temp = jnp.full((b,), temperature, jnp.float32)
    tk = jnp.full((b,), top_k, jnp.int32)
    tp = jnp.full((b,), top_p, jnp.float32)
    positions = jnp.broadcast_to(
        jnp.arange(prompt_len, dtype=jnp.int32), (b, prompt_len)
    )
    logits, cache = prefill_fn(
        params, prompt.astype(jnp.int32), positions,
        jnp.full((b,), prompt_len - 1, jnp.int32), split(),
    )
    first = np.asarray(sample_fn(logits, split(), temp, tk, tp))
    prompts_host = [
        [int(t) for t in row] for row in np.asarray(prompt)
    ]  # plain int lists ONCE — not np-scalar conversion per tick
    out: List[List[int]] = [[int(first[r])] for r in range(b)]
    tok = first.astype(np.int32)
    pos = np.full(b, prompt_len, np.int32)
    widx = np.full(b, prompt_len, np.int32)
    kmax = draft_tokens
    k_eff = np.full(b, max(kmax, 0), np.int32)
    ticks = drafted_total = accepted_total = 0
    acceptance_hist = (
        registry.histogram("serving_spec_acceptance_ratio")
        if registry is not None
        else None
    )

    while any(len(t) < max_new_tokens for t in out):
        drafts = np.zeros((b, kmax), np.int32)
        dlen = np.zeros(b, np.int32)
        for r in range(b):
            rem = max_new_tokens - len(out[r])
            if rem <= 0:
                widx[r] = cfg.seq_len  # park: finished rows write nothing
                continue
            d = draft_for_row(
                drafter, prompts_host[r] + out[r], int(k_eff[r]),
                int(widx[r]), cfg.seq_len, rem,
            )
            dlen[r] = len(d)
            drafts[r, : len(d)] = d
        block, accepted, cache = verify_fn(
            params, jnp.asarray(tok), jnp.asarray(drafts),
            jnp.asarray(dlen), jnp.asarray(pos), jnp.asarray(widx),
            temp, tk, tp, cache, split(),
        )
        # one sync per verify tick — the tick boundary, not per slot
        block, accepted = np.asarray(block), np.asarray(accepted)  # host-sync: tick-boundary
        ticks += 1
        for r in range(b):
            if len(out[r]) >= max_new_tokens:
                continue
            a = int(accepted[r])
            out[r].extend(int(t) for t in block[r, : a + 1])
            tok[r] = int(block[r, a])
            pos[r] += a + 1
            widx[r] += a + 1
            drafted_total += int(dlen[r])
            accepted_total += a
            if acceptance_hist is not None and int(dlen[r]) > 0:
                acceptance_hist.observe(a / int(dlen[r]))
            if adaptive and kmax > 0:
                k_eff[r] = adapt_draft_len(
                    int(k_eff[r]), int(dlen[r]), a, kmax
                )
    tokens = jnp.asarray(
        [row[:max_new_tokens] for row in out], jnp.int32
    )
    if not return_stats:
        return tokens
    # tokens emitted BY verify ticks (each row's first token came from the
    # prefill sample, not a tick)
    emitted = int(sum(len(row[:max_new_tokens]) for row in out)) - b
    stats = {
        "ticks": ticks,
        "drafted": drafted_total,
        "accepted": accepted_total,
        "acceptance_rate": (
            round(accepted_total / drafted_total, 4) if drafted_total else None
        ),
        "tokens_per_tick": round(emitted / max(ticks, 1), 3),
    }
    return tokens, stats
