"""FSDP / ZeRO-3 parameter sharding over a mesh axis.

Capability parity: ``param_sharding.py`` in the reference — each sufficiently
large parameter lives sliced 1/N-per-device along one of its own axes; the
full weight is materialized just-in-time for compute with an ``all_gather``
whose backward pass is a ``psum_scatter`` (reduce-scatter), so gradients are
never all-reduced at full size.  Reference cites: shard/gather transforms
``param_sharding.py:58-191``, custom gradient ``:129-142``, partition-aware
grad sync ``:293-322``, two-phase eval_shape init ``:253-274``.

Rebuilt here with the reference's latent bugs fixed (SURVEY.md §2.4 #6-#10)
and generalized to multi-axis meshes: a parameter may be sharded over the
``data`` axis (FSDP) *and* carry tensor/pipeline partitioning on other axes —
``sync_gradients`` means each gradient only over the axes its parameter is
**not** partitioned on.
"""

from __future__ import annotations

import functools
import logging
from typing import Any, Callable, Sequence, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_parallel.core.metrics import pvary_missing

logger = logging.getLogger("tpu_parallel")

Pytree = Any

# Parameters smaller than this stay replicated: the all-gather latency would
# cost more than the memory saved (reference default: param_sharding.py:60).
DEFAULT_MIN_WEIGHT_SIZE = 2**18


@jax.named_scope("shard_params")
def shard_params(
    params: Pytree, axis_name: str, min_weight_size: int = DEFAULT_MIN_WEIGHT_SIZE
) -> Pytree:
    """Slice each large parameter 1/N along one of its dims over ``axis_name``.

    Runs inside a ``shard_map`` region.  For each leaf, the largest dim that
    divides the axis size evenly and is not already partitioned is chosen;
    the local slice is taken with ``dynamic_slice_in_dim`` at this device's
    axis index, and the leaf is wrapped in ``nn.Partitioned`` so partition
    specs can later be read off with ``nn.get_partition_spec``.

    Identity when ``axis_name`` is unbound (no mesh): an FSDP-configured
    model then runs on plain single-device params — same degrade-gracefully
    contract as the structural-TP layers (``tp.axis_size_or_none``), so
    ``export_single_device_params`` output serves directly.
    """
    from tpu_parallel.parallel.tp import axis_size_or_none

    if axis_size_or_none(axis_name) is None:
        return params
    axis_idx = lax.axis_index(axis_name)
    axis_size = lax.psum(1, axis_name)

    def split(x: Union[nn.Partitioned, jax.Array]):
        if isinstance(x, nn.Partitioned):
            value, names = x.value, list(x.names)
        else:
            value, names = x, [None] * x.ndim
        if axis_name in names:
            logger.warning(
                "parameter %s already partitioned on %s; skipping", value.shape, axis_name
            )
            return x
        if value.size <= min_weight_size:
            return x
        # Prefer the largest dim for an even 1/N split.
        order = np.argsort(value.shape)[::-1]
        for dim in order:
            dim = int(dim)
            if value.shape[dim] % axis_size == 0 and names[dim] is None:
                shard_size = value.shape[dim] // axis_size
                local = lax.dynamic_slice_in_dim(
                    value, axis_idx * shard_size, shard_size, axis=dim
                )
                names[dim] = axis_name
                return nn.Partitioned(local, names=tuple(names))
        logger.warning(
            "could not shard parameter of shape %s over axis %s: "
            "no dim divisible; keeping replicated",
            value.shape,
            axis_name,
        )
        return x

    return jax.tree_util.tree_map(
        split, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def _gather_with_scattered_grad(x: jax.Array, axis_name: str, axis: int) -> jax.Array:
    """All-gather ``x`` along ``axis``; backward is a mean reduce-scatter.

    The custom gradient is the heart of ZeRO-3: the forward materializes the
    full weight (``all_gather`` rides ICI), and the cotangent — which is the
    *summed* gradient of the full weight across the data axis — comes back as
    each device's 1/N slice via ``psum_scatter``, divided by N to make the
    DP-mean convention line up with replicated parameters.
    """

    @jax.custom_gradient
    def gather(p):
        def grad_fn(g):
            with jax.named_scope("fsdp_grad_reduce_scatter"):
                return (
                    lax.psum_scatter(
                        g, axis_name, scatter_dimension=axis, tiled=True
                    )
                    / lax.psum(1, axis_name)
                )

        with jax.named_scope("fsdp_weight_all_gather"):
            full = lax.all_gather(p, axis_name, axis=axis, tiled=True)
        return full, grad_fn

    return gather(x)


@jax.named_scope("gather_params")
def gather_params(params: Pytree, axis_name: str) -> Pytree:
    """Materialize full weights from their 1/N shards for compute.

    Identity when ``axis_name`` is unbound (see :func:`shard_params`) —
    exported single-device params are already full."""
    from tpu_parallel.parallel.tp import axis_size_or_none

    if axis_size_or_none(axis_name) is None:
        return params

    def gather(p):
        if isinstance(p, nn.Partitioned) and axis_name in p.names:
            axis = p.names.index(axis_name)
            value = _gather_with_scattered_grad(p.value, axis_name, axis)
            names = tuple(n if i != axis else None for i, n in enumerate(p.names))
            if any(n is not None for n in names):
                return nn.Partitioned(value, names=names)
            return value
        return p

    return jax.tree_util.tree_map(
        gather, params, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )


def maybe_shard(target: Union[nn.Module, Callable], config):
    """FSDP-wrap ``target`` iff ``config.fsdp`` — the one place the
    axis/min-size plumbing lives (callers: Block stack, embeddings, lm_head;
    a site that skips this wrap silently leaves its module replicated)."""
    if getattr(config, "fsdp", False):
        return shard_module_params(target, config.data_axis, config.fsdp_min_size)
    return target


def shard_module_params(
    target: Union[nn.Module, Callable],
    axis_name: str,
    min_weight_size: int = DEFAULT_MIN_WEIGHT_SIZE,
):
    """Wrap a module (or module class) so its params live FSDP-sharded.

    Uses ``nn.map_variables``: parameters are gathered on the way *into*
    compute and re-sharded on the way *out* (init writes shards), so the
    module body never knows it is sharded.  Mirrors the intent of
    ``param_sharding.py:179-191`` with the config-nesting bugs fixed.
    """
    return nn.map_variables(
        target,
        trans_in_fn=functools.partial(gather_params, axis_name=axis_name),
        trans_out_fn=functools.partial(
            shard_params, axis_name=axis_name, min_weight_size=min_weight_size
        ),
        mapped_collections="params",
        mutable=True,
    )


@jax.named_scope("sync_gradients")
def sync_gradients(
    grads: Pytree,
    axis_names: Union[str, Sequence[str]],
    psum_axes: Union[str, Sequence[str]] = (),
    replicated_loss_axes: Union[str, Sequence[str]] = ("model",),
) -> Pytree:
    """Reduce each gradient over exactly the axes its param is replicated on.

    Per-rank shard_map gradients obey ``g_r = d(sum_over_ranks L_s)/d theta_r``
    (collective transposes route every rank's loss cotangent into every rank's
    backward).  Syncing therefore depends on how the loss relates to each axis:

    - Gradients of **replicated** parameters are pmean-ed over ``axis_names``
      (reference ``param_sharding.py:293-322``) and psum-ed over ``psum_axes``
      (axes where ranks hold disjoint gradient *pieces* — e.g. the pipe axis,
      where the loss lives on the last stage only).
    - A parameter **partitioned** on a data-style axis is already per-device
      correct there (FSDP's gather backward does psum_scatter/axis_size);
      reducing again would be wrong.
    - A parameter partitioned on an axis where the loss is *computed
      redundantly by every rank* (``replicated_loss_axes`` — the tensor/expert
      -parallel axis: all ranks hold the same tokens and the same loss value)
      comes out exactly axis_size too large: the backward sums axis_size
      identical loss cotangents, and no collective divides them back down.
      Those gradients are divided by the axis size here.  (Empirically pinned
      by ``tests/test_tp.py::test_tp_training_grads_match_dense`` and
      ``tests/test_moe.py::test_moe_ep_gradients_match_single_device``.)
    """
    if isinstance(axis_names, str):
        axis_names = (axis_names,)
    if isinstance(psum_axes, str):
        psum_axes = (psum_axes,)
    if isinstance(replicated_loss_axes, str):
        replicated_loss_axes = (replicated_loss_axes,)

    def sync(g):
        # pvary_missing: a gradient that is provably identical across an axis
        # (invarying under check_vma) must be promoted before reducing over
        # it — same values, same result, but the types line up
        if isinstance(g, nn.Partitioned):
            mean_axes = [a for a in axis_names if a not in g.names]
            sum_axes = [a for a in psum_axes if a not in g.names]
            div_axes = [
                a for a in replicated_loss_axes if a in g.names and a in axis_names
            ]
            value = g.value
            if mean_axes:
                value = lax.pmean(pvary_missing(value, mean_axes), mean_axes)
            if sum_axes:
                value = lax.psum(pvary_missing(value, sum_axes), sum_axes)
            for a in div_axes:
                value = value / jnp.asarray(lax.psum(1, a), value.dtype)
            return g.replace(value=value)
        g = lax.pmean(pvary_missing(g, axis_names), axis_names)
        if psum_axes:
            g = lax.psum(pvary_missing(g, psum_axes), psum_axes)
        return g

    return jax.tree_util.tree_map(
        sync, grads, is_leaf=lambda x: isinstance(x, nn.Partitioned)
    )
