"""Replicated serving cluster: a router + admission-control frontend
driving N continuous-batching engine replicas with a fault-tolerant
request lifecycle (docs/12_cluster.md).

``Frontend.submit()/step()/drain()`` is the whole surface: pluggable
routing (round-robin / least-loaded / prefix-affinity consistent
hashing), token-budget backpressure with typed rejections, priority
classes with anti-starvation aging, per-request deadlines that cancel
in-engine work, and replica-death retries that replay delivered tokens
as a forced prefix so streamed output stays exactly consistent.
"""

from tpu_parallel.cluster.frontend import (
    ClusterOutput,
    Frontend,
    FrontendConfig,
)
from tpu_parallel.cluster.replica import (
    DEAD,
    DEGRADED,
    HEALTHY,
    FaultPlan,
    ReplicaDead,
    ReplicaHandle,
)
from tpu_parallel.cluster.router import (
    LeastLoadedRouter,
    PrefixAffinityRouter,
    RoundRobinRouter,
    Router,
    least_loaded,
    make_router,
    prefix_route_key,
)

__all__ = [
    "Frontend",
    "FrontendConfig",
    "ClusterOutput",
    "ReplicaHandle",
    "ReplicaDead",
    "FaultPlan",
    "HEALTHY",
    "DEGRADED",
    "DEAD",
    "Router",
    "RoundRobinRouter",
    "LeastLoadedRouter",
    "PrefixAffinityRouter",
    "least_loaded",
    "make_router",
    "prefix_route_key",
]
