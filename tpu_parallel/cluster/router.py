"""Pluggable request routing across serving replicas.

Three policies, one contract: ``route(prompt, candidates)`` returns the
replica to try first (or None when no candidate exists).  ``candidates``
is the frontend's pre-filtered view — alive, accepting, not excluded for
this request — ordered by replica id, so policies stay pure ranking
logic with no health bookkeeping of their own.

- :class:`RoundRobinRouter` — the baseline: cycle the candidate list.
  Ignores load AND locality; every comparison in ``SERVE_r03.json``
  starts here.
- :class:`LeastLoadedRouter` — rank by :meth:`ReplicaHandle.load`
  (queue depth + active slots + discounted pending prefill tokens),
  ties to the lowest replica id.  The right default when prompts share
  nothing.
- :class:`PrefixAffinityRouter` — SGLang-style cache-aware routing:
  consistent-hash the request's BUCKET-ALIGNED prompt prefix onto a
  replica, so repeated prefixes (system prompts, few-shot headers) land
  where that replica's :class:`~tpu_parallel.serving.prefix_cache.
  PrefixCache` already holds their K/V.  Two properties matter and both
  come from the hash RING (not ``hash(prefix) % n``):

  * **Stability under failure** — when a replica dies, only the keys it
    owned move (to their ring successors); every other prefix keeps its
    replica and its warm cache.  Modulo hashing would reshuffle nearly
    everything on any membership change.
  * **Deterministic placement** — positions come from ``sha1``, not
    Python's salted ``hash``, so placement is identical across processes
    and runs (routing tests and multi-frontend deployments see one map).

  Affinity yields to load: when the hash-owner is OVERLOADED (queue
  depth at/over ``overload_queue_depth``), the router falls back to
  least-loaded — a hot prefix must not melt one replica while its peers
  idle.  Fallbacks are counted (``fallbacks``) and surface in the
  frontend's ``cluster_affinity_fallbacks`` gauge.

The prefix key mirrors :meth:`PrefixCache.lookup` alignment: the largest
bucket STRICTLY shorter than the prompt (a full-prompt hit can't exist —
the first sampled token needs the last real token's forward pass), whole
prompt when no bucket is shorter.  Aligning router and cache on the same
boundary is the point: the router's unit of placement is exactly the
cache's unit of reuse.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import List, Optional, Sequence, Tuple

from tpu_parallel.cluster.replica import ReplicaHandle


def prefix_route_key(
    prompt: Sequence[int], buckets: Optional[Sequence[int]]
) -> Tuple[int, ...]:
    """The bucket-aligned placement key for ``prompt``: its largest
    proper bucket-prefix (the longest prefix a :class:`PrefixCache`
    could ever serve), or the whole prompt when every bucket is too
    long / no buckets exist."""
    prompt = tuple(int(t) for t in prompt)
    if buckets:
        for b in sorted(buckets, reverse=True):
            if b < len(prompt):
                return prompt[:b]
    return prompt


def _stable_hash(data: bytes) -> int:
    """Process-stable 64-bit hash (sha1 prefix) — Python's ``hash`` is
    salted per process and would scramble placement every run."""
    return int.from_bytes(hashlib.sha1(data).digest()[:8], "big")


def hash_prompt_key(
    prompt: Sequence[int], buckets: Optional[Sequence[int]]
) -> int:
    """Ring position of a prompt: the stable hash of its bucket-aligned
    prefix key.  One function because TWO ring users must agree on it —
    the in-process :class:`PrefixAffinityRouter` and the fleet router
    placing the same prompt onto daemon processes (a disagreement would
    send a prefix to one replica's cache and its retries to another's)."""
    key = prefix_route_key(prompt, buckets)
    return _stable_hash(
        b"".join(int(t).to_bytes(8, "big", signed=True) for t in key)
    )


class HashRing:
    """The consistent-hash ring itself, transport-agnostic: members are
    any stable ids (in-process replica ints, fleet daemon ``host:port``
    strings), positions come from ``sha1(f"{member}:{vnode}")``, and
    lookups take a precomputed key hash — the ring neither knows nor
    cares what a member or a key IS.

    Extracted from :class:`PrefixAffinityRouter` (which now delegates)
    so the fleet router reuses the exact placement function, weighted
    membership and all: the stability argument — only a joining/leaving
    member's keys move, a down-weighted member keeps its LOWEST vnode
    indices so restored weight restores exactly the keys that left — is
    proven once and inherited everywhere.
    """

    def __init__(self, members, vnodes: int = 64):
        if not members:
            raise ValueError("HashRing needs at least 1 member")
        if vnodes < 1:
            raise ValueError(f"vnodes={vnodes} < 1")
        self.vnodes = vnodes
        self._weights = {m: 1.0 for m in members}
        if len(self._weights) != len(members):
            raise ValueError(f"duplicate ring members in {members!r}")
        self._rebuild()

    def _rebuild(self) -> None:
        ring = []
        for member in sorted(self._weights):
            # a weighted member keeps its LOWEST vnode indices, so
            # raising the weight back restores exactly the keys that
            # left (placement stays a pure function of the weight map)
            n = max(1, int(round(self.vnodes * self._weights[member])))
            for v in range(n):
                ring.append((_stable_hash(f"{member}:{v}".encode()), member))
        ring.sort()
        self._ring_points = [p for p, _ in ring]
        self._ring_members = [m for _, m in ring]

    @property
    def weights(self) -> dict:
        """Current per-member ring weights (1.0 = full vnode share)."""
        return dict(self._weights)

    def __contains__(self, member) -> bool:
        return member in self._weights

    def __len__(self) -> int:
        return len(self._weights)

    def set_weight(self, member, weight: float) -> None:
        """Rebalance: scale one member's share of the ring (0 < w <= 1)."""
        if not 0.0 < weight <= 1.0:
            raise ValueError(f"ring weight {weight} outside (0, 1]")
        if member not in self._weights:
            raise ValueError(f"{member!r} not on the ring")
        self._weights[member] = weight
        self._rebuild()

    def add_member(self, member, weight: float = 1.0) -> None:
        """Join the ring (no-op when already a member) — only keys whose
        nearest point is one of the NEW vnodes move."""
        if not 0.0 < weight <= 1.0:
            raise ValueError(f"ring weight {weight} outside (0, 1]")
        self._weights.setdefault(member, weight)
        self._rebuild()

    def remove_member(self, member) -> None:
        """Leave the ring; the retiree's keys slide to their ring
        successors, everyone else keeps a warm cache."""
        if len(self._weights) <= 1:
            raise ValueError("cannot remove the last ring member")
        self._weights.pop(member, None)
        self._rebuild()

    def owner(self, key_hash: int):
        """The member owning ``key_hash``, ignoring health — the stable
        answer to "where does this key live?"."""
        i = bisect.bisect_right(self._ring_points, key_hash)
        return self._ring_members[i % len(self._ring_members)]

    def walk(self, key_hash: int):
        """Yield DISTINCT members in ring order starting at the key's
        owner — the retry-with-exclusion order: callers take the first
        member that is routable/not excluded, so keys of dead members
        slide to their successors while every other key keeps its home."""
        start = bisect.bisect_right(self._ring_points, key_hash)
        n = len(self._ring_members)
        seen = set()
        for off in range(n):
            member = self._ring_members[(start + off) % n]
            if member not in seen:
                seen.add(member)
                yield member


class Router:
    """Routing-policy contract (and registry of the built-in names)."""

    name = "base"

    def route(
        self,
        prompt: Sequence[int],
        candidates: List[ReplicaHandle],
    ) -> Optional[ReplicaHandle]:
        raise NotImplementedError


class RoundRobinRouter(Router):
    """Cycle through candidates in replica-id order, one per decision."""

    name = "rr"

    def __init__(self):
        self._next = 0

    def route(self, prompt, candidates):
        if not candidates:
            return None
        pick = candidates[self._next % len(candidates)]
        self._next += 1
        return pick


def least_loaded(candidates: List[ReplicaHandle]) -> Optional[ReplicaHandle]:
    if not candidates:
        return None
    return min(candidates, key=lambda h: (h.load(), h.replica_id))


class LeastLoadedRouter(Router):
    """Lowest ``load()`` wins; ties break to the lowest replica id so
    placement is deterministic."""

    name = "least"

    def route(self, prompt, candidates):
        return least_loaded(candidates)


class PrefixAffinityRouter(Router):
    """Consistent-hash placement on the bucket-aligned prompt prefix,
    least-loaded fallback on overload (see the module docstring).

    ``replica_ids`` fixes the INITIAL ring membership (every replica the
    cluster was built with, dead or alive — health never changes the
    ring, only which owners are currently routable).  ``vnodes`` virtual
    nodes per replica smooth the key distribution; 64 keeps per-replica
    share within a few percent of fair for any realistic replica count.

    The ring is additionally WEIGHTED and membership-mutable — the
    cluster autopilot's rebalance/scale actuators: ``set_weight(rid, w)``
    shrinks a hot replica's vnode count to ``round(vnodes * w)`` (its
    HIGHEST-index vnodes are dropped, so every key still owned by a
    surviving vnode keeps its home — the consistent-hashing property the
    ring exists for), and ``add_replica`` / ``remove_replica`` grow and
    shrink membership when the autopilot resizes the fleet (again only
    the joining/leaving replica's keys move).
    """

    name = "prefix"

    def __init__(
        self,
        replica_ids: Sequence[int],
        buckets: Optional[Sequence[int]] = None,
        vnodes: int = 64,
        overload_queue_depth: int = 8,
    ):
        if not replica_ids:
            raise ValueError("PrefixAffinityRouter needs at least 1 replica")
        self.buckets = tuple(buckets) if buckets else None
        self.overload_queue_depth = overload_queue_depth
        self.vnodes = vnodes
        self.fallbacks = 0  # affinity target overloaded -> least-loaded
        self.ring = HashRing([int(rid) for rid in replica_ids], vnodes)

    @property
    def weights(self) -> dict:
        """Current per-replica ring weights (1.0 = full vnode share)."""
        return self.ring.weights

    def set_weight(self, replica_id: int, weight: float) -> None:
        """Rebalance: scale one replica's share of the ring (0 < w <= 1).
        The autopilot halves a hot replica's weight when its load runs
        past ``imbalance_factor`` x the fleet mean, and restores it once
        the fleet is balanced again."""
        try:
            self.ring.set_weight(int(replica_id), weight)
        except ValueError as exc:
            if "not on the ring" in str(exc):
                raise ValueError(
                    f"replica {replica_id} not on the ring"
                ) from None
            raise

    def add_replica(self, replica_id: int, weight: float = 1.0) -> None:
        """Scale-up: join the ring (no-op when already a member) — only
        keys whose nearest point is one of the NEW vnodes move."""
        self.ring.add_member(int(replica_id), weight)

    def remove_replica(self, replica_id: int) -> None:
        """Scale-down: leave the ring; the retiree's keys slide to their
        ring successors, everyone else keeps a warm cache."""
        self.ring.remove_member(int(replica_id))

    def owner(self, prompt: Sequence[int]) -> int:
        """The ring owner of this prompt's prefix key, ignoring health —
        the stable answer to "where does this prefix live?"."""
        return self.ring.owner(hash_prompt_key(prompt, self.buckets))

    def route(self, prompt, candidates):
        if not candidates:
            return None
        # walk the ring clockwise; first ROUTABLE owner wins, so keys of
        # dead/excluded replicas slide to their successors while every
        # other key keeps its home
        by_id = {c.replica_id: c for c in candidates}
        pick = None
        for rid in self.ring.walk(hash_prompt_key(prompt, self.buckets)):
            if rid in by_id:
                pick = by_id[rid]
                break
        if pick is None:
            return None
        if pick.queue_depth >= self.overload_queue_depth:
            self.fallbacks += 1
            return least_loaded(candidates)
        return pick


def make_router(
    policy: str,
    replica_ids: Sequence[int],
    buckets: Optional[Sequence[int]] = None,
    **kwargs,
) -> Router:
    """Build a router by policy name (``rr`` / ``least`` / ``prefix``) —
    the string surface ``serve_bench --router`` and the frontend expose."""
    if policy == "rr":
        return RoundRobinRouter()
    if policy == "least":
        return LeastLoadedRouter()
    if policy == "prefix":
        return PrefixAffinityRouter(replica_ids, buckets=buckets, **kwargs)
    raise ValueError(
        f"unknown router policy {policy!r} (want rr | least | prefix)"
    )
