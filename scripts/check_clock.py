"""Static check: serving/cluster/daemon code never reads wall time
directly.

Every timestamp in ``tpu_parallel/serving/``, ``tpu_parallel/cluster/``
and ``tpu_parallel/daemon/`` must flow through the INJECTABLE clock (the
``clock`` callable the engine, scheduler, tracer, cluster frontend and
daemon shell all accept).  That is what makes
queue-timeout, deadline, aging and failover tests deterministic — they
advance a fake clock instead of sleeping — and what keeps every subsystem
on ONE time axis (an engine on ``time.monotonic`` and a frontend on a
fake clock would disagree about every deadline).  A direct
``time.time()`` / ``time.monotonic()`` / ``time.perf_counter()`` call is
a hole in that contract: code that works under pytest but measures
something else in production.

Like ``check_scopes.py``, the contract used to be prose; this makes it a
tier-1 test (``tests/test_cluster.py::test_serving_time_flows_through_clock``).
A REFERENCE to a clock function (``clock: Callable = time.monotonic`` as
a default argument) is fine — only CALLS are flagged, because a call is
a read of wall time while a reference is dependency injection of the
default time source.

The daemon (``tpu_parallel/daemon/``) is the layer that finally serves
real clients on real time — but even there, wall-clock READS are
permitted only in ``daemon/wallclock.py`` (``WALLCLOCK_FILES``), the
one adapter the daemon injects everywhere else.  That keeps the rest of
the daemon — journal, recovery, drain, dedupe — runnable on a fake
clock, deterministic under test like the core it wraps.

Usage: ``python scripts/check_clock.py [paths...]`` — prints one
``file:line: <call> bypasses the injectable clock`` per violation,
exits nonzero on any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

# direct wall-time reads; sleep is included because a sleeping serving
# module is equally untestable on a fake clock
CLOCK_CALLS = frozenset(
    {"time", "monotonic", "perf_counter", "monotonic_ns", "time_ns",
     "perf_counter_ns", "sleep"}
)

DEFAULT_PATHS = (
    "tpu_parallel/serving",
    "tpu_parallel/cluster",
    "tpu_parallel/daemon",
    "tpu_parallel/fleet",
)

# the ONE file allowed to read wall time: the daemon's WallClock
# adapter.  Matched on normalized relative path suffix so explicit-path
# invocations agree with the directory walk.
WALLCLOCK_FILES = ("tpu_parallel/daemon/wallclock.py",)


def is_wallclock_file(fname: str) -> bool:
    norm = os.path.normpath(fname).replace(os.sep, "/")
    return any(norm.endswith(ok) for ok in WALLCLOCK_FILES)


def check_source(source: str, filename: str) -> List[str]:
    """Return ``file:line: message`` strings for every direct wall-time
    CALL in ``source`` — ``time.<fn>()`` attribute calls, and bare
    ``<fn>()`` calls when ``<fn>`` was imported from the time module."""
    tree = ast.parse(source, filename=filename)
    problems: List[str] = []

    # names bound by `from time import monotonic [as mono]`
    from_time: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module == "time":
            for alias in node.names:
                if alias.name in CLOCK_CALLS:
                    from_time.add(alias.asname or alias.name)

    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        flagged = None
        if (
            isinstance(func, ast.Attribute)
            and func.attr in CLOCK_CALLS
            and isinstance(func.value, ast.Name)
            and func.value.id == "time"
        ):
            flagged = f"time.{func.attr}"
        elif isinstance(func, ast.Name) and func.id in from_time:
            flagged = func.id
        if flagged is not None:
            problems.append(
                f"{filename}:{node.lineno}: {flagged}() bypasses the "
                "injectable clock"
            )
    return problems


def check_paths(paths=DEFAULT_PATHS) -> List[str]:
    problems: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            files = [path]
        else:
            files = sorted(
                os.path.join(root, f)
                for root, _, names in os.walk(path)
                for f in names
                if f.endswith(".py")
            )
        for fname in files:
            if is_wallclock_file(fname):
                continue  # the daemon's one sanctioned wall-time surface
            with open(fname) as fh:
                problems.extend(check_source(fh.read(), fname))
    return problems


def main(argv: List[str]) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo_root)
    paths = argv[1:] or list(DEFAULT_PATHS)
    problems = check_paths(paths)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"check_clock: {len(problems)} direct wall-time call(s)",
            file=sys.stderr,
        )
        return 1
    print("check_clock: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
