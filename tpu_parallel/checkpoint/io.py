"""Checkpoint / resume on top of orbax — the TPU-native answer.

The reference has no persistence at all (SURVEY.md §5: "no orbax/flax
serialization anywhere"; its ``TrainState`` is checkpointable-by-construction
but nothing saves it).  This module supplies the capability: sharded
``TrainState`` pytrees (including ``nn.Partitioned``-boxed leaves) saved with
orbax and restored *onto the same mesh layout* via an abstract target derived
from the trainer's init function — every leaf comes back with its
NamedSharding, so restore never materializes a full replica on one host.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional

import jax
import orbax.checkpoint as ocp

Pytree = Any


class Checkpointer:
    """Thin orbax wrapper bound to one run directory.

    ``abstract_state``: pytree of ShapeDtypeStruct (with shardings) matching
    the live state — build it with :func:`abstract_state_of`.
    """

    def __init__(self, directory: str, max_to_keep: int = 3):
        self.directory = os.path.abspath(directory)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, state: Pytree, *, wait: bool = False) -> None:
        self.manager.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()

    def restore(self, abstract_state: Pytree, step: Optional[int] = None) -> Pytree:
        step = step if step is not None else self.manager.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found in {self.directory}")
        try:
            return self.manager.restore(
                step, args=ocp.args.StandardRestore(abstract_state)
            )
        except ValueError as e:
            # possibly structure drift (optional state fields added/removed
            # since the checkpoint was written).  The drift path re-raises
            # for anything it cannot soundly absorb, so chain back to the
            # original error when it fails too — no message parsing.
            try:
                return self._restore_with_drift(abstract_state, step)
            except Exception as drift_exc:
                # chain so BOTH failures surface: the original Standard
                # Restore mismatch and whatever broke the drift path
                raise e from drift_exc

    def _restore_with_drift(self, abstract_state: Pytree, step: int) -> Pytree:
        """Restore a checkpoint whose structure drifted from the live state:
        optional fields added since it was written (e.g. a pre-``ema_params``
        checkpoint into the current ``TrainState``) or written with fields
        the current config no longer carries (EMA turned off on resume).

        Orbax keys the saved tree by dataclass field name; each overlapping
        field restores through its own dict-shaped ``PyTreeRestore`` with
        ``partial_restore=True`` (so the on-disk tree may hold more than the
        target), and fields absent on disk keep their template defaults.
        """
        import dataclasses

        if not dataclasses.is_dataclass(abstract_state):
            raise ValueError(
                f"cannot drift-restore a non-dataclass state "
                f"({type(abstract_state).__name__})"
            )
        # the manager's registered handler is StandardCheckpointHandler and
        # refuses PyTreeRestore args; a bare PyTreeCheckpointer on the step
        # directory accepts partial_restore (the on-disk layout is the same)
        step_dir = os.path.join(self.directory, str(step), "default")
        restored = {}
        for f in dataclasses.fields(abstract_state):
            if not f.metadata.get("pytree_node", True):
                continue  # apply_fn/tx: functions, never serialized
            value = getattr(abstract_state, f.name)
            if value is None:
                continue  # disabled optional field: ignore any on-disk copy
            item = {f.name: value}
            try:
                with ocp.PyTreeCheckpointer() as ptc:
                    out = ptc.restore(
                        step_dir,
                        args=ocp.args.PyTreeRestore(
                            item=item,
                            restore_args=(
                                ocp.checkpoint_utils.construct_restore_args(item)
                            ),
                            partial_restore=True,
                        ),
                    )
            except (ValueError, KeyError, TypeError):
                # Only fields that are optional *by construction* (dataclass
                # default None, like ema_params) may degrade to None —
                # TypeError covers the on-disk None marker saved while the
                # feature was off.  A restore failure on a required field
                # (params, opt_state, ...) is corruption or intra-field
                # drift and must surface, not silently null the state.
                if f.default is not None:
                    raise
                import warnings

                warnings.warn(
                    f"checkpoint at step {step} has no usable {f.name!r}; "
                    "restoring it as None",
                    stacklevel=2,
                )
                restored[f.name] = None
                continue
            restored[f.name] = out[f.name]
        if all(v is None for v in restored.values()):
            raise ValueError(
                f"checkpoint at step {step} shares no fields with the "
                "restore target — structure drift too large"
            )
        return abstract_state.replace(**restored)

    @property
    def latest_step(self) -> Optional[int]:
        return self.manager.latest_step()

    def wait(self) -> None:
        self.manager.wait_until_finished()

    def close(self) -> None:
        self.manager.close()


def abstract_state_of(init_fn: Callable, *example_args) -> Pytree:
    """Abstract (shape/dtype/sharding) twin of ``init_fn(*example_args)``.

    ``init_fn`` should be the jitted sharded init from
    ``build_train_functions`` — its output shardings become the restore
    layout.
    """
    return jax.eval_shape(init_fn, *example_args)
