"""Static check: every collective call sits inside a ``jax.named_scope``.

``jax.named_scope`` labels are how collectives show up legibly in XProf/
Perfetto traces (docs/11_observability.md) — the reference repo's entire
observability story, and this framework's contract since PR 1 ("every
collective in the framework is scoped", ``utils/profiling.py``).  That
contract used to be prose; this makes it a tier-1 test
(``tests/test_obs.py::test_collectives_named_scoped``): a new ``psum`` /
``all_gather`` / ``psum_scatter`` / ``ppermute`` / ``all_to_all`` landing
in ``tpu_parallel/parallel/`` or ``tpu_parallel/ops/`` outside a scope
fails fast instead of shipping an unlabelable trace.

A call counts as scoped when it is lexically inside (a) a ``with
jax.named_scope(...)`` block, or (b) a function decorated with
``@jax.named_scope(...)`` (nested defs inherit the enclosing scope —
scan/loop bodies defined inside a scoped function carry its label).
``psum(1, axis)`` is exempt: it is the idiomatic static axis-size query,
folded to a constant by XLA — no collective is emitted.

Usage: ``python scripts/check_scopes.py [paths...]`` — prints one
``file:line: <call> outside jax.named_scope`` per violation, exits
nonzero on any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

COLLECTIVES = frozenset(
    {"psum", "all_gather", "psum_scatter", "ppermute", "all_to_all"}
)

DEFAULT_PATHS = ("tpu_parallel/parallel", "tpu_parallel/ops")


def _call_name(node: ast.Call) -> str:
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_named_scope_call(node: ast.expr) -> bool:
    return isinstance(node, ast.Call) and _call_name(node) == "named_scope"


def _is_axis_size_query(node: ast.Call) -> bool:
    """``psum(1, axis)`` — a static size probe, not a real collective."""
    if _call_name(node) != "psum" or not node.args:
        return False
    first = node.args[0]
    return isinstance(first, ast.Constant) and first.value == 1


def check_source(source: str, filename: str) -> List[str]:
    """Return ``file:line: message`` strings for every unscoped collective
    call in ``source``."""
    tree = ast.parse(source, filename=filename)
    problems: List[str] = []

    def visit(node: ast.AST, scoped: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            scoped = scoped or any(
                _is_named_scope_call(dec) for dec in node.decorator_list
            )
        elif isinstance(node, ast.With):
            scoped = scoped or any(
                _is_named_scope_call(item.context_expr)
                for item in node.items
            )
        elif isinstance(node, ast.Call):
            name = _call_name(node)
            if (
                name in COLLECTIVES
                and not scoped
                and not _is_axis_size_query(node)
            ):
                problems.append(
                    f"{filename}:{node.lineno}: {name} outside "
                    "jax.named_scope"
                )
        for child in ast.iter_child_nodes(node):
            visit(child, scoped)

    visit(tree, False)
    return problems


def check_paths(paths=DEFAULT_PATHS) -> List[str]:
    problems: List[str] = []
    for path in paths:
        if os.path.isfile(path):
            files = [path]
        else:
            files = sorted(
                os.path.join(root, f)
                for root, _, names in os.walk(path)
                for f in names
                if f.endswith(".py")
            )
        for fname in files:
            with open(fname) as fh:
                problems.extend(check_source(fh.read(), fname))
    return problems


def main(argv: List[str]) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo_root)
    paths = argv[1:] or list(DEFAULT_PATHS)
    problems = check_paths(paths)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(f"check_scopes: {len(problems)} unscoped collective(s)",
              file=sys.stderr)
        return 1
    print("check_scopes: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
