"""Block-paged KV cache tests: BlockAllocator invariants (refcounts,
double-free, fragmentation round-trip), copy-on-write under concurrent
sharers, greedy bitwise parity with the fixed-slot engine across every
serving path (per-step / fused / speculative / chunked / int8 / cluster
crash-replay), zero-copy prefix sharing, admission-by-blocks, donation
and compile-count pins.  (The ``check_blocks`` mutation fence moved to
``tests/test_checkers.py``, the single entry point over the
``scripts/check_all.py`` registry.)"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import FaultPlan, Frontend, ReplicaHandle
from tpu_parallel.cluster.replica import DEAD
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.serving import (
    FINISHED,
    REJECTED,
    BlockAllocator,
    PagedCachePool,
    Request,
    SchedulerConfig,
    ServingEngine,
)
from tpu_parallel.serving.request import REJECT_CAPACITY

BT = 8  # block_tokens used throughout (divides tiny_test's seq_len=32)


@pytest.fixture(scope="module")
def env():
    """One tiny float32 model + mixed-length prompts with a long shared
    header (so prefix sharing and COW paths actually exercise)."""
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(11)
    shared = [
        int(t)
        for t in np.asarray(
            jax.random.randint(rng, (20,), 1, cfg.vocab_size)
        )
    ]
    prompts = [
        shared[:9],
        shared[:17] + [3, 1, 4],
        shared[:17] + [5, 9],
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, 1), (6,), 1, cfg.vocab_size
            )
        )],
    ]
    probe = jax.random.randint(rng, (1, 20), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    return cfg, model, params, prompts


def _run_engine(env, paged, n_new=8, stagger=False, **kw):
    cfg, model, params, prompts = env
    kwargs = dict(
        n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
        decode_steps_per_tick=1,
    )
    kwargs.update(kw)
    if paged:
        kwargs.setdefault("kv_block_tokens", BT)
    else:
        kwargs.pop("kv_block_tokens", None)
    eng = ServingEngine(model, params, **kwargs)
    outs = []
    for i, p in enumerate(prompts):
        outs.append(
            eng.add_request(Request(request_id=str(i), prompt=p,
                                    max_new_tokens=n_new))
        )
        if stagger:
            eng.step()
    eng.run(max_ticks=500)
    assert all(o.status == FINISHED for o in outs)
    return [o.tokens for o in outs], eng


# -- BlockAllocator invariants ----------------------------------------------


def test_allocator_refcounts_and_double_free():
    """Refcounts never go negative: freeing an unreferenced block raises
    (the double-free guard), as does sharing one; a shared block only
    returns to the free list when the LAST reference drops."""
    al = BlockAllocator(4)
    a = al.alloc()
    assert al.refcount(a) == 1 and al.in_use == 1
    al.share(a)
    assert al.refcount(a) == 2
    assert al.free(a) is False  # one sharer left: stays allocated
    assert al.free(a) is True  # last reference: back on the free list
    with pytest.raises(ValueError, match="double free"):
        al.free(a)
    with pytest.raises(ValueError, match="share of unallocated"):
        al.share(a)
    with pytest.raises(ValueError):
        al.free(99)
    al.check()
    assert al.n_free == 4


def test_allocator_exhaustion_raises():
    al = BlockAllocator(2)
    al.alloc(), al.alloc()
    with pytest.raises(RuntimeError, match="exhausted"):
        al.alloc()


def test_allocator_fragmentation_round_trip():
    """Seeded alloc/share/free storm: every intermediate state passes the
    refcount/free-list audit and the storm ends with the free list
    holding exactly the pool capacity (no leak, no double-entry)."""
    rng = np.random.RandomState(0)
    al = BlockAllocator(16)
    held = []  # (block, refs_held)
    for _ in range(600):
        op = rng.randint(3)
        if op == 0 and al.n_free:
            held.append([al.alloc(), 1])
        elif op == 1 and held:
            ent = held[rng.randint(len(held))]
            al.share(ent[0])
            ent[1] += 1
        elif held:
            i = rng.randint(len(held))
            blk, refs = held[i]
            al.free(blk)
            if refs == 1:
                held.pop(i)
            else:
                held[i][1] -= 1
        al.check()
    for blk, refs in held:
        for _ in range(refs):
            al.free(blk)
    al.check()
    assert al.n_free == 16 and al.in_use == 0


# -- engine parity with the fixed-slot layout --------------------------------


@pytest.mark.parametrize(
    "mode",
    ["per_step", "fused", "spec", "chunked", "bucketed_prefix",
     "fused_prefix"],
)
def test_paged_greedy_parity(env, mode):
    """Acceptance: greedy output bitwise identical to the fixed-slot
    engine under every serving path — the paged gather/scatter is a pure
    relayout."""
    kw = dict(
        per_step=dict(),
        fused=dict(decode_steps_per_tick=4),
        spec=dict(draft_tokens=4),
        chunked=dict(
            prefill_chunk_tokens=8, prefill_buckets=(8, 16, 32),
            prefix_cache_size=4,
        ),
        bucketed_prefix=dict(
            prefill_buckets=(8, 16, 32), prefix_cache_size=4,
        ),
        fused_prefix=dict(
            decode_steps_per_tick=4, prefill_buckets=(8, 16, 32),
            prefix_cache_size=4,
        ),
    )[mode]
    fixed, _ = _run_engine(env, paged=False, stagger=True, **kw)
    paged, eng = _run_engine(env, paged=True, stagger=True, **kw)
    assert fixed == paged, f"paged {mode} diverged from fixed-slot"
    eng.pool.allocator.check()


def test_paged_int8_parity(env):
    import dataclasses

    cfg, model, params, prompts = env
    m8 = GPTLM(dataclasses.replace(cfg, kv_cache_dtype="int8"))
    env8 = (m8.config, m8, params, prompts)
    fixed, _ = _run_engine(env8, paged=False, stagger=True)
    paged, eng = _run_engine(env8, paged=True, stagger=True)
    assert fixed == paged, "paged int8 decode diverged from fixed-slot"
    eng.pool.allocator.check()


def test_paged_cluster_crash_replay_exact(env):
    """The cluster crash guarantee holds over the paged pool: a replica
    dying mid-request is replayed forced-prefix on the survivor, greedy
    output bitwise equal to a no-fault paged baseline (itself pinned to
    the fixed-slot engine by the parity suite)."""
    cfg, model, params, prompts = env

    def mk():
        return ServingEngine(
            model, params, n_slots=4, decode_steps_per_tick=1,
            kv_block_tokens=BT,
            scheduler=SchedulerConfig(max_prefills_per_tick=4),
        )

    baseline = mk()
    base_outs = [
        baseline.add_request(Request(prompt=p, max_new_tokens=8))
        for p in prompts
    ]
    baseline.run(max_ticks=500)
    assert all(o.status == FINISHED for o in base_outs)

    h0 = ReplicaHandle(0, mk(), fault_plan=FaultPlan(crash_at_tick=3))
    h1 = ReplicaHandle(1, mk())
    fe = Frontend([h0, h1], router="rr")
    outs = [
        fe.submit(Request(prompt=p, max_new_tokens=8)) for p in prompts
    ]
    fe.run(max_ticks=500)
    assert h0.health == DEAD and fe.summary()["retries"] > 0
    for i, (out, base) in enumerate(zip(outs, base_outs)):
        assert out.status == FINISHED
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(base.tokens),
            err_msg=f"request {i} diverged after paged failover",
        )


# -- prefix sharing and copy-on-write ----------------------------------------


def test_paged_prefix_hit_zero_copies(env):
    """A paged prefix hit is a table pointer write + refcount bump —
    counter-verified: shared blocks were mapped, NO copy-on-write ran
    (block-aligned buckets), and the paged pool doesn't even expose the
    fixed layout's row-copy surface."""
    _, eng = _run_engine(
        env, paged=True, stagger=True,
        prefill_buckets=(8, 16, 32), prefix_cache_size=4,
    )
    assert eng.metrics.prefix_hits > 0
    assert eng.metrics.prefix_shared_blocks > 0
    assert eng.pool.shared_block_maps > 0
    # aligned sharing: remainders start at block boundaries, so the hit
    # path never copies a single block
    assert eng.pool.cow_copies == 0
    for name in ("copy_prefix", "stack_prefix", "extract", "insert"):
        assert not hasattr(eng.pool, name), (
            f"PagedCachePool.{name} exists — the O(prefix) row-copy "
            "economy leaked back into the paged layout"
        )


def test_paged_cow_under_concurrent_sharers(env):
    """With a block size COARSER than the bucket quantum, stored prefixes
    end mid-block, so the owner's decode and every hitter's remainder
    write land in SHARED blocks: each sharer copy-on-writes its own copy
    of that one block and greedy output still matches the fixed-slot
    engine bitwise."""
    kw = dict(
        kv_block_tokens=16,  # bucket 8 ends mid-block -> shared tails
        prefill_buckets=(8, 16, 32), prefix_cache_size=4,
    )
    cfg, model, params, prompts = env
    fixed, _ = _run_engine(env, paged=False, stagger=True,
                           prefill_buckets=(8, 16, 32),
                           prefix_cache_size=4)
    paged, eng = _run_engine(env, paged=True, stagger=True, **kw)
    assert fixed == paged, "COW path diverged from fixed-slot"
    assert eng.pool.cow_copies > 0, (
        "mid-block sharing never copy-on-wrote — the COW path is dead "
        "and sharers are scribbling on each other"
    )
    eng.pool.allocator.check()


def test_paged_pool_cow_isolates_sharers(env):
    """Pool-level COW: two slots mapping one shared block diverge on
    first write — the writer gets a fresh block, the other sharer (and
    the stored entry) keep reading the original bytes."""
    cfg, model, params, _ = env
    import dataclasses

    pm = GPTLM(
        dataclasses.replace(cfg, kv_block_tokens=BT, kv_pool_blocks=8)
    )
    pool = PagedCachePool(pm, params, n_slots=2)
    assert pool.acquire() == 0 and pool.acquire() == 1
    pool.begin_slot(0, 2 * BT)
    pool.ensure_writable(0, 0, BT)
    blocks = pool.snapshot_blocks(0, BT)  # entry holds one reference
    pool.map_prefix(1, blocks, BT)  # slot 1 shares the same block
    shared = int(pool.block_table[1, 0])
    assert shared == int(pool.block_table[0, 0])
    assert pool.allocator.refcount(shared) == 3  # owner + entry + sharer
    pool.ensure_writable(1, 0, BT)  # slot 1's first write: COW
    assert pool.cow_copies == 1
    assert int(pool.block_table[1, 0]) != shared
    assert pool.allocator.refcount(shared) == 2
    pool.release(1)
    pool.release(0)
    pool.free_stored(blocks)
    pool.allocator.check()
    assert pool.allocator.n_free == 8


def test_paged_release_returns_all_blocks(env):
    """Fragmentation round-trip at the engine level: after a full run the
    only live blocks are the prefix cache's refcounted entries; dropping
    those returns the free list to capacity."""
    _, eng = _run_engine(
        env, paged=True, stagger=True,
        prefill_buckets=(8, 16, 32), prefix_cache_size=4,
    )
    held = {
        b
        for blocks, _ in eng._prefix._entries.values()
        for b in blocks
    }  # distinct: a short key's blocks are a prefix of a longer key's
    assert eng.pool.blocks_in_use == len(held)
    for blocks, _ in list(eng._prefix._entries.values()):
        eng.pool.free_stored(blocks)
    eng.pool.allocator.check()
    assert eng.pool.blocks_in_use == 0


# -- admission by blocks ------------------------------------------------------


def test_paged_capacity_decoupled_from_seq_len(env):
    """Acceptance: at EQUAL pool bytes, paged admits >= 2x the concurrent
    short requests — a fixed pool buys whole seq_len rows (2 here), the
    paged pool buys blocks (one per short request)."""
    cfg, model, params, _ = env
    short = [[7, 3, 5]] * 8  # 3 prompt + 4 new = 7 tokens = 1 block
    fixed = ServingEngine(
        model, params, n_slots=2, decode_steps_per_tick=1,
        scheduler=SchedulerConfig(max_prefills_per_tick=8),
    )
    # same K/V bytes: 2 slots x seq_len 32 == 8 blocks x 8 tokens
    paged = ServingEngine(
        model, params, n_slots=8, kv_block_tokens=BT, kv_pool_blocks=8,
        decode_steps_per_tick=1,
        scheduler=SchedulerConfig(max_prefills_per_tick=8),
    )
    for eng in (fixed, paged):
        for i, p in enumerate(short):
            out = eng.add_request(
                Request(request_id=str(i), prompt=p, max_new_tokens=4)
            )
            assert out.status != REJECTED
        eng.step()
    assert fixed.in_flight == 2  # slot-bound
    assert paged.in_flight == 8  # block-bound: 4x the same bytes
    assert paged.in_flight >= 2 * fixed.in_flight
    paged.run(max_ticks=200)
    fixed.run(max_ticks=200)
    paged.pool.allocator.check()
    assert paged.pool.blocks_free == 8


def test_paged_block_gate_holds_head_until_blocks_free(env):
    """Transient block exhaustion QUEUES (head-of-line) instead of
    rejecting: the queued head admits once a running request retires its
    blocks, and everything finishes."""
    cfg, model, params, _ = env
    eng = ServingEngine(
        model, params, n_slots=4, kv_block_tokens=BT, kv_pool_blocks=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
    )
    outs = [
        eng.add_request(
            Request(request_id=str(i), prompt=[5, 3], max_new_tokens=12)
        )  # 14 tokens = 2 blocks: exactly one fits at a time
        for i in range(3)
    ]
    eng.step()
    assert eng.in_flight == 1 and eng.scheduler.depth == 2
    eng.run(max_ticks=500)
    assert all(o.status == FINISHED for o in outs)
    eng.pool.allocator.check()
    assert eng.pool.blocks_free == 2


def test_paged_cow_cannot_exhaust_pool_midtick(env):
    """Regression (post-review): with buckets NOT aligned to the block
    size, prefix sharing lands mid-block and sharers' writes
    copy-on-write — each COW claims a fresh block the plain
    ceil(total/bt) admission estimate cannot see.  Un-reserved, two
    admitted requests' COWs exhausted a tight pool MID-TICK
    (RuntimeError out of step(), every in-flight request killed).  The
    admission gate now carries a COW reserve per non-aligned bucket AND
    evicts LRU prefix entries under block pressure instead of starving
    the queue head behind blocks that stored prefixes hold forever."""
    cfg, model, params, _ = env
    eng = ServingEngine(
        model, params, n_slots=2, decode_steps_per_tick=1,
        prefill_buckets=(12, 24), prefix_cache_size=4,
        kv_block_tokens=8, kv_pool_blocks=5,
    )
    assert eng._cow_reserve > 0
    rng = np.random.RandomState(0)
    outs = [
        eng.add_request(
            Request(
                request_id=str(i),
                prompt=list(rng.randint(1, cfg.vocab_size, 14)),
                max_new_tokens=n_new,
            )
        )
        for i, n_new in enumerate((10, 2, 6))
    ]
    eng.run(max_ticks=800)  # un-fixed: RuntimeError 'block pool exhausted'
    assert all(o.status == FINISHED for o in outs)
    assert eng.pool.cow_copies > 0  # the hazard actually exercised
    assert eng._prefix.evictions > 0  # the pressure valve actually opened
    eng.pool.allocator.check()


def test_paged_first_token_finish_seeds_prefix(env):
    """Regression (post-review): a request finishing on its very first
    token (max_new_tokens=1 / immediate EOS) retires its slot inside the
    admission tick's _activate, and release() wipes the paged slot's
    block table — the prefix store must snapshot BEFORE activation or
    step() dies with ValueError 'cannot snapshot' (the fixed-slot path
    only survived the old ordering because extract() on a released slot
    still read intact row bytes)."""
    cfg, model, params, prompts = env
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=1, kv_block_tokens=BT,
        prefill_buckets=(8, 16), prefix_cache_size=4,
    )
    one = eng.add_request(Request(prompt=prompts[0], max_new_tokens=1))
    eng.run(max_ticks=50)  # un-fixed: ValueError out of step()
    assert one.status == FINISHED and len(one.tokens) == 1
    hits0 = eng.metrics.prefix_hits
    again = eng.add_request(Request(prompt=prompts[0], max_new_tokens=4))
    eng.run(max_ticks=100)
    assert again.status == FINISHED
    assert eng.metrics.prefix_hits > hits0  # the 1-token run seeded it
    assert list(again.tokens[:1]) == list(one.tokens)  # greedy parity
    eng.pool.allocator.check()
    # same immediate retirement through the chunked completion path
    engc = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        decode_steps_per_tick=1, kv_block_tokens=BT,
        prefill_buckets=(8, 16), prefix_cache_size=4,
        prefill_chunk_tokens=6,
    )
    outc = engc.add_request(Request(prompt=prompts[1], max_new_tokens=1))
    engc.run(max_ticks=50)
    assert outc.status == FINISHED and len(outc.tokens) == 1
    engc.pool.allocator.check()


def test_paged_prefix_pin_survives_same_tick_eviction(env):
    """Regression (post-review): _admit_batch_paged looks up every hit
    up front but maps per group — with a size-1 prefix cache, an
    earlier-processed miss group's store LRU-evicts the hit entry
    (free_stored, refcount to zero) before the later group's map_prefix,
    raising 'share of unallocated block' — or silently attending another
    request's K/V if the freed block was re-allocated first.  The
    admission pin keeps looked-up blocks alive until mapped."""
    cfg, model, params, prompts = env
    eng = ServingEngine(
        model, params, n_slots=4,
        scheduler=SchedulerConfig(max_prefills_per_tick=4),
        decode_steps_per_tick=1, kv_block_tokens=4,
        prefill_buckets=(4, 8), prefix_cache_size=1,
    )
    P = prompts[0][:5]
    seed = eng.add_request(Request(prompt=P, max_new_tokens=6))
    eng.run(max_ticks=100)
    assert seed.status == FINISHED
    # same tick: A (miss -> group (0, w) first; its store evicts P's
    # entry) + B (hit on P's entry -> group (4, w') second)
    a = eng.add_request(Request(prompt=[7, 7, 5, 2, 9], max_new_tokens=6))
    b = eng.add_request(Request(prompt=P, max_new_tokens=6))
    eng.run(max_ticks=100)  # un-fixed: ValueError out of step()
    assert a.status == FINISHED and b.status == FINISHED
    assert list(b.tokens) == list(seed.tokens)  # greedy, same prompt
    eng.pool.allocator.check()


def test_paged_admission_rejects_impossible_request(env):
    """A request whose worst case exceeds the WHOLE pool can never admit
    — typed capacity reject at submit, same vocabulary the cluster
    frontend already understands."""
    cfg, model, params, _ = env
    eng = ServingEngine(
        model, params, n_slots=2, kv_block_tokens=BT, kv_pool_blocks=2,
    )
    out = eng.add_request(Request(prompt=[1] * 20, max_new_tokens=10))
    assert out.status == REJECTED
    assert out.finish_reason == REJECT_CAPACITY
    assert "KV blocks" in out.detail


# -- donation and compile pins ------------------------------------------------


def test_paged_fused_tick_donation_invalidates_old_buffers(env):
    """The paged pool rides the same donation-and-ownership contract as
    the fixed-slot pool: after a fused tick (and a per-step tick) the
    previous tick's cache and device-state buffers are DELETED — no
    second pool copy exists, stale references raise on use (mirrors
    ``test_fused_tick_donation_invalidates_old_buffers``)."""
    cfg, model, params, prompts = env
    for steps in (1, 4):
        eng = ServingEngine(
            model, params, n_slots=2, decode_steps_per_tick=steps,
            kv_block_tokens=BT,
        )
        out = eng.add_request(Request(prompt=prompts[0], max_new_tokens=12))
        eng.step()  # admit + first decode tick
        old_cache = jax.tree_util.tree_leaves(eng.pool.cache)
        old_state = (
            jax.tree_util.tree_leaves(eng._dev_state) if steps > 1 else []
        )
        eng.step()  # decode-only tick: donates cache (and fused state)
        assert all(leaf.is_deleted() for leaf in old_cache), (
            f"T={steps}: old paged pool buffers survived the tick "
            "(donation regressed — a second full pool copy is alive)"
        )
        assert all(leaf.is_deleted() for leaf in old_state)
        # the block table is NOT donated: the host mirror stays the
        # authority and the device copy is reused across ticks
        assert eng._dev_table is not None
        assert not eng._dev_table.is_deleted()
        eng.run(max_ticks=200)
        assert out.status == FINISHED and len(out.tokens) == 12


def test_paged_fused_compile_count_pin(env):
    """The paged fused tick compiles ONCE: the block table rides the
    carry-adjacent inputs at a fixed [n_slots, max_blocks] shape, so
    admissions, retirements and table growth never retrace."""
    from tpu_parallel.serving import engine as engine_mod

    engine_mod._paged_engine_fns.cache_clear()
    engine_mod._paged_fused_engine_fn.cache_clear()
    cfg, model, params, prompts = env
    eng = ServingEngine(
        model, params, n_slots=4, decode_steps_per_tick=4,
        kv_block_tokens=BT, prefill_buckets=(8, 16, 32),
        prefix_cache_size=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    outs = []
    for i, p in enumerate(prompts):
        outs.append(
            eng.add_request(
                Request(request_id=str(i), prompt=p,
                        max_new_tokens=6 + i)
            )
        )
        eng.step()
    eng.run(max_ticks=300)
    assert all(o.status == FINISHED for o in outs)
    assert eng._fused_fn._cache_size() == 1, (
        f"paged fused tick retraced: {eng._fused_fn._cache_size()} "
        "compiles (table upload must be loop-invariant)"
    )


# (The block-table mutation fence — and every other AST contract gate —
# is wired tier-1 through the single scripts/check_all.py registry entry
# point in tests/test_checkers.py.)
