"""Durable serving daemon: the long-lived wall-clock process around the
cluster frontend (docs/13_daemon.md).

``daemon/journal.py`` is the write-ahead request journal (append-only
JSONL, sequence numbers, batched fsync) and its crash-recovery replay;
``daemon/daemon.py`` is the shell — recovery, dedupe-token idempotence,
the SIGTERM/SIGHUP signal contract and the tick pump; ``daemon/http.py``
is the stdlib HTTP + SSE network face; ``daemon/wallclock.py`` is the
ONE place in the serving stack allowed to read real time
(``scripts/check_clock.py`` enforces it).
"""

from tpu_parallel.daemon.daemon import (
    DAEMON_TRACK,
    EXIT_CLEAN,
    EXIT_FORCED,
    DaemonConfig,
    ServingDaemon,
)
from tpu_parallel.daemon.http import DaemonHTTPServer, build_request
from tpu_parallel.daemon.journal import (
    JOURNAL_VERSION,
    REC_DECISION,
    REC_META,
    REC_RECOVERY,
    REC_SHUTDOWN,
    REC_SUBMIT,
    REC_TERMINAL,
    REC_TOKENS,
    JournalCorrupt,
    JournalEntry,
    JournalWriter,
    RecoveryState,
    drop_torn_tail,
    load_state,
    read_journal,
    replay_state,
)
from tpu_parallel.daemon.wallclock import WallClock

__all__ = [
    "DAEMON_TRACK",
    "EXIT_CLEAN",
    "EXIT_FORCED",
    "DaemonConfig",
    "DaemonHTTPServer",
    "JOURNAL_VERSION",
    "JournalCorrupt",
    "JournalEntry",
    "JournalWriter",
    "REC_DECISION",
    "REC_META",
    "REC_RECOVERY",
    "REC_SHUTDOWN",
    "REC_SUBMIT",
    "REC_TERMINAL",
    "REC_TOKENS",
    "RecoveryState",
    "ServingDaemon",
    "WallClock",
    "build_request",
    "drop_torn_tail",
    "load_state",
    "read_journal",
    "replay_state",
]
