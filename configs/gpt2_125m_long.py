"""GPT-2 125M at 8k context: the streamed flash kernels on one chip.

Above ``STREAM_SEQ_THRESHOLD`` (4096) the flash kernels walk K/V as a grid
dimension with O(block) VMEM residency, so 8k-32k sequences fit a v5e chip
(docs/05_performance.md).  ``loss_chunk`` keeps the [B, S, vocab] logits
from ever materializing — at seq 8192 x vocab 50304 they would be ~0.8 GB
bf16 per batch row.  For longer-still contexts shard the token axis
instead (``attn_impl="ring"`` + a ``seq`` mesh axis — docs/04).

Measured on v5e-1 (round 5, SWEEP_r05/r05b): batch 16 x 8192 with 8
accumulation minibatches and UNROLLED layers trains at 48.1k
tokens/sec/chip, **MFU 0.4023** — the per-pass shape (2 rows) keeps the
unrolled compile inside budget (the round-4 "batch 8 crashes" was the
8-row single-pass trace), and the round-5 batch ladder carries the rest.
The scan ladder tops out at 0.3797 (batch 32, 8 minibatches; batch 16/4: 0.3783).  Longer contexts, same recipe at one row
per pass: 16k = 29.4k tok/s (MFU 0.3814), 32k = 17.0k tok/s (MFU 0.3769)
— attention's FLOPs share grows with seq while flash runs below matmul
peak, so MFU declines gently; throughput per token-window is the metric
that matters at fixed global tokens.  Round-4 record for reference:
batch 4 x 8192 scan, 44.5k tok/s, MFU 0.372.
"""

from ml_collections import ConfigDict

from configs.common import model_overrides


def get_config():
    c = ConfigDict()
    c.simulate_cpu_devices = 0
    c.model = "gpt2_125m"
    c.model_overrides = model_overrides(
        seq_len=8192,
        attn_impl="flash",  # auto-selects the streamed kernels at this length
        remat_policy="proj_attn",
        loss_chunk=1024,
        # unrolled beats scan by ~6% here too; per-pass 2 rows keeps the
        # 8k unrolled trace inside the remote-compile budget
        scan_layers=False,
    )
    c.mesh = ConfigDict(dict(data=-1, model=1, pipe=1, seq=1))
    c.global_batch_size = 16
    c.num_minibatches = 8
    c.steps = 50
    c.optimizer = "adamw"
    c.lr_schedule = "cosine"
    c.ema_decay = 0.0
    c.learning_rate = 3e-4
    c.warmup_steps = 10
    c.weight_decay = 0.1
    c.grad_clip = 1.0
    c.seed = 0
    c.log_every = 10
    c.donate = True
    c.checkpoint_dir = ""
    c.checkpoint_every = 100
    c.data_path = ""
    c.data_format = "flat"
    c.eos_id = 50256
    c.eval_steps = 0
    c.eval_every = 0
    c.keep_best = False
    return c
