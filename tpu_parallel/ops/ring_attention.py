"""Ring attention: causal attention with the sequence sharded over a mesh axis.

Long-context / context-parallel capability (no reference equivalent —
SURVEY.md §5).  Each device holds a contiguous sequence chunk of Q, K, V
(``[batch, seq/n, heads, head_dim]``).  K/V chunks rotate around the ring
with ``lax.ppermute`` (ICI neighbour exchange, overlappable with compute by
XLA's latency-hiding scheduler) while each device's Q chunk accumulates
attention over every K/V chunk with an online-softmax combine — memory stays
O(seq/n) per device, communication is the ring's bisection bandwidth.

Causality across chunks: chunk ``c`` of K/V is fully visible to Q chunk
``r`` when ``c < r``, diagonally masked when ``c == r``, and fully masked
when ``c > r`` (rows are masked elementwise; the compute is uniform across
ranks, as SPMD requires).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


@jax.named_scope("ring_attention")
def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    use_checkpoint: bool = True,
    window: int = 0,
    segment_ids: Optional[jax.Array] = None,
    causal: bool = True,
) -> jax.Array:
    """Ring attention on seq-sharded [batch, local_seq, heads, hd]
    (causal by default; ``causal=False`` is the bidirectional/encoder form —
    every position sees every same-segment position).

    Must run inside a ``shard_map`` region binding ``axis_name``.  Returns
    the local output chunk.  ``use_checkpoint`` remats the per-step combine
    so the backward pass replays the ring instead of storing every rotated
    K/V chunk (keeps the O(seq/n) memory promise under autodiff).
    ``window > 0`` adds sliding-window masking on the global positions:
    causal = Mistral-style (query t sees keys in (t - window, t]);
    bidirectional = encoder local attention (the symmetric band
    |q - k| < window).
    ``segment_ids`` (the LOCAL chunk's [batch, local_seq] ids) masks packed
    sequences: the ids rotate around the ring with their K/V chunk, so each
    step can mask cross-document pairs exactly.
    """
    n_chunks = lax.psum(1, axis_name)
    my_chunk = lax.axis_index(axis_name)
    b, local_s, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv != 0:
        raise ValueError(f"q heads {h} not a multiple of k/v heads {h_kv}")
    group = h // h_kv
    scale = 1.0 / (d**0.5)
    # keep MXU operands in the input dtype (bf16 runs the systolic array at
    # full rate; fp32 operands would halve it) and accumulate fp32 via
    # preferred_element_type — same recipe as the Pallas flash kernels.
    # GQA is native: K/V stay at kv-head width — they are what rides the
    # ring, so grouped queries cut the ppermute traffic by `group` —
    # and queries reshape to [B, H_kv, G, ls, D] to contract against them.
    qf = (q * jnp.asarray(scale, q.dtype)).transpose(0, 2, 1, 3).reshape(
        b, h_kv, group, local_s, d
    )
    seg_local = (
        None if segment_ids is None else segment_ids.astype(jnp.int32)
    )

    def combine(carry, kv_and_src):
        """One ring step: attend local q to the currently-held kv chunk."""
        out, m_prev, l_prev = carry
        k_cur, v_cur, seg_cur, src_chunk = kv_and_src
        kf = k_cur.transpose(0, 2, 1, 3)  # [B, H_kv, ls, D]
        vf = v_cur.transpose(0, 2, 1, 3)
        s = jnp.einsum(
            "bngqd,bnkd->bngqk", qf, kf, preferred_element_type=jnp.float32
        )
        q_pos = my_chunk * local_s + lax.broadcasted_iota(jnp.int32, s.shape, 3)
        k_pos = src_chunk * local_s + lax.broadcasted_iota(jnp.int32, s.shape, 4)
        mask = (
            q_pos >= k_pos
            if causal
            else jnp.ones(s.shape, bool)  # bidirectional: all visible
        )
        if window:
            # positions here are global, so the band needs no per-chunk
            # offset bookkeeping — the flash ring path encodes the same
            # geometry statically via flash_chunk_attention's q_offset.
            # causal: one-sided (keys at most window-1 behind); encoder
            # local attention (non-causal): the symmetric band |q-k|<window
            near = q_pos - k_pos < window
            if not causal:
                near = jnp.logical_and(near, k_pos - q_pos < window)
            mask = jnp.logical_and(mask, near)
        if seg_cur is not None:
            same = (
                seg_local[:, None, None, :, None]
                == seg_cur[:, None, None, None, :]
            )
            mask = jnp.logical_and(mask, same)
        s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        # a fully-masked row keeps m == NEG_INF; exp(s - m) would be exp(0)=1
        # there, so zero masked entries explicitly.
        p = jnp.where(mask, jnp.exp(s - m_new), 0.0)
        alpha = jnp.exp(m_prev - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        # probs cast to the K/V dtype for the MXU; fp32 accumulate
        out = out * alpha + jnp.einsum(
            "bngqk,bnkd->bngqd",
            p.astype(vf.dtype),
            vf,
            preferred_element_type=jnp.float32,
        )
        return (out, m_new, l_new)

    if use_checkpoint:
        combine = jax.checkpoint(combine)

    def step(carry, _):
        (out, m, l), (k_cur, v_cur, seg_cur, src_chunk) = carry
        new_acc = combine((out, m, l), (k_cur, v_cur, seg_cur, src_chunk))
        # rotate kv (and its segment ids) to the next rank (rank i's chunk
        # moves to rank i+1), so after step t this rank holds chunk
        # (my_chunk - t - 1) mod n.
        perm = [(i, (i + 1) % n_chunks) for i in range(n_chunks)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        seg_next = (
            None if seg_cur is None else lax.ppermute(seg_cur, axis_name, perm)
        )
        src_next = (src_chunk - 1) % n_chunks
        return (new_acc, (k_next, v_next, seg_next, src_next)), None

    out0 = jnp.zeros((b, h_kv, group, local_s, d), jnp.float32)
    m0 = jnp.full((b, h_kv, group, local_s, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, h_kv, group, local_s, 1), jnp.float32)
    # the accumulators come out of `combine` varying over every axis q varies
    # on PLUS the ring axis itself (axis_index makes the body's outputs
    # ring-varying even when the inputs are replicated, e.g. on a size-1
    # axis); promote the whole init carry so the scan type-checks under
    # shard_map's replication checker.  vma_of(my_chunk) is {axis_name}
    # exactly when variance is being tracked — empty under check_vma=False,
    # where promotion would only plant an invalid psum in the backward.
    from tpu_parallel.core.metrics import pvary_missing, vma_of

    # ordered tuple, not a set: the axes feed pcast, and a nondeterministic
    # order would make the jaxpr differ run-to-run (compile-cache poison)
    q_vma = vma_of(q)
    ring_vma = q_vma + tuple(a for a in vma_of(my_chunk) if a not in q_vma)
    out0, m0, l0, k0, v0 = (
        pvary_missing(x, ring_vma) for x in (out0, m0, l0, k, v)
    )
    seg0 = None if seg_local is None else pvary_missing(seg_local, ring_vma)
    init = ((out0, m0, l0), (k0, v0, seg0, my_chunk))
    ((out, m, l), _), _ = lax.scan(step, init, None, length=n_chunks)
    out = out / jnp.maximum(l, 1e-20)
    out = out.reshape(b, h, local_s, d)
    return out.transpose(0, 2, 1, 3).astype(q.dtype)


def combine_chunks(out_a, lse_a, out_b, lse_b):
    """Exactly merge two attention partials (chunk-normalized out + lse).

    ``out``: [batch, seq, heads, head_dim] fp32; ``lse``: [batch, heads,
    seq].  An empty partial is represented by ``lse <= NEG_INF/2`` (its out
    must be zeros); ``NEG_INF`` is finite (-1e30) so the arithmetic never
    produces nan — the weight just underflows to exactly 0.
    """
    m = jnp.maximum(lse_a, lse_b)
    # guard the all-empty row (both partials masked): keep weights at 0
    m_safe = jnp.where(m <= NEG_INF / 2, 0.0, m)
    w_a = jnp.exp(lse_a - m_safe)[:, :, :, None].transpose(0, 2, 1, 3)
    w_b = jnp.exp(lse_b - m_safe)[:, :, :, None].transpose(0, 2, 1, 3)
    # each partial is normalized within its chunk, so the merge renormalizes:
    # out = (o_a e^{lse_a} + o_b e^{lse_b}) / (e^{lse_a} + e^{lse_b})
    denom = jnp.maximum(w_a + w_b, 1e-38)
    out = (out_a * w_a + out_b * w_b) / denom
    lse = m_safe + jnp.log(
        jnp.exp(lse_a - m_safe) + jnp.exp(lse_b - m_safe)
    )
    lse = jnp.where(m <= NEG_INF / 2, NEG_INF, lse)
    return out, lse


@jax.named_scope("ring_flash_attention")
def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = "seq",
    block_q: int = 512,
    block_k: int = 512,
    interpret: Optional[bool] = None,
    use_checkpoint: bool = True,
    window: int = 0,
    segment_ids: Optional[jax.Array] = None,
    causal: bool = True,
) -> jax.Array:
    """Ring attention with the per-chunk math on the Pallas flash kernels.

    Same contract as :func:`ring_attention` (causal, seq-sharded
    [batch, local_seq, heads, head_dim] inside shard_map), but each ring
    step runs :func:`~tpu_parallel.ops.flash_attention.flash_chunk_attention`
    instead of materializing fp32 [*, local_s, local_s] score tensors — the
    jnp path runs the MXU well below peak (docs/05_performance.md measures
    the same gap for plain flash vs XLA attention).  Per step the diagonal
    chunk uses the causal kernel, strictly-past chunks the full kernel, and
    future chunks contribute an empty partial without running a kernel
    (``lax.cond``; SPMD-legal under shard_map since control flow is
    per-device there).

    Gradients flow through the chunk kernels' custom VJP — the lse
    cotangent of :func:`combine_chunks` folds into the backward delta —
    and ``use_checkpoint`` remats each step so rotated K/V chunks are not
    stored (same memory contract as :func:`ring_attention`).

    ``window > 0`` adds sliding-window masking.  The kernel band geometry is
    static, but a q chunk sits ``j * local_seq`` positions after the chunk
    held at ring step ``j`` — a *static* offset per step-distance — so each
    held chunk dispatches through ``lax.switch`` on ``my - src``: diagonal
    (causal + window), one branch per partially-visible back-distance
    (``q_offset = j * local_seq``), and skip for chunks the window misses
    entirely (which also skips their kernels' FLOPs, keeping the
    O(seq * window) compute promise).
    """
    from tpu_parallel.ops.flash_attention import flash_chunk_attention

    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of k/v heads {k.shape[2]}"
        )
    n_chunks = lax.psum(1, axis_name)
    my_chunk = lax.axis_index(axis_name)
    b, local_s, h, d = q.shape
    seg_local = (
        None if segment_ids is None else segment_ids.astype(jnp.int32)
    )

    def one_chunk(carry, kv_and_src):
        out, lse = carry
        k_cur, v_cur, seg_cur, src_chunk = kv_and_src
        seg_kw = (
            {}
            if seg_cur is None
            else dict(segment_ids_q=seg_local, segment_ids_kv=seg_cur)
        )

        def diag(_):
            o, s = flash_chunk_attention(
                q, k_cur, v_cur, causal=True, window=window,
                block_q=block_q, block_k=block_k, interpret=interpret,
                **seg_kw,
            )
            return o.astype(jnp.float32), s

        def back(j):
            # chunk j ranks back (j > 0) or ahead (j < 0, bidirectional
            # only): its keys start j*local_s before our queries — a
            # SIGNED static offset the kernel band handles either way.
            # Fully inside the window -> plain full kernel; straddling the
            # band edge -> windowed kernel with the static offset
            offset = j * local_s
            fully_visible = abs(offset) + local_s - 1 < window

            def run(_):
                o, s = flash_chunk_attention(
                    q, k_cur, v_cur, causal=False,
                    window=0 if fully_visible else window,
                    q_offset=0 if fully_visible else offset,
                    block_q=block_q, block_k=block_k, interpret=interpret,
                    **seg_kw,
                )
                return o.astype(jnp.float32), s

            return run

        def full(_):
            o, s = flash_chunk_attention(
                q, k_cur, v_cur, causal=False,
                block_q=block_q, block_k=block_k, interpret=interpret,
                **seg_kw,
            )
            return o.astype(jnp.float32), s

        def skip(_):
            from tpu_parallel.core.metrics import pvary_missing, vma_of

            zeros = jnp.zeros((b, local_s, h, d), jnp.float32)
            empty = jnp.full((b, h, local_s), NEG_INF, jnp.float32)
            # promote to q's varying axes so the cond branches type-match
            # under shard_map's replication checker
            return (
                pvary_missing(zeros, vma_of(q)),
                pvary_missing(empty, vma_of(q)),
            )

        if not causal and window:
            # encoder local attention: the symmetric band |q - k| < window.
            # Chunks more than max_back ranks away IN EITHER direction miss
            # the band entirely (their kernels are skipped); the diagonal
            # runs the symmetric windowed kernel, offset chunks the banded
            # kernel with a SIGNED static offset.
            max_back = min(n_chunks - 1, -(-(window - 1) // local_s))
            # back(0) IS the symmetric diagonal (offset 0: the banded
            # kernel, or the plain full kernel when the whole chunk sits
            # inside the band); out-of-band distances clip onto the shared
            # leading skip entry
            branches = [skip] + [
                back(j) for j in range(-max_back, max_back + 1)
            ]
            j_signed = my_chunk - src_chunk
            in_band = jnp.abs(j_signed) <= max_back
            idx = jnp.where(in_band, j_signed + max_back + 1, 0)
            o_c, lse_c = lax.switch(idx, branches, None)
        elif not causal:
            # bidirectional, no window: every chunk fully visible
            o_c, lse_c = full(None)
        elif window:
            # chunks more than max_back ranks back are fully out of window:
            # chunk j's closest (q, k) pair sits (j-1)*local_s + 1 apart, so
            # it contributes iff (j-1)*local_s + 1 < window
            # <=> j <= ceil((window - 1) / local_s)
            max_back = min(n_chunks - 1, -(-(window - 1) // local_s))
            branches = [diag] + [back(j) for j in range(1, max_back + 1)] + [skip]
            j_back = my_chunk - src_chunk  # < 0: future chunk (skip)
            idx = jnp.where(
                j_back < 0, max_back + 1, jnp.minimum(j_back, max_back + 1)
            )
            o_c, lse_c = lax.switch(idx, branches, None)
        else:
            o_c, lse_c = lax.cond(
                src_chunk == my_chunk,
                diag,
                lambda op: lax.cond(src_chunk < my_chunk, full, skip, op),
                None,
            )
        return combine_chunks(out, lse, o_c, lse_c)

    if use_checkpoint:
        one_chunk = jax.checkpoint(one_chunk)

    def step(carry, _):
        acc, (k_cur, v_cur, seg_cur, src_chunk) = carry
        acc = one_chunk(acc, (k_cur, v_cur, seg_cur, src_chunk))
        perm = [(i, (i + 1) % n_chunks) for i in range(n_chunks)]
        k_next = lax.ppermute(k_cur, axis_name, perm)
        v_next = lax.ppermute(v_cur, axis_name, perm)
        seg_next = (
            None if seg_cur is None else lax.ppermute(seg_cur, axis_name, perm)
        )
        return (acc, (k_next, v_next, seg_next, (src_chunk - 1) % n_chunks)), None

    out0 = jnp.zeros((b, local_s, h, d), jnp.float32)
    lse0 = jnp.full((b, h, local_s), NEG_INF, jnp.float32)
    from tpu_parallel.core.metrics import pvary_missing, vma_of

    # include the ring axis itself, in deterministic order — see the
    # matching notes in ring_attention
    q_vma = vma_of(q)
    ring_vma = q_vma + tuple(a for a in vma_of(my_chunk) if a not in q_vma)
    out0, lse0, k0, v0 = (pvary_missing(x, ring_vma) for x in (out0, lse0, k, v))
    seg0 = None if seg_local is None else pvary_missing(seg_local, ring_vma)
    ((out, _), _), _ = lax.scan(
        step, ((out0, lse0), (k0, v0, seg0, my_chunk)), None, length=n_chunks
    )
    return out.astype(q.dtype)
