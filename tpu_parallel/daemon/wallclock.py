"""The daemon's ONE wall-clock surface.

Everything under ``tpu_parallel/serving`` and ``tpu_parallel/cluster``
runs on an injectable clock — that is the determinism contract
``scripts/check_clock.py`` enforces, and it is what lets the chaos
harness replay fault storms tick-for-tick.  The daemon is the layer
that finally has to touch real time (it serves real clients on real
sockets), but it touches it HERE and nowhere else: :class:`WallClock`
is injected into the :class:`~tpu_parallel.cluster.frontend.Frontend`
as its ``clock`` and into the daemon loop as its sleep source, so every
deadline, SLO window and journal timestamp flows through one swappable
object.  Tests hand the daemon a fake clock instead and the whole
recovery/drain story runs deterministically — the daemon shell stays as
testable as the core it wraps.

``check_clock`` permits direct ``time.*`` reads in THIS FILE ONLY (see
``WALLCLOCK_FILES`` there); a ``time.monotonic()`` anywhere else in the
daemon package is a static-check failure, not a code-review argument.
"""

from __future__ import annotations

import time


class WallClock:
    """Callable monotonic clock + sleep, the production time source.

    The daemon passes the instance itself as the frontend's ``clock``
    (it is callable) and uses :meth:`sleep` to pace the tick pump.  A
    fake replacement needs only ``__call__`` and ``sleep`` — see
    ``tests/test_daemon.py``.
    """

    def __call__(self) -> float:
        return time.monotonic()

    def sleep(self, seconds: float) -> None:
        if seconds > 0:
            time.sleep(seconds)
