"""Autoregressive generation with a KV cache, fully jitted.

No reference capability exists (the reference is training-only tutorial
scripts — SURVEY.md §0); this provides the inference path users expect of a
framework.  The decode loop is a ``lax.scan`` over single-token steps: each
step appends K/V to the per-layer ``cache`` collection
(:class:`~tpu_parallel.models.layers.Attention` decode mode) and attends
against the cached prefix only — O(seq) per generated token instead of the
O(seq^2) of re-running the full forward.

Works for MHA and GQA, learned and RoPE positions, scan and unrolled layer
stacks.  TP meshes work by wrapping :func:`generate` in ``shard_map`` (the
cache shards over heads exactly as activations do).  Pipeline-parallel
decoding is not supported.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.models.gpt import GPTLM
from tpu_parallel.parallel.tp import export_single_device_params  # noqa: F401  (re-export: mesh-trained state -> generate-able params)


def _sample(logits: jax.Array, rng: jax.Array, temperature: float, top_k: int):
    """One token per row from [batch, vocab] logits."""
    # models emit cfg.dtype (bf16) logits; sample in fp32 so the temperature
    # scale and the categorical's gumbel trick don't round at bf16
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


@functools.partial(
    jax.jit, static_argnums=(0,), static_argnames=("max_new_tokens", "temperature", "top_k")
)
def generate(
    model: GPTLM,
    params,
    prompt: jax.Array,
    rng: Optional[jax.Array] = None,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [batch, P].

    Returns [batch, max_new_tokens] of sampled tokens (greedy when
    ``temperature == 0``).  The prompt must fit the model's ``seq_len``
    together with the new tokens (the cache is allocated at ``seq_len``).
    """
    cfg = model.config
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds seq_len ({cfg.seq_len})"
        )
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # Prefill: one batched forward over the prompt creates and fills the
    # cache ('cache' is created on the fly because it is marked mutable).
    positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
    logits, variables = model.apply(
        {"params": params},
        prompt,
        positions=positions,
        train=False,
        decode=True,
        mutable=["cache"],
    )
    rng, sub = jax.random.split(rng)
    first = _sample(logits[:, -1], sub, temperature, top_k)

    def step(carry, _):
        cache, tok, pos, rng = carry
        logits, updated = model.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((b, 1), pos, jnp.int32),
            train=False,
            decode=True,
            mutable=["cache"],
        )
        rng, sub = jax.random.split(rng)
        nxt = _sample(logits[:, -1], sub, temperature, top_k)
        return (updated["cache"], nxt, pos + 1, rng), tok

    init = (variables["cache"], first, jnp.int32(prompt_len), rng)
    (_, last, _, _), toks = lax.scan(step, init, None, length=max_new_tokens - 1)
    # scan emits the *input* token of each step; append the final sample
    return jnp.concatenate([toks.T, last[:, None]], axis=1)
