"""Mixture-of-Experts MLP with expert parallelism (top-k routing).

``moe_top_k=1`` is Switch (gate = raw router probability); ``>1`` is
GShard-style with gates renormalized over the chosen experts and capacity
claimed choice-major under the same static-shape dispatch.

No reference capability exists (SURVEY.md §2.2: EP "Absent"); built for the
framework's EP slot, TPU-first:

- **Static shapes everywhere**: capacity-based routing (``capacity_factor``)
  with one-hot dispatch/combine einsums — the Mesh-TensorFlow/Switch
  formulation that XLA compiles to dense MXU work, no dynamic gather.
- **Expert parallelism over the ``model`` mesh axis**: each rank owns
  ``n_experts / ep`` experts (weights stacked per-rank via ModuleShard, so
  gradient sync already treats them as partitioned).  Activations are
  replicated over the model axis (the batch shards over data/seq), so
  dispatch needs **no communication at all**: each rank slices out its own
  experts' dispatch/combine masks, runs only its experts (``1/ep`` of the
  expert FLOPs), and the partial combines close with one ``psum`` — the
  same collective shape as a TP row-parallel projection, so the existing
  pmean-over-model gradient sync stays exact.
- **Router in fp32** (numerically fragile softmax over experts), activations
  in the model dtype.
- Load-balance auxiliary loss (Switch: ``E * sum(f_i * P_i)``) sown into a
  ``"losses"`` collection; ``make_gpt_loss`` folds it into the objective.
  ``aux_scale`` gates the sown value — the pipeline schedule passes 0.0 on
  bubble ticks so garbage activations contribute exactly zero to (and take
  no gradient from) the router regularizer.

Works mesh-free too (no bound model axis): all experts live on the one
device, no slicing, no psum — same module, same params layout rules as the
rest of the structural-TP design.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.parallel.tp import ModuleShard, axis_size_or_none


class ExpertFFN(nn.Module):
    """One expert: the standard transformer FFN at model dtype.

    Projection outputs carry the same ``"proj"`` checkpoint names as the
    dense MLP (layers.py), so the proj/proj_attn remat policies save the
    expert matmuls instead of recomputing them in the backward.
    """

    config: "TransformerConfig"  # noqa: F821 — forward ref, see layers.py

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        from jax.ad_checkpoint import checkpoint_name

        cfg = self.config
        hidden = cfg.mlp_ratio * cfg.d_model
        if cfg.mlp == "swiglu":
            gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="gate")(x)
            up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
            h = nn.silu(checkpoint_name(gate, "proj")) * checkpoint_name(up, "proj")
        else:
            h = nn.gelu(
                checkpoint_name(nn.Dense(hidden, dtype=cfg.dtype, name="up")(x), "proj")
            )
        return checkpoint_name(
            nn.Dense(cfg.d_model, dtype=cfg.dtype, name="down")(h), "proj"
        )


def _dispatch_masks(onehots, gates, n_experts: int, capacity: int):
    """[T, E, C] dispatch/combine one-hots from per-choice expert one-hots.

    Choices claim capacity slots choice-major (every token's first choice
    before any second choice), tracked by a running per-expert count so the
    slot index stays unique across choices.  Shared by the dense
    (full-token-set) and all_to_all (per-sender-slice) dispatch paths —
    only the token set and the capacity quota differ."""
    tokens = onehots[0].shape[0]
    count = jnp.zeros((n_experts,), jnp.float32)
    dispatch = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
    combine = jnp.zeros((tokens, n_experts, capacity), jnp.float32)
    for j, onehot in enumerate(onehots):
        position = (jnp.cumsum(onehot, axis=0) - 1.0 + count[None, :]) * onehot
        in_capacity = (position < capacity).astype(jnp.float32) * onehot
        pos_idx = jnp.sum(position, axis=-1).astype(jnp.int32)  # [T]
        pos_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
        # [T, E, C]: 1 where token t's choice j landed in slot c of expert e
        dispatch_j = in_capacity[:, :, None] * pos_onehot[:, None, :]
        dispatch = dispatch + dispatch_j
        combine = combine + dispatch_j * gates[:, j, None, None]
        count = count + jnp.sum(onehot, axis=0)
    return dispatch, combine


def _topk_gates(probs, top_k: int):
    """(gates [T, k], one-hots list) for top-k routing: Switch keeps the raw
    router probability at k=1; GShard renormalizes over the chosen experts
    so the combined output is a convex mixture."""
    n_experts = probs.shape[-1]
    gate_vals, expert_idx = lax.top_k(probs, top_k)  # [T, k] each
    if top_k == 1:
        gates = gate_vals
    else:
        gates = gate_vals / jnp.sum(gate_vals, axis=-1, keepdims=True)
    onehots = [
        jax.nn.one_hot(expert_idx[:, j], n_experts, dtype=jnp.float32)
        for j in range(top_k)
    ]
    return gates, onehots


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: top-k routed experts, EP over ``model``."""

    config: "TransformerConfig"  # noqa: F821

    @nn.compact
    def __call__(
        self, x: jax.Array, train: bool = True, aux_scale: jax.Array | None = None
    ) -> jax.Array:
        cfg = self.config
        n_experts = cfg.moe_experts
        ep_size = axis_size_or_none(cfg.model_axis) or 1
        if n_experts % ep_size != 0:
            raise ValueError(
                f"moe_experts={n_experts} not divisible by model axis {ep_size}"
            )
        local_experts = n_experts // ep_size
        b, s, d = x.shape
        tokens = b * s
        xf = x.reshape(tokens, d)

        # --- route (fp32) ---------------------------------------------------
        top_k = cfg.moe_top_k
        if not 1 <= top_k <= n_experts:
            # moe_experts=0 disables MoE entirely (dense MLP); top_k has no
            # analogous "off" value, so reject rather than silently clamp
            raise ValueError(
                f"moe_top_k={top_k} must be in [1, moe_experts={n_experts}]"
            )
        router = nn.Dense(
            n_experts, use_bias=False, dtype=jnp.float32, name="router"
        )
        if cfg.moe_dispatch not in ("dense", "alltoall"):
            raise ValueError(
                f"moe_dispatch={cfg.moe_dispatch!r} (dense | alltoall)"
            )
        if cfg.moe_dispatch == "alltoall" and cfg.moe_router == "expert_choice":
            raise NotImplementedError(
                "expert_choice routing needs the dense dispatch (each "
                "expert takes its global top-capacity tokens; a sharded "
                "token set cannot rank them locally)"
            )
        if (
            cfg.moe_dispatch == "alltoall"
            and cfg.moe_router == "topk"
            and ep_size > 1
        ):
            # ep == 1 falls through to the dense path: with one rank there
            # is no axis to exchange over, and the masks are already local
            return self._topk_alltoall(x, router, aux_scale, ep_size, train)
        logits = router(xf.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]

        if cfg.moe_router == "expert_choice":
            return self._expert_choice(
                x, xf, probs, aux_scale, ep_size, local_experts, train
            )
        if cfg.moe_router != "topk":
            raise ValueError(
                f"moe_router={cfg.moe_router!r} (topk | expert_choice)"
            )
        gates, onehots = _topk_gates(probs, top_k)

        # Load-balance loss: E * sum_i fraction_i * router_prob_i, with
        # fraction_i the share of (token, choice) assignments to expert i
        # (Switch's f_i at top_k=1).  aux_scale (0.0 on pipeline bubble
        # ticks) zeroes both the value and, through the multiply, its
        # gradient into the router.
        assign_frac = sum(oh.mean(axis=0) for oh in onehots) / top_k
        balance = n_experts * jnp.sum(assign_frac * probs.mean(axis=0))
        if aux_scale is not None:
            balance = balance * jnp.asarray(aux_scale, jnp.float32)
        self.sow(
            "losses",
            "moe_balance",
            balance,
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )

        # --- capacity + dispatch masks (static shapes) ----------------------
        capacity = max(
            1, int(cfg.moe_capacity_factor * top_k * tokens / n_experts + 0.999)
        )
        dispatch, combine = _dispatch_masks(onehots, gates, n_experts, capacity)

        # --- expert parallelism: slice my experts, partial-combine, psum ----
        return self._apply_experts(
            x, xf, dispatch, combine, ep_size, local_experts, train
        )

    def _topk_alltoall(self, x, router, aux_scale, ep_size, train):
        """Sharded-token dispatch: each EP rank routes its ``T/ep`` token
        slice locally and exchanges expert payloads with one ``all_to_all``
        each way.

        Per-rank mask memory and dispatch-einsum cost drop from
        ``[T, E, C]`` to ``[T/ep, E, C/ep]`` (``ep^2`` smaller); expert
        FLOPs are unchanged.  Capacity becomes a per-(sender, expert)
        quota of ``C/ep`` slots — identical results to the dense path
        while nothing overflows (pinned by
        ``tests/test_moe.py::test_alltoall_matches_dense``), different
        drop CHOICES under pressure (GShard's formulation: a hot sender
        can drop while another sender's quota sits idle).

        Wire protocol (``E = ep * E_local``, ``C_s`` = per-sender quota):
        ``x_send [E, C_s, d]`` --a2a(split 0, concat 1)--> ``[E_local,
        ep*C_s, d]`` (slot blocks in sender-rank order) -> experts ->
        ``y_exp [E_local, ep*C_s, d]`` --a2a(split 1, concat 0)-->
        ``[E, C_s, d]`` back at the sender -> combine -> ``[T/ep, d]``
        --all_gather--> the replicated ``[T, d]`` the trunk expects."""
        cfg = self.config
        n_experts = cfg.moe_experts
        top_k = cfg.moe_top_k
        b, s, d = x.shape
        tokens = b * s
        if tokens % ep_size:
            raise ValueError(
                f"tokens={tokens} not divisible by EP axis size {ep_size} "
                "(alltoall dispatch shards the token set)"
            )
        t_local = tokens // ep_size
        rank = lax.axis_index(cfg.model_axis)
        xs = lax.dynamic_slice_in_dim(
            x.reshape(tokens, d), rank * t_local, t_local, axis=0
        )

        logits = router(xs.astype(jnp.float32))  # [T/ep, E]
        probs = jax.nn.softmax(logits, axis=-1)
        gates, onehots = _topk_gates(probs, top_k)

        # balance loss on GLOBAL statistics: local means pmean'd over the
        # EP axis reproduce the dense path's full-batch fractions exactly
        assign_frac = sum(oh.mean(axis=0) for oh in onehots) / top_k
        assign_frac = lax.pmean(assign_frac, cfg.model_axis)
        mean_probs = lax.pmean(probs.mean(axis=0), cfg.model_axis)
        balance = n_experts * jnp.sum(assign_frac * mean_probs)
        if aux_scale is not None:
            balance = balance * jnp.asarray(aux_scale, jnp.float32)
        self.sow(
            "losses",
            "moe_balance",
            balance,
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )

        cap_send = max(
            1, int(cfg.moe_capacity_factor * top_k * t_local / n_experts + 0.999)
        )
        dispatch, combine = _dispatch_masks(onehots, gates, n_experts, cap_send)

        # dispatch my tokens into per-expert slots, exchange payloads
        x_send = jnp.einsum(
            "td,tec->ecd", xs.astype(jnp.float32), dispatch
        ).astype(cfg.dtype)  # [E, C_s, d]
        with jax.named_scope("moe_dispatch_a2a"):
            x_recv = lax.all_to_all(
                x_send, cfg.model_axis, split_axis=0, concat_axis=1, tiled=True
            )  # [E_local, ep*C_s, d]

        import functools

        expert_stack = nn.vmap(
            ExpertFFN,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        y_exp = ModuleShard(
            functools.partial(expert_stack, cfg),
            axis_name=cfg.model_axis,
            name="experts",
        )(x_recv)  # [E_local, ep*C_s, d]

        with jax.named_scope("moe_combine_a2a"):
            y_back = lax.all_to_all(
                y_exp, cfg.model_axis, split_axis=1, concat_axis=0, tiled=True
            )  # [E, C_s, d] — my tokens' outputs, expert-major
        ys = jnp.einsum(
            "ecd,tec->td", y_back.astype(jnp.float32), combine
        )  # [T/ep, d]
        with jax.named_scope("moe_token_all_gather"):
            y = lax.all_gather(
                ys, cfg.model_axis, axis=0, tiled=True
            )  # [T, d] replicated over EP, as the trunk expects
        y = y.astype(cfg.dtype).reshape(b, s, d)
        if cfg.dropout_rate > 0.0:
            y = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(y)
        return y

    def _expert_choice(
        self, x, xf, probs, aux_scale, ep_size, local_experts, train
    ):
        """Expert-choice routing: each expert takes its top-``capacity``
        tokens by router probability (Zhou et al., 2022).  Every expert is
        exactly full, so there is no balance loss to tune — a zero is still
        sown to keep the losses collection shape stable for the pipeline's
        bubble masking."""
        cfg = self.config
        n_experts = cfg.moe_experts
        tokens = xf.shape[0]
        capacity = max(1, int(cfg.moe_capacity_factor * tokens / n_experts + 0.999))
        if capacity > tokens:
            raise ValueError(
                f"expert capacity {capacity} > {tokens} tokens — lower "
                "moe_capacity_factor or use more tokens per batch"
            )
        # gates [E, C]: the chosen tokens' router probs; idx [E, C] token ids
        gates, idx = lax.top_k(probs.T, capacity)
        picked = jax.nn.one_hot(idx, tokens, dtype=jnp.float32)  # [E, C, T]
        dispatch = picked.transpose(2, 0, 1)  # [T, E, C]
        combine = (picked * gates[:, :, None]).transpose(2, 0, 1)

        del aux_scale  # EC has no balance loss to gate; the sown zero keeps
        # the losses collection shape stable for the pipeline bubble masking
        self.sow(
            "losses",
            "moe_balance",
            jnp.float32(0.0),
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )
        return self._apply_experts(
            x, xf, dispatch, combine, ep_size, local_experts, train
        )

    def _apply_experts(
        self, x, xf, dispatch, combine, ep_size, local_experts, train
    ):
        """Shared tail: slice my experts' masks, run the expert FFNs at
        1/ep cost, partial-combine, close with one psum."""
        cfg = self.config
        b, s, d = x.shape
        if ep_size > 1:
            rank = lax.axis_index(cfg.model_axis)
            dispatch = lax.dynamic_slice_in_dim(
                dispatch, rank * local_experts, local_experts, axis=1
            )
            combine = lax.dynamic_slice_in_dim(
                combine, rank * local_experts, local_experts, axis=1
            )

        x_exp = jnp.einsum("td,tec->ecd", xf.astype(jnp.float32), dispatch)
        x_exp = x_exp.astype(cfg.dtype)  # [E/ep, C, d]

        expert_stack = nn.vmap(
            ExpertFFN,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        if ep_size > 1:
            import functools

            y_exp = ModuleShard(
                functools.partial(expert_stack, cfg),
                axis_name=cfg.model_axis,
                name="experts",
            )(x_exp)
        else:
            y_exp = expert_stack(cfg, name="experts")(x_exp)

        # --- back to tokens -------------------------------------------------
        # Partial combine over my experts; the psum sums the disjoint expert
        # contributions (TP row-parallel shape; pmean-over-model grad sync
        # keeps upstream gradients exact, see tests/test_moe.py).
        y = jnp.einsum("ecd,tec->td", y_exp.astype(jnp.float32), combine)
        if ep_size > 1:
            with jax.named_scope("moe_combine_psum"):
                y = lax.psum(y, cfg.model_axis)
        y = y.astype(cfg.dtype).reshape(b, s, d)
        if cfg.dropout_rate > 0.0:
            y = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(y)
        return y
