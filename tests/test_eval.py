"""Evaluation path: metrics-only step with dropout off, on sharded meshes."""

import jax
import numpy as np

from tpu_parallel.runtime import MeshConfig
from tpu_parallel.train_lib import Trainer, TrainerConfig


def _trainer(mesh_cfg, **ov):
    config = TrainerConfig(
        model="tiny",
        model_overrides=dict(num_microbatches=1, **ov),
        mesh=mesh_cfg,
        global_batch_size=16,
        steps=4,
        log_every=100,
        donate=False,
    )
    return Trainer(config)


def test_evaluate_returns_global_metrics(devices):
    t = _trainer(MeshConfig(data=8))
    t.init()
    ev = t.evaluate(steps=3)
    assert set(ev) >= {"loss", "accuracy"}
    assert ev["loss"] > 0


def test_evaluate_is_deterministic_with_dropout_model(devices):
    """Eval uses train=False: repeated evals on one batch agree exactly,
    even for a model with dropout (the train step would not)."""
    t = _trainer(MeshConfig(data=8), dropout_rate=0.3)
    t.init()
    a = t.evaluate(steps=1)["loss"]
    b = t.evaluate(steps=1)["loss"]
    assert np.isclose(a, b), (a, b)


def test_evaluate_does_not_change_state(devices):
    t = _trainer(MeshConfig(data=8))
    t.init()
    before = jax.tree_util.tree_leaves(t.state.params)[0].copy()
    t.evaluate(steps=2)
    after = jax.tree_util.tree_leaves(t.state.params)[0]
    np.testing.assert_array_equal(np.asarray(before), np.asarray(after))
