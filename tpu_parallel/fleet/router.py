"""The fleet router core: one client-facing serving surface over N
daemon processes.

This is the transport-agnostic half of the fleet (docs/14_fleet.md):
everything here is driven through two injected seams — a ``clock``
(``scripts/check_clock.py`` keeps wall time out of this package) and a
:class:`FleetTransport` (the wire; ``fleet/http.py`` implements it over
urllib, unit tests implement it over scripted in-memory daemons).  The
router owns four pieces of state and nothing else:

- the **consistent-hash ring** (:class:`~tpu_parallel.cluster.router.
  HashRing` over daemon addresses) — the same placement function the
  in-process :class:`PrefixAffinityRouter` uses over replica ids, so a
  prompt's bucket-aligned prefix lands on the daemon whose radix cache
  already holds it, and only a dead daemon's keys slide to successors;
- the **peer breaker** (:class:`~tpu_parallel.fleet.peers.PeerSet`) —
  HEALTHY→DEGRADED→DEAD from probe + request evidence, backoff
  re-probe, half-open recovery;
- the **request table** — every accepted request's client-visible
  tokens and its current backing ``(addr, daemon request id)``.  The
  tokens the router has relayed are what make cross-host handoff
  possible: when a daemon dies mid-stream, the request is resubmitted
  to a survivor as ``prompt + delivered`` with the remaining token
  budget — the same forced-prefix mechanism daemon crash recovery
  replays through, so greedy continuations stay bitwise;
- the **dedupe ledger** — client ``dedupe_token`` → router request id.
  The daemon's journal makes retries idempotent per host; the ledger
  makes them idempotent fleet-wide, because after a handoff the
  original token only exists on a dead host.

Admission is typed end to end: a daemon's 503 (draining / degraded /
journal error / role) excludes that peer and tries the next ring
successor; a transport failure feeds the breaker and does the same;
running out of peers is a typed ``no_peer`` 503, never a hang.  Remote
KV migration rides two transport calls (``kv_export`` → ``kv_import``):
recovered and newly joined peers warm-start their hottest chains from a
donor, and a draining peer ships live prefixes forward — imports
re-verify per-block CRCs engine-side, so corrupt bytes are a counted
typed refusal, never served K/V.

Prefill/decode disaggregation (``fleet/roles.py``, docs/14_fleet.md)
reuses all of the above as a HOT path: when the topology holds both
prefill- and decode-role peers, fresh submissions place on
prefill-capable peers only, and at first-token time the router ships
the prompt's written KV blocks (``kv_export_request`` → chunked
``kv_import``) to a decode-role peer picked by the same
prefix-affinity ring, then re-points the stream there via the SAME
forced-prefix handoff the death path uses — fired on success instead
of death, bitwise for greedy, client-stable SSE indices.  Every way
the migration can fail is a typed ``fleet_handoff_fallbacks_total``
reason and the request keeps decoding colocated: disaggregation can
lose latency, never tokens.
"""

from __future__ import annotations

import itertools
import os
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from tpu_parallel.cluster.replica import DEAD, HEALTHY
from tpu_parallel.cluster.router import HashRing, hash_prompt_key, _stable_hash
from tpu_parallel.fleet.peers import PeerPolicy, PeerSet
from tpu_parallel.fleet.roles import (
    PHASE_DECODE,
    ROLE_DECODE,
    ROLE_GAUGE,
    ROLE_MIXED,
    ROLES,
    can_prefill,
    disaggregated,
    validate_role,
)
from tpu_parallel.obs.exporters import (
    _prom_labels,
    _prom_value,
    parse_prometheus_text,
    prometheus_text,
)
from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.obs.spool import read_span_log
from tpu_parallel.obs.stitch import phase_breakdown
from tpu_parallel.obs.tracer import NULL_TRACER, TraceContext
from tpu_parallel.serving.kv_wire import DEFAULT_MAX_WIRE_BYTES, chunk_body
from tpu_parallel.serving.request import (
    CANCELLED,
    EXPIRED,
    FAILED,
    FINISHED,
    REJECTED,
)

FLEET_TRACK = "fleet"  # the router's tracer track

# fleet-level typed rejection reasons (the daemon's reasons pass through)
REJECT_NO_PEER = "no_peer"
REJECT_HANDOFFS = "handoff_limit"

_TERMINAL = frozenset({FINISHED, FAILED, CANCELLED, EXPIRED, REJECTED})
# daemon response codes that are the CLIENT's problem — no retry helps
_CLIENT_ERROR_CODES = frozenset({400, 404, 413})
# consecutive no-peer handoff attempts a stream relay tolerates (one
# probe-interval wait apiece) before failing the request typed
_STREAM_RETRY_LIMIT = 40

__all__ = [
    "FLEET_TRACK",
    "REJECT_NO_PEER",
    "REJECT_HANDOFFS",
    "FleetRouter",
    "FleetTransport",
    "TransportError",
]


class TransportError(Exception):
    """Any wire-level failure talking to one peer — refused connection,
    timeout, torn stream.  One exception type because the breaker does
    not care WHICH symptom a dead host shows."""

    def __init__(self, addr: str, detail: str):
        super().__init__(f"{addr}: {detail}")
        self.addr = addr
        self.detail = detail


class FleetTransport:
    """The wire contract the router drives (duck-typed; this class just
    documents it).  Every method either returns the peer's typed
    response — ``(status_code, parsed body)`` — or raises
    :class:`TransportError`; an HTTP error code is a RESPONSE (the peer
    is alive and saying something typed), only failing to get one is
    transport failure.

    Every method takes ``trace`` — a :class:`~tpu_parallel.obs.tracer.
    TraceContext` or None — and a real transport propagates it as the
    ``X-TP-Trace`` header so the receiving daemon's spans join the
    sender's trace.  ``scripts/check_trace.py`` enforces that every
    call SITE in the fleet package passes the kwarg: forgetting it is a
    silent trace break, exactly the bug class an AST gate exists for."""

    def healthz(
        self, addr: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def submit(
        self, addr: str, body: dict, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def result(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def cancel(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def stream(
        self, addr: str, request_id: str, idle_timeout: float, trace=None
    ) -> Iterator[dict]:
        """Yield the daemon's SSE events as dicts; raise
        :class:`TransportError` on disconnect/idle-timeout (including
        MID-iteration — that is the handoff trigger)."""
        raise NotImplementedError

    def kv_export(
        self, addr: str, max_blocks: int, timeout: float, trace=None
    ) -> Tuple[int, bytes]:
        raise NotImplementedError

    def kv_export_request(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, bytes]:
        """Export ONE live request's written KV prefix (the
        prefill→decode handoff donor leg)."""
        raise NotImplementedError

    def kv_import(
        self, addr: str, blob: bytes, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        raise NotImplementedError

    def metricsz(
        self, addr: str, timeout: float, trace=None
    ) -> Tuple[int, str]:
        """The peer's Prometheus text exposition (the fleet
        aggregation scrape leg)."""
        raise NotImplementedError

    def tracez(
        self, addr: str, trace_id: Optional[str], timeout: float,
        trace=None,
    ) -> Tuple[int, dict]:
        """The peer's span-log payload (``/v1/tracez``), optionally
        filtered to one trace id."""
        raise NotImplementedError


class _FleetRequest:
    """One accepted client request: its client-visible token stream and
    which daemon currently computes it."""

    __slots__ = (
        "rid", "body", "prompt", "max_new", "dedupe_token", "addr",
        "daemon_rid", "base", "tokens", "status", "finish_reason",
        "detail", "handoffs", "inflight", "done_at", "disagg_done",
        "trace", "t_submit", "t_first",
    )

    def __init__(self, rid: str, body: dict, addr: str, daemon_rid: str,
                 status: str):
        self.rid = rid
        self.body = body
        self.prompt = [int(t) for t in body["prompt"]]
        self.max_new = int(body.get("max_new_tokens", 32))
        self.dedupe_token = body.get("dedupe_token")
        self.addr = addr
        self.daemon_rid = daemon_rid
        self.base = 0  # tokens generated by PREVIOUS incarnations
        self.tokens: List[int] = []  # full client-visible generation
        self.status = status
        self.finish_reason: Optional[str] = None
        self.detail: Optional[str] = None
        self.handoffs = 0
        self.inflight = False  # a handoff submit is on the wire
        self.done_at: Optional[float] = None  # clock time of terminal
        # the prefill→decode migration is ONE-SHOT per request: fired
        # (or typed-fallen-back) at first-token time, never retried —
        # a request that already moved, or already failed to, decodes
        # where it sits
        self.disagg_done = False
        self.trace: Optional[TraceContext] = None
        self.t_submit: Optional[float] = None  # router clock at accept
        self.t_first: Optional[float] = None  # first live token relayed

    @property
    def terminal(self) -> bool:
        return self.status in _TERMINAL

    def record(self) -> dict:
        """The client-facing record — same shape the daemon returns, so
        swapping a single daemon for a fleet does not change a client."""
        return {
            "request_id": self.rid,
            "status": self.status,
            "finish_reason": self.finish_reason,
            "detail": self.detail,
            "tokens": list(self.tokens),
            "handoffs": self.handoffs,
            "peer": self.addr,
        }


class FleetRouter:
    """See the module docstring.  Thread-safety: handler threads call
    ``submit`` / ``result`` / ``stream`` / ``cancel``; the pump thread
    calls ``probe_tick``.  All shared state mutates under one lock, and
    NO network I/O ever runs under it — every transport call (including
    the blocking reads of a stream relay and the writes back to a slow
    client) happens with the lock released, with state re-checked on
    re-acquire, so one wedged peer or client can never stall the
    fleet's other requests."""

    def __init__(
        self,
        peer_addrs: Sequence[str],
        *,
        clock,
        transport: FleetTransport,
        buckets: Optional[Sequence[int]] = None,
        policy: Optional[PeerPolicy] = None,
        registry: Optional[MetricRegistry] = None,
        tracer=None,
        vnodes: int = 64,
        max_handoffs: int = 8,
        warm_start_blocks: int = 16,
        warm_on_recovery: bool = True,
        terminal_ttl_seconds: float = 600.0,
        roles: Optional[Dict[str, str]] = None,
        disagg_max_wire_bytes: int = DEFAULT_MAX_WIRE_BYTES,
        span_spool=None,
    ):
        self.clock = clock
        self.transport = transport
        self.buckets = tuple(buckets) if buckets else None
        self.policy = policy or PeerPolicy()
        self.registry = registry if registry is not None else MetricRegistry()
        self.tracer = tracer if tracer is not None else NULL_TRACER
        self.ring = HashRing(list(peer_addrs), vnodes)
        self.peers = PeerSet(peer_addrs, clock, self.policy)
        self.max_handoffs = max_handoffs
        self.warm_start_blocks = warm_start_blocks
        self.warm_on_recovery = warm_on_recovery
        self.terminal_ttl_seconds = terminal_ttl_seconds
        self.disagg_max_wire_bytes = disagg_max_wire_bytes
        # addr -> fleet role.  Config-pinned entries (the ``roles``
        # kwarg, later ``set_role`` calls) are OVERRIDES the probe loop
        # never touches; everyone else starts mixed and updates from
        # the role their /healthz advertises.
        self._roles: Dict[str, str] = {
            addr: ROLE_MIXED for addr in peer_addrs
        }
        self._role_overrides: Set[str] = set()
        for addr, role in (roles or {}).items():
            self._roles[addr] = validate_role(role)
            self._role_overrides.add(addr)
        self._lock = threading.RLock()
        self._requests: Dict[str, _FleetRequest] = {}
        self._ledger: Dict[str, str] = {}  # dedupe_token -> rid
        self._stale: Dict[str, List[str]] = {}  # addr -> handed-off rids
        self._seq = itertools.count()
        # disambiguates handoff dedupe tokens for requests the client
        # submitted WITHOUT a token: local request ids restart at
        # f000000 in every router instance, so two routers (or one
        # restarted) over the same daemons would otherwise replay each
        # other's handoff records out of the daemons' dedupe tables
        self._instance = uuid.uuid4().hex[:8]
        self._stop = threading.Event()
        self._m_submits = self.registry.counter("fleet_submissions_total")
        self._m_dedupe = self.registry.counter("fleet_dedupe_hits_total")
        self._m_handoffs = self.registry.counter("fleet_handoffs_total")
        self._m_completions = self.registry.counter("fleet_completions_total")
        self._m_probes = self.registry.counter("fleet_probes_total")
        self._m_probe_failures = self.registry.counter(
            "fleet_probe_failures_total"
        )
        self._m_peer_deaths = self.registry.counter("fleet_peer_deaths_total")
        self._m_kv_export_bytes = self.registry.counter(
            "fleet_kv_export_bytes_total"
        )
        self._m_disagg = self.registry.counter("fleet_handoff_disagg_total")
        self._m_handoff_bytes = self.registry.counter(
            "fleet_handoff_bytes_total"
        )
        self._m_handoff_seconds = self.registry.counter(
            "fleet_handoff_seconds_total"
        )
        # span spooling (tracez) + fleet metrics aggregation state.  The
        # spool has its own lock: drains happen from the pump thread AND
        # any handler thread serving /v1/tracez, and must not contend
        # with the request-table lock (a drain does file IO).
        self._spool = span_spool
        self._spool_lock = threading.Lock()
        self._peer_metrics: Dict[str, str] = {}  # addr -> last /metricsz

    def _observe_phase(self, phase: str, seconds: float) -> None:
        self.registry.histogram(
            "fleet_phase_seconds", phase=phase
        ).observe(max(0.0, seconds))

    def _note_clock_sync(
        self, addr: str, t_send: float, t_recv: float, body
    ) -> None:
        """Record one (send, recv, peer-reported) timestamp triple — the
        stitcher's clock-alignment sample.  Any wire response carrying a
        ``ts`` field feeds it; min-RTT samples win at stitch time."""
        if not self.tracer.enabled or not isinstance(body, dict):
            return
        ts = body.get("ts")
        if isinstance(ts, (int, float)) and not isinstance(ts, bool):
            self.tracer.instant(
                "clock_sync", track=FLEET_TRACK, peer=addr,
                t_send=t_send, t_recv=t_recv, peer_ts=float(ts),
            )

    # -- roles (prefill/decode disaggregation) -----------------------------

    def set_role(self, addr: str, role: str) -> bool:
        """Pin ``addr``'s fleet role (the autopilot's re-role lever and
        the operator override).  The pin survives probe updates — a
        re-roled daemon whose config still says mixed keeps routing as
        its new role.  False for an unknown peer."""
        validate_role(role)
        with self._lock:
            if self.peers.get(addr) is None:
                return False
            self._roles[addr] = role
            self._role_overrides.add(addr)
        self.registry.gauge("fleet_role", peer=addr).set(ROLE_GAUGE[role])
        return True

    def role_of(self, addr: str) -> str:
        with self._lock:
            return self._roles.get(addr, ROLE_MIXED)

    def role_counts(self) -> Dict[str, int]:
        """Current fleet role census (the autopilot's sense input)."""
        with self._lock:
            counts = {role: 0 for role in ROLES}
            for addr in self.peers.states():
                counts[self._roles.get(addr, ROLE_MIXED)] += 1
            return counts

    def pick_rerole(self, to_role: str) -> Optional[str]:
        """A deterministic IDLE, HEALTHY, mixed-role peer the autopilot
        may re-role toward ``to_role`` — idle because flipping a daemon
        mid-stream would strand its open requests behind a role gate.
        None when no such peer exists (the autopilot's typed refusal)."""
        validate_role(to_role)
        with self._lock:
            busy: Set[str] = {
                req.addr
                for req in self._requests.values()
                if not req.terminal
            }
            healthy = set(self.peers.healthy())
            candidates = sorted(
                addr
                for addr, role in self._roles.items()
                if role == ROLE_MIXED
                and addr in healthy
                and addr not in busy
            )
        return candidates[0] if candidates else None

    def _disagg_active(self) -> bool:
        """Disaggregation is a TOPOLOGY property: live iff the fleet
        holds at least one prefill-role and one decode-role peer.
        All-mixed fleets run the PR 16 colocated path untouched."""
        return disaggregated(self._roles)

    # -- placement ---------------------------------------------------------

    def _walk(self, prompt: Sequence[int]) -> Iterator[str]:
        return self.ring.walk(hash_prompt_key(prompt, self.buckets))

    def _pick(
        self,
        prompt: Sequence[int],
        exclude: Set[str],
        need: Optional[str] = None,
    ) -> Optional[str]:
        """Ring-ordered placement honoring health AND role: the first
        HEALTHY ring successor of the prompt's prefix key, else the
        first DEGRADED one (a shaky peer beats a typed no_peer), else
        None.  ``need="prefill"`` skips decode-only peers (fresh
        submissions would bounce off their typed role gate);
        ``need="decode"`` walks the same prefix-affinity ring but keeps
        ONLY decode-role peers — the disaggregation target choice."""
        states = self.peers.states()
        fallback = None
        for addr in self._walk(prompt):
            if addr in exclude:
                continue
            role = self._roles.get(addr, ROLE_MIXED)
            if need == "prefill" and not can_prefill(role):
                continue
            if need == "decode" and role != ROLE_DECODE:
                continue
            state = states.get(addr)
            if state == HEALTHY:
                return addr
            if state is not None and state != DEAD and fallback is None:
                fallback = addr
        return fallback

    # -- client surface ----------------------------------------------------

    def submit(
        self, body: dict, trace: Optional[TraceContext] = None
    ) -> Tuple[int, dict]:
        """Route one client submission; returns ``(http_code, record)``.
        Retries with exclusion across ring successors on transport
        failure or a typed 503/429 from the daemon; the accepted record
        is the ROUTER's (its request id outlives any one daemon).

        ``trace`` is an ADOPTED context (the HTTP surface parsed a
        client's ``X-TP-Trace`` header); absent one, an enabled tracer
        mints a fresh trace here — the router is the fleet's trace
        origin, and its ``route`` span is the single ROOT every other
        process's spans stitch under."""
        prompt = body.get("prompt")
        if (
            not isinstance(prompt, list)
            or not prompt
            or not all(isinstance(t, int) for t in prompt)
        ):
            return 400, {
                "error": "'prompt' must be a non-empty list of token ids"
            }
        dedupe = body.get("dedupe_token")
        with self._lock:
            if dedupe is not None and dedupe in self._ledger:
                self._m_dedupe.inc()
                req = self._requests[self._ledger[dedupe]]
                return 200, req.record()
            attempts = len(self.ring)
        ctx = trace
        if ctx is None and self.tracer.enabled:
            ctx = TraceContext.new()
        t0 = self.clock()
        exclude: Set[str] = set()
        last: Tuple[int, dict] = (503, {
            "error": "no routable peer",
            "status": REJECTED,
            "finish_reason": REJECT_NO_PEER,
        })
        for _ in range(attempts):
            with self._lock:
                # under a disaggregated topology fresh work lands only
                # on prefill-capable peers; decode-role daemons would
                # answer with their typed role 503 anyway (this filter
                # just saves the round trip)
                addr = self._pick(
                    prompt, exclude,
                    need="prefill" if self._disagg_active() else None,
                )
            if addr is None:
                break
            # the wire span's id is assigned BEFORE the call so the
            # daemon's spans can parent to it: the fork rides the
            # X-TP-Trace header, the span is recorded on return
            wire_ctx = ctx.fork() if ctx is not None else None
            t_send = self.clock()
            try:
                code, rec = self.transport.submit(
                    addr, body, self.policy.request_timeout_seconds,
                    trace=wire_ctx,
                )
            except TransportError:
                self.peers.note_failure(addr)
                exclude.add(addr)
                continue
            t_recv = self.clock()
            self.peers.note_success(addr)
            self._note_clock_sync(addr, t_send, t_recv, rec)
            if code == 200:
                redundant = None
                with self._lock:
                    if dedupe is not None and dedupe in self._ledger:
                        # a concurrent retry committed while our submit
                        # was on the wire: theirs is the record, ours is
                        # redundant daemon work to reap best-effort
                        self._m_dedupe.inc()
                        req = self._requests[self._ledger[dedupe]]
                        redundant = (addr, rec["request_id"])
                    else:
                        rid = f"f{next(self._seq):06d}"
                        req = _FleetRequest(
                            rid, dict(body), addr, rec["request_id"],
                            rec.get("status", "queued"),
                        )
                        self._requests[rid] = req
                        req.t_submit = t0
                        if dedupe is not None:
                            self._ledger[dedupe] = rid
                        self._m_submits.inc()
                        self.registry.counter(
                            "fleet_routed_total", peer=addr
                        ).inc()
                        if self.tracer.enabled and ctx is not None:
                            req.trace = ctx
                            self.tracer.bind_trace(rid, ctx)
                            root = self.tracer.record(
                                "route", FLEET_TRACK, t0, self.clock(),
                                rid=rid, peer=addr,
                            )
                            # the ROOT of the cross-process tree: it IS
                            # the context's span, and it parents to
                            # nothing (a self-parented root would make
                            # the stitched tree rootless)
                            root.span_id = ctx.span_id
                            root.parent_id = None
                            wire = self.tracer.record(
                                "wire:submit", FLEET_TRACK, t_send,
                                t_recv, rid=rid, peer=addr,
                            )
                            wire.span_id = wire_ctx.span_id
                        if self.tracer.enabled:
                            self.tracer.instant(
                                "route", track=FLEET_TRACK, rid=rid,
                                peer=addr,
                            )
                    record = req.record()
                if redundant is not None:
                    try:
                        self.transport.cancel(
                            redundant[0], redundant[1],
                            self.policy.request_timeout_seconds,
                            trace=wire_ctx,
                        )
                    except TransportError:
                        pass
                return 200, record
            if code in _CLIENT_ERROR_CODES:
                return code, rec
            # typed decline (503 draining/degraded/journal, 429
            # backpressure): this peer is out for THIS request;
            # the ring successor gets it
            self.registry.counter(
                "fleet_rejects_total",
                reason=str(rec.get("finish_reason") or code),
            ).inc()
            exclude.add(addr)
            last = (code, rec)
        if last[0] == 503:
            self.registry.counter(
                "fleet_rejects_total", reason=REJECT_NO_PEER
            ).inc()
        return last

    def result(self, rid: str) -> Tuple[int, dict]:
        """The request's current client-visible record, refreshed from
        its backing daemon when still live.  A transport failure on the
        refresh feeds the breaker and triggers handoff — a client that
        only ever POLLS still cannot lose an accepted request to a host
        death."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return 404, {"error": f"unknown request {rid}"}
            if req.terminal:
                return 200, req.record()
            addr, daemon_rid, base = req.addr, req.daemon_rid, req.base
            tr = req.trace
        try:
            code, rec = self.transport.result(
                addr, daemon_rid, self.policy.request_timeout_seconds,
                trace=tr,
            )
        except TransportError:
            self.peers.note_failure(addr)
            with self._lock:
                stranded = not req.terminal and req.addr == addr
            if stranded:
                self._handoff(req, {addr})
            with self._lock:
                return 200, req.record()
        self.peers.note_success(addr)
        disowned = False
        with self._lock:
            if req.terminal or req.addr != addr:
                return 200, req.record()  # a stream/handoff won the race
            if code == 200:
                self._merge_locked(req, base, rec)
            else:
                # the daemon answered but disowned the request (journal
                # lost / restarted empty): recompute it elsewhere
                disowned = True
        if disowned:
            self._handoff(req, {addr})
        with self._lock:
            return 200, req.record()

    def cancel(self, rid: str) -> Tuple[int, dict]:
        with self._lock:
            req = self._requests.get(rid)
            if req is None or req.terminal:
                return 404, {"error": f"unknown/done request {rid}"}
            addr, daemon_rid = req.addr, req.daemon_rid
            tr = req.trace
            self._finalize_locked(req, CANCELLED, "cancelled")
        try:
            self.transport.cancel(
                addr, daemon_rid, self.policy.request_timeout_seconds,
                trace=tr,
            )
        except TransportError:
            self.peers.note_failure(addr)  # best effort; record stands
        return 200, {"cancelled": rid}

    def stream(self, rid: str) -> Iterator[dict]:
        """Relay the request's event stream with CLIENT-STABLE indices:
        already-known tokens replay first, then live daemon events.  A
        torn daemon stream hands the request off and the relay resumes
        on the survivor — the client sees one uninterrupted stream whose
        token sequence is bitwise what the original daemon would have
        produced."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                yield {"error": f"unknown request {rid}"}
                return
            replay = list(req.tokens)
            tr = req.trace
        sent = 0
        for tok in replay:
            yield {"request_id": rid, "token": tok, "index": sent}
            sent += 1
        misses = 0  # consecutive failed handoff attempts (no progress)
        while True:
            # snapshot under the lock, YIELD outside it — a generator
            # suspended mid-yield into a slow client socket must never
            # hold the router hostage
            with self._lock:
                if req.terminal:
                    pending = list(req.tokens[sent:])
                    final = {
                        "request_id": rid, "finished": True,
                        "status": req.status,
                        "finish_reason": req.finish_reason,
                    }
                else:
                    pending = None
                    addr, daemon_rid, base = (
                        req.addr, req.daemon_rid, req.base
                    )
            if pending is not None:
                for tok in pending:
                    yield {"request_id": rid, "token": tok, "index": sent}
                    sent += 1
                yield final
                return
            moved = False
            relay = None
            if self.tracer.enabled and tr is not None:
                # one relay span per daemon attach: a handed-off stream
                # shows as consecutive relay spans on the fleet track
                relay = self.tracer.start(
                    "relay", FLEET_TRACK, rid=rid, peer=addr
                )
            try:
                try:
                    for ev in self.transport.stream(
                        addr, daemon_rid,
                        self.policy.stream_idle_timeout_seconds,
                        trace=tr,
                    ):
                        if "token" in ev and "index" in ev:
                            idx = base + int(ev["index"])
                            with self._lock:
                                if idx == len(req.tokens):
                                    req.tokens.append(int(ev["token"]))
                            if idx == sent:
                                yield {
                                    "request_id": rid,
                                    "token": int(ev["token"]),
                                    "index": idx,
                                }
                                sent += 1
                                if req.t_first is None:
                                    now = self.clock()
                                    with self._lock:
                                        if req.t_first is None:
                                            req.t_first = now
                                            if req.t_submit is not None:
                                                self._observe_phase(
                                                    "ttft",
                                                    now - req.t_submit,
                                                )
                            if self._maybe_disagg(req):
                                # first token delivered and the request
                                # just migrated to its decode peer:
                                # re-snapshot and re-attach there — the
                                # client's stream never blinks, the
                                # indices never reset
                                moved = True
                                break
                        if ev.get("finished"):
                            with self._lock:
                                self._finalize_locked(
                                    req,
                                    ev.get("status") or FINISHED,
                                    ev.get("finish_reason"),
                                )
                                final = {
                                    "request_id": rid, "finished": True,
                                    "status": req.status,
                                    "finish_reason": req.finish_reason,
                                }
                            yield final
                            return
                finally:
                    if relay is not None:
                        relay.finish()
                if moved:
                    misses = 0
                    continue  # re-attach to the decode peer NOW
                # the daemon closed the stream cleanly without a
                # terminal event (drain): refresh the record — the
                # request may have finished between events — then
                # re-attach
                self.peers.note_success(addr)
                self.result(rid)
                misses = 0
                sleep = getattr(self.clock, "sleep", None)
                if sleep is not None:
                    sleep(self.policy.probe_interval_seconds)
            except TransportError:
                self.peers.note_failure(addr)
                with self._lock:
                    resolved = req.terminal or req.addr != addr
                if resolved:
                    continue  # someone else already resolved it
                if self._handoff(req, {addr}):
                    misses = 0
                    continue
                with self._lock:
                    if req.terminal:
                        continue  # handoff budget exhausted: typed fail
                misses += 1
                if misses > _STREAM_RETRY_LIMIT:
                    with self._lock:
                        self._finalize_locked(req, FAILED, REJECT_NO_PEER)
                    continue
                # no peer can take it RIGHT NOW (fleet-wide outage):
                # wait one probe interval for the breaker to readmit
                # someone instead of spinning on dead sockets
                sleep = getattr(self.clock, "sleep", None)
                if sleep is not None:
                    sleep(self.policy.probe_interval_seconds)

    # -- request bookkeeping ----------------------------------------------

    def _merge_locked(self, req: _FleetRequest, base: int, rec: dict):
        """Fold a daemon record (tokens are DAEMON-local, starting at
        ``base`` client tokens) into the router's view."""
        tokens = rec.get("tokens") or []
        full = req.tokens[:base] + [int(t) for t in tokens]
        if len(full) >= len(req.tokens):
            req.tokens = full
        status = rec.get("status")
        if status in _TERMINAL:
            self._finalize_locked(req, status, rec.get("finish_reason"))
        elif status:
            req.status = status

    def _finalize_locked(
        self, req: _FleetRequest, status: str, finish_reason
    ) -> None:
        if req.terminal:
            return
        req.status = status
        req.finish_reason = finish_reason
        req.done_at = self.clock()
        self._m_completions.inc()
        if req.t_submit is not None:
            self._observe_phase("total", req.done_at - req.t_submit)
        if req.t_first is not None:
            self._observe_phase("decode", req.done_at - req.t_first)
        if self.tracer.enabled:
            self.tracer.instant(
                "complete", track=FLEET_TRACK, rid=req.rid,
                status=status, reason=str(finish_reason),
            )
        self.tracer.release_trace(req.rid)

    def _handoff(
        self,
        req: _FleetRequest,
        exclude: Set[str],
        targets: Optional[List[str]] = None,
        record_stale: bool = True,
    ) -> bool:
        """Replay ``req`` onto another peer via forced prefix: prompt +
        every token the router has relayed, with the remaining token
        budget.  Greedy continuations are bitwise — this is the same
        mechanism daemon crash recovery replays through, driven from
        the other side of the wire.  Returns False when no peer can
        take it (the request FAILS typed if the handoff budget is
        exhausted, else stays pointed at its dead peer for the next
        probe/poll to retry).

        Two callers, one mechanism: the DEATH path walks the ring for
        survivors and records the old daemon request as stale (its
        journal may revive it); the DISAGGREGATION path passes
        ``targets=[decode_peer]`` (exactly the peer whose radix tree
        just imported the prompt's KV) with ``record_stale=False`` —
        the source is alive, so the caller cancels it actively instead.

        Called WITHOUT the lock held: state is snapshotted under the
        lock, the replacement submit runs on the wire with the lock
        released, and the re-point is committed under the lock again
        (``req.inflight`` keeps concurrent callers — a poll, a stream,
        the probe pump — from double-submitting the same request)."""
        with self._lock:
            if req.terminal:
                return True
            if req.inflight:
                return False  # another thread is already moving it
            if req.handoffs >= self.max_handoffs:
                self._finalize_locked(req, FAILED, REJECT_HANDOFFS)
                return False
            remaining = req.max_new - len(req.tokens)
            if remaining <= 0:
                # every budgeted token was relayed before the host died
                # — the stream just never saw its terminal event
                self._finalize_locked(req, FINISHED, "length")
                return True
            req.inflight = True
            old_addr, old_rid = req.addr, req.daemon_rid
            tr = req.trace
            delivered = list(req.tokens)
            body = dict(req.body)
            body["prompt"] = req.prompt + delivered
            body["max_new_tokens"] = remaining
            # every handoff is a CONTINUATION — the phase marker is what
            # lets a decode-role daemon accept it through its role gate
            body["phase"] = PHASE_DECODE
            # a DERIVED dedupe token: idempotent if this same handoff
            # is retried, never colliding with the client's token
            # (which lives in the dead daemon's journal).  Seeded from
            # the CLIENT's token because it is unique per LOGICAL
            # request: router-local ids restart at f000000 per router
            # instance, and a daemon outliving its router must not
            # answer a new router's handoff with some old router's
            # handed-off stream.  Tokenless requests fall back to the
            # instance nonce, which scopes the local id the same way.
            seed = req.dedupe_token or f"{self._instance}:{req.rid}"
            body["dedupe_token"] = f"fleet:{seed}:h{req.handoffs + 1}"
            exclude = set(exclude) | {old_addr}
            attempts = len(targets) if targets is not None \
                else len(self.ring)
        queue = list(targets) if targets is not None else None
        try:
            for _ in range(attempts):
                with self._lock:
                    if req.terminal:
                        return True  # cancelled under us: nothing to do
                    addr = queue.pop(0) if queue else (
                        None if queue is not None
                        else self._pick(body["prompt"], exclude)
                    )
                if addr is None:
                    return False
                # fresh fork per attempt: each wire submit is its own
                # crossing, and the accepting daemon's spans parent to
                # the one that actually carried the handoff
                h_ctx = tr.fork() if tr is not None else None
                t_send = self.clock()
                try:
                    code, rec = self.transport.submit(
                        addr, body, self.policy.request_timeout_seconds,
                        trace=h_ctx,
                    )
                except TransportError:
                    self.peers.note_failure(addr)
                    exclude.add(addr)
                    continue
                t_recv = self.clock()
                self.peers.note_success(addr)
                self._note_clock_sync(addr, t_send, t_recv, rec)
                if code != 200:
                    exclude.add(addr)
                    continue
                if self.tracer.enabled and h_ctx is not None:
                    wire = self.tracer.record(
                        "wire:handoff", FLEET_TRACK, t_send, t_recv,
                        rid=req.rid, peer=addr, src=old_addr,
                    )
                    wire.span_id = h_ctx.span_id
                orphan = False
                with self._lock:
                    if req.terminal:
                        orphan = True  # finalized while on the wire
                    else:
                        if record_stale:
                            self._stale.setdefault(
                                old_addr, []
                            ).append(old_rid)
                        req.addr = addr
                        req.daemon_rid = rec["request_id"]
                        req.base = len(delivered)
                        req.handoffs += 1
                        self._m_handoffs.inc()
                        if self.tracer.enabled:
                            self.tracer.instant(
                                "handoff", track=FLEET_TRACK,
                                rid=req.rid, src=old_addr, dst=addr,
                                delivered=len(delivered),
                            )
                if orphan:
                    try:
                        self.transport.cancel(
                            addr, rec["request_id"],
                            self.policy.request_timeout_seconds,
                            trace=h_ctx,
                        )
                    except TransportError:
                        pass
                return True
            return False
        finally:
            with self._lock:
                req.inflight = False

    # -- prefill/decode disaggregation (the handoff hot path) --------------

    def _disagg_fallback(self, req: _FleetRequest, reason: str) -> bool:
        """Every way the disaggregated handoff can fail funnels here:
        counted under its typed reason, traced, and the request simply
        KEEPS DECODING WHERE IT IS — the colocated continuation is
        always live, so disaggregation can lose latency but never
        tokens, and never recomputes silently (the reason says exactly
        what it fell back from)."""
        self.registry.counter(
            "fleet_handoff_fallbacks_total", reason=reason
        ).inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "disagg_fallback", track=FLEET_TRACK, rid=req.rid,
                reason=reason,
            )
        return False

    def _maybe_disagg(self, req: _FleetRequest) -> bool:
        """Fire the prefill→decode migration for ``req`` at first-token
        time: export the prompt's written KV blocks from the prefill
        peer, stream them (bounded chunk segments) into the decode
        peer's radix tree, then re-point the request there via the
        forced-prefix handoff — the continuation admits against the
        just-landed blocks and the greedy stream stays bitwise.  True
        iff the request moved; every failure is a typed
        ``_disagg_fallback`` and the request continues colocated.

        One-shot per request (``disagg_done``), called from the stream
        relay's own thread between events — so the relay re-attaches to
        the decode peer immediately after, with no token gap: tokens
        the prefill peer computes during the transfer overlap are part
        of ``delivered`` when the handoff body is built."""
        with self._lock:
            if (
                req.terminal
                or req.disagg_done
                or req.inflight
                or not req.tokens
                or not self._disagg_active()
            ):
                return False
            if not can_prefill(self._roles.get(req.addr, ROLE_MIXED)):
                return False  # already sitting on a decode peer
            req.disagg_done = True  # one shot, success or fallback
            src, src_rid = req.addr, req.daemon_rid
            tr = req.trace
            dst = self._pick(req.prompt, {src}, need="decode")
        t0 = self.clock()
        if dst is None:
            return self._disagg_fallback(req, "no_decode_peer")
        kx_ctx = tr.fork() if tr is not None else None
        try:
            code, blob = self.transport.kv_export_request(
                src, src_rid, self.policy.request_timeout_seconds,
                trace=kx_ctx,
            )
        except TransportError:
            self.peers.note_failure(src)
            return self._disagg_fallback(req, "export_transport")
        t_export = self.clock()
        self.peers.note_success(src)
        if code != 200:
            self.registry.counter(
                "fleet_kv_wire_refusals_total",
                reason=f"export_http_{code}",
            ).inc()
            return self._disagg_fallback(req, "export_refused")
        if not blob:
            # nothing block-aligned written yet (short prompt): moving
            # the request would force a full re-prefill on the decode
            # peer — worse than staying put
            return self._disagg_fallback(req, "export_empty")
        self._m_kv_export_bytes.inc(len(blob))
        self._m_handoff_bytes.inc(len(blob))
        if self.tracer.enabled and kx_ctx is not None:
            kx = self.tracer.record(
                "wire:kv_export", FLEET_TRACK, t0, t_export,
                rid=req.rid, peer=src, bytes=len(blob),
            )
            kx.span_id = kx_ctx.span_id
        # re-frame as the bounded chunk stream: the decode daemon lands
        # whole frames as segments arrive (Mooncake-style overlap), and
        # a transfer torn mid-stream is a typed ``segment`` refusal
        # there, never a half-imported prefix
        wire = b"".join(
            chunk_body(blob, max_wire_bytes=self.disagg_max_wire_bytes)
        )
        ki_ctx = tr.fork() if tr is not None else None
        t_imp0 = self.clock()
        try:
            code, body = self.transport.kv_import(
                dst, wire, self.policy.request_timeout_seconds,
                trace=ki_ctx,
            )
        except TransportError:
            # the decode peer died mid-transfer: breaker evidence AND
            # typed fallback — the stream never left the prefill peer
            self.peers.note_failure(dst)
            return self._disagg_fallback(req, "decode_peer_dead")
        t_imp1 = self.clock()
        self.peers.note_success(dst)
        self._note_clock_sync(dst, t_imp0, t_imp1, body)
        if self.tracer.enabled and ki_ctx is not None:
            ki = self.tracer.record(
                "wire:kv_import", FLEET_TRACK, t_imp0, t_imp1,
                rid=req.rid, peer=dst, bytes=len(wire),
            )
            ki.span_id = ki_ctx.span_id
        self._observe_phase("kv_wire", t_imp1 - t0)
        if code != 200:
            self.registry.counter(
                "fleet_kv_wire_refusals_total",
                reason=str(body.get("reason", code)),
            ).inc()
            return self._disagg_fallback(req, "import_refused")
        verdicts = body.get("verdicts") or {}
        for verdict, n in verdicts.items():
            self.registry.counter(
                "fleet_kv_imports_total", status=str(verdict)
            ).inc(int(n))
        if self.tracer.enabled:
            self.tracer.instant(
                "kv_migrate", track=FLEET_TRACK, src=src, dst=dst,
                bytes=len(blob), code=code,
            )
        landed = int(verdicts.get("imported", 0)) + int(
            verdicts.get("already_cached", 0)
        )
        if landed <= 0:
            # typed import verdicts (weights_version skew, shape
            # incompatibility, no prefix cache): the blocks did NOT
            # land, so a continuation there would recompute the prompt
            # — fall back under the dominant verdict's name
            reasons = sorted(
                v for v in verdicts
                if v not in ("imported", "already_cached")
            )
            return self._disagg_fallback(
                req, reasons[0] if reasons else "nothing_landed"
            )
        if not self._handoff(
            req, set(), targets=[dst], record_stale=False
        ):
            return self._disagg_fallback(req, "handoff_refused")
        # the source is alive and still decoding the original: reap it
        # actively (its record is disowned; this is compute hygiene)
        try:
            self.transport.cancel(
                src, src_rid, self.policy.request_timeout_seconds,
                trace=tr,
            )
        except TransportError:
            self.peers.note_failure(src)
        self._m_disagg.inc()
        elapsed = max(0.0, self.clock() - t0)
        self._m_handoff_seconds.inc(elapsed)
        self._observe_phase("handoff", elapsed)
        return True

    # -- health ------------------------------------------------------------

    def probe_tick(self) -> None:
        """Poll due peers' ``/healthz``, fold the evidence, and act on
        transitions: a peer going DEAD gets its open requests handed
        off; a DEAD peer answering again gets its stale (already
        handed-off) daemon requests cancelled and, when enabled, a
        KV warm start from a healthy donor.  Each tick also runs the
        TTL eviction of long-terminal requests."""
        self._evict_expired()
        for addr in self.peers.probe_due():
            state = self.peers.get(addr)
            if state is None:
                continue
            was = state.state
            self._m_probes.inc()
            state.last_probe = self.clock()
            t_send = self.clock()
            try:
                code, _body = self.transport.healthz(
                    addr, self.policy.connect_timeout_seconds,
                    trace=None,
                )
                ok = code == 200
            except TransportError:
                ok = False
                _body = {}
            t_recv = self.clock()
            if ok:
                # probes are the clock-alignment workhorse: frequent,
                # small, so their min-RTT samples bound the offset well
                self._note_clock_sync(addr, t_send, t_recv, _body)
                self._scrape_peer_metrics(addr)
                # fold the role the daemon ADVERTISES — unless pinned
                # by config/set_role, the daemon's word is the truth
                # (a restarted daemon may come back under a new role)
                adv = _body.get("role") if isinstance(_body, dict) else None
                if adv in ROLES:
                    with self._lock:
                        if addr not in self._role_overrides:
                            self._roles[addr] = adv
                self.peers.note_success(addr)
                if was == DEAD:
                    if self.tracer.enabled:
                        self.tracer.instant(
                            "peer_recovered", track=FLEET_TRACK, peer=addr
                        )
                    self._reconcile_recovered(addr)
            else:
                self._m_probe_failures.inc()
                now_state = self.peers.note_failure(addr)
                if was != DEAD and now_state == DEAD:
                    self._m_peer_deaths.inc()
                    with self._lock:
                        # a dead peer's series must not be re-exported
                        # as if freshly scraped
                        self._peer_metrics.pop(addr, None)
                    if self.tracer.enabled:
                        self.tracer.instant(
                            "peer_dead", track=FLEET_TRACK, peer=addr
                        )
                    self._handoff_open(addr)
        for addr, state in self.peers.states().items():
            self.registry.gauge("fleet_peer_state", peer=addr).set(
                {HEALTHY: 0.0, DEAD: 2.0}.get(state, 1.0)
            )
            self.registry.gauge("fleet_role", peer=addr).set(
                ROLE_GAUGE.get(
                    self._roles.get(addr, ROLE_MIXED), 0.0
                )
            )
        self._drain_spool()

    def _drain_spool(self) -> None:
        """Flush finished spans to the span log (telemetry: an IO fault
        here is counted by the spool, never fatal to the pump)."""
        if self._spool is None:
            return
        with self._spool_lock:
            try:
                self._spool.drain(self.tracer)
            except OSError:
                pass

    def _scrape_peer_metrics(self, addr: str) -> None:
        """Cache the peer's latest ``/metricsz`` text for the fleet
        aggregation surface.  Best effort: a scrape failure of a peer
        that just answered its probe is NOT breaker evidence, and a
        transport predating ``metricsz`` simply opts the peer out."""
        try:
            code, text = self.transport.metricsz(
                addr, self.policy.connect_timeout_seconds, trace=None
            )
        except (TransportError, NotImplementedError, AttributeError):
            return
        if code == 200 and isinstance(text, str):
            with self._lock:
                self._peer_metrics[addr] = text
        else:
            with self._lock:
                self._peer_metrics.pop(addr, None)

    def _handoff_open(self, dead_addr: str) -> None:
        """Move every open request off a peer the breaker just declared
        DEAD — streams find out on their own (torn socket), but a
        request nobody is streaming would otherwise wait for its next
        client poll."""
        with self._lock:
            stranded = [
                req for req in self._requests.values()
                if not req.terminal and req.addr == dead_addr
            ]
        for req in stranded:
            self._handoff(req, {dead_addr})

    def _evict_expired(self) -> None:
        """The fleet counterpart of the daemon's journal retention: a
        terminal request (and its dedupe-ledger entry) is kept for
        ``terminal_ttl_seconds`` of late polls, then dropped; stale
        handoff records for peers no longer in the fleet go with them.
        Without this a long-lived router leaks every request it ever
        served."""
        now = self.clock()
        with self._lock:
            expired = [
                rid for rid, req in self._requests.items()
                if req.terminal and req.done_at is not None
                and now - req.done_at >= self.terminal_ttl_seconds
            ]
            for rid in expired:
                req = self._requests.pop(rid)
                if (
                    req.dedupe_token is not None
                    and self._ledger.get(req.dedupe_token) == rid
                ):
                    del self._ledger[req.dedupe_token]
            for addr in list(self._stale):
                if self.peers.get(addr) is None:
                    del self._stale[addr]
        if expired:
            self.registry.counter("fleet_evictions_total").inc(
                len(expired)
            )

    def _reconcile_recovered(self, addr: str) -> None:
        """A daemon came back from DEAD: its journal faithfully revived
        requests the router already moved elsewhere.  Cancel those so
        the host does not burn ticks computing answers nobody will
        read (the router's ledger is the only client-visible authority
        — this is compute hygiene, not correctness)."""
        with self._lock:
            stale = self._stale.pop(addr, [])
        for daemon_rid in stale:
            try:
                self.transport.cancel(
                    addr, daemon_rid,
                    self.policy.request_timeout_seconds, trace=None,
                )
            except TransportError:
                self.peers.note_failure(addr)
                break
        if self.warm_on_recovery:
            self.warm_start(addr)

    # -- remote KV migration ----------------------------------------------

    def warm_start(
        self,
        newcomer: str,
        donor: Optional[str] = None,
        max_blocks: Optional[int] = None,
    ) -> dict:
        """Pre-seed ``newcomer``'s radix cache from a donor's hottest
        chains over the wire.  Returns the import response body (its
        ``verdicts`` map counts typed migration statuses); every
        verdict and refusal is counted under ``fleet_kv_*``.  Best
        effort: no donor, an empty export, or a refusal leaves the
        newcomer merely cold, never wrong.

        A newcomer that already warm-started from its OWN SSD manifest
        (``/healthz`` ``kv.disk_seeded_chains > 0``) is left alone —
        local disk is both cheaper and hotter than a donor's wire
        export.  Old daemons without the field fall through to the
        wire path unchanged."""
        try:
            code, body = self.transport.healthz(
                newcomer, self.policy.connect_timeout_seconds,
                trace=None,
            )
        except TransportError:
            code, body = 0, {}
        if code == 200 and isinstance(body, dict):
            kv = body.get("kv")
            seeded = (
                kv.get("disk_seeded_chains", 0)
                if isinstance(kv, dict)
                else 0
            )
            if isinstance(seeded, (int, float)) and seeded > 0:
                self.registry.counter(
                    "fleet_kv_warm_local_total"
                ).inc()
                return {"warm_local": int(seeded)}
        if donor is None:
            healthy = [a for a in self.peers.healthy() if a != newcomer]
            if not healthy:
                return {}
            # deterministic donor choice: the newcomer's ring successor
            donor = next(
                (a for a in self.ring.walk(_stable_hash(newcomer.encode()))
                 if a in healthy),
                healthy[0],
            )
        return self._ship_kv(donor, newcomer, max_blocks)

    def drain_peer(
        self, addr: str, target: Optional[str] = None
    ) -> dict:
        """Forward a draining peer's live prefixes to a survivor (its
        ring successor by default) so the keys that are about to slide
        to it arrive with their K/V already warm."""
        if target is None:
            target = next(
                (a for a in self.ring.walk(_stable_hash(addr.encode()))
                 if a != addr and a in self.peers.healthy()),
                None,
            )
            if target is None:
                return {}
        return self._ship_kv(addr, target, None)

    def _ship_kv(
        self, src: str, dst: str, max_blocks: Optional[int]
    ) -> dict:
        blocks = max_blocks if max_blocks is not None \
            else self.warm_start_blocks
        try:
            code, blob = self.transport.kv_export(
                src, blocks, self.policy.request_timeout_seconds,
                trace=None,
            )
        except TransportError:
            self.peers.note_failure(src)
            return {}
        self.peers.note_success(src)
        if code != 200:
            # a typed refusal from a LIVE donor (draining, bad params):
            # counted, never breaker evidence — warm starts are best
            # effort and must not demote a responsive peer
            self.registry.counter(
                "fleet_kv_wire_refusals_total",
                reason=f"export_http_{code}",
            ).inc()
            return {}
        if not blob:
            return {"verdicts": {}}
        self._m_kv_export_bytes.inc(len(blob))
        try:
            code, body = self.transport.kv_import(
                dst, blob, self.policy.request_timeout_seconds,
                trace=None,
            )
        except TransportError:
            self.peers.note_failure(dst)
            return {}
        self.peers.note_success(dst)
        if code == 200:
            for verdict, n in (body.get("verdicts") or {}).items():
                self.registry.counter(
                    "fleet_kv_imports_total", status=str(verdict)
                ).inc(int(n))
        else:
            self.registry.counter(
                "fleet_kv_wire_refusals_total",
                reason=str(body.get("reason", code)),
            ).inc()
        if self.tracer.enabled:
            self.tracer.instant(
                "kv_migrate", track=FLEET_TRACK, src=src, dst=dst,
                bytes=len(blob), code=code,
            )
        return body

    # -- membership / lifecycle -------------------------------------------

    def add_peer(self, addr: str, warm: bool = True) -> None:
        """Join a daemon to the fleet: on the ring (only its keys move),
        in the breaker (DEGRADED until its first good probe), and —
        when ``warm`` — KV warm-started from a donor."""
        with self._lock:
            self.ring.add_member(addr)
            self.peers.add(addr)
            self._roles.setdefault(addr, ROLE_MIXED)
        if warm:
            self.warm_start(addr)

    def remove_peer(self, addr: str) -> None:
        """Leave: drain-forward its prefixes, then drop it from ring
        and breaker; its open requests hand off immediately."""
        self.drain_peer(addr)
        with self._lock:
            if len(self.ring) > 1:
                self.ring.remove_member(addr)
            self.peers.remove(addr)
            self._roles.pop(addr, None)
            self._role_overrides.discard(addr)
        self._handoff_open(addr)

    def status(self) -> dict:
        now = self.clock()
        with self._lock:
            open_reqs = [
                r.rid for r in self._requests.values() if not r.terminal
            ]
            inflight: Dict[str, int] = {}
            for r in self._requests.values():
                if not r.terminal:
                    inflight[r.addr] = inflight.get(r.addr, 0) + 1
            peers = self.peers.summary(now=now)
            for addr, info in peers.items():
                info["role"] = self._roles.get(addr, ROLE_MIXED)
                info["inflight"] = inflight.get(addr, 0)
            return {
                "peers": peers,
                "roles": dict(self._roles),
                "disagg": self._disagg_active(),
                "requests": len(self._requests),
                "open": len(open_reqs),
                "open_ids": open_reqs,
                "ledger": len(self._ledger),
                "stale": {a: len(v) for a, v in self._stale.items()},
            }

    # -- trace + metrics surfaces (docs/11_observability.md) ---------------

    def trace_payload(self, trace_id: Optional[str] = None) -> dict:
        """The router's OWN span log, served at ``GET /v1/tracez`` —
        one process's contribution to a stitched fleet timeline."""
        if self._spool is None:
            return {"proc": "router", "pid": os.getpid(),
                    "records": [], "skipped": {}}
        self._drain_spool()
        with self._spool_lock:
            records, skipped = read_span_log(self._spool.path, trace_id)
        return {"proc": self._spool.proc, "pid": self._spool.pid,
                "records": records, "skipped": skipped}

    def request_timeline(self, rid: str) -> Tuple[int, dict]:
        """Per-request latency attribution (``GET /v1/requestz/<rid>``):
        pull the request's trace from the router's own spool and every
        routable peer's ``/v1/tracez``, then break the wall time down
        by phase — queue wait, prefill, decode, KV wire bytes/seconds,
        SSE relay.  Durations are per-process clock DELTAS, so no clock
        alignment is needed to attribute them."""
        with self._lock:
            req = self._requests.get(rid)
            if req is None:
                return 404, {"error": f"unknown request {rid}"}
            record = req.record()
            tr = req.trace
        if tr is None:
            return 200, {
                "request_id": rid, "trace_id": None, "record": record,
                "phases": {}, "detail": "tracing disabled",
            }
        processes = [self.trace_payload(tr.trace_id)]
        for addr in self.peers.routable():
            try:
                code, body = self.transport.tracez(
                    addr, tr.trace_id,
                    self.policy.request_timeout_seconds, trace=None,
                )
            except (TransportError, NotImplementedError,
                    AttributeError):
                continue
            if code == 200 and isinstance(body, dict):
                body.setdefault("proc", addr)
                processes.append(body)
        records = [
            r
            for p in processes
            for r in p.get("records", [])
            if r.get("trace_id") == tr.trace_id
        ]
        breakdown = phase_breakdown(records)
        return 200, {
            "request_id": rid,
            "trace_id": tr.trace_id,
            "record": record,
            "phases": breakdown["phases"],
            "kv_wire_bytes": breakdown["kv_wire_bytes"],
            "spans": breakdown["spans"],
            "processes": [
                {"proc": p.get("proc"), "pid": p.get("pid"),
                 "records": len(p.get("records", []))}
                for p in processes
            ],
        }

    def fleet_metrics_text(self) -> str:
        """ONE scrape target for the whole fleet: the router's own
        registry, then every peer's last-scraped series re-emitted with
        a ``peer`` label, then fleet-level sums (``fleet:<name>:sum``,
        the recording-rule naming) across peers for every counter and
        histogram family.  A peer whose text fails to parse is counted
        visibly — an aggregator must never silently drop a peer."""
        own = prometheus_text(self.registry).rstrip("\n")
        typed = {
            line.split()[2]
            for line in own.splitlines()
            if line.startswith("# TYPE ")
        }
        with self._lock:
            peer_texts = sorted(self._peer_metrics.items())
        lines: List[str] = [own] if own else []
        sums: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
        for addr, text in peer_texts:
            try:
                samples = parse_prometheus_text(text)
            except ValueError:
                self.registry.counter(
                    "fleet_peer_scrape_parse_errors_total", peer=addr
                ).inc()
                continue
            for s in samples:
                name, kind = s["name"], s["type"]
                family = name
                if kind == "histogram":
                    for suffix in ("_bucket", "_sum", "_count"):
                        if name.endswith(suffix):
                            family = name[: -len(suffix)]
                            break
                if kind and family not in typed:
                    typed.add(family)
                    lines.append(f"# TYPE {family} {kind}")
                labels = dict(s["labels"])
                labels["peer"] = addr
                lines.append(
                    f"{name}{_prom_labels(labels)} "
                    f"{_prom_value(s['value'])}"
                )
                # histogram components are cumulative counters, so they
                # sum across peers just like counters do; gauges do not
                # (a sum of utilizations is not a utilization)
                if kind in ("counter", "histogram"):
                    key = (name, tuple(sorted(s["labels"].items())))
                    sums[key] = sums.get(key, 0.0) + s["value"]
        for (name, labelitems), value in sorted(sums.items()):
            lines.append(
                f"fleet:{name}:sum{_prom_labels(dict(labelitems))} "
                f"{_prom_value(value)}"
            )
        return "\n".join(lines) + "\n"

    def stop(self) -> None:
        self._stop.set()

    def run(self, poll_seconds: float = 0.25) -> None:
        """The router pump: probe peers until :meth:`stop`.  Paced on
        the injected clock's ``sleep`` — the process entry point hands
        in a WallClock, tests never call this at all (they call
        ``probe_tick`` directly)."""
        while not self._stop.is_set():
            self.probe_tick()
            self.clock.sleep(poll_seconds)
