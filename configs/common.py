"""Shared config plumbing for the shipped configs.

ml_collections only allows CLI overrides on *declared* fields, so every
config pre-declares the commonly tuned model knobs here — e.g.
``--config.model_overrides.attn_impl=flash`` works out of the box instead
of raising AttributeError.  Extra kwargs become additional declared fields.
"""

from ml_collections import ConfigDict, config_dict


def model_overrides(**kw) -> ConfigDict:
    # defaults mirror TransformerConfig/GPTConfig so declaring them here is
    # behavior-neutral; they exist to make the fields CLI-addressable
    base = dict(
        # attention: "xla" | "flash" | "ring" | "ulysses"
        attn_impl="xla",
        flash_block_q=512,
        flash_block_k=512,
        # sliding-window attention (0 = full causal)
        attn_window=0,
        # remat: "full" | "proj" | "proj_attn" | "dots" (remat=False to disable)
        remat=True,
        remat_policy="full",
        scan_layers=True,
        dropout_rate=0.0,
        loss_chunk=0,
        # MoE routing family (only meaningful with moe_experts > 0)
        moe_router="topk",
        # bidirectional (encoder) attention — pairs with objective="mlm"
        bidirectional=config_dict.placeholder(bool),
        # model-shape knobs: placeholders (None = keep the model's default;
        # the Trainer drops None-valued overrides) so e.g.
        # --config.model_overrides.n_layers=2 works on any config
        vocab_size=config_dict.placeholder(int),
        seq_len=config_dict.placeholder(int),
        n_layers=config_dict.placeholder(int),
        d_model=config_dict.placeholder(int),
        n_heads=config_dict.placeholder(int),
        n_kv_heads=config_dict.placeholder(int),
    )
    base.update(kw)
    return ConfigDict(base)
