"""Device-mesh construction for DP x FSDP x TP x PP (x SP) parallelism.

The reference only ever builds a 1-D mesh over one ``"data"`` axis inline in
each script (``data_paral.py:150-152``, ``param_sharding.py`` equivalent).
Here the mesh is a first-class object: named axes, arbitrary shape, built with
``jax.experimental.mesh_utils.create_device_mesh`` so the logical axes map onto
the physical ICI torus well (innermost axes get the tightest rings), and
DCN-aware when a pod spans multiple slices.

Axis convention (outermost -> innermost):

- ``pipe``  — pipeline stages.  Lowest-bandwidth traffic (one activation
  handoff per microbatch) so it tolerates the slowest links (DCN).
- ``data``  — data parallelism; FSDP shards parameters over this same axis
  (ZeRO-3 style), so its traffic is one gradient reduce-scatter + param
  all-gather per step.
- ``seq``   — sequence/context parallelism (ring attention KV rotation).
- ``model`` — tensor parallelism.  Per-layer activation collectives — the most
  latency-sensitive — so it sits innermost, on the fastest ICI ring.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

DATA_AXIS = "data"
MODEL_AXIS = "model"
PIPE_AXIS = "pipe"
SEQ_AXIS = "seq"

# Outer-to-inner ordering used when materializing the physical mesh.
AXIS_ORDER: Tuple[str, ...] = (PIPE_AXIS, DATA_AXIS, SEQ_AXIS, MODEL_AXIS)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Logical mesh shape. ``-1`` on any ONE axis means "all remaining devices"."""

    data: int = -1
    model: int = 1
    pipe: int = 1
    seq: int = 1

    def resolved(self, n_devices: int) -> "MeshConfig":
        sizes = dict(data=self.data, model=self.model, pipe=self.pipe, seq=self.seq)
        wild = [name for name, v in sizes.items() if v == -1]
        if len(wild) > 1:
            raise ValueError(f"at most one mesh axis may be -1, got {wild}")
        if wild:
            fixed = 1
            for name, v in sizes.items():
                if name != wild[0]:
                    fixed *= v
            if fixed <= 0 or n_devices % fixed != 0:
                raise ValueError(
                    f"{n_devices} devices not divisible by the fixed axes "
                    f"product {fixed} (mesh {sizes})"
                )
            sizes[wild[0]] = n_devices // fixed
        if sizes["data"] * sizes["model"] * sizes["pipe"] * sizes["seq"] != n_devices:
            raise ValueError(
                f"mesh shape data={sizes['data']} model={sizes['model']} "
                f"pipe={sizes['pipe']} seq={sizes['seq']} does not cover "
                f"{n_devices} devices"
            )
        return MeshConfig(**sizes)

    def axis_sizes(self) -> dict:
        return {
            PIPE_AXIS: self.pipe,
            DATA_AXIS: self.data,
            SEQ_AXIS: self.seq,
            MODEL_AXIS: self.model,
        }


def make_mesh(
    config: MeshConfig = MeshConfig(),
    devices: Optional[Sequence] = None,
    *,
    allow_split_physical_axes: bool = True,
):
    """Build a ``jax.sharding.Mesh`` with named axes from a logical shape.

    Uses ``mesh_utils.create_device_mesh`` so that on TPU the logical axes are
    laid out along physical ICI rings ("model" innermost), and falls back to a
    plain reshape on CPU-simulated devices.  Drops axes of size 1 is NOT done —
    keeping all four axes means the same ``PartitionSpec``s work for every
    strategy combination (an axis of size 1 is free).
    """
    import jax
    from jax.sharding import Mesh

    if devices is None:
        devices = jax.devices()
    cfg = config.resolved(len(devices))
    sizes = cfg.axis_sizes()
    shape = tuple(sizes[a] for a in AXIS_ORDER)

    if devices[0].platform == "cpu":
        dev_array = np.asarray(devices).reshape(shape)
    else:
        from jax.experimental import mesh_utils

        try:
            dev_array = mesh_utils.create_device_mesh(
                shape,
                devices=devices,
                allow_split_physical_axes=allow_split_physical_axes,
            )
        except (ValueError, AssertionError, NotImplementedError):
            dev_array = np.asarray(devices).reshape(shape)
    return Mesh(dev_array, AXIS_ORDER)


def mesh_from_sizes(data: int = -1, model: int = 1, pipe: int = 1, seq: int = 1, devices=None):
    return make_mesh(MeshConfig(data=data, model=model, pipe=pipe, seq=seq), devices=devices)


def factor_mesh(
    n_devices: int, *, want_model: int = 1, want_pipe: int = 1, want_seq: int = 1
) -> MeshConfig:
    """Best-effort factorization of ``n_devices`` into (pipe, data, seq, model).

    Shrinks the requested model/pipe/seq degrees to the largest divisors that
    fit (in that priority order).  Useful for dry-runs where the device count
    is dictated from outside.
    """

    def largest_divisor(n: int, want: int) -> int:
        for d in range(min(want, n), 0, -1):
            if n % d == 0:
                return d
        return 1

    model = largest_divisor(n_devices, want_model)
    rem = n_devices // model
    pipe = largest_divisor(rem, want_pipe)
    rem //= pipe
    seq = largest_divisor(rem, want_seq)
    return MeshConfig(data=rem // seq, model=model, pipe=pipe, seq=seq)
