"""Mixture-of-Experts MLP with expert parallelism (Switch-style top-1).

No reference capability exists (SURVEY.md §2.2: EP "Absent"); built for the
framework's EP slot, TPU-first:

- **Static shapes everywhere**: capacity-based routing (``capacity_factor``)
  with one-hot dispatch/combine einsums — the Mesh-TensorFlow/Switch
  formulation that XLA compiles to dense MXU work, no dynamic gather.
- **Expert parallelism over the ``model`` mesh axis**: each rank owns
  ``n_experts / tp`` experts (weights stacked per-rank via ModuleShard, so
  gradient sync already treats them as partitioned).  Activations are
  replicated over the model axis (the batch shards over data/seq), so
  dispatch needs no communication at all: each rank slices out its own
  experts' slots, runs them (``1/ep`` of the expert FLOPs), and the
  combine closes with one ``psum`` — the same collective shape as a TP
  row-parallel projection.
- **Router in fp32** (numerically fragile softmax over experts), activations
  in the model dtype.
- Load-balance auxiliary loss (Switch: ``E * sum(f_i * P_i)``) sown into a
  ``"losses"`` collection; ``make_gpt_loss`` folds it into the objective.

Works mesh-free too (no bound model axis): all experts live on the one
device and the all_to_alls vanish — same module, same params layout rules
as the rest of the structural-TP design.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.parallel.tp import ModuleShard, axis_size_or_none


class ExpertFFN(nn.Module):
    """One expert: the standard transformer FFN at model dtype."""

    config: "TransformerConfig"  # noqa: F821 — forward ref, see layers.py

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.config
        hidden = cfg.mlp_ratio * cfg.d_model
        if cfg.mlp == "swiglu":
            gate = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="gate")(x)
            up = nn.Dense(hidden, use_bias=False, dtype=cfg.dtype, name="up")(x)
            h = nn.silu(gate) * up
        else:
            h = nn.gelu(nn.Dense(hidden, dtype=cfg.dtype, name="up")(x))
        return nn.Dense(cfg.d_model, dtype=cfg.dtype, name="down")(h)


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: top-1 routed experts, EP over ``model``."""

    config: "TransformerConfig"  # noqa: F821

    @nn.compact
    def __call__(
        self, x: jax.Array, train: bool = True, aux_scale: jax.Array | None = None
    ) -> jax.Array:
        """``aux_scale``: multiplier on the sown balance loss — the pipeline
        schedule passes 0.0 on bubble ticks so garbage activations never
        contribute to (or take gradients from) the router regularizer."""
        cfg = self.config
        n_experts = cfg.moe_experts
        ep_size = axis_size_or_none(cfg.model_axis) or 1
        if n_experts % ep_size != 0:
            raise ValueError(
                f"moe_experts={n_experts} not divisible by model axis {ep_size}"
            )
        local_experts = n_experts // ep_size
        b, s, d = x.shape
        tokens = b * s
        xf = x.reshape(tokens, d)

        # --- route (fp32) ---------------------------------------------------
        logits = nn.Dense(
            n_experts, use_bias=False, dtype=jnp.float32, name="router"
        )(xf.astype(jnp.float32))
        probs = jax.nn.softmax(logits, axis=-1)  # [T, E]
        gate = jnp.max(probs, axis=-1)  # [T]
        expert_idx = jnp.argmax(probs, axis=-1)  # [T]
        onehot = jax.nn.one_hot(expert_idx, n_experts, dtype=jnp.float32)

        # Switch load-balance loss: E * sum_i fraction_i * router_prob_i
        frac = onehot.mean(axis=0)
        mean_prob = probs.mean(axis=0)
        self.sow(
            "losses",
            "moe_balance",
            n_experts * jnp.sum(frac * mean_prob),
            reduce_fn=lambda a, b_: a + b_,
            init_fn=lambda: jnp.float32(0.0),
        )

        # --- capacity + dispatch masks (static shapes) ----------------------
        capacity = max(
            1, int(cfg.moe_capacity_factor * tokens / n_experts + 0.999)
        )
        position = (jnp.cumsum(onehot, axis=0) - 1.0) * onehot  # [T, E]
        in_capacity = (position < capacity).astype(jnp.float32) * onehot
        pos_idx = jnp.sum(position, axis=-1).astype(jnp.int32)  # [T]
        pos_onehot = jax.nn.one_hot(pos_idx, capacity, dtype=jnp.float32)
        # [T, E, C]: 1 where token t landed in slot c of expert e
        dispatch = in_capacity[:, :, None] * pos_onehot[:, None, :]
        combine = dispatch * gate[:, None, None]

        # --- to experts -----------------------------------------------------
        x_exp = jnp.einsum("td,tec->ecd", xf.astype(jnp.float32), dispatch)
        x_exp = x_exp.astype(cfg.dtype)  # [E, C, d]
        if ep_size > 1:
            # each rank keeps its experts' slots from EVERY rank:
            # [E, C, d] -> [E/ep, ep*C, d], rank-ordered along the slot axis
            x_exp = lax.all_to_all(
                x_exp, cfg.model_axis, split_axis=0, concat_axis=1, tiled=True
            )

        expert_stack = nn.vmap(
            ExpertFFN,
            in_axes=0,
            out_axes=0,
            variable_axes={"params": 0},
            split_rngs={"params": True},
        )
        if ep_size > 1:
            import functools

            y_exp = ModuleShard(
                functools.partial(expert_stack, cfg),
                axis_name=cfg.model_axis,
                name="experts",
            )(x_exp)
        else:
            y_exp = expert_stack(cfg, name="experts")(x_exp)

        if ep_size > 1:
            y_exp = lax.all_to_all(
                y_exp, cfg.model_axis, split_axis=1, concat_axis=0, tiled=True
            )

        # --- back to tokens -------------------------------------------------
        y = jnp.einsum("ecd,tec->td", y_exp.astype(jnp.float32), combine)
        y = y.astype(cfg.dtype).reshape(b, s, d)
        if cfg.dropout_rate > 0.0:
            y = nn.Dropout(rate=cfg.dropout_rate, deterministic=not train)(y)
        return y
