"""Zero-downtime rolling weight hot-swap tests: engine rebind validation
(recompile-free), the versioned weight manifest, the swap state machine's
corner cases (refused typed during drain / double-swap, 1-replica swap
without dropping a request, frozen clock never promotes a canary), and
the SLO-guarded automatic rollback story — canary death, latency
regression, and the logit-fingerprint spot check each end with the fleet
100% on the old version and zero failed requests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import (
    DEAD,
    PROBATION,
    FaultPlan,
    Frontend,
    FrontendConfig,
    ReplicaHandle,
    RestartPolicy,
    SwapPolicy,
)
from tpu_parallel.cluster.swap import (
    ROLLBACK_CANARY_DEATH,
    ROLLBACK_SLO_TTFT,
    ROLLBACK_SPOT_CHECK,
    SWAP_CANARY,
    SWAP_REFUSED_DRAINING,
    SWAP_REFUSED_IN_PROGRESS,
    SWAP_REFUSED_SHAPE,
    SWAP_REFUSED_VERSION,
)
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.serving import (
    FINISHED,
    Request,
    SchedulerConfig,
    ServingEngine,
)

NEW_TOKENS = 8


@pytest.fixture(scope="module")
def env():
    """Tiny model + TWO same-shape weight sets (different seeds) + greedy
    references under each, shared by every test here."""
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(7)
    lens = [3, 9, 6, 12, 5, 7, 4, 8]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    probe = jax.random.randint(rng, (1, max(lens)), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    params_v2 = model.init(
        {"params": jax.random.PRNGKey(2)}, probe, train=False
    )["params"]
    refs_v1 = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=NEW_TOKENS,
        ))[0]
        for p in prompts
    ]
    refs_v2 = [
        np.asarray(generate(
            model, params_v2, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=NEW_TOKENS,
        ))[0]
        for p in prompts
    ]
    return cfg, model, params, params_v2, prompts, refs_v1, refs_v2


def _cluster(env, n_replicas, clock, fault_plans=None, policy=None,
             watchdog=(5, 20)):
    """N per-step replicas with engine factories behind a frontend on the
    given fake clock."""
    cfg, model, params, _, _, _, _ = env

    def mk(i):
        return ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            clock=clock, decode_steps_per_tick=1,
        )

    fault_plans = fault_plans or {}
    handles = [
        ReplicaHandle(
            i, mk(i), fault_plan=fault_plans.get(i),
            engine_factory=(lambda i=i: mk(i)),
        )
        for i in range(n_replicas)
    ]
    config = FrontendConfig(
        retry_limit=16,
        watchdog_ticks=watchdog[0], watchdog_kill_ticks=watchdog[1],
        restart=policy or RestartPolicy(
            backoff_seconds=0.1, probation_ticks=2, probation_requests=2
        ),
    )
    return Frontend(handles, router="least", clock=clock, config=config)


def _drive(fe, t, dt=0.05, max_ticks=800, submit=None, until=None):
    """Tick the frontend on the fake clock until work AND the swap are
    resolved (or ``until`` says stop).  ``submit(tick)`` may inject
    arrivals per tick."""
    ticks = 0
    while ticks < max_ticks:
        if submit is not None:
            submit(ticks)
        t[0] += dt
        fe.step()
        ticks += 1
        state = fe.swap_status()["state"]
        resolved = state not in ("rolling", "rolling_back")
        if until is not None:
            if until(ticks):
                return ticks
        elif not fe.has_work() and resolved and (
            submit is None or getattr(submit, "done", True)
        ):
            return ticks
    return ticks


# -- engine rebind ----------------------------------------------------------


def test_rebind_params_validates_and_is_recompile_free(env):
    """rebind_params refuses mid-flight engines and mismatched trees,
    and a same-shape rebind reuses every compiled program — outputs flip
    to the new weights with zero new compiles."""
    cfg, model, params, params_v2, prompts, refs_v1, refs_v2 = env
    eng = ServingEngine(
        model, params, n_slots=2,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
    )
    out = eng.add_request(
        Request(prompt=prompts[0], max_new_tokens=NEW_TOKENS)
    )
    out_long = eng.add_request(
        Request(prompt=prompts[0], max_new_tokens=20)
    )
    eng.step()
    assert not out_long.done  # still decoding: the rebind must refuse
    with pytest.raises(RuntimeError, match="work in flight"):
        eng.rebind_params(params_v2)
    eng.run()
    assert out.status == FINISHED and out_long.status == FINISHED
    assert list(out.tokens) == list(refs_v1[0])

    # wrong leaf shape refuses with the offending path named
    bad = jax.tree_util.tree_map(lambda x: x, params_v2)
    flat, treedef = jax.tree_util.tree_flatten(bad)
    flat[0] = np.zeros(np.asarray(flat[0]).shape + (1,), np.float32)
    with pytest.raises(ValueError, match="same-shape"):
        eng.rebind_params(jax.tree_util.tree_unflatten(treedef, flat))
    assert eng.weights_version == "initial"

    fused_compiles = eng._fused_fn._cache_size()
    eng.rebind_params(params_v2, version="v2")
    assert eng.weights_version == "v2"
    out2 = eng.add_request(
        Request(prompt=prompts[0], max_new_tokens=NEW_TOKENS)
    )
    eng.run()
    assert list(out2.tokens) == list(refs_v2[0])
    # same jitted program family, same compile count — the swap paid no
    # retrace (params are a plain traced operand)
    assert eng._fused_fn._cache_size() == fused_compiles


# -- weight manifest --------------------------------------------------------


def test_weight_manifest_roundtrip_and_corruption(tmp_path, env):
    """save/load_serving_weights round-trips params + identity and
    refuses a tampered manifest (WeightsCorrupt), which begin_swap
    surfaces as the typed fingerprint_mismatch refusal."""
    from tpu_parallel.checkpoint.io import (
        WeightsCorrupt,
        load_serving_weights,
        params_fingerprint,
        save_serving_weights,
    )

    cfg, model, params, params_v2, prompts, _, _ = env
    d = str(tmp_path / "weights")
    manifest = save_serving_weights(d, 3, params_v2, version="v2")
    assert manifest.version == "v2" and manifest.step == 3
    assert manifest.fingerprint == params_fingerprint(params_v2)
    assert manifest.fingerprint != params_fingerprint(params)

    restored, loaded = load_serving_weights(d, like=params)
    assert loaded == manifest
    chex_equal = jax.tree_util.tree_all(
        jax.tree_util.tree_map(
            lambda a, b: bool(np.array_equal(np.asarray(a), np.asarray(b))),
            restored, params_v2,
        )
    )
    assert chex_equal

    # tamper with the manifest: the load must refuse loudly...
    import json as _json

    mpath = tmp_path / "weights" / "weights_manifest_3.json"
    rec = _json.loads(mpath.read_text())
    rec["fingerprint"] = "0" * 64
    mpath.write_text(_json.dumps(rec))
    with pytest.raises(WeightsCorrupt):
        load_serving_weights(d, step=3, like=params)

    # ...and begin_swap turns it into the typed refusal
    t = [0.0]
    fe = _cluster(env, 1, lambda: t[0])
    st = fe.begin_swap(d, step=3)
    assert st["state"] == "refused"
    assert st["verdict"] == "fingerprint_mismatch"

    # intact manifest drives a full checkpoint-sourced swap
    save_serving_weights(d, 4, params_v2, version="v2b")
    st = fe.begin_swap(d, step=4)
    assert st["state"] == "rolling" and st["to_version"] == "v2b"


# -- typed refusals ---------------------------------------------------------


def test_swap_refusals_typed(env):
    cfg, model, params, params_v2, prompts, _, _ = env
    t = [0.0]
    fe = _cluster(env, 2, lambda: t[0])
    # wrong shapes refuse typed (not an exception mid-rollout)
    flat, treedef = jax.tree_util.tree_flatten(params_v2)
    flat[0] = np.zeros(np.asarray(flat[0]).shape + (1,), np.float32)
    st = fe.begin_swap(
        params=jax.tree_util.tree_unflatten(treedef, flat), version="bad"
    )
    assert (st["state"], st["verdict"]) == ("refused", SWAP_REFUSED_SHAPE)
    # a version already in service could never be told apart on rollback
    st = fe.begin_swap(params=params_v2, version="initial")
    assert (st["state"], st["verdict"]) == (
        "refused", SWAP_REFUSED_VERSION,
    )
    # double begin_swap refuses while a rollout is live
    st = fe.begin_swap(params=params_v2, version="v2")
    assert st["state"] == "rolling"
    st = fe.begin_swap(params=params_v2, version="v3")
    assert (st["state"], st["verdict"]) == (
        "refused", SWAP_REFUSED_IN_PROGRESS,
    )

    # swap during drain is refused typed
    t2 = [0.0]
    fe2 = _cluster(env, 2, lambda: t2[0])
    fe2.drain()
    st = fe2.begin_swap(params=params_v2, version="v2")
    assert (st["state"], st["verdict"]) == (
        "refused", SWAP_REFUSED_DRAINING,
    )


# -- the happy rolling swap -------------------------------------------------


def test_rolling_swap_completes_zero_failures_bitwise(env):
    """Two replicas swap one at a time under load: zero failed requests,
    every in-flight-at-swap stream bitwise identical to the no-swap
    baseline (it finishes on the old weights), post-swap requests served
    on the new version match ITS reference, fleet ends 100% new."""
    cfg, model, params, params_v2, prompts, refs_v1, refs_v2 = env
    t = [0.0]
    fe = _cluster(env, 2, lambda: t[0])
    outs = [
        fe.submit(Request(prompt=p, max_new_tokens=NEW_TOKENS))
        for p in prompts[:4]
    ]
    for _ in range(3):
        t[0] += 0.05
        fe.step()
    inflight = [o for o in outs if not o.done and o.tokens]
    assert inflight, "choreography: requests must be mid-stream at swap"
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=40, canary_ticks=2, canary_seconds=0.1,
            canary_requests=1,
        ),
    )
    assert st["state"] == "rolling"

    later = []

    def submit(tick):
        if tick % 4 == 0 and len(later) < 4:
            later.append(
                fe.submit(
                    Request(
                        prompt=prompts[4 + len(later)],
                        max_new_tokens=NEW_TOKENS,
                    )
                )
            )
        submit.done = len(later) >= 4

    submit.done = False
    _drive(fe, t, submit=submit)
    s = fe.swap_status()
    assert s["state"] == "completed" and s["verdict"] == "completed"
    assert all(v == "v2" for v in s["replica_versions"].values())
    assert all(o.status == FINISHED for o in outs + later)
    for o in inflight:
        i = outs.index(o)
        assert list(o.tokens) == list(refs_v1[i]), (
            f"in-flight-at-swap request {i} diverged from the no-swap "
            "baseline"
        )
    # canary accounting flowed: at least one request finished on a canary
    assert s["canary_finished"] >= 0
    summary = fe.summary()
    assert summary["swaps"] == 1 and summary["swap_rollbacks"] == 0
    assert summary["failed"] == 0
    # requests that ran post-swap on a v2 replica match the v2 reference
    v2_served = [
        (4 + k, o) for k, o in enumerate(later)
        if list(o.tokens) == list(refs_v2[4 + k])
    ]
    assert v2_served, "no post-swap request was served by the new weights"


def test_one_replica_cluster_swaps_without_dropping(env):
    """A 1-replica fleet swaps in place: pending work HOLDS during the
    exclusion (no no_replica loud failure — capacity is coming back) and
    every request finishes."""
    cfg, model, params, params_v2, prompts, refs_v1, refs_v2 = env
    t = [0.0]
    fe = _cluster(env, 1, lambda: t[0])
    outs = [
        fe.submit(Request(prompt=p, max_new_tokens=NEW_TOKENS))
        for p in prompts[:2]
    ]
    for _ in range(2):
        t[0] += 0.05
        fe.step()
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=40, canary_ticks=2, canary_seconds=0.1,
            canary_requests=1,
        ),
    )
    assert st["state"] == "rolling"
    # arrivals DURING the exclusion must pend, not fail
    outs.append(
        fe.submit(Request(prompt=prompts[2], max_new_tokens=NEW_TOKENS))
    )
    outs.append(
        fe.submit(Request(prompt=prompts[3], max_new_tokens=NEW_TOKENS))
    )
    _drive(fe, t)
    s = fe.swap_status()
    assert s["state"] == "completed"
    assert s["replica_versions"] == {0: "v2"}
    assert all(o.status == FINISHED for o in outs)
    assert fe.summary()["failed"] == 0
    # the post-swap requests were served by the new weights
    assert list(outs[2].tokens) == list(refs_v2[2])
    assert list(outs[3].tokens) == list(refs_v2[3])


def test_frozen_clock_never_promotes_canary(env):
    """canary_seconds is measured on the INJECTABLE clock: a frozen
    clock accrues clean ticks and finished requests forever without ever
    promoting the canary — determinism is a feature, not an accident."""
    cfg, model, params, params_v2, prompts, _, _ = env
    t = [0.0]
    fe = _cluster(env, 1, lambda: t[0])
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=2, canary_ticks=1, canary_seconds=0.5,
            canary_requests=1,
        ),
    )
    assert st["state"] == "rolling"
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=4))
    for _ in range(60):  # NO clock advance
        fe.step()
    s = fe.swap_status()
    assert out.status == FINISHED  # the canary serves; it just never
    assert s["state"] == "rolling"  # gets promoted on a frozen clock
    assert s["replica_phase"][0] == SWAP_CANARY
    assert fe.replicas[0].health == PROBATION
    assert fe.swap_status()["canary_finished"] >= 1
    # thaw the clock: the same canary promotes and the swap completes
    _drive(fe, t)
    assert fe.swap_status()["state"] == "completed"


# -- relocation (forced-prefix) ---------------------------------------------


def test_swap_drain_timeout_relocates_bitwise(env):
    """A straggler still decoding when drain_ticks expires is relocated
    through the forced-prefix path onto a same-version peer: greedy
    output stays bitwise identical, no retry is counted (a swap is not a
    fault), and the relocation is counted in its own metric."""
    cfg, model, params, params_v2, prompts, refs_v1, _ = env
    t = [0.0]
    fe = _cluster(env, 2, lambda: t[0])
    long_new = 16
    ref_long = np.asarray(generate(
        model, params, jnp.asarray(prompts[0], jnp.int32)[None, :],
        max_new_tokens=long_new,
    ))[0]
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=long_new))
    t[0] += 0.05
    fe.step()
    assert not out.done
    target = out.replicas[0]
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=2, canary_ticks=2, canary_seconds=0.1,
            canary_requests=1,
        ),
    )
    assert st["state"] == "rolling"
    _drive(fe, t)
    assert fe.swap_status()["state"] == "completed"
    assert out.status == FINISHED
    assert list(out.tokens) == list(ref_long)
    assert len(out.replicas) >= 2 and out.replicas[0] == target
    assert out.retries == 0  # relocation is not a fault
    reloc = fe.registry.counter("cluster_swap_relocations_total").value
    assert reloc >= 1


# -- rollback ---------------------------------------------------------------


def test_canary_death_rolls_back_whole_fleet(env):
    """A canary that stops making progress is watchdog-killed; its death
    during the audition triggers automatic rollback: the rollout halts,
    every live replica ends on the OLD version, the verdict is typed,
    and no request is lost."""
    cfg, model, params, params_v2, prompts, refs_v1, _ = env
    t = [0.0]
    SWAP_AT = 6
    fe = _cluster(
        env, 3, lambda: t[0],
        fault_plans={0: FaultPlan(stall_at_tick=SWAP_AT + 2,
                                  stall_ticks=300)},
        watchdog=(3, 8),
    )
    outs = []

    def submit(tick):
        if tick % 3 == 0 and len(outs) < 8:
            outs.append(
                fe.submit(
                    Request(
                        prompt=prompts[len(outs)],
                        max_new_tokens=NEW_TOKENS,
                    )
                )
            )
        if tick == SWAP_AT:
            st = fe.begin_swap(
                params=params_v2, version="v2",
                policy=SwapPolicy(
                    drain_ticks=10, canary_ticks=6, canary_seconds=0.2,
                    canary_requests=2,
                ),
            )
            assert st["state"] == "rolling"
        submit.done = len(outs) >= 8

    submit.done = False
    _drive(fe, t, submit=submit)
    s = fe.swap_status()
    assert s["state"] == "rolled_back"
    assert s["verdict"] == ROLLBACK_CANARY_DEATH
    live = [h for h in fe.replicas if h.health != DEAD]
    assert live and all(h.weights_version == "initial" for h in live)
    assert all(o.status == FINISHED for o in outs)
    assert fe.summary()["swap_rollbacks"] == 1
    assert fe.summary()["swaps"] == 0


def test_rollback_mid_rollout_zero_mixed_version_routing(env):
    """Regression strikes on the SECOND canary: replica 0 is already
    promoted to v2.  The rollback must (a) never route NEW requests to
    any still-v2 replica while it reverts, and (b) end with the whole
    fleet on v1 — proven end to end: every post-rollback request's
    greedy output matches the v1 reference bitwise."""
    cfg, model, params, params_v2, prompts, refs_v1, _ = env
    t = [0.0]
    fe = _cluster(env, 3, lambda: t[0], watchdog=(3, 8))
    pol = SwapPolicy(
        drain_ticks=10, canary_ticks=2, canary_seconds=0.1,
        canary_requests=1,
    )
    st = fe.begin_swap(params=params_v2, version="v2", policy=pol)
    assert st["state"] == "rolling"

    outs = []
    ticks = [0]

    # feed traffic until replica 1 becomes the canary, then stall it by
    # killing it directly (the watchdog path is covered elsewhere)
    def until_second_canary(_):
        s = fe.swap_status()
        if outs and len(outs) < 6 or not outs:
            if ticks[0] % 3 == 0 and len(outs) < 6:
                outs.append(
                    fe.submit(
                        Request(
                            prompt=prompts[len(outs)],
                            max_new_tokens=NEW_TOKENS,
                        )
                    )
                )
        ticks[0] += 1
        return s.get("canary") == 1 or s["state"] != "rolling"

    _drive(fe, t, until=until_second_canary)
    s = fe.swap_status()
    assert s["canary"] == 1 and s["replica_phase"][0] == "promoted"
    assert fe._handle(0).weights_version == "v2"
    # the canary dies mid-audition
    fe._handle(1).kill("test: canary corpse")
    t[0] += 0.05
    fe.step()
    s = fe.swap_status()
    assert s["state"] == "rolling_back"
    assert s["verdict"] == ROLLBACK_CANARY_DEATH

    # while replica 0 still holds v2, fresh requests must not land on it
    post = []
    guard_ticks = 0
    while (
        fe._handle(0).weights_version == "v2" and guard_ticks < 200
    ):
        before = fe.registry.counter(
            "cluster_dispatched_total", replica=0
        ).value
        post.append(
            fe.submit(
                Request(
                    prompt=prompts[len(post) % len(prompts)],
                    max_new_tokens=NEW_TOKENS,
                )
            )
        )
        t[0] += 0.05
        fe.step()
        after = fe.registry.counter(
            "cluster_dispatched_total", replica=0
        ).value
        if fe._handle(0).weights_version == "v2":
            # still on the abandoned version after this tick: nothing
            # may have been dispatched to it (the same tick can legally
            # revert the replica and THEN dispatch to it on v1)
            assert after == before, (
                "a fresh request was routed to a replica still holding "
                "the abandoned version"
            )
        guard_ticks += 1
    _drive(fe, t)
    s = fe.swap_status()
    assert s["state"] == "rolled_back"
    live = [h for h in fe.replicas if h.health != DEAD]
    assert all(h.weights_version == "initial" for h in live)
    for o in outs + post:
        assert o.status == FINISHED, (o.status, o.finish_reason)
    # post-rollback requests are pure v1 streams — zero mixed routing
    for k, o in enumerate(post):
        assert list(o.tokens) == list(refs_v1[k % len(prompts)])


def test_slo_ttft_regression_rolls_back(env):
    """The canary window's mean TTFT beyond ttft_factor x the pre-swap
    baseline triggers rollback with the slo_ttft verdict."""
    cfg, model, params, params_v2, prompts, _, _ = env
    t = [0.0]
    fe = _cluster(env, 2, lambda: t[0])
    # build a baseline: several quickly-served requests pre-swap
    base = [
        fe.submit(Request(prompt=p, max_new_tokens=4))
        for p in prompts[:5]
    ]
    _drive(fe, t, until=lambda _: not fe.has_work())
    assert all(o.status == FINISHED for o in base)
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=4, canary_ticks=2, canary_seconds=0.1,
            canary_requests=2, ttft_factor=2.0, baseline_min_requests=3,
        ),
    )
    assert st["state"] == "rolling"
    # wait for the canary, then inject a slow canary window directly
    # into its histogram (the plumbing from real finishes is covered by
    # the completing-swap tests; this pins the guard's arithmetic)
    _drive(
        fe, t,
        until=lambda _: fe.swap_status().get("canary") is not None
        or fe.swap_status()["state"] != "rolling",
    )
    s = fe.swap_status()
    assert s["canary"] is not None
    baseline = s["baseline_ttft_mean"]
    assert baseline is not None and baseline > 0
    for _ in range(2):
        fe._swap._c_ttft.observe(baseline * 10)
    t[0] += 0.05
    fe.step()
    assert fe.swap_status()["verdict"] == ROLLBACK_SLO_TTFT
    _drive(fe, t)
    s = fe.swap_status()
    assert s["state"] == "rolled_back"
    assert all(
        h.weights_version == "initial"
        for h in fe.replicas if h.health != DEAD
    )


def test_spot_check_mismatch_rolls_back(env):
    """The logit-fingerprint spot check: the canary's greedy output is
    replayed offline with the SHIPPED weights — an engine silently
    serving different weights (corrupted load) is caught and rolled
    back even though its latency looks perfectly healthy."""
    cfg, model, params, params_v2, prompts, _, _ = env
    t = [0.0]
    fe = _cluster(env, 1, lambda: t[0])
    probe = jax.random.randint(
        jax.random.PRNGKey(0), (1, 12), 1, cfg.vocab_size
    )
    params_corrupt = model.init(
        {"params": jax.random.PRNGKey(99)}, probe, train=False
    )["params"]
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=2, canary_ticks=1, canary_seconds=0.05,
            canary_requests=1, spot_check=True,
        ),
    )
    assert st["state"] == "rolling"
    _drive(
        fe, t,
        until=lambda _: fe.swap_status().get("canary") == 0
        or fe.swap_status()["state"] != "rolling",
    )
    assert fe.swap_status()["canary"] == 0
    # simulate a corrupted load: the engine is NOT serving the weights
    # the operator shipped
    fe._handle(0).engine.params = params_corrupt
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=4))
    _drive(fe, t)
    s = fe.swap_status()
    assert s["state"] == "rolled_back"
    assert s["verdict"] == ROLLBACK_SPOT_CHECK
    assert out.status == FINISHED
    # the rollback restored the STASHED old params, not the corrupt ones
    assert fe._handle(0).engine.params is params


# -- crash mid-swap ---------------------------------------------------------


def test_crash_mid_swap_resolves_via_breaker_and_completes(env):
    """The swap target crashes while draining: its work replays via the
    normal forced-prefix death path, the circuit breaker restarts it,
    and the rollout RETRIES the replica once it is healthy again —
    completing with the whole fleet on the new version, no deadlock, no
    lost request."""
    cfg, model, params, params_v2, prompts, refs_v1, _ = env
    t = [0.0]
    long_new = 16
    ref_long = np.asarray(generate(
        model, params, jnp.asarray(prompts[1], jnp.int32)[None, :],
        max_new_tokens=long_new,
    ))[0]
    # the target crashes shortly after the swap begins (its own tick 5)
    fe = _cluster(
        env, 2, lambda: t[0],
        fault_plans={0: FaultPlan(crash_at_tick=5)},
    )
    out_long = fe.submit(
        Request(prompt=prompts[1], max_new_tokens=long_new)
    )
    t[0] += 0.05
    fe.step()
    target = out_long.replicas[0]
    assert target == 0  # least-loaded places the first request on 0
    st = fe.begin_swap(
        params=params_v2, version="v2",
        policy=SwapPolicy(
            drain_ticks=40, canary_ticks=2, canary_seconds=0.1,
            canary_requests=1,
        ),
    )
    assert st["state"] == "rolling"
    outs = []

    def submit(tick):
        if tick % 4 == 0 and len(outs) < 4:
            outs.append(
                fe.submit(
                    Request(
                        prompt=prompts[2 + len(outs)],
                        max_new_tokens=NEW_TOKENS,
                    )
                )
            )
        submit.done = len(outs) >= 4

    submit.done = False
    ticks = _drive(fe, t, submit=submit, max_ticks=1500)
    assert ticks < 1500, "rollout wedged after a mid-swap crash"
    s = fe.swap_status()
    assert s["state"] == "completed", s
    assert all(v == "v2" for v in s["replica_versions"].values())
    assert fe.summary()["replica_deaths"] >= 1
    assert fe.summary()["restarts"] >= 1
    assert out_long.status == FINISHED
    # the crashed stream replayed forced-prefix on the old weights peer
    assert list(out_long.tokens) == list(ref_long)
    assert all(o.status == FINISHED for o in outs)
    assert fe.summary()["failed"] == 0


# -- chaos plumbing ---------------------------------------------------------


def test_chaos_swap_storm_resolves(env):
    """Tier-1 chaos smoke with the swap@T operator event armed: a
    null-value rolling swap begins mid-storm (seeded crashes, stalls and
    flaps hitting the fleet, including mid-rollout) and must RESOLVE —
    completed or rolled back, zero version mix among live replicas,
    every request finished bitwise-exact — without wedging.  Seed 3 is
    pinned to a storm whose stall overlaps traffic and whose crashes
    land around the rollout (3 deaths, 3 restarts)."""
    import os
    import random
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import chaos_bench
    finally:
        sys.path.pop(0)
    cfg, model, params, _, _, _, _ = env
    rnd = random.Random(3)
    prompts = chaos_bench.make_prompts(cfg, rnd, 12, 3, 12)
    refs = chaos_bench.baseline_tokens(model, params, prompts, 6, 2)
    record, violations = chaos_bench.run_soak(
        model, params, cfg, prompts, refs, seed=3, n_replicas=2,
        n_slots=2, new_tokens=6, horizon=48, max_ticks=2500, swap=True,
    )
    assert violations == [], violations
    assert record["swap_at_tick"] is not None
    assert record["swap_state"] in ("completed", "rolled_back")
    assert record["replica_deaths"] >= 1  # the storm hit the fleet
    assert record["restarts"] >= 1  # ...and the breaker healed it
    assert record["bitwise_exact"] and record["all_terminal"]


def test_fault_plan_swap_kind_deterministic():
    """from_seed grows the swap@T operator-event kind: drawn only when
    requested, deterministic per (rng state, ticks, kinds), and never
    imposed on the classic fault kinds."""
    import random

    a = FaultPlan.from_seed(random.Random(5), 40, kinds=("swap",))
    b = FaultPlan.from_seed(random.Random(5), 40, kinds=("swap",))
    assert a == b
    assert a.swap_at_tick is not None and 3 <= a.swap_at_tick < 40
    assert a.crash_at_tick is None
    c = FaultPlan.from_seed(random.Random(5), 40, kinds=("crash",))
    assert c.swap_at_tick is None
    with pytest.raises(ValueError, match="unknown fault kinds"):
        FaultPlan.from_seed(random.Random(5), 40, kinds=("swapp",))
    mixed = FaultPlan.from_seed(
        random.Random(7), 40, kinds=("swap", "crash", "stall")
    )
    assert mixed.swap_at_tick is not None
    assert mixed.crash_at_tick is not None
