"""Write-ahead request journal: the daemon's crash-recovery contract.

Append-only JSONL with monotone sequence numbers and batched fsync.
Every record the daemon must not lose across a ``kill -9`` goes through
here BEFORE the effect is acknowledged to a client:

- ``submit``   — an ACCEPTED submission (the full request payload plus
  the client's dedupe token).  Synced durably before the accept is
  returned, so an acknowledged request can never vanish.  The payload
  field names are intentionally the serve_bench trace-schema names
  (``arrival`` / ``prompt`` / ``prompt_len`` / ``prefix_group`` /
  ``priority`` / ``deadline`` / ``max_new_tokens``) — ONE workload
  exchange format, so ``serve_bench --trace-replay`` (alias
  ``--workload``) replays a production journal directly.
- ``tokens``   — tokens delivered to a request this tick (``index`` is
  the position of the first one).  Batched per tick; a torn tail loses
  at most the unsynced suffix, and greedy recovery regenerates exactly
  those tokens (forced-prefix replay is bitwise).
- ``terminal`` — a request reached a terminal state (status + typed
  ``finish_reason``).  A journaled terminal is what makes the dedupe
  token idempotent: a resubmission after it returns the completed
  record instead of re-admitting.
- ``decision`` — swap rollouts, autopilot actions, drain begin: the
  operator-action audit trail.
- ``recovery`` — a restart replayed the journal (counts ride along).
- ``shutdown`` — the process exited; ``clean`` distinguishes a drained
  exit (nothing open) from a forced fast shutdown (the journal IS the
  recovery contract for whatever was still open).

Durability model: every ``append`` writes and flushes the line to the
OS immediately (a crashed *process* loses nothing flushed); ``fsync``
— the expensive disk barrier that survives a crashed *machine* — is
batched: forced for ``submit``/``shutdown`` records, otherwise issued
once at least ``fsync_batch`` records are pending (``sync()`` at each
tick boundary).  Recovery (:func:`read_journal`) tolerates exactly one
torn record at the END of the file (the write the crash interrupted);
corruption anywhere else raises :class:`JournalCorrupt` loudly.

Timestamps come from the injected clock and are only comparable within
one process lifetime (the wall clock is monotonic per process) — replay
logic never compares times across a restart, only sequence numbers.
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

JOURNAL_VERSION = 1

# record kinds (the "record" field)
REC_META = "journal_meta"
REC_SUBMIT = "submit"
REC_TOKENS = "tokens"
REC_TERMINAL = "terminal"
REC_DECISION = "decision"
REC_RECOVERY = "recovery"
REC_SHUTDOWN = "shutdown"

# record kinds whose append forces an immediate fsync: an accepted
# submission must be durable before the client hears "accepted", a
# recovery record is the restart's first promise, and a shutdown record
# is the last thing the process does
_SYNC_NOW = frozenset({REC_SUBMIT, REC_RECOVERY, REC_SHUTDOWN})


class JournalCorrupt(RuntimeError):
    """The journal failed its integrity scan somewhere a torn tail
    cannot explain (mid-file garbage, non-monotone sequence numbers)."""


class JournalWriter:
    """Append-only JSONL writer with sequence numbers and batched fsync.

    ``clock`` is injectable (the daemon passes its :class:`~tpu_parallel.
    daemon.wallclock.WallClock`); every record gets ``seq`` (monotone,
    continuing across restarts via ``next_seq``) and ``at`` (clock time,
    process-local).  ``fsync_batch`` records may ride the OS page cache
    between disk barriers — except the kinds in ``_SYNC_NOW``, which
    sync before ``append`` returns.
    """

    def __init__(
        self,
        path: str,
        clock: Callable[[], float],
        *,
        fsync_batch: int = 32,
        next_seq: int = 0,
    ):
        if fsync_batch < 1:
            raise ValueError(f"fsync_batch={fsync_batch} < 1")
        self.path = path
        self.clock = clock
        self.fsync_batch = fsync_batch
        self._seq = next_seq
        self._pending = 0  # records flushed to OS but not yet fsynced
        self.records = 0  # lifetime appends (this writer)
        self.fsyncs = 0
        self.truncated_tail = drop_torn_tail(path)
        fresh = not os.path.exists(path) or os.path.getsize(path) == 0
        self._fh = open(path, "a", encoding="utf-8")
        if fresh:
            self.append({"record": REC_META, "journal_version": JOURNAL_VERSION})
            self.sync()

    def append(self, record: Dict) -> Dict:
        """Assign seq + timestamp, write one line, flush to the OS.
        Returns the full record as written.  Sync-now kinds fsync before
        returning; everything else waits for :meth:`sync`."""
        rec = dict(record)
        rec["seq"] = self._seq
        self._seq += 1
        rec.setdefault("at", round(self.clock(), 6))
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        self.records += 1
        self._pending += 1
        if rec.get("record") in _SYNC_NOW or self._pending >= self.fsync_batch:
            self.sync()
        return rec

    def sync(self) -> bool:
        """Batched disk barrier: fsync when anything is pending (tick
        boundary) — a no-op on a clean writer.  Returns whether a real
        fsync was issued."""
        if self._pending == 0:
            return False
        self._fh.flush()
        os.fsync(self._fh.fileno())
        self.fsyncs += 1
        self._pending = 0
        return True

    @property
    def next_seq(self) -> int:
        return self._seq

    def close(self) -> None:
        if not self._fh.closed:
            self.sync()
            self._fh.close()

    def abort(self) -> None:
        """Crash simulation for tests: drop the handle without the
        closing sync (flushed lines survive, like a SIGKILL'd process)."""
        if not self._fh.closed:
            self._fh.close()


def drop_torn_tail(path: str) -> int:
    """Truncate a torn final record before APPENDING to a journal.

    ``read_journal`` tolerates a torn tail while *reading*, but a writer
    reopening in append mode would concatenate its first record onto the
    fragment — turning tolerable tail damage into mid-file garbage that
    bricks the journal (:class:`JournalCorrupt`) on the NEXT restart.
    Dropping the fragment loses nothing: it was never durable, and the
    reader already ignored it.  Returns the bytes truncated (0 when the
    file is absent, empty, or newline-terminated)."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return 0
    with open(path, "rb+") as fh:
        fh.seek(0, os.SEEK_END)
        size = fh.tell()
        fh.seek(size - 1)
        if fh.read(1) == b"\n":
            return 0
        # scan back to the last complete line's newline (chunked so a
        # long torn record doesn't load the whole file)
        pos = size
        keep = 0
        while pos > 0:
            step = min(4096, pos)
            fh.seek(pos - step)
            chunk = fh.read(step)
            nl = chunk.rfind(b"\n")
            if nl != -1:
                keep = pos - step + nl + 1
                break
            pos -= step
        fh.truncate(keep)
        fh.flush()
        os.fsync(fh.fileno())
        return size - keep


def read_journal(path: str) -> Tuple[List[Dict], int]:
    """Scan a journal file.  Returns ``(records, torn)`` where ``torn``
    counts dropped trailing garbage (0 or 1 — the record a crash tore
    mid-write).  Mid-file corruption or a sequence-number regression
    raises :class:`JournalCorrupt`: a journal that lies about its order
    must not drive recovery."""
    records: List[Dict] = []
    bad_at: Optional[int] = None
    with open(path, encoding="utf-8") as fh:
        for lineno, line in enumerate(fh, 1):
            if bad_at is not None:
                raise JournalCorrupt(
                    f"{path}:{bad_at}: unparseable record is not at the "
                    "tail — the journal is corrupt beyond a torn write"
                )
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                bad_at = lineno  # legal only as the final line
                continue
            if not isinstance(rec, dict) or "record" not in rec:
                bad_at = lineno
                continue
            records.append(rec)
    last = -1
    for rec in records:
        seq = rec.get("seq")
        if seq is None:
            continue
        if seq <= last:
            raise JournalCorrupt(
                f"{path}: sequence regressed {last} -> {seq}"
            )
        last = seq
    return records, (0 if bad_at is None else 1)


@dataclasses.dataclass
class JournalEntry:
    """Replay state for one journaled request: the submit payload, the
    durable token prefix, and the terminal record (None = the crash
    caught it accepted-but-unfinished — recovery re-admits it)."""

    submit: Dict
    tokens: List[int] = dataclasses.field(default_factory=list)
    terminal: Optional[Dict] = None

    @property
    def request_id(self) -> str:
        return self.submit["request_id"]

    @property
    def dedupe_token(self) -> Optional[str]:
        return self.submit.get("dedupe_token")

    @property
    def unfinished(self) -> bool:
        return self.terminal is None


@dataclasses.dataclass
class RecoveryState:
    """Everything a restart needs from the journal: per-request entries
    in submit order, the dedupe index, the next sequence number, and the
    scan's damage/shutdown accounting."""

    entries: Dict[str, JournalEntry]
    order: List[str]
    dedupe: Dict[str, str]  # dedupe_token -> request_id
    next_seq: int
    torn_records: int
    clean_shutdown: bool
    recoveries: int  # prior recovery records (restart count)
    decisions: int

    @property
    def unfinished(self) -> List[JournalEntry]:
        return [
            self.entries[rid]
            for rid in self.order
            if self.entries[rid].unfinished
        ]

    @property
    def finished(self) -> List[JournalEntry]:
        return [
            self.entries[rid]
            for rid in self.order
            if not self.entries[rid].unfinished
        ]


def replay_state(records: List[Dict], torn: int = 0) -> RecoveryState:
    """Fold a journal scan into :class:`RecoveryState`.  Token records
    apply by INDEX (idempotent across overlapping replays: a re-delivery
    of positions already durable overwrites them with identical values
    under greedy decoding); a terminal closes its entry."""
    entries: Dict[str, JournalEntry] = {}
    order: List[str] = []
    dedupe: Dict[str, str] = {}
    next_seq = 0
    clean = False
    recoveries = 0
    decisions = 0
    for rec in records:
        seq = rec.get("seq")
        if seq is not None:
            next_seq = max(next_seq, seq + 1)
        kind = rec.get("record")
        if kind == REC_SUBMIT:
            rid = rec["request_id"]
            if rid not in entries:  # duplicate submits cannot re-open
                entries[rid] = JournalEntry(submit=rec)
                order.append(rid)
                tok = rec.get("dedupe_token")
                if tok:
                    dedupe[tok] = rid
        elif kind == REC_TOKENS:
            entry = entries.get(rec["request_id"])
            if entry is None:
                continue
            index = int(rec.get("index", len(entry.tokens)))
            toks = [int(t) for t in rec.get("tokens", ())]
            del entry.tokens[index:]
            entry.tokens.extend(toks)
        elif kind == REC_TERMINAL:
            entry = entries.get(rec["request_id"])
            if entry is not None:
                entry.terminal = rec
        elif kind == REC_SHUTDOWN:
            clean = bool(rec.get("clean"))
        elif kind == REC_RECOVERY:
            recoveries += 1
            clean = False
        elif kind == REC_DECISION:
            decisions += 1
        if kind in (REC_SUBMIT, REC_TOKENS, REC_TERMINAL):
            clean = False  # work after a shutdown record reopens the log
    return RecoveryState(
        entries=entries,
        order=order,
        dedupe=dedupe,
        next_seq=next_seq,
        torn_records=torn,
        clean_shutdown=clean,
        recoveries=recoveries,
        decisions=decisions,
    )


def load_state(path: str) -> RecoveryState:
    """One-call journal scan + fold (missing/empty file = empty state)."""
    if not os.path.exists(path) or os.path.getsize(path) == 0:
        return replay_state([], 0)
    records, torn = read_journal(path)
    return replay_state(records, torn)
