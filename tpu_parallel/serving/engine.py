"""Continuous-batching inference engine: iteration-level scheduling over a
fixed pool of KV-cache slots.

The static path (``models/generate.py``) decodes a batch run-to-completion:
every request starts together and the whole batch waits for the longest
generation.  This engine decodes the SLOT POOL instead — one jitted
single-token step over all ``n_slots`` rows per tick, compiled once — and
lets requests join (prefill into a freed slot) and leave (EOS / length
retirement) between ticks:

- tick = [admissions] + [one decode step] + [retirements]
- admission prefills the request ALONE (batch 1, its exact prompt length)
  and row-inserts the fresh cache into a free slot
  (:mod:`~tpu_parallel.serving.cache_pool`); the prefill's last hidden
  state samples the request's first token, so TTFT is one prefill, not a
  queue-drain.
- the decode step threads per-slot positions and per-slot cache write
  indices (``write_index`` — the slot-indexed write path in
  ``models/layers.py``) because rows sit at different depths of their
  generations; the attention mask already keys off stored per-slot
  positions, so mixed-depth rows read correctly.
- sampling knobs are per-REQUEST traced arrays (temperature / top_k /
  top_p per slot, :func:`sample_tokens`): two requests with different
  knobs share a tick without recompiling.
- inactive (free) slots still run through the step — their sampled tokens
  are ignored and their writes land harmlessly in dead rows; masking work
  out of a fixed-shape jitted step is the standard slot-pool trade.

Greedy equivalence: for requests submitted together, per-request outputs
are token-identical to static ``generate()`` on the same prompts (pinned
in ``tests/test_serving.py``) — row-parallel ops make batch composition
invisible to each row, and both paths share
:func:`~tpu_parallel.models.generate.decode_step`.

TP serving: pass ``mesh`` (and mesh-sharded ``params``) and the engine
wraps its prefill/decode cores in the same
:func:`~tpu_parallel.models.generate.build_sharded_serving` harness as
``generate_sharded`` — weights stay split, the cache pool shards over
heads, sampling runs on gathered ``[n_slots, vocab]`` logits (small), with
``fold_axes=()`` so every rank draws identical noise (slot arrays ride
replicated over the data axis; data ranks duplicate decode work).  Pipe
meshes are refused — serve those through ``generate_sharded``.
"""

from __future__ import annotations

import functools
import time
from typing import Callable, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from tpu_parallel.models.generate import (
    _HashableTree,
    build_sharded_serving,
    decode_step,
)
from tpu_parallel.serving.cache_pool import (
    CachePool,
    cache_partition_specs,
    insert_rows,
)
from tpu_parallel.serving.metrics import ServingMetrics
from tpu_parallel.serving.request import (
    FINISHED,
    REJECTED,
    RUNNING,
    Request,
    RequestOutput,
    StreamEvent,
)
from tpu_parallel.serving.scheduler import FIFOScheduler, SchedulerConfig


def sample_tokens(
    logits: jax.Array,
    rng: jax.Array,
    temperature: jax.Array,
    top_k: jax.Array,
    top_p: jax.Array,
) -> jax.Array:
    """Per-ROW sampling from [batch, vocab] logits with per-row knobs.

    The vectorized counterpart of ``models.generate._sample``: the knobs
    are traced [batch] arrays, so one compiled program serves every knob
    combination in the pool.  Same semantics per row — ``temperature == 0``
    is exact argmax; ``top_k``/``top_p`` compose by intersection after the
    temperature scale; ``top_k <= 0`` / ``top_p`` outside (0, 1) disable
    that filter; the argmax token always survives the nucleus cut.
    """
    lf = logits.astype(jnp.float32)
    greedy = jnp.argmax(lf, axis=-1).astype(jnp.int32)
    # guard the temperature divide: greedy rows take the argmax branch of
    # the final where, so their scaled logits are never read
    t = jnp.where(temperature > 0.0, temperature, 1.0)[:, None]
    x = lf / t
    vocab = x.shape[-1]
    # per-row top-k with traced k: the kth-largest value via one sort
    k = jnp.clip(top_k.astype(jnp.int32), 0, vocab)
    asc = jnp.sort(x, axis=-1)
    kth = jnp.take_along_axis(
        asc, jnp.clip(vocab - k, 0, vocab - 1)[:, None], axis=-1
    )
    x = jnp.where((k > 0)[:, None] & (x < kth), -jnp.inf, x)
    # per-row nucleus on the (already top-k-filtered) distribution
    desc = jnp.sort(x, axis=-1)[:, ::-1]
    probs = jax.nn.softmax(desc, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep = cum - probs < top_p[:, None]  # mass BEFORE the token < p
    cutoff = jnp.min(jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True)
    use_p = ((top_p > 0.0) & (top_p < 1.0))[:, None]
    x = jnp.where(use_p & (x < cutoff), -jnp.inf, x)
    sampled = jax.random.categorical(rng, x, axis=-1).astype(jnp.int32)
    return jnp.where(temperature > 0.0, sampled, greedy)


def _full_last_logits(cfg, params, hidden):
    """lm_head over the last position only, FULL vocab width on every rank
    (one tiny [batch, vocab] all_gather under TP — the per-row knob sampler
    needs the whole row; batch is n_slots, not tokens)."""
    from tpu_parallel.models.gpt import _lm_head_params, _make_lm_head
    from tpu_parallel.parallel.tp import axis_size_or_none

    head = _make_lm_head(cfg, name=None, gather=False, fsdp_wrap=False)
    logits = head.apply(
        {"params": _lm_head_params(cfg, params)}, hidden[:, -1:]
    )[:, 0]
    if axis_size_or_none(cfg.model_axis) is not None:
        logits = lax.all_gather(logits, cfg.model_axis, axis=-1, tiled=True)
    return logits


def _prefill_core(model, params, prompt, rng):
    """Batch-1 (or batch-N) prefill: fills a fresh cache, returns the last
    position's full-vocab logits + the cache.  ``rng`` unused (sampling
    happens outside so the prefill compiles per prompt LENGTH only, not
    per knob set)."""
    del rng
    b, prompt_len = prompt.shape
    positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
    hidden, variables = model.apply(
        {"params": params},
        prompt,
        positions=positions,
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
    )
    return _full_last_logits(model.config, params, hidden), variables["cache"]


def _decode_core(
    model, params, tok, pos, widx, temperature, top_k, top_p, cache, rng
):
    """One engine tick over the slot pool: slot-indexed cache writes,
    per-slot sampling.  Returns (next_tokens [n_slots], new cache)."""
    hidden, cache = decode_step(
        model, params, cache, tok, pos, write_index=widx
    )
    logits = _full_last_logits(model.config, params, hidden)
    nxt = sample_tokens(logits, rng, temperature, top_k, top_p)
    return nxt, cache


@functools.lru_cache(maxsize=8)
def _engine_fns(model):
    """Jitted engine step functions for the single-host path, cached per
    model so every engine instance (tests build many) shares traces.

    The cache-pool operand is DONATED in the decode step and the insert:
    the old pool tree is dead the moment the call returns, and without
    donation XLA holds a second full pool (the engine's dominant HBM) at
    every tick."""
    prefill = jax.jit(
        lambda params, prompt, rng: _prefill_core(model, params, prompt, rng)
    )
    decode = jax.jit(
        lambda params, tok, pos, widx, temp, tk, tp, cache, rng: _decode_core(
            model, params, tok, pos, widx, temp, tk, tp, cache, rng
        ),
        donate_argnums=7,
    )
    sample = jax.jit(sample_tokens)
    insert = jax.jit(insert_rows, donate_argnums=0)
    return prefill, decode, sample, insert


@functools.lru_cache(maxsize=8)
def _sharded_engine_fns(model, mesh, specs: _HashableTree,
                        cache_specs: _HashableTree):
    """shard_map-wrapped engine step functions (TP serving), through the
    same ``build_sharded_serving`` harness as ``generate_sharded`` —
    ``fold_axes=()`` keeps sampling noise identical on every rank (the
    slot arrays are replicated, so outputs must be too)."""
    from jax.sharding import PartitionSpec as P

    param_specs = specs.tree()
    cspecs = cache_specs.tree()
    prefill = build_sharded_serving(
        model, mesh, param_specs, (P(),), (P(), cspecs), _prefill_core,
        fold_axes=(),
    )
    decode = build_sharded_serving(
        model, mesh, param_specs,
        (P(), P(), P(), P(), P(), P(), cspecs), (P(), cspecs), _decode_core,
        fold_axes=(),
    )
    sample = jax.jit(sample_tokens)
    # the shard_map-wrapped decode cannot donate (build_sharded_serving
    # does not expose donation), so the TP tick holds a transient second
    # pool; the insert at least recycles its operand
    insert = jax.jit(insert_rows, donate_argnums=0)
    return prefill, decode, sample, insert


class ServingEngine:
    """In-process continuous-batching engine over one model + params.

    ``step()`` runs one scheduling + decode tick and returns the tick's
    :class:`StreamEvent`s (incremental delivery); ``run()`` loops until
    idle.  ``add_request`` is non-blocking: the returned
    :class:`RequestOutput` fills in as ticks run.

    ``n_slots`` fixes the pool (HBM = ``n_slots x seq_len`` K/V per layer
    — ``kv_cache_dtype="int8"`` halves it); ``scheduler`` takes a
    :class:`SchedulerConfig` (or a ready scheduler) for admission policy;
    ``clock`` is injectable for deterministic timeout tests.
    """

    def __init__(
        self,
        model,
        params,
        n_slots: int = 8,
        scheduler: Union[SchedulerConfig, FIFOScheduler, None] = None,
        mesh=None,
        param_specs=None,
        rng: Optional[jax.Array] = None,
        metrics: Optional[ServingMetrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        cfg = model.config
        if getattr(cfg, "pipe_size", 1) > 1:
            raise NotImplementedError(
                "the serving engine does not run pipeline meshes — serve "
                "pipe-split models through generate_sharded"
            )
        self.model = model
        self.params = params
        self.clock = clock
        self.metrics = metrics if metrics is not None else ServingMetrics()
        if isinstance(scheduler, FIFOScheduler):
            self.scheduler = scheduler
        else:
            self.scheduler = FIFOScheduler(scheduler)
        self._rng = rng if rng is not None else jax.random.PRNGKey(0)

        pool_shardings = None
        if mesh is not None:
            import flax.linen as nn
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            if param_specs is None:
                param_specs = nn.get_partition_spec(params)
            cspecs = cache_partition_specs(model, params, n_slots, mesh)
            # allocate the pool sharded at birth: a TP-split pool must
            # never transit one device whole
            pool_shardings = jax.tree_util.tree_map(
                lambda spec: NamedSharding(mesh, spec), cspecs,
                is_leaf=lambda x: isinstance(x, P),
            )
            fns = _sharded_engine_fns(
                model, mesh, _HashableTree.of(param_specs),
                _HashableTree.of(cspecs),
            )
        else:
            fns = _engine_fns(model)
        self._prefill_fn, self._decode_fn, self._sample_fn, insert = fns
        self.pool = CachePool(
            model, params, n_slots, insert_fn=insert,
            shardings=pool_shardings,
        )

        n = n_slots
        self._tok = np.zeros(n, np.int32)
        self._pos = np.zeros(n, np.int32)
        self._widx = np.zeros(n, np.int32)
        self._temp = np.zeros(n, np.float32)
        self._topk = np.zeros(n, np.int32)
        self._topp = np.zeros(n, np.float32)
        self._active = np.zeros(n, bool)
        self._slot_out: List[Optional[RequestOutput]] = [None] * n

    # -- submission --------------------------------------------------------

    def add_request(self, request: Request) -> RequestOutput:
        """Submit; returns the live output record (status REJECTED when the
        prompt cannot fit or admission control refuses)."""
        out = RequestOutput(request, arrival_time=self.clock())
        total = len(request.prompt) + request.max_new_tokens
        if total > self.model.config.seq_len:
            out.status = REJECTED
            out.finish_reason = (
                f"prompt ({len(request.prompt)}) + max_new_tokens "
                f"({request.max_new_tokens}) exceeds seq_len "
                f"({self.model.config.seq_len})"
            )
            self.metrics.record_rejected()
            return out
        if not self.scheduler.submit(out):
            out.status = REJECTED
            out.finish_reason = "queue full"
            self.metrics.record_rejected()
            return out
        return out

    # -- the tick ----------------------------------------------------------

    def step(self) -> List[StreamEvent]:
        """One engine tick: expire stale queue entries, admit into free
        slots (bounded by the scheduler's prefill budget), one decode step
        over the pool, retire finished slots.  Returns this tick's events."""
        now = self.clock()
        events: List[StreamEvent] = []
        for out in self.scheduler.expire(now):
            # terminal notification with no token (token/index = -1):
            # expiry is asynchronous — unlike REJECTED, which the caller
            # sees synchronously on add_request — so stream consumers need
            # the event or they wait forever
            out.finish_reason = "max_wait"
            out.finish_time = now
            event = StreamEvent(
                request_id=out.request.request_id,
                token=-1,
                index=-1,
                finished=True,
                finish_reason="max_wait",
            )
            if out.request.on_token is not None:
                out.request.on_token(event)
            events.append(event)
            self.metrics.record_expired()
        admitted = self.scheduler.schedule(self.pool.n_free, now)
        for out in admitted:
            events.extend(self._admit(out))
        decoded = False
        if self._active.any():
            events.extend(self._decode_tick())
            decoded = True
        self.metrics.record_tick(
            now=self.clock(),
            queue_depth=self.scheduler.depth,
            occupancy=self.pool.occupancy,
            # expiry notifications carry token=-1 — not generated tokens
            new_tokens=sum(1 for ev in events if ev.token >= 0),
            prefills=len(admitted),
            decoded=decoded,
        )
        return events

    def has_work(self) -> bool:
        return self.scheduler.depth > 0 or bool(self._active.any())

    def run(self, max_ticks: Optional[int] = None) -> List[StreamEvent]:
        """Tick until idle (or ``max_ticks``); returns all events."""
        events: List[StreamEvent] = []
        ticks = 0
        while self.has_work() and (max_ticks is None or ticks < max_ticks):
            events.extend(self.step())
            ticks += 1
        return events

    # -- internals ---------------------------------------------------------

    def _next_rng(self) -> jax.Array:
        self._rng, sub = jax.random.split(self._rng)
        return sub

    def _admit(self, out: RequestOutput) -> List[StreamEvent]:
        req = out.request
        slot = self.pool.acquire()
        assert slot is not None, "scheduler admitted beyond free slots"
        prompt = jnp.asarray(req.prompt, jnp.int32)[None, :]
        logits, fresh = self._prefill_fn(
            self.params, prompt, self._next_rng()
        )
        self.pool.insert(fresh, slot)
        sp = req.sampling
        first = self._sample_fn(
            logits,
            self._next_rng(),
            jnp.asarray([sp.temperature], jnp.float32),
            jnp.asarray([sp.top_k], jnp.int32),
            jnp.asarray([sp.top_p], jnp.float32),
        )
        tok0 = int(np.asarray(first)[0])
        prompt_len = len(req.prompt)
        self._tok[slot] = tok0
        self._pos[slot] = prompt_len
        self._widx[slot] = prompt_len
        self._temp[slot] = sp.temperature
        self._topk[slot] = sp.top_k
        self._topp[slot] = sp.top_p
        self._active[slot] = True
        self._slot_out[slot] = out
        out.status = RUNNING
        out.first_token_time = self.clock()
        return [self._deliver(slot, tok0)]

    def _decode_tick(self) -> List[StreamEvent]:
        nxt, self.pool.cache = self._decode_fn(
            self.params,
            jnp.asarray(self._tok),
            jnp.asarray(self._pos),
            jnp.asarray(self._widx),
            jnp.asarray(self._temp),
            jnp.asarray(self._topk),
            jnp.asarray(self._topp),
            self.pool.cache,
            self._next_rng(),
        )
        nxt = np.asarray(nxt)
        events = []
        # every slot's current token was just written into the cache;
        # advance even the slots that retire on this token's delivery
        for slot in np.nonzero(self._active)[0]:
            self._pos[slot] += 1
            self._widx[slot] += 1
            self._tok[slot] = int(nxt[slot])
            events.append(self._deliver(int(slot), int(nxt[slot])))
        return events

    def _deliver(self, slot: int, token: int) -> StreamEvent:
        """Record one generated token for the request in ``slot``; retire
        the slot when the token finishes the request (EOS or length)."""
        out = self._slot_out[slot]
        req = out.request
        now = self.clock()
        out.tokens.append(token)
        out.token_times.append(now)
        finish_reason = None
        if req.eos_token_id is not None and token == req.eos_token_id:
            finish_reason = "eos"
        elif len(out.tokens) >= req.max_new_tokens:
            finish_reason = "length"
        event = StreamEvent(
            request_id=req.request_id,
            token=token,
            index=len(out.tokens) - 1,
            finished=finish_reason is not None,
            finish_reason=finish_reason,
        )
        if finish_reason is not None:
            out.status = FINISHED
            out.finish_reason = finish_reason
            out.finish_time = now
            self._active[slot] = False
            self._slot_out[slot] = None
            self.pool.release(slot)
            self.metrics.record_finished(out)
        if req.on_token is not None:
            req.on_token(event)
        return event
