from tpu_parallel.core.accumulate import (
    accumulate_gradients,
    accumulate_gradients_loop,
    accumulate_gradients_scan,
)
from tpu_parallel.core.metrics import (
    Metrics,
    accumulate_metrics,
    compute,
    format_metrics,
    metric,
    print_metrics,
    sync_metrics,
    zeros_like_metrics,
)
from tpu_parallel.core.rng import fold_rng_over_axis, split_rng_like
from tpu_parallel.core.state import Batch, Pytree, TextBatch, TrainState, get_num_params

__all__ = [
    "accumulate_gradients",
    "accumulate_gradients_loop",
    "accumulate_gradients_scan",
    "Metrics",
    "accumulate_metrics",
    "compute",
    "format_metrics",
    "metric",
    "print_metrics",
    "sync_metrics",
    "zeros_like_metrics",
    "fold_rng_over_axis",
    "split_rng_like",
    "Batch",
    "Pytree",
    "TextBatch",
    "TrainState",
    "get_num_params",
]
