from tpu_parallel.models.mlp import MLPClassifier, MLPConfig

__all__ = ["MLPClassifier", "MLPConfig"]
