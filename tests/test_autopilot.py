"""SLO-autopilot tests: closed-loop overload control over the cluster
frontend — hysteresis-gated shedding (typed ``shed``, lowest effective
priority first, bounded by ``max_shed_fraction``), replica autoscaling
through the probation gate (typed-refused mid-swap), admission retuning
within bounds, prefix-ring rebalancing, the pre-dispatch deadline shed
bugfix, and the headline reproducibility guarantee: same trace + same
policy => identical typed action log."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import (
    AP_REFUSED,
    AP_REFUSED_NO_IDLE_PEER,
    AP_REFUSED_NO_ROLE_CONTROLLER,
    AP_REFUSED_SWAP,
    AP_REROLE,
    AP_SCALE_DOWN,
    AP_SCALE_UP,
    AP_SHED_CANCEL,
    AP_SHED_OFF,
    AP_SHED_ON,
    HEALTHY,
    PROBATION,
    RETIRED,
    AutopilotPolicy,
    Frontend,
    FrontendConfig,
    PrefixAffinityRouter,
    ReplicaHandle,
    RestartPolicy,
)
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.serving import (
    CANCELLED,
    REJECT_SHED,
    REJECTED,
    FIFOScheduler,
    Request,
    SchedulerConfig,
    ServingEngine,
)


@pytest.fixture(scope="module")
def env():
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(11)
    lens = [3, 5, 7, 4, 6, 8]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    probe = jax.random.randint(rng, (1, max(lens)), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = [
        np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=6,
        ))[0]
        for p in prompts
    ]
    return cfg, model, params, prompts, refs


def _fleet(env, clock, n=2, slots=2, **fe_kw):
    cfg, model, params, _, _ = env

    def factory():
        return ServingEngine(
            model, params, n_slots=slots,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            clock=clock, decode_steps_per_tick=1,
        )

    handles = [
        ReplicaHandle(i, factory(), engine_factory=factory)
        for i in range(n)
    ]
    kw = dict(
        router="least", clock=clock,
        config=FrontendConfig(
            watchdog_ticks=4, watchdog_kill_ticks=16,
            restart=RestartPolicy(
                backoff_seconds=0.1, probation_ticks=2,
                probation_requests=2,
            ),
        ),
    )
    kw.update(fe_kw)
    return Frontend(handles, **kw), factory


# -- policy validation -------------------------------------------------------


def test_policy_validation():
    AutopilotPolicy(max_replicas=4)  # defaults are coherent
    with pytest.raises(ValueError):
        AutopilotPolicy(queue_age_target=0.0)
    with pytest.raises(ValueError):
        AutopilotPolicy(max_shed_fraction=1.5)
    with pytest.raises(ValueError):
        AutopilotPolicy(max_replicas=1, min_replicas=2)
    with pytest.raises(ValueError):
        AutopilotPolicy(token_budget_bounds=(8, 4))
    with pytest.raises(ValueError):
        AutopilotPolicy(imbalance_factor=1.0)
    with pytest.raises(ValueError):
        AutopilotPolicy(breach_ticks=0)


def test_scheduler_retune_hook():
    """The autopilot's scheduler hook: live max_prefills_per_tick /
    max_queue changes, validated, leaving queued entries alone."""
    sched = FIFOScheduler(SchedulerConfig(max_prefills_per_tick=1))
    cfg = sched.retune(max_prefills_per_tick=4)
    assert cfg.max_prefills_per_tick == 4
    assert sched.config.max_prefills_per_tick == 4
    sched.retune(max_queue=2)
    assert sched.config.max_queue == 2
    assert sched.config.max_prefills_per_tick == 4  # untouched
    with pytest.raises(ValueError):
        sched.retune(max_prefills_per_tick=0)


# -- pre-dispatch deadline shed (satellite bugfix) ---------------------------


def test_expired_in_queue_never_dispatched(env):
    """Regression: a request whose deadline expired while pending must
    be dropped AT DISPATCH (typed ``deadline``), never handed to a
    replica for a wasted prefill — and the cancel counters observe it
    exactly once."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    # occupy the only slot so the victim waits in the frontend backlog
    blocker = fe.submit(Request(prompt=prompts[0], max_new_tokens=6))
    fe.step()
    victim = fe.submit(
        Request(prompt=prompts[1], max_new_tokens=6, deadline=0.5)
    )
    # the deadline expires while the request is still pending; the next
    # dispatch pass must cancel instead of place
    t[0] = 1.0
    fe.step()
    assert victim.status == CANCELLED
    assert victim.finish_reason == "deadline"
    assert victim.replicas == []  # never reached a replica
    assert fe.registry.counter("cluster_cancelled_total").value == 1.0
    assert (
        fe.registry.counter(
            "cluster_dispatched_total", replica=0
        ).value == 1.0  # only the blocker was ever dispatched
    )
    fe.run(max_ticks=60)
    assert blocker.status == "finished"


def test_dispatch_deadline_checked_on_mid_tick_clock(env):
    """The tick's SECOND dispatch pass reads a fresh clock: a deadline
    expiring mid-tick (engine work advanced the clock) is still caught
    before placement."""
    _, _, _, prompts, _ = env
    t = [0.0]

    def clock():  # advances on every read — the adversarial clock
        t[0] += 0.2
        return t[0]

    fe, _ = _fleet(env, clock, n=1, slots=1)
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=4,
                            deadline=0.6))
    for _ in range(30):
        if out.done:
            break
        fe.step()
    assert out.done
    # wherever it died, it must never have been dispatched after expiry:
    # a cancelled-by-deadline request with zero replica attempts proves
    # the dispatch-time check fired (placement would have recorded one)
    if out.status == CANCELLED:
        assert out.finish_reason == "deadline"


# -- shedding ----------------------------------------------------------------


def _overload(fe, prompts, t, n=12, priority=0, deadline=None):
    outs = []
    for i in range(n):
        outs.append(fe.submit(Request(
            prompt=list(prompts[i % len(prompts)]), max_new_tokens=6,
            priority=priority, deadline=deadline,
        )))
    return outs


def test_shed_hysteresis_and_typed_reject(env):
    """Backlog age past target for breach_ticks => shedding engages; a
    NEW lowest-priority submission rejects typed ``shed``; a clear
    window disengages (asymmetric hysteresis, both transitions logged)."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.5, window_ticks=4, breach_ticks=2,
        clear_ticks=3, max_shed_fraction=1.0, max_replicas=1,
    ))
    _overload(fe, prompts, t, n=8)
    # age the backlog past the target without letting it drain
    for _ in range(3):
        t[0] += 0.4
        fe.step()
    assert ap.shedding
    assert [a.kind for a in ap.actions][:1] == [AP_SHED_ON]
    low = fe.submit(Request(prompt=prompts[0], max_new_tokens=4,
                            priority=0))
    assert low.status == REJECTED and low.finish_reason == REJECT_SHED
    # a HIGHER class than everything pending sails through the shed gate
    high = fe.submit(Request(prompt=prompts[1], max_new_tokens=4,
                             priority=99))
    assert high.status != REJECTED
    fe.run(max_ticks=400)
    for _ in range(6):  # idle clear window disengages
        t[0] += 0.05
        fe.step()
    assert not ap.shedding
    kinds = [a.kind for a in ap.actions]
    assert AP_SHED_OFF in kinds
    assert fe.registry.counter(
        "cluster_rejected_total", reason="shed"
    ).value == 1.0


def test_shed_fraction_bound(env):
    """The shed budget is hard: at max_shed_fraction=0.25 at most a
    quarter of a window's submissions shed, the rest admit."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=100, breach_ticks=2,
        clear_ticks=50, max_shed_fraction=0.25, max_replicas=1,
    ))
    _overload(fe, prompts, t, n=6)
    for _ in range(3):
        t[0] += 0.4
        fe.step()
    assert ap.shedding
    outs = _overload(fe, prompts, t, n=20)
    shed = [o for o in outs if o.finish_reason == REJECT_SHED]
    admitted = [o for o in outs if not o.done]
    total_submitted = fe._submitted.value - ap._win_sub0
    assert shed, "nothing shed under sustained overload"
    assert len(shed) <= 0.25 * total_submitted
    assert admitted, "shedding must stay a bounded slice, not a rout"
    # zero max_shed_fraction pins shedding fully off (chaos-soak trim)
    assert AutopilotPolicy(max_shed_fraction=0.0, max_replicas=1)


def test_shed_cancels_provably_unmeetable(env):
    """While shedding, a QUEUED request whose deadline cannot be met
    (waited + estimate > deadline) is cancelled typed ``shed`` before
    wasting a prefill; meetable neighbors survive."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=8, breach_ticks=2,
        clear_ticks=8, max_shed_fraction=1.0,
        min_service_seconds=0.1, service_seconds_per_token=0.1,
        max_replicas=1,
    ))
    blocker = fe.submit(Request(prompt=prompts[0], max_new_tokens=6))
    fe.step()
    # 6 tokens * 0.1s + 0.1s floor = 0.7s estimate: at t=0.8 a 1.4s
    # deadline is provably gone while a 10s deadline is comfortable
    doomed = fe.submit(Request(prompt=prompts[1], max_new_tokens=6,
                               deadline=1.4))
    fine = fe.submit(Request(prompt=prompts[2], max_new_tokens=6,
                             deadline=10.0))
    for _ in range(3):
        t[0] += 0.4
        fe.step()
    assert doomed.status == CANCELLED
    assert doomed.finish_reason == REJECT_SHED
    assert not fine.done or fine.status == "finished"
    assert fe.registry.counter(
        "cluster_autopilot_shed_total", kind="cancel"
    ).value == 1.0
    fe.run(max_ticks=400)
    assert blocker.status == "finished" and fine.status == "finished"


def test_shed_floor_covers_engine_queued_backlog(env):
    """Review regression: when the backlog lives in ENGINE queues (the
    frontend backlog is empty), the shed floor still ranks against the
    queued work — a higher class sails through, and with nothing
    waiting anywhere no arrival is shed at all."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=8, breach_ticks=2,
        clear_ticks=50, max_shed_fraction=1.0, max_replicas=1,
    ))
    # r0 runs, r1 sits in the ENGINE queue; frontend backlog drains empty
    fe.submit(Request(prompt=prompts[0], max_new_tokens=20, priority=0))
    fe.submit(Request(prompt=prompts[1], max_new_tokens=20, priority=0))
    t[0] += 0.4
    fe.step()
    t[0] += 0.4
    fe.step()
    assert ap.shedding
    assert not fe._pending  # the waiting work is all engine-queued
    assert fe.replicas[0].engine.scheduler.depth >= 1
    high = fe.submit(Request(prompt=prompts[2], max_new_tokens=4,
                             priority=5))
    assert high.status != REJECTED  # ranks above the queued floor
    low = fe.submit(Request(prompt=prompts[3], max_new_tokens=4,
                            priority=0))
    assert low.status == REJECTED and low.finish_reason == REJECT_SHED
    fe.run(max_ticks=600)
    # shedding may still be engaged, but with NOTHING waiting anywhere
    # an arrival of any class admits (floor is None => no shed)
    if ap.shedding:
        out = fe.submit(Request(prompt=prompts[4], max_new_tokens=4,
                                priority=0))
        assert out.status != REJECTED
        fe.run(max_ticks=200)


# -- scaling -----------------------------------------------------------------


def test_scale_up_enters_probation_and_serves(env):
    """Sustained breach grows the fleet: the new replica appears under
    the next free id, starts in PROBATION (half-open, must prove
    itself), is promoted by clean ticks, and serves work."""
    _, _, _, prompts, refs = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=4, breach_ticks=2,
        clear_ticks=4, max_shed_fraction=0.0, max_replicas=2,
        scale_cooldown_ticks=2,
    ))
    outs = _overload(fe, prompts, t, n=8)
    t[0] += 0.4
    fe.step()
    t[0] += 0.4
    fe.step()
    assert len(fe.replicas) == 2
    fresh = fe._by_id[1]
    assert fresh.health == PROBATION
    assert fe.summary()["scale_ups"] == 1
    while fe.has_work():
        t[0] += 0.05
        fe.step()
    assert fresh.health == HEALTHY  # promoted through the normal gate
    assert fresh.engine.metrics.finished >= 1  # it actually served
    assert all(o.status == "finished" for o in outs)
    for o, p in zip(outs[: len(prompts)], prompts):
        ref = np.asarray(generate(
            env[1], env[2], jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=6,
        ))[0]
        np.testing.assert_array_equal(np.asarray(o.tokens), ref)


def test_scale_down_retires_idle_replica(env):
    """An idle replica past scale_down_idle_ticks retires through the
    drain path: fleet shrinks to min_replicas, the handle leaves for
    the retired list with a released pool, never below the floor."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=3, slots=2)
    fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=5.0, window_ticks=4, breach_ticks=2,
        clear_ticks=4, max_shed_fraction=0.0, max_replicas=3,
        min_replicas=2, scale_cooldown_ticks=2, scale_down_idle_ticks=4,
    ))
    for _ in range(20):
        t[0] += 0.05
        fe.step()
    assert len(fe.replicas) == 2  # exactly one retired: the floor holds
    assert len(fe.retired) == 1
    gone = fe.retired[0]
    assert gone.health == RETIRED
    assert gone.engine.pool.n_free == gone.engine.pool.n_slots
    assert gone.engine.draining
    assert fe.summary()["scale_downs"] == 1
    # the survivors still serve
    out = fe.submit(Request(prompt=prompts[0], max_new_tokens=4))
    fe.run(max_ticks=100)
    assert out.status == "finished"


def test_scale_up_never_reuses_a_retired_id(env):
    """Review regression: replica ids are monotone — a scale-up after a
    scale-down must NOT reuse the retiree's id (its terminal gauge row
    and trace history belong to a different engine)."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=2, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=4, breach_ticks=2,
        clear_ticks=2, max_shed_fraction=0.0, max_replicas=3,
        min_replicas=2, scale_cooldown_ticks=2, scale_down_idle_ticks=3,
    ))
    _overload(fe, prompts, t, n=10)
    t[0] += 0.4
    fe.step()
    t[0] += 0.4
    fe.step()
    assert 2 in fe._by_id  # scaled up to replica 2
    fe.run(max_ticks=600)
    for _ in range(10):  # idle: replica 2 retires
        t[0] += 0.05
        fe.step()
    assert len(fe.retired) == 1  # the LONGEST-idle replica retired
    retired_id = fe.retired[0].replica_id
    _overload(fe, prompts, t, n=10)
    for _ in range(4):
        t[0] += 0.4
        fe.step()
        if len(fe.replicas) == 3:
            break
    assert len(fe.replicas) == 3
    # fresh monotone id — never the retiree's, never a reuse of 2
    assert 3 in fe._by_id and retired_id not in fe._by_id
    assert [a.kind for a in ap.actions].count(AP_SCALE_UP) == 2
    fe.run(max_ticks=600)


def test_scale_refused_typed_during_swap(env):
    """Acceptance pin: a due scale action NEVER interleaves with an
    in-progress rolling swap — it is refused with the typed
    ``swap_in_progress`` reason (action log + counter)."""
    _, _, params, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=2, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=4, breach_ticks=2,
        clear_ticks=4, max_shed_fraction=0.0, max_replicas=4,
        scale_cooldown_ticks=2,
    ))
    st = fe.begin_swap(params=params, version="v2")
    assert st["state"] == "rolling"
    _overload(fe, prompts, t, n=10)
    for _ in range(4):
        t[0] += 0.4
        fe.step()
        if any(a.kind == AP_REFUSED for a in ap.actions):
            break
    refusals = [a for a in ap.actions if a.kind == AP_REFUSED]
    assert refusals and refusals[0].reason == AP_REFUSED_SWAP
    assert all(a.kind != AP_SCALE_UP for a in ap.actions)
    assert len(fe.replicas) == 2  # the fleet did not move mid-rollout
    assert fe.registry.counter(
        "cluster_autopilot_refusals_total", reason=AP_REFUSED_SWAP
    ).value >= 1.0
    fe.run(max_ticks=600)


def test_scale_up_rebinds_to_fleet_standard_weights(env):
    """A scale-up after a completed swap must serve the NEW fleet
    standard, not the factory's pre-swap weights."""
    cfg, model, params, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=2, slots=1)
    fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=4, breach_ticks=2,
        clear_ticks=4, max_shed_fraction=0.0, max_replicas=3,
        scale_cooldown_ticks=2,
    ))
    st = fe.begin_swap(params=params, version="v2")
    assert st["state"] == "rolling"
    while fe.swap_status()["state"] == "rolling":
        t[0] += 0.1
        fe.step()
    assert fe.swap_status()["state"] == "completed"
    _overload(fe, prompts, t, n=10)
    for _ in range(3):
        t[0] += 0.4
        fe.step()
    assert len(fe.replicas) == 3
    assert fe._by_id[2].weights_version == "v2"
    fe.run(max_ticks=600)


# -- rebalance ---------------------------------------------------------------


def test_ring_weight_rebalance_and_membership():
    """Weighted-ring mechanics: set_weight shifts only the hot
    replica's keys; add/remove move only the joiner/leaver's keys;
    weights restore losslessly (placement is a pure function of the
    weight map)."""
    r = PrefixAffinityRouter([0, 1, 2], vnodes=32)
    keys = [[i, i + 1, i + 2] for i in range(300)]
    before = [r.owner(k) for k in keys]
    r.set_weight(0, 0.5)
    after = [r.owner(k) for k in keys]
    moved = [i for i, (a, b) in enumerate(zip(before, after)) if a != b]
    # only keys leaving the depressed replica move, and only AWAY from it
    assert moved
    assert all(before[i] == 0 for i in moved)
    assert sum(1 for o in after if o == 0) < sum(
        1 for o in before if o == 0
    )
    r.set_weight(0, 1.0)
    assert [r.owner(k) for k in keys] == before  # lossless restore
    r.add_replica(3)
    grown = [r.owner(k) for k in keys]
    assert all(b == g or g == 3 for b, g in zip(before, grown))
    r.remove_replica(3)
    assert [r.owner(k) for k in keys] == before
    with pytest.raises(ValueError):
        r.set_weight(0, 0.0)
    with pytest.raises(ValueError):
        r.set_weight(99, 0.5)
    solo = PrefixAffinityRouter([0])
    with pytest.raises(ValueError):
        solo.remove_replica(0)


def test_autopilot_rebalances_hot_ring_owner(env):
    """A replica loaded past imbalance_factor x the fleet mean gets its
    ring weight halved (typed rebalance action), shifting future
    placement off it."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=2, slots=4, router="prefix")
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=100.0,  # never shed: isolate the rebalancer
        max_shed_fraction=0.0, max_replicas=2,
        # with one idle peer, max load == 2 x mean exactly — a factor
        # below 2 makes the skew detectable in a 2-replica fleet
        imbalance_factor=1.5, rebalance_cooldown_ticks=1,
    ))
    hot = fe.replicas[0]
    # pile queued work on one replica directly (past the frontend) so
    # its load() runs far beyond the fleet mean
    for i in range(8):
        hot.submit(Request(prompt=list(prompts[i % len(prompts)]),
                           max_new_tokens=4))
    t[0] += 0.05
    fe.step()
    rebalances = [a for a in ap.actions if a.kind == "rebalance"]
    assert rebalances and rebalances[0].reason == "imbalance"
    assert fe.router.weights[hot.replica_id] == 0.5
    fe.run(max_ticks=300)


# -- retune ------------------------------------------------------------------


def test_retune_budget_and_prefill_share_within_bounds(env):
    """Sustained breach tightens the token budget and surges the
    prefill share to the ceiling; a clear stretch relaxes both back to
    the OPERATOR's pre-autopilot settings — never past them, never
    outside the configured bounds."""
    _, _, _, prompts, _ = env
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=2)
    fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=2, breach_ticks=2,
        clear_ticks=2, max_shed_fraction=0.0, max_replicas=1,
        token_budget_bounds=(64, 512), token_budget_step=0.5,
        prefill_surge_share=4,
    ))
    _overload(fe, prompts, t, n=8)
    for _ in range(6):
        t[0] += 0.4
        fe.step()
    assert fe.config.max_inflight_tokens is not None
    assert 64 <= fe.config.max_inflight_tokens < 512
    sched = fe.replicas[0].engine.scheduler
    assert sched.config.max_prefills_per_tick == 4  # surge bound
    fe.run(max_ticks=400)
    for _ in range(10):  # clear stretch relaxes back
        t[0] += 0.05
        fe.step()
    # restored to the operator's own configured share (2), NOT forced
    # down to the policy floor (1) — and the operator's UNBOUNDED token
    # budget comes back as exactly that (None), not a lingering cap
    assert sched.config.max_prefills_per_tick == 2
    assert fe.config.max_inflight_tokens is None


# -- determinism (acceptance) ------------------------------------------------


def test_action_log_deterministic(env):
    """Same trace + same policy + same clock => byte-identical typed
    action logs and identical outcomes, twice."""
    _, _, _, prompts, _ = env

    def run():
        t = [0.0]
        fe, _ = _fleet(env, lambda: t[0], n=2, slots=1)
        ap = fe.enable_autopilot(AutopilotPolicy(
            queue_age_target=0.3, window_ticks=4, breach_ticks=2,
            clear_ticks=4, max_shed_fraction=0.5, max_replicas=4,
            scale_cooldown_ticks=3, scale_down_idle_ticks=6,
            min_service_seconds=0.05, service_seconds_per_token=0.05,
        ))
        outs = []
        for i in range(24):
            outs.append(fe.submit(Request(
                prompt=list(prompts[i % len(prompts)]),
                max_new_tokens=6, priority=i % 3,
                deadline=2.0 if i % 2 else None,
            )))
            t[0] += 0.1
            fe.step()
        for _ in range(120):
            t[0] += 0.1
            fe.step()
            if not fe.has_work():
                break
        log = [dataclasses.astuple(a) for a in ap.actions]
        outcomes = [
            (o.status, o.finish_reason, list(o.tokens)) for o in outs
        ]
        return log, outcomes

    log1, outcomes1 = run()
    log2, outcomes2 = run()
    assert log1 == log2
    assert outcomes1 == outcomes2
    assert log1  # the scenario actually exercised the controller


# -- status / telemetry ------------------------------------------------------


def test_autopilot_status_and_metrics(env):
    """autopilot_status() / summary() exposure and the
    cluster_autopilot_* series appear end to end (tracer track
    included)."""
    from tpu_parallel.obs import Tracer

    _, _, _, prompts, _ = env
    t = [0.0]
    tracer = Tracer()
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1, tracer=tracer)
    assert fe.autopilot_status() == {"enabled": False}
    assert fe.summary()["autopilot"] is None
    ap = fe.enable_autopilot(AutopilotPolicy(
        queue_age_target=0.3, window_ticks=4, breach_ticks=2,
        clear_ticks=4, max_shed_fraction=1.0, max_replicas=1,
    ))
    with pytest.raises(RuntimeError):
        fe.enable_autopilot(AutopilotPolicy(max_replicas=1))
    _overload(fe, prompts, t, n=6)
    for _ in range(3):
        t[0] += 0.4
        fe.step()
    fe.submit(Request(prompt=prompts[0], max_new_tokens=4))
    status = fe.autopilot_status()
    assert status["enabled"] and status["shedding"]
    assert status["queue_age_p95"] > 0.3
    assert status["shed_rejects"] == 1
    s = fe.summary()
    assert s["autopilot"]["shedding"] and s["autopilot"]["shed_rejects"] == 1
    names = {
        (c["name"], tuple(sorted(c["labels"].items())))
        for c in fe.registry.snapshot()["counters"]
    }
    assert ("cluster_autopilot_actions_total", (("kind", "shed_on"),)) \
        in names
    assert ("cluster_autopilot_shed_total", (("kind", "reject"),)) in names
    gauges = {g["name"] for g in fe.registry.snapshot()["gauges"]}
    assert "cluster_autopilot_shedding" in gauges
    assert "cluster_autopilot_queue_age_p95_seconds" in gauges
    assert "autopilot" in tracer.tracks()
    assert ap.actions
    fe.run(max_ticks=400)


# -- chaos collision (acceptance) --------------------------------------------


def test_chaos_storm_with_autoscaling_keeps_invariants(env):
    """Acceptance pin: the autopilot's autoscaling armed DURING a seeded
    crash/stall storm — scale-ups really fire mid-storm, and every PR 8
    healing invariant holds unchanged (termination, bitwise exactness
    vs the no-fault baseline, no leaked slots/reservations, dead
    replicas healed).  Deterministic: same seed, same storm, same
    action log."""
    import os
    import random
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import chaos_bench
    finally:
        sys.path.pop(0)

    cfg, model, params, _, _ = env
    rnd = random.Random(0)
    prompts = chaos_bench.make_prompts(cfg, rnd, 12, 3, 12)
    refs = chaos_bench.baseline_tokens(model, params, prompts, 6, 2)

    def soak():
        return chaos_bench.run_soak(
            model, params, cfg, prompts, refs, seed=0, n_replicas=2,
            n_slots=2, new_tokens=6, horizon=48, max_ticks=2500,
            autopilot=True, autopilot_queue_age_target=0.1,
        )

    record, violations = soak()
    assert violations == [], violations
    assert record["all_terminal"] and record["bitwise_exact"]
    assert record["autopilot_scale_ups"] >= 1  # scaling really collided
    assert record["fleet_size_final"] > 2
    assert record["replica_deaths"] >= 1 and record["restarts"] >= 1
    record2, violations2 = soak()
    assert violations2 == []
    assert record["autopilot_actions"] == record2["autopilot_actions"]
    assert record["fleet_size_final"] == record2["fleet_size_final"]


# -- production soak (satellite CI gate) -------------------------------------


@pytest.mark.slow
def test_production_soak_trace_swap_storm_autopilot(env):
    """One-command production soak: a production-shaped recorded trace
    (mixed priorities + deadlines) drives the fleet through a seeded
    fault storm, a mid-run rolling weight swap AND the autopilot's
    scale/shed loop in one run.  The swap must resolve, every non-shed
    request must finish bitwise identical to the single-engine
    baseline, and the shed count stays under the policy bound."""
    import os
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, os.path.join(repo, "scripts"))
    try:
        import serve_bench
    finally:
        sys.path.pop(0)

    cfg, model, params, _, _ = env
    import random

    rnd = random.Random(3)
    prompts = [
        [rnd.randrange(1, cfg.vocab_size)
         for _ in range(rnd.randint(3, 10))]
        for _ in range(36)
    ]
    dt = 0.05
    schedule = serve_bench.build_schedule(
        prompts, [0] * len(prompts), rate=6.0, seed=3, new_tokens=6,
        priority_dist=[(0, 6), (1, 3), (2, 1)],
        deadline_dist=[(4.0, 3), (None, 1)],
    )
    refs = [
        [int(x) for x in np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=6,
        ))[0]]
        for p in prompts
    ]

    from tpu_parallel.cluster import (
        AutopilotPolicy,
        FaultPlan,
        SwapPolicy,
    )

    t = [0.0]
    clock = lambda: t[0]  # noqa: E731

    def factory():
        return ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            clock=clock, decode_steps_per_tick=1,
        )

    handles = [
        ReplicaHandle(
            i, factory(),
            fault_plan=[
                FaultPlan(crash_at_tick=20),
                FaultPlan(stall_at_tick=10, stall_ticks=6),
                None,
            ][i],
            engine_factory=factory,
        )
        for i in range(3)
    ]
    fe = Frontend(
        handles, router="least", clock=clock,
        config=FrontendConfig(
            retry_limit=16, watchdog_ticks=3, watchdog_kill_ticks=10,
            restart=RestartPolicy(
                backoff_seconds=4 * dt, probation_ticks=3,
                probation_requests=2,
            ),
        ),
    )
    policy = AutopilotPolicy(
        queue_age_target=1.0, window_ticks=8, breach_ticks=2,
        clear_ticks=8, max_shed_fraction=0.3, max_replicas=4,
        min_replicas=3, scale_cooldown_ticks=8,
        scale_down_idle_ticks=None,
    )
    fe.enable_autopilot(policy, factory)

    outs, submitted, ticks, swap_started = [], 0, 0, False
    while ticks < 4000:
        now = ticks * dt
        while (
            submitted < len(schedule)
            and schedule[submitted]["arrival"] <= now
        ):
            outs.append(fe.submit(
                serve_bench._schedule_request(schedule[submitted])
            ))
            submitted += 1
        if ticks == 30 and not swap_started:
            st = fe.begin_swap(params=params, version="soak-v2",
                               policy=SwapPolicy(
                                   drain_ticks=12, canary_ticks=3,
                                   canary_seconds=2 * dt,
                               ))
            assert st["state"] == "rolling", st
            swap_started = True
        t[0] += dt
        fe.step()
        ticks += 1
        if (
            submitted >= len(schedule)
            and not fe.has_work()
            and fe.swap_status()["state"] not in (
                "rolling", "rolling_back"
            )
            and not any(
                h.health in ("backoff", "probation")
                for h in fe.replicas
            )
        ):
            break

    assert fe.swap_status()["state"] in ("completed", "rolled_back")
    assert all(o.done for o in outs), "soak must terminate"
    shed = [
        o for o in outs if o.finish_reason == REJECT_SHED
    ]
    assert len(shed) <= policy.max_shed_fraction * len(outs)
    for i, out in enumerate(outs):
        if out.finish_reason in (REJECT_SHED, "deadline"):
            continue
        assert out.status == "finished", (i, out.status, out.finish_reason)
        assert list(out.tokens) == refs[i], (
            f"request {i} diverged from the single-engine baseline"
        )
    # the storm was real and the healing machinery ran under the
    # autopilot (deaths from the crash plan, restart through probation)
    s = fe.summary()
    assert s["replica_deaths"] >= 1
    assert s["restarts"] >= 1

# -- the fourth lever: the fleet's prefill:decode role ratio -----------------


class _StubRoleController:
    """The duck-typed slice of FleetRouter the autopilot steers:
    ``role_counts()`` / ``pick_rerole(to_role)`` / ``set_role``."""

    def __init__(self, roles):
        self.roles = dict(roles)
        self.set_calls = []

    def role_counts(self):
        counts = {}
        for role in self.roles.values():
            counts[role] = counts.get(role, 0) + 1
        return counts

    def pick_rerole(self, to_role):
        for addr in sorted(self.roles):
            if self.roles[addr] == "mixed":
                return addr
        return None

    def set_role(self, addr, role):
        self.set_calls.append((addr, role))
        self.roles[addr] = role
        return True


def test_policy_validation_role_targets():
    with pytest.raises(ValueError):
        AutopilotPolicy(max_replicas=2, decode_itl_target=0.0)
    with pytest.raises(ValueError):
        AutopilotPolicy(max_replicas=2, prefill_backlog_target=-1.0)
    with pytest.raises(ValueError):
        AutopilotPolicy(max_replicas=2, role_cooldown_ticks=0)


def test_rerole_hysteresis_and_cooldown(env):
    """A sustained decode-ITL breach — never a single tick's spike —
    re-roles exactly ONE idle mixed peer to decode, then the role
    cooldown holds the ratio still until its window elapses."""
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    rc = _StubRoleController(
        {"h0:80": "mixed", "h1:80": "mixed", "h2:80": "mixed"}
    )
    ap = fe.enable_autopilot(AutopilotPolicy(
        max_replicas=1, min_replicas=1, scale_down_idle_ticks=None,
        window_ticks=8, breach_ticks=3, role_cooldown_ticks=4,
        prefill_backlog_target=0.5, decode_itl_target=0.05,
    ), role_controller=rc)
    for tick in range(1, 3):
        ap.observe_fleet(decode_itl_seconds=0.2)
        t[0] += 0.01
        fe.step()
        assert not rc.set_calls, f"actuated below breach_ticks ({tick})"
        assert ap.status()["role_breach_streak"] == tick
        assert ap.status()["role_breach_dir"] == "decode_itl"
    ap.observe_fleet(decode_itl_seconds=0.2)
    t[0] += 0.01
    fe.step()  # streak hits breach_ticks: actuate
    assert rc.set_calls == [("h0:80", "decode")]
    reroles = [a for a in ap.actions if a.kind == AP_REROLE]
    assert len(reroles) == 1
    assert reroles[0].reason == "decode_itl"
    assert dict(reroles[0].detail)["to_role"] == "decode"
    assert dict(reroles[0].detail)["role_mixed"] == 2
    assert ap.status()["role_counts"] == {"decode": 1, "mixed": 2}
    # still breaching: the cooldown (4 ticks) holds the ratio
    for _ in range(3):
        ap.observe_fleet(decode_itl_seconds=0.2)
        t[0] += 0.01
        fe.step()
        assert len(rc.set_calls) == 1, "re-roled inside the cooldown"
    ap.observe_fleet(decode_itl_seconds=0.2)
    t[0] += 0.01
    fe.step()  # cooldown elapsed, breach sustained: one more
    assert rc.set_calls == [("h0:80", "decode"), ("h1:80", "decode")]


def test_rerole_decode_wins_and_refusals_are_typed(env):
    """When BOTH fleet signals breach, decode ITL (the client-visible
    one) directs the flip; and when the lever cannot act — no role
    controller armed, or no idle mixed peer left — the refusal is a
    typed action, one per cooldown window, never silence."""
    t = [0.0]
    fe, _ = _fleet(env, lambda: t[0], n=1, slots=1)
    ap = fe.enable_autopilot(AutopilotPolicy(
        max_replicas=1, min_replicas=1, scale_down_idle_ticks=None,
        window_ticks=8, breach_ticks=2, role_cooldown_ticks=3,
        prefill_backlog_target=0.5, decode_itl_target=0.05,
    ))  # role_controller=None: the lever is due but unarmed
    for _ in range(4):
        ap.observe_fleet(
            prefill_backlog_seconds=2.0, decode_itl_seconds=0.2
        )
        t[0] += 0.01
        fe.step()
    refusals = [a for a in ap.actions if a.kind == AP_REFUSED]
    assert len(refusals) == 1  # one per cooldown window, not per tick
    assert refusals[0].reason == AP_REFUSED_NO_ROLE_CONTROLLER
    assert ap.status()["role_breach_dir"] == "decode_itl"

    t2 = [0.0]
    fe2, _ = _fleet(env, lambda: t2[0], n=1, slots=1)
    rc = _StubRoleController({"h0:80": "prefill", "h1:80": "decode"})
    ap2 = fe2.enable_autopilot(AutopilotPolicy(
        max_replicas=1, min_replicas=1, scale_down_idle_ticks=None,
        window_ticks=8, breach_ticks=2, role_cooldown_ticks=3,
        decode_itl_target=0.05,
    ), role_controller=rc)
    for _ in range(3):
        ap2.observe_fleet(decode_itl_seconds=0.2)
        t2[0] += 0.01
        fe2.step()
    assert not rc.set_calls  # nothing mixed left to flip
    refusals = [a for a in ap2.actions if a.kind == AP_REFUSED]
    assert len(refusals) == 1
    assert refusals[0].reason == AP_REFUSED_NO_IDLE_PEER
    assert dict(refusals[0].detail)["to_role"] == "decode"
