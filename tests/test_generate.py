"""KV-cache generation tests: cache decode must equal full re-forwarding."""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate


def _greedy_no_cache(model, params, prompt, n_new):
    """Reference: argmax loop re-running the full forward each step."""
    toks = prompt
    out = []
    for _ in range(n_new):
        logits = model.apply({"params": params}, toks, train=False)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        out.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    return jnp.stack(out, axis=1)


@pytest.mark.parametrize("variant", ["gpt", "llama", "gqa", "unrolled"])
def test_generate_matches_full_forward(rng, variant):
    overrides = dict(
        gpt={},
        llama=dict(positional="rope", norm="rmsnorm", mlp="swiglu"),
        gqa=dict(n_kv_heads=2),
        unrolled=dict(scan_layers=False),
    )[variant]
    cfg = tiny_test(dtype=jnp.float32, remat=False, **overrides)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 5), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    got = generate(model, params, prompt, max_new_tokens=8, temperature=0.0)
    want = _greedy_no_cache(model, params, prompt, 8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_decode_default_positions_match_explicit(rng):
    """decode=True with positions=None uses the model-level step counter.

    Learned positional embeddings must see global positions even when the
    caller omits them — prefill then one decode step must equal the same
    calls with explicit positions.
    """
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 5), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]

    def run(with_positions):
        pos_p = (
            jnp.broadcast_to(jnp.arange(5), (2, 5)) if with_positions else None
        )
        logits, v = model.apply(
            {"params": params}, prompt, positions=pos_p,
            train=False, decode=True, mutable=["cache"],
        )
        tok = jnp.argmax(logits[:, -1:], axis=-1).astype(jnp.int32)
        pos_d = jnp.full((2, 1), 5, jnp.int32) if with_positions else None
        logits2, _ = model.apply(
            {"params": params, "cache": v["cache"]}, tok, positions=pos_d,
            train=False, decode=True, mutable=["cache"],
        )
        return logits2

    np.testing.assert_allclose(
        np.asarray(run(True)), np.asarray(run(False)), rtol=1e-5, atol=1e-5
    )


def test_generate_sampling_shapes(rng):
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (3, 4), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    out = generate(
        model, params, prompt, jax.random.PRNGKey(7),
        max_new_tokens=6, temperature=0.8, top_k=5,
    )
    assert out.shape == (3, 6)
    assert out.dtype == jnp.int32
    assert (out >= 0).all() and (out < cfg.vocab_size).all()


def test_export_single_device_params_roundtrip(mesh_data8, rng):
    """Mesh-trained (DP) params export to the mesh-free layout and generate."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import make_gpt_loss
    from tpu_parallel.models.generate import export_single_device_params
    from tpu_parallel.parallel.spmd import build_train_functions

    cfg = tiny_test(dtype=jnp.float32)
    batch = lm_batch(jax.random.PRNGKey(0), 8, cfg.seq_len, cfg.vocab_size)
    model = GPTLM(cfg)
    tx = optax.adamw(1e-3)

    def model_init(r, b):
        from tpu_parallel.core.state import TrainState

        v = model.init({"params": r}, b.tokens, positions=b.positions, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    funcs = build_train_functions(
        model_init, make_gpt_loss(cfg), mesh_data8, batch,
        batch_spec=P("data"), donate=False,
    )
    state = funcs.init_fn(rng, batch)
    params = export_single_device_params(state.params)
    prompt = jnp.zeros((1, 4), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=4)
    assert out.shape == (1, 4)


def test_export_fsdp_sharded_params_and_generate(mesh_data8, rng):
    """FSDP (data-axis) shard names are slices of REAL dims — the global
    array already holds the full weight, so export drops the names (even on
    a leading dim, e.g. the vocab axis) and plain generate serves the
    result; the model's fsdp wrap degrades to identity without a mesh."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import make_gpt_loss
    from tpu_parallel.models.generate import export_single_device_params
    from tpu_parallel.parallel.spmd import build_train_functions

    cfg = tiny_test(dtype=jnp.float32, fsdp=True, fsdp_min_size=0)
    batch = lm_batch(jax.random.PRNGKey(0), 8, cfg.seq_len, cfg.vocab_size)
    model = GPTLM(cfg)
    tx = optax.adamw(1e-3)

    def model_init(r, b):
        from tpu_parallel.core.state import TrainState

        v = model.init({"params": r}, b.tokens, positions=b.positions, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    funcs = build_train_functions(
        model_init, make_gpt_loss(cfg), mesh_data8, batch,
        batch_spec=P("data"), donate=False,
    )
    state = funcs.init_fn(rng, batch)
    params = export_single_device_params(state.params)
    prompt = jnp.zeros((1, 4), jnp.int32)
    out = generate(model, params, prompt, max_new_tokens=4)
    assert out.shape == (1, 4)
    # exported logits equal the mesh's own forward on the same tokens
    toks = jnp.zeros((8, cfg.seq_len), jnp.int32)
    single = model.apply({"params": params}, toks[:1], train=False)
    mesh_fwd = jax.jit(
        jax.shard_map(
            lambda p, t: model.apply({"params": p}, t, train=False),
            mesh=mesh_data8,
            in_specs=(nn.get_partition_spec(state.params), P("data")),
            out_specs=P("data"),
            check_vma=False,
        )
    )(state.params, toks)
    np.testing.assert_allclose(
        np.asarray(single[0]), np.asarray(mesh_fwd[0]), rtol=2e-5, atol=2e-5
    )


def test_export_refuses_tp_sharded_params(mesh_data4_model2, rng):
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import make_gpt_loss
    from tpu_parallel.models.generate import export_single_device_params
    from tpu_parallel.parallel.spmd import build_train_functions

    cfg = tiny_test()
    batch = lm_batch(jax.random.PRNGKey(0), 8, cfg.seq_len, cfg.vocab_size)
    model = GPTLM(cfg)
    tx = optax.adamw(1e-3)

    def model_init(r, b):
        from tpu_parallel.core.state import TrainState

        v = model.init({"params": r}, b.tokens, positions=b.positions, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    funcs = build_train_functions(
        model_init, make_gpt_loss(cfg), mesh_data4_model2, batch,
        batch_spec=P("data"), grad_sync_axes=("data", "model"), donate=False,
    )
    state = funcs.init_fn(rng, batch)
    with pytest.raises(ValueError, match="split over mesh axis"):
        export_single_device_params(state.params)


def test_generate_rejects_overflow(rng):
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jnp.zeros((1, cfg.seq_len - 2), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]
    with pytest.raises(ValueError, match="exceeds seq_len"):
        generate(model, params, prompt, max_new_tokens=8)


def test_generate_sharded_tp_matches_full_forward(mesh_data4_model2, rng):
    """Mesh decoding: greedy generate_sharded on a DP x TP mesh agrees with
    the full (cache-free) forward under the same mesh — the serving path for
    weights export_single_device_params refuses to merge."""
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import generate_sharded

    mesh = mesh_data4_model2
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (8, 5), 0, cfg.vocab_size)

    def init(r, p):
        return model.init({"params": r}, p, train=False)["params"]

    import flax.linen as nn

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, prompt))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, prompt)

    got = generate_sharded(
        model, params, prompt, mesh, max_new_tokens=6, temperature=0.0
    )
    assert got.shape == (8, 6)

    # ground truth: cache-free greedy loop under the same mesh
    def full_forward(params, tokens):
        return model.apply({"params": params}, tokens, train=False)

    fwd = jax.jit(
        jax.shard_map(
            full_forward, mesh=mesh, in_specs=(specs, P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    toks = prompt
    want = []
    for _ in range(6):
        logits = fwd(params, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    want = jnp.stack(want, axis=1)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_top_p_restricts_support(rng):
    """Nucleus sampling never emits tokens outside the top-p prefix; a tiny
    top_p degenerates to greedy."""
    from tpu_parallel.models.generate import _sample

    logits = jnp.log(
        jnp.asarray([[0.5, 0.3, 0.15, 0.04, 0.01]], jnp.float32)
    )
    # p=0.6: mass-before-token is (0, .5, .8, ...) -> keep {0, 1}
    seen = set()
    for i in range(50):
        tok = _sample(
            logits, jax.random.PRNGKey(i), temperature=1.0, top_k=0, top_p=0.6
        )
        seen.add(int(tok[0]))
    assert seen <= {0, 1} and len(seen) == 2
    # tiny p keeps only the argmax
    for i in range(10):
        tok = _sample(
            logits, jax.random.PRNGKey(i), temperature=1.0, top_k=0, top_p=1e-6
        )
        assert int(tok[0]) == 0


def test_generate_int8_kv_cache_close_to_bf16(rng):
    """int8-quantized KV cache: decode logits stay close to the exact
    cache's (a random-init model's argmax margins sit below the ~1/127
    quantization noise, so token equality is the wrong assertion — logit
    closeness catches real wiring bugs: wrong scales, misplaced writes)."""
    cfg16 = tiny_test(dtype=jnp.float32, remat=False)
    cfg8 = tiny_test(dtype=jnp.float32, remat=False, kv_cache_dtype="int8")
    model16, model8 = GPTLM(cfg16), GPTLM(cfg8)
    prompt = jax.random.randint(rng, (2, 5), 0, cfg16.vocab_size)
    params = model16.init(
        {"params": jax.random.PRNGKey(1)}, prompt, train=False
    )["params"]

    def prefill(model):
        logits, vs = model.apply(
            {"params": params}, prompt, train=False, decode=True,
            mutable=["cache"],
        )
        return logits[:, -1], vs

    pre16, vs16 = prefill(model16)
    pre8, vs8 = prefill(model8)
    # same next token for both (a near-tie argmax would otherwise send the
    # two decodes down different branches and compare unrelated logits)
    nxt = jnp.argmax(pre16, axis=-1).astype(jnp.int32)

    def one_step(model, vs):
        step_logits, _ = model.apply(
            {"params": params, **vs}, nxt[:, None], train=False, decode=True,
            mutable=["cache"],
        )
        return step_logits[:, -1]

    step16 = one_step(model16, vs16)
    step8 = one_step(model8, vs8)
    np.testing.assert_allclose(np.asarray(pre8), np.asarray(pre16), rtol=0.1, atol=0.05)
    np.testing.assert_allclose(np.asarray(step8), np.asarray(step16), rtol=0.1, atol=0.05)


def test_int8_kv_cache_halves_storage(rng):
    """The int8 cache's payload bytes are ~half the bf16 cache's.

    head_dim=64 (the shipped models' width) so the per-(position, head)
    fp32 scale amortizes to 6% — the tiny default head_dim would make the
    overhead look artificially large."""
    cfg = tiny_test(kv_cache_dtype="int8", d_model=256, n_heads=4)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (1, 4), 0, cfg.vocab_size)
    _, variables = model.apply(
        {"params": model.init({"params": jax.random.PRNGKey(0)}, prompt, train=False)["params"]},
        prompt,
        train=False,
        decode=True,
        mutable=["cache"],
    )

    def nbytes(tree):
        return sum(
            x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree)
        )

    total = nbytes(variables["cache"])
    # bf16 equivalent: 2 bytes per K/V element, no scales
    kv_elems = sum(
        x.size
        for path, x in jax.tree_util.tree_leaves_with_path(variables["cache"])
        if x.dtype == jnp.int8
    )
    assert kv_elems > 0
    bf16_total = kv_elems * 2
    assert total < 0.65 * bf16_total, (total, bf16_total)


def test_beam_search_beats_or_matches_greedy(rng):
    """The winning beam's sequence log-prob is >= greedy's by construction."""
    from tpu_parallel.models.generate import generate_beam

    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (2, 4), 0, cfg.vocab_size)
    params = model.init({"params": jax.random.PRNGKey(1)}, prompt, train=False)[
        "params"
    ]

    def seq_logprob(new_tokens):
        toks = jnp.concatenate([prompt, new_tokens], axis=1)
        logits = model.apply({"params": params}, toks, train=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        n = new_tokens.shape[1]
        # token at position prompt+i is predicted from position prompt+i-1
        picked = jnp.take_along_axis(
            logp[:, prompt.shape[1] - 1 : -1], new_tokens[:, :, None], axis=-1
        )[:, :, 0]
        assert picked.shape[1] == n
        return picked.sum(axis=1)

    greedy = generate(model, params, prompt, max_new_tokens=5, temperature=0.0)
    beam, scores = generate_beam(
        model, params, prompt, max_new_tokens=5, num_beams=4
    )
    lp_greedy = seq_logprob(greedy)
    lp_beam = seq_logprob(beam)
    assert (np.asarray(lp_beam) >= np.asarray(lp_greedy) - 1e-5).all()
    # reported scores equal the independently recomputed sequence log-prob
    np.testing.assert_allclose(
        np.asarray(scores), np.asarray(lp_beam), rtol=1e-4, atol=1e-4
    )


def test_beam_search_exact_with_full_beam(rng):
    """At horizon 2 with num_beams = vocab_size, beam search IS exhaustive
    (step 1 keeps every one-token prefix, step 2 scores all V^2 pairs), so
    the result must be the brute-force optimum.  Deeper horizons prune
    intermediate prefixes and carry no optimality guarantee."""
    import itertools

    from tpu_parallel.models.generate import generate_beam

    cfg = tiny_test(
        dtype=jnp.float32, remat=False, vocab_size=6, d_model=16, n_heads=2,
        n_layers=2, seq_len=16,
    )
    model = GPTLM(cfg)
    prompt = jnp.asarray([[1, 2]])
    params = model.init({"params": jax.random.PRNGKey(2)}, prompt, train=False)[
        "params"
    ]
    horizon = 2  # k=V is exhaustive only to depth 2 (see docstring)
    beam, score = generate_beam(
        model, params, prompt, max_new_tokens=horizon, num_beams=6
    )

    def seq_logprob(new_tokens):
        toks = jnp.concatenate([prompt, jnp.asarray([new_tokens])], axis=1)
        logits = model.apply({"params": params}, toks, train=False)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        picked = jnp.take_along_axis(
            logp[:, prompt.shape[1] - 1 : -1],
            jnp.asarray([new_tokens])[:, :, None],
            axis=-1,
        )[0, :, 0]
        return float(picked.sum())

    best = max(
        itertools.product(range(6), repeat=horizon), key=seq_logprob
    )
    assert tuple(np.asarray(beam)[0].tolist()) == best
    np.testing.assert_allclose(float(score[0]), seq_logprob(best), rtol=1e-4)


def test_generate_sharded_pp_matches_full_forward(mesh_2x2x2, rng):
    """Pipeline-parallel decoding: greedy generate_sharded on a 3-D
    pipe x data x model mesh equals the teacher-forced argmax rollout of the
    GPipe training forward under the same mesh — the llama_1b_3d serving
    path (ring decode + cache_valid gating, pp.execute_pipeline_decode)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import generate_sharded
    from tpu_parallel.parallel import pp

    mesh = mesh_2x2x2
    cfg = tiny_test(dtype=jnp.float32, remat=False, pipe_size=2)
    model = GPTLM(cfg)
    prompt = jax.random.randint(rng, (4, 5), 0, cfg.vocab_size)

    def init(r, p):
        return model.init({"params": r}, p, train=False)["params"]

    import flax.linen as nn

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, prompt))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, prompt)

    # ground truth: GPipe training forward; logits are real on the last
    # pipe rank only — mask + psum broadcasts them to every rank
    def full_forward(params, tokens):
        logits = model.apply({"params": params}, tokens, train=False)
        return lax.psum(
            logits * pp.last_stage_mask(cfg.pipe_axis)[None, None], cfg.pipe_axis
        )

    fwd = jax.jit(
        jax.shard_map(
            full_forward, mesh=mesh, in_specs=(specs, P("data")),
            out_specs=P("data"), check_vma=False,
        )
    )
    toks = prompt
    want = []
    for _ in range(6):
        logits = fwd(params, toks)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
        want.append(nxt)
        toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
    want = jnp.stack(want, axis=1)

    got = generate_sharded(
        model, params, prompt, mesh, max_new_tokens=6, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_pp_decode_prefill_logits_match_train_forward(mesh_pipe4_data2, rng):
    """Decode-mode prefill through the 4-stage decode ring produces the same
    logits as the GPipe training forward (per-stage caches must hold exactly
    the real activation's K/V)."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.parallel import pp

    mesh = mesh_pipe4_data2
    cfg = tiny_test(dtype=jnp.float32, remat=False, pipe_size=4)
    model = GPTLM(cfg)
    tokens = jax.random.randint(rng, (4, 8), 0, cfg.vocab_size)

    def init(r, p):
        return model.init({"params": r}, p, train=False)["params"]

    import flax.linen as nn

    probe = jax.shard_map(
        init, mesh=mesh, in_specs=(P(), P("data")), out_specs=P(),
        check_vma=False,
    )
    specs = nn.get_partition_spec(jax.eval_shape(probe, rng, tokens))
    params = jax.jit(
        jax.shard_map(
            init, mesh=mesh, in_specs=(P(), P("data")), out_specs=specs,
            check_vma=False,
        )
    )(rng, tokens)

    def train_fwd(params, tokens):
        logits = model.apply({"params": params}, tokens, train=False)
        return lax.psum(
            logits * pp.last_stage_mask(cfg.pipe_axis)[None, None], cfg.pipe_axis
        )

    def decode_fwd(params, tokens):
        b, t = tokens.shape
        positions = jnp.broadcast_to(jnp.arange(t), (b, t))
        logits, _ = model.apply(
            {"params": params}, tokens, positions=positions, train=False,
            decode=True, mutable=["cache"],
        )
        return logits  # already psum-broadcast by the decode ring

    outs = {}
    for name, fn in (("train", train_fwd), ("decode", decode_fwd)):
        outs[name] = jax.jit(
            jax.shard_map(
                fn, mesh=mesh, in_specs=(specs, P("data")),
                out_specs=P("data"), check_vma=False,
            )
        )(params, tokens)
    np.testing.assert_allclose(
        np.asarray(outs["decode"]), np.asarray(outs["train"]),
        rtol=1e-4, atol=1e-4,
    )


def test_sample_sharded_matches_full_vocab(mesh_data4_model2, rng):
    """Vocab-parallel sampling over the model axis is exact: greedy equals
    argmax; top-k never leaves the global top-k and its empirical
    distribution tracks the renormalized softmax; Gumbel-max temperature
    sampling tracks the full softmax."""
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.models.generate import _sample_sharded
    from tpu_parallel.parallel.tp import split_over_axis

    mesh = mesh_data4_model2
    vocab = 64
    rows = 2048  # rows double as independent draws
    logits = jnp.tile(
        jax.random.normal(rng, (1, vocab)) * 2.0, (rows, 1)
    )

    def run(temperature, top_k, top_p, key):
        def body(full, k_):
            from tpu_parallel.core.rng import fold_rng_over_axis

            # decorrelate the data shards (generate folds over data itself;
            # this harness must too or the 4 shards draw identical tokens
            # and the frequency checks lose 4x their statistical power)
            k_ = fold_rng_over_axis(k_, "data")
            shard = split_over_axis(full, "model", axis=-1)
            return _sample_sharded(shard, k_, temperature, top_k, top_p, "model")

        return jax.jit(
            jax.shard_map(
                body, mesh=mesh, in_specs=(P("data"), P()),
                out_specs=P("data"), check_vma=False,
            )
        )(logits, key)

    # greedy == argmax everywhere
    greedy = run(0.0, 0, 0.0, jax.random.PRNGKey(0))
    np.testing.assert_array_equal(
        np.asarray(greedy), np.asarray(logits.argmax(-1))
    )

    probs = np.asarray(jax.nn.softmax(logits[0].astype(jnp.float32)))
    # temperature=1: noise is drawn per [rows, vs] slice with the key
    # folded over the data axis, so all 2048 rows are independent draws
    temp = np.asarray(run(1.0, 0, 0.0, jax.random.PRNGKey(1)))
    freq = np.bincount(temp, minlength=vocab) / rows
    assert np.abs(freq - probs).max() < 0.05, "temperature sampling off"

    # top-k: support restricted to the global top-k, frequencies track the
    # renormalized distribution
    k = 8
    topk = np.asarray(run(1.0, k, 0.0, jax.random.PRNGKey(2)))
    top_set = set(np.asarray(jax.lax.top_k(logits[0], k)[1]).tolist())
    assert set(topk.tolist()) <= top_set
    pk = probs.copy()
    mask = np.ones(vocab, bool)
    mask[list(top_set)] = False
    pk[mask] = 0.0
    pk = pk / pk.sum()
    freq_k = np.bincount(topk, minlength=vocab) / rows
    assert np.abs(freq_k - pk).max() < 0.05, "top-k sampling off"

    # top-p falls back to the gathered path and still restricts support
    topp = np.asarray(run(1.0, 0, 0.3, jax.random.PRNGKey(3)))
    order = np.argsort(-probs)
    cum = np.cumsum(probs[order])
    nucleus = set(order[: int(np.searchsorted(cum, 0.3) + 1)].tolist())
    assert set(topp.tolist()) <= nucleus


def test_ragged_prompts_match_per_row(rng):
    """Left-padded ragged batch == each row generated alone unpadded: the
    per-slot position table masks pads out of every attention read and each
    row continues from its own length."""
    cfg = tiny_test(dtype=jnp.float32)
    model = GPTLM(cfg)
    lens = [3, 7, 5]
    pad_to = max(lens)
    rows = [
        jax.random.randint(jax.random.fold_in(rng, i), (1, L), 1, cfg.vocab_size)
        for i, L in enumerate(lens)
    ]
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, rows[0], train=False
    )["params"]

    # per-row reference: each prompt alone, no padding
    refs = [
        np.asarray(generate(model, params, r, max_new_tokens=6)) for r in rows
    ]

    # batched: left-pad to the longest
    prompt = jnp.zeros((len(lens), pad_to), jnp.int32)
    mask = jnp.zeros((len(lens), pad_to), bool)
    for i, (r, L) in enumerate(zip(rows, lens)):
        prompt = prompt.at[i, pad_to - L :].set(r[0])
        mask = mask.at[i, pad_to - L :].set(True)
    got = np.asarray(
        generate(model, params, prompt, max_new_tokens=6, prompt_mask=mask)
    )
    for i in range(len(lens)):
        np.testing.assert_array_equal(got[i], refs[i][0], err_msg=f"row {i}")


def test_ragged_prompts_rope_and_window(rng):
    """Ragged batching composes with RoPE positions and sliding-window
    decode (the window compares stored positions, not slot indices)."""
    cfg = tiny_test(
        dtype=jnp.float32, positional="rope", norm="rmsnorm", attn_window=4
    )
    model = GPTLM(cfg)
    lens = [2, 6]
    pad_to = max(lens)
    rows = [
        jax.random.randint(jax.random.fold_in(rng, 9 + i), (1, L), 1, cfg.vocab_size)
        for i, L in enumerate(lens)
    ]
    params = model.init(
        {"params": jax.random.PRNGKey(0)}, rows[0], train=False
    )["params"]
    refs = [
        np.asarray(generate(model, params, r, max_new_tokens=5)) for r in rows
    ]
    prompt = jnp.zeros((2, pad_to), jnp.int32)
    mask = jnp.zeros((2, pad_to), bool)
    for i, (r, L) in enumerate(zip(rows, lens)):
        prompt = prompt.at[i, pad_to - L :].set(r[0])
        mask = mask.at[i, pad_to - L :].set(True)
    got = np.asarray(
        generate(model, params, prompt, max_new_tokens=5, prompt_mask=mask)
    )
    for i in range(2):
        np.testing.assert_array_equal(got[i], refs[i][0], err_msg=f"row {i}")


def test_relative_bias_sharded_generate_aligned(mesh_data8, rng):
    """A relative-bias model decodes on the sharded path without a mask:
    the internal placeholder mask must not trip the ragged refusal."""
    import optax
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.data import lm_batch
    from tpu_parallel.models import make_gpt_loss
    from tpu_parallel.models.generate import generate_sharded
    from tpu_parallel.parallel.spmd import build_train_functions

    cfg = tiny_test(
        dtype=jnp.float32, positional="relative", norm="rmsnorm",
        dense_bias=False,
    )
    batch = lm_batch(jax.random.PRNGKey(0), 8, cfg.seq_len, cfg.vocab_size)
    model = GPTLM(cfg)
    tx = optax.adamw(1e-3)

    def model_init(r, b):
        from tpu_parallel.core.state import TrainState

        v = model.init({"params": r}, b.tokens, train=False)
        return TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=tx, rng=r
        )

    funcs = build_train_functions(
        model_init, make_gpt_loss(cfg), mesh_data8, batch,
        batch_spec=P("data"), donate=False,
    )
    state = funcs.init_fn(rng, batch)
    out = generate_sharded(
        model, state.params, jnp.zeros((8, 4), jnp.int32), mesh_data8,
        max_new_tokens=4,
    )
    assert out.shape == (8, 4)


def test_beam_lazy_matches_eager(rng):
    """The lazy (source-row-table) beam decode is token- and score-exact
    against the eager per-step cache reorder — MHA, GQA, and int8-cache
    variants.  A wrong ancestry table would route some beam to another
    beam's K/V history and diverge within a step or two."""
    from tpu_parallel.models.generate import generate_beam

    variants = [
        dict(),
        dict(n_kv_heads=2),  # grouped queries through beam_decode_attention
        dict(kv_cache_dtype="int8"),
        dict(scan_layers=False),
    ]
    for overrides in variants:
        cfg = tiny_test(dtype=jnp.float32, remat=False, **overrides)
        model = GPTLM(cfg)
        prompt = jax.random.randint(rng, (3, 5), 0, cfg.vocab_size)
        params = model.init(
            {"params": jax.random.PRNGKey(7)}, prompt, train=False
        )["params"]
        lazy_toks, lazy_scores = generate_beam(
            model, params, prompt, max_new_tokens=8, num_beams=4, lazy=True
        )
        eager_toks, eager_scores = generate_beam(
            model, params, prompt, max_new_tokens=8, num_beams=4, lazy=False
        )
        np.testing.assert_array_equal(
            np.asarray(lazy_toks), np.asarray(eager_toks), err_msg=str(overrides)
        )
        np.testing.assert_allclose(
            np.asarray(lazy_scores),
            np.asarray(eager_scores),
            rtol=1e-5,
            atol=1e-5,
            err_msg=str(overrides),
        )
