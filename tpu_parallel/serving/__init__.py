"""Continuous-batching serving: iteration-level scheduling over a slot
pool of KV caches (docs/10_serving_engine.md)."""

from tpu_parallel.serving.cache_pool import CachePool, insert_rows
from tpu_parallel.serving.engine import ServingEngine, sample_tokens
from tpu_parallel.serving.metrics import ServingMetrics, percentile
from tpu_parallel.serving.request import (
    EXPIRED,
    FINISHED,
    QUEUED,
    REJECTED,
    RUNNING,
    Request,
    RequestOutput,
    SamplingParams,
    StreamEvent,
)
from tpu_parallel.serving.scheduler import FIFOScheduler, SchedulerConfig

__all__ = [
    "CachePool",
    "insert_rows",
    "ServingEngine",
    "sample_tokens",
    "ServingMetrics",
    "percentile",
    "Request",
    "RequestOutput",
    "SamplingParams",
    "StreamEvent",
    "QUEUED",
    "RUNNING",
    "FINISHED",
    "REJECTED",
    "EXPIRED",
    "FIFOScheduler",
    "SchedulerConfig",
]
