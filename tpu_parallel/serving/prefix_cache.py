"""LRU prefix cache: skip recomputing shared prompt prefixes entirely.

Production prompt streams are heavily prefix-shared — system prompts,
few-shot headers, templated instructions — and a continuous-batching
engine re-prefills those identical tokens for every request.  Cached K/V
is a pure function of (token ids, positions, params), including the int8
path's per-(position, kv-head) quantization, so a prefix computed once
can be COPIED into a fresh slot (:meth:`CachePool.copy_prefix`) with
bit-identical results; only the prompt remainder runs the model.

Keys are BUCKET-ALIGNED token prefixes (the engine's prefill buckets), so
lookups are O(#buckets) exact-match probes instead of a longest-common-
prefix search: for a prompt of length L the engine probes the largest
bucket B <= L-1 downward and takes the first hit.  (L-1, not L: a full-
prompt hit would leave no remainder token, and the FIRST sampled token
needs the last real token's hidden state — cached K/V alone cannot
produce logits.)

Entries are whole pool rows (seq_len-long K/V per layer) — real HBM — so
the cache is small and LRU-evicted; ``max_entries`` bounds it.  Hit/miss/
eviction counters feed :class:`~tpu_parallel.serving.metrics.ServingMetrics`.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Optional, Sequence, Tuple


class PrefixCache:
    """Exact-match LRU over bucket-aligned token prefixes.

    Keys are token-id tuples (dict hashing gives the "hash-keyed" lookup
    with zero collision risk); values are ``(row_tree, length)`` where
    ``row_tree`` is a batch-1 cache row whose first ``length`` positions
    hold the prefix (the engine trims validity at copy time, so rows are
    stored as extracted — no rewrite on the store path).
    """

    def __init__(self, max_entries: int = 8):
        if max_entries < 1:
            raise ValueError(f"max_entries={max_entries} < 1")
        self.max_entries = max_entries
        self._entries: "OrderedDict[Tuple[int, ...], tuple]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key) -> bool:
        return tuple(key) in self._entries

    def reset_counters(self) -> None:
        """Zero the hit/miss/eviction tallies (entries stay) — benches
        call this after a warm-up phase so measured-window rates are not
        polluted by warm traffic."""
        self.hits = self.misses = self.evictions = 0

    def lookup(self, prompt: Sequence[int], buckets: Sequence[int]):
        """Longest bucket-aligned cached prefix of ``prompt`` STRICTLY
        shorter than the prompt; returns ``(row_tree, length)`` or None.
        One counted hit or miss per call (per admission, not per probe).
        """
        prompt = tuple(int(t) for t in prompt)
        for b in sorted(buckets, reverse=True):
            if b >= len(prompt):
                continue
            entry = self._entries.get(prompt[:b])
            if entry is not None:
                self._entries.move_to_end(prompt[:b])
                self.hits += 1
                return entry
        self.misses += 1
        return None

    def store(self, prompt: Sequence[int], buckets: Sequence[int],
              row_tree) -> list:
        """Store ``row_tree`` (a freshly prefilled slot row for ``prompt``)
        under EVERY bucket-aligned proper-prefix key not already cached —
        a long prompt seeds its short shared header (the system-prompt
        case) and its long few-shot prefix in one pass, all referencing
        the SAME immutable row (copy_prefix trims validity to each key's
        length at hit time, so one stored row serves every aligned
        sub-prefix).  First writer wins per key.  Returns the newly stored
        prefix lengths."""
        prompt = tuple(int(t) for t in prompt)
        stored = []
        for b in sorted(buckets, reverse=True):
            if b >= len(prompt):
                continue
            key = prompt[:b]
            if key in self._entries:
                self._entries.move_to_end(key)
                continue
            self._entries[key] = (row_tree, b)
            stored.append(b)
        while len(self._entries) > self.max_entries:
            self._entries.popitem(last=False)
            self.evictions += 1
        return stored
