"""The fleet's wire layer: an urllib transport speaking to daemon HTTP
servers, and the client-facing fleet HTTP server.

Both halves are deliberately thin.  :class:`HTTPFleetTransport` maps
the :class:`~tpu_parallel.fleet.router.FleetTransport` contract onto
the daemon endpoints (``daemon/http.py``) — an HTTP status code is a
RESPONSE (returned typed), failing to get one is a
:class:`TransportError` (fed to the breaker).  :class:`FleetHTTPServer`
re-serves the daemon's exact client contract (``/v1/submit``,
``/v1/stream``, ``/v1/result``, ``/v1/cancel``, ``/healthz``,
``/statez``, ``/metricsz``) over a :class:`FleetRouter`, so a client
pointed at one daemon can be re-pointed at a whole fleet without
changing a line — the ISSUE's client-contract-unchanged requirement.

Timeouts come from the router's :class:`PeerPolicy` via the caller; the
only timing primitive here is the socket timeout urllib applies, so the
module stays clean under ``scripts/check_clock.py``'s fleet walk.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Iterator, Optional, Tuple

from tpu_parallel.fleet.router import (
    FleetRouter,
    FleetTransport,
    TransportError,
)
from tpu_parallel.obs.tracer import TRACE_HEADER, TraceContext

_MAX_BODY_BYTES = 1 << 20  # same submit cap as the daemon server

__all__ = ["HTTPFleetTransport", "FleetHTTPServer"]


class HTTPFleetTransport(FleetTransport):
    """The production transport: plain urllib against daemon servers.
    Stateless — every call names its peer ``addr`` (``host:port``)."""

    def _request(
        self,
        addr: str,
        method: str,
        path: str,
        timeout: float,
        data: Optional[bytes] = None,
        content_type: str = "application/json",
        binary_response: bool = False,
        trace: Optional[TraceContext] = None,
    ):
        headers = {"Content-Type": content_type} if data else {}
        if trace is not None:
            headers[TRACE_HEADER] = trace.header_value()
        req = urllib.request.Request(
            f"http://{addr}{path}", data=data, method=method,
            headers=headers,
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                code, payload = resp.status, resp.read()
        except urllib.error.HTTPError as exc:
            code, payload = exc.code, exc.read()
        except (urllib.error.URLError, OSError) as exc:
            raise TransportError(addr, f"{method} {path}: {exc}") from None
        if binary_response:
            return code, payload
        try:
            return code, json.loads(payload or b"{}")
        except ValueError:
            raise TransportError(
                addr, f"{method} {path}: non-JSON {code} response"
            ) from None

    def healthz(
        self, addr: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        return self._request(addr, "GET", "/healthz", timeout,
                             trace=trace)

    def submit(
        self, addr: str, body: dict, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        return self._request(
            addr, "POST", "/v1/submit", timeout,
            data=json.dumps(body).encode(), trace=trace,
        )

    def result(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        return self._request(
            addr, "GET", f"/v1/result/{request_id}", timeout,
            trace=trace,
        )

    def cancel(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        return self._request(
            addr, "POST", f"/v1/cancel/{request_id}", timeout,
            data=b"{}", trace=trace,
        )

    def stream(
        self, addr: str, request_id: str, idle_timeout: float,
        trace=None,
    ) -> Iterator[dict]:
        """Attach to the daemon's SSE stream; ``idle_timeout`` is the
        per-read socket timeout — the daemon's keepalive comments (which
        we skip) reset it, so only a genuinely wedged or dead peer trips
        it.  Any tear mid-iteration raises :class:`TransportError`: the
        router's handoff trigger."""
        headers = (
            {TRACE_HEADER: trace.header_value()}
            if trace is not None else {}
        )
        req = urllib.request.Request(
            f"http://{addr}/v1/stream/{request_id}", headers=headers
        )
        try:
            resp = urllib.request.urlopen(req, timeout=idle_timeout)
        except urllib.error.HTTPError as exc:
            exc.read()
            raise TransportError(
                addr, f"stream {request_id}: HTTP {exc.code}"
            ) from None
        except (urllib.error.URLError, OSError) as exc:
            raise TransportError(
                addr, f"stream {request_id}: {exc}"
            ) from None

        def events() -> Iterator[dict]:
            try:
                with resp:
                    for raw in resp:
                        line = raw.strip()
                        if not line.startswith(b"data:"):
                            continue  # keepalive comment / separator
                        try:
                            yield json.loads(line[len(b"data:"):].strip())
                        except ValueError:
                            raise TransportError(
                                addr, "stream: malformed SSE data"
                            ) from None
            except TransportError:
                raise
            except (OSError, ValueError) as exc:
                raise TransportError(
                    addr, f"stream torn: {exc}"
                ) from None

        return events()

    def kv_export(
        self, addr: str, max_blocks: int, timeout: float, trace=None
    ) -> Tuple[int, bytes]:
        return self._request(
            addr, "GET", f"/v1/kv/export?max_blocks={int(max_blocks)}",
            timeout, binary_response=True, trace=trace,
        )

    def kv_export_request(
        self, addr: str, request_id: str, timeout: float, trace=None
    ) -> Tuple[int, bytes]:
        rid = urllib.parse.quote(request_id, safe="")
        return self._request(
            addr, "GET", f"/v1/kv/export?request_id={rid}",
            timeout, binary_response=True, trace=trace,
        )

    def kv_import(
        self, addr: str, blob: bytes, timeout: float, trace=None
    ) -> Tuple[int, dict]:
        return self._request(
            addr, "POST", "/v1/kv/import", timeout, data=blob,
            content_type="application/octet-stream", trace=trace,
        )

    def metricsz(
        self, addr: str, timeout: float, trace=None
    ) -> Tuple[int, str]:
        code, payload = self._request(
            addr, "GET", "/metricsz", timeout, binary_response=True,
            trace=trace,
        )
        return code, payload.decode("utf-8", errors="replace")

    def tracez(
        self, addr: str, trace_id: Optional[str], timeout: float,
        trace=None,
    ) -> Tuple[int, dict]:
        query = (
            f"?trace_id={urllib.parse.quote(trace_id, safe='')}"
            if trace_id else ""
        )
        return self._request(
            addr, "GET", f"/v1/tracez{query}", timeout, trace=trace,
        )


class _FleetHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    router: FleetRouter = None  # bound by FleetHTTPServer
    max_body_bytes = _MAX_BODY_BYTES

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_POST(self):
        r = self.router
        if self.path == "/v1/submit":
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1
            if length < 0 or length > self.max_body_bytes:
                self.close_connection = True
                return self._json(413, {
                    "error": (
                        f"body of {length} bytes exceeds the "
                        f"{self.max_body_bytes}-byte submit limit"
                    ),
                })
            try:
                body = json.loads(self.rfile.read(length) or b"{}")
            except (ValueError, OSError):
                body = None
            if not isinstance(body, dict):
                return self._json(400, {"error": "malformed JSON body"})
            # adopt the client's trace context when it sent a
            # well-formed one; garbage parses to None and the router
            # mints its own
            ctx = TraceContext.parse(self.headers.get(TRACE_HEADER))
            code, record = r.submit(body, trace=ctx)
            return self._json(code, record)
        if self.path.startswith("/v1/cancel/"):
            rid = self.path[len("/v1/cancel/"):]
            code, payload = r.cancel(rid)
            return self._json(code, payload)
        return self._json(404, {"error": f"no route {self.path}"})

    def do_GET(self):
        r = self.router
        if self.path == "/healthz":
            routable = r.peers.routable()
            code = 200 if routable else 503
            return self._json(code, {
                "ok": code == 200,
                "peers": r.peers.states(),
                "ts": r.clock(),
            })
        if self.path == "/statez":
            return self._json(200, r.status())
        if self.path == "/metricsz":
            # the FLEET exposition: router series + peer series under a
            # ``peer`` label + cross-peer sums — one scrape target
            body = r.fleet_metrics_text().encode()
            self.send_response(200)
            self.send_header(
                "Content-Type", "text/plain; version=0.0.4"
            )
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        if self.path.startswith("/v1/tracez"):
            query = urllib.parse.urlparse(self.path).query
            trace_id = (
                urllib.parse.parse_qs(query).get("trace_id", [None])[0]
            )
            return self._json(200, r.trace_payload(trace_id))
        if self.path.startswith("/v1/requestz/"):
            rid = self.path[len("/v1/requestz/"):]
            code, payload = r.request_timeline(rid)
            return self._json(code, payload)
        if self.path.startswith("/v1/result/"):
            rid = self.path[len("/v1/result/"):]
            code, record = r.result(rid)
            return self._json(code, record)
        if self.path.startswith("/v1/stream/"):
            return self._stream(self.path[len("/v1/stream/"):])
        return self._json(404, {"error": f"no route {self.path}"})

    def _stream(self, rid: str) -> None:
        r = self.router
        code, _record = r.result(rid)
        if code != 200:
            return self._json(code, {"error": f"unknown request {rid}"})
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for ev in r.stream(rid):
                self.wfile.write(
                    f"data: {json.dumps(ev)}\n\n".encode()
                )
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client hung up mid-stream: stop generating for it,
            # fleet-wide — same semantics as the single-daemon server
            r.cancel(rid)


class FleetHTTPServer:
    """The fleet's client face: a threading HTTP server over one
    :class:`FleetRouter`, started on a background thread so the
    router's probe pump (``router.run()``) owns the main thread."""

    def __init__(
        self,
        router: FleetRouter,
        host: str = "127.0.0.1",
        port: int = 0,
        max_body_bytes: int = _MAX_BODY_BYTES,
    ):
        if max_body_bytes < 1:
            raise ValueError(f"max_body_bytes={max_body_bytes} < 1")
        handler = type("_BoundFleetHandler", (_FleetHandler,), {
            "router": router,
            "max_body_bytes": max_body_bytes,
        })
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FleetHTTPServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever,
            kwargs={"poll_interval": 0.1}, daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
