"""Fleet tests: the kv_wire codec (bitwise round-trips, typed refusal
of every damage shape, version skew preserved over the wire), the
transport-agnostic FleetRouter on a fake clock + scripted in-memory
daemons (breaker transitions, retry-with-exclusion, bitwise cross-host
handoff, the fleet-wide dedupe ledger, KV warm-start accounting), and
the real-subprocess fleet smoke that ``scripts/check_all.py`` also
runs (router + 2 daemon processes, one SIGKILL, one remote import)."""

import dataclasses
import os
import random
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.daemon import IOFaultPlan, iofaults
from tpu_parallel.fleet import (
    DEAD,
    DEGRADED,
    HEALTHY,
    REJECT_NO_PEER,
    REJECT_ROLE,
    FleetRouter,
    FleetTransport,
    PeerPolicy,
    PeerSet,
    TransportError,
)
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.serving import (
    Request,
    SchedulerConfig,
    ServingEngine,
    block_checksums,
)
from tpu_parallel.serving.kv_hierarchy import (
    MIGRATE_IMPORTED,
    MIGRATE_WEIGHTS_VERSION,
    KVPrefixExport,
)
from tpu_parallel.serving.kv_wire import (
    SEGMENT_OVERHEAD,
    WIRE_HEADER_SCHEMA,
    WIRE_MAGIC,
    WIRE_REASONS,
    WIRE_SEGMENT,
    ChunkReassembler,
    WireFormatError,
    decode_export,
    decode_export_chunks,
    decode_exports,
    encode_export,
    encode_export_chunks,
    encode_exports,
    is_chunk_stream,
    read_export_file,
    write_export_file,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- wire codec --------------------------------------------------------------


def _synthetic_export(dtype, seed=0, n_blocks=2, block_tokens=4):
    """A hand-built export whose checksums are real (computed by the
    same ``block_checksums`` the pool uses), so ``verify=True`` decode
    paths exercise the genuine integrity check."""
    rnd = np.random.default_rng(seed)
    length = n_blocks * block_tokens
    shape_a = (n_blocks, block_tokens, 3)
    shape_b = (n_blocks, 2, block_tokens, 2)
    if np.dtype(dtype).kind in "iu":
        leaves = (
            rnd.integers(-100, 100, shape_a).astype(dtype),
            rnd.integers(-100, 100, shape_b).astype(dtype),
        )
    else:
        leaves = (
            rnd.standard_normal(shape_a).astype(dtype),
            rnd.standard_normal(shape_b).astype(dtype),
        )
    return KVPrefixExport(
        tokens=tuple(int(t) for t in rnd.integers(1, 250, length)),
        length=length,
        block_tokens=block_tokens,
        weights_version="initial",
        meta=(("leaf_a", (block_tokens, 3)), ("leaf_b", (2, block_tokens, 2))),
        leaves=leaves,
        checksums=block_checksums(list(leaves), n_blocks),
    )


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_wire_roundtrip_bitwise(dtype):
    """encode -> decode is the identity: every field equal, every leaf
    byte-identical (dtype included), and re-encoding the decoded export
    reproduces the original frame byte-for-byte (canonical headers)."""
    if dtype == "bfloat16":
        np_dtype = np.dtype(jnp.bfloat16)
    else:
        np_dtype = np.dtype(dtype)
    export = _synthetic_export(np_dtype, seed=3)
    blob = encode_export(export)
    back = decode_export(blob)
    assert back.tokens == export.tokens
    assert back.length == export.length
    assert back.block_tokens == export.block_tokens
    assert back.weights_version == export.weights_version
    assert back.meta == export.meta
    assert back.checksums == export.checksums
    assert len(back.leaves) == len(export.leaves)
    for got, want in zip(back.leaves, export.leaves):
        assert got.dtype == want.dtype
        assert got.shape == want.shape
        assert got.tobytes() == want.tobytes()
    assert encode_export(back) == blob


def test_wire_multi_frame_stream():
    """Concatenated frames (the /v1/kv/export body) decode back to the
    same list, and an empty stream is a valid empty answer."""
    exports = [
        _synthetic_export(np.float32, seed=1),
        _synthetic_export(np.int8, seed=2, n_blocks=3),
    ]
    blob = encode_exports(exports)
    back = decode_exports(blob)
    assert len(back) == 2
    for got, want in zip(back, exports):
        assert got.tokens == want.tokens
        for g, w in zip(got.leaves, want.leaves):
            assert g.tobytes() == w.tobytes()
    assert decode_exports(b"") == []


def test_wire_truncation_refuses_typed():
    """Every prefix truncation refuses with a typed reason — never a
    stray struct/json/numpy exception, never a partial export."""
    blob = encode_export(_synthetic_export(np.float32, seed=4))
    cuts = {0, 2, 4, 7, 11, 40, len(blob) // 2, len(blob) - 1}
    for cut in sorted(cuts):
        with pytest.raises(WireFormatError) as exc:
            decode_export(blob[:cut])
        assert exc.value.reason in WIRE_REASONS
    # trailing garbage after a whole frame is damage too, not data
    with pytest.raises(WireFormatError):
        decode_export(blob + b"\x00")
    # a mid-stream truncation refuses the WHOLE multi-frame body
    stream = encode_exports(
        [_synthetic_export(np.float32, seed=5)] * 2
    )
    with pytest.raises(WireFormatError):
        decode_exports(stream[:-3])


def _tamper_header(blob, mutate):
    """Rewrite a frame's JSON header through ``mutate`` with a VALID
    CRC, so the tampered values reach the schema checks instead of
    tripping ``header_crc`` first."""
    import json
    import struct
    import zlib

    hlen, _hcrc = struct.unpack_from(">II", blob, 4)
    header = json.loads(blob[12:12 + hlen])
    mutate(header)
    hbytes = json.dumps(
        header, sort_keys=True, separators=(",", ":")
    ).encode("utf-8")
    return (
        blob[:4]
        + struct.pack(">II", len(hbytes), zlib.crc32(hbytes) & 0xFFFFFFFF)
        + hbytes
        + blob[12 + hlen:]
    )


def test_wire_negative_dim_refuses_typed():
    """A crafted header claiming a negative leaf dim refuses typed
    (``header_schema``) — a negative element count would otherwise read
    the whole remaining buffer and walk the stream offset BACKWARDS,
    turning ``decode_exports`` into an unbounded loop."""
    blob = encode_export(_synthetic_export(np.float32, seed=8))

    def negate(header):
        header["leaves"][0]["shape"][0] *= -1

    bad = _tamper_header(blob, negate)
    with pytest.raises(WireFormatError) as exc:
        decode_export(bad)
    assert exc.value.reason == WIRE_HEADER_SCHEMA
    # the multi-frame decoder refuses (terminates) on the same damage
    with pytest.raises(WireFormatError):
        decode_exports(bad + blob)

    # an absurdly huge dim must land in "bigger than the buffer", not
    # wrap through fixed-width arithmetic into something plausible
    def huge(header):
        header["leaves"][0]["shape"][0] = 1 << 62

    with pytest.raises(WireFormatError) as exc:
        decode_export(_tamper_header(blob, huge))
    assert exc.value.reason in WIRE_REASONS


def test_wire_bad_magic_typed():
    blob = bytearray(encode_export(_synthetic_export(np.float32)))
    blob[0] ^= 0xFF
    with pytest.raises(WireFormatError) as exc:
        decode_export(bytes(blob))
    assert exc.value.reason == WIRE_MAGIC


def test_wire_single_bit_flips_refuse_typed():
    """Seeded single-bit flips anywhere in the frame — magic, length
    words, header JSON, payload — ALWAYS refuse typed: there is no bit
    whose flip decodes into a silently different export."""
    blob = encode_export(_synthetic_export(np.float32, seed=6))
    rnd = random.Random(1234)
    for _ in range(64):
        bit = rnd.randrange(len(blob) * 8)
        flipped = bytearray(blob)
        flipped[bit // 8] ^= 1 << (bit % 8)
        with pytest.raises(WireFormatError) as exc:
            decode_export(bytes(flipped))
        assert exc.value.reason in WIRE_REASONS, bit


def test_wire_file_roundtrip_and_read_rot():
    """The file helpers ride the iofaults read gate: a clean read is
    bitwise, an armed read-side bit flip surfaces as the same typed
    refusal the wire path gives — never garbage K/V off disk."""
    import tempfile

    export = _synthetic_export(np.float32, seed=7)
    with tempfile.TemporaryDirectory() as tmp:
        path = write_export_file(
            os.path.join(tmp, "kv.wire"), [export]
        )
        back = read_export_file(path)
        assert len(back) == 1
        assert back[0].tokens == export.tokens
        with iofaults.inject(
            IOFaultPlan(flip_read_at=0, flip_read_bit=31337)
        ) as inj:
            with pytest.raises(WireFormatError) as exc:
                read_export_file(path)
            assert exc.value.reason in WIRE_REASONS
            assert inj.injected["bit_flip"] == 1


def test_chunk_roundtrip_and_streaming_drain():
    """The streaming framing: a multi-frame export body split into
    bounded segments reassembles bitwise, whole frames surface EARLY
    (before the terminal arrives — the Mooncake-style overlap), and an
    empty export list still ships as one lone terminal so the receiver
    can tell 'nothing hot' from 'transfer died'."""
    exports = [
        _synthetic_export(np.float32, seed=21),
        _synthetic_export(np.int8, seed=22, n_blocks=3),
    ]
    segments = encode_export_chunks(exports, max_wire_bytes=128)
    assert len(segments) > 3, "body never actually split"
    for seg in segments:
        assert is_chunk_stream(seg)
        assert len(seg) <= 128 + SEGMENT_OVERHEAD
    back = decode_export_chunks(b"".join(segments))
    assert len(back) == 2
    for got, want in zip(back, exports):
        assert got.tokens == want.tokens
        assert got.checksums == want.checksums
        for g, w in zip(got.leaves, want.leaves):
            assert g.tobytes() == w.tobytes()
    # incremental receive: the first frame lands while later segments
    # are still "in flight"
    asm = ChunkReassembler()
    landed = []
    for seg in segments[:-1]:
        asm.feed(seg)
        landed.extend(asm.drain())
    assert landed and not asm.finished
    asm.feed(segments[-1])
    landed.extend(asm.drain())
    asm.close()
    assert len(landed) == 2
    lone = encode_export_chunks([], max_wire_bytes=128)
    assert len(lone) == 1
    assert decode_export_chunks(b"".join(lone)) == []


def test_chunk_damage_matrix_refuses_typed():
    """Every chunk-stream damage shape — lost segment, reordering, a
    flipped payload bit, a corrupted terminal checksum, a missing
    terminal (the mid-transfer death), bytes after the terminal,
    truncated preludes — refuses with the typed ``segment`` reason;
    none of them ever yields a partial decode."""
    segments = encode_export_chunks(
        [_synthetic_export(np.float32, seed=23)], max_wire_bytes=64
    )
    assert len(segments) >= 4
    body = b"".join(segments)

    def refused(buf):
        with pytest.raises(WireFormatError) as exc:
            decode_export_chunks(buf)
        assert exc.value.reason == WIRE_SEGMENT

    refused(b"".join(segments[:1] + segments[2:]))  # lost segment
    refused(b"".join([segments[1], segments[0]] + segments[2:]))
    flipped = bytearray(segments[1])
    flipped[SEGMENT_OVERHEAD] ^= 1  # payload bit
    refused(b"".join([segments[0], bytes(flipped)] + segments[2:]))
    bad_term = bytearray(segments[-1])
    bad_term[-1] ^= 1  # whole-stream CRC in the terminal
    refused(b"".join(segments[:-1] + [bytes(bad_term)]))
    refused(b"".join(segments[:-1]))  # stream ends without terminal
    refused(body + segments[0])  # bytes after the terminal
    refused(body[:-1])  # truncated terminal prelude
    refused(body[:SEGMENT_OVERHEAD - 2])
    # a reassembler poisoned by damage refuses every further feed, and
    # an unterminated incremental stream refuses at close — the death
    # of the sender is never mistaken for a complete transfer
    asm = ChunkReassembler()
    asm.feed(segments[0])
    with pytest.raises(WireFormatError):
        asm.feed(segments[0])  # seq replay
    with pytest.raises(WireFormatError):
        asm.feed(segments[1])  # poisoned
    asm2 = ChunkReassembler()
    for seg in segments[:-1]:
        asm2.feed(seg)
    with pytest.raises(WireFormatError) as exc:
        asm2.close()
    assert exc.value.reason == WIRE_SEGMENT


@pytest.fixture(scope="module")
def env():
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(11)
    prompt = [
        int(t)
        for t in np.asarray(
            jax.random.randint(rng, (17,), 1, cfg.vocab_size)
        )
    ]
    probe = jax.random.randint(rng, (1, 20), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    return cfg, model, params, prompt


def _mk_engine(env):
    cfg, model, params, _prompt = env
    return ServingEngine(
        model, params, n_slots=2, decode_steps_per_tick=1,
        scheduler=SchedulerConfig(max_prefills_per_tick=2),
        kv_block_tokens=4, prefix_cache_size=16, kv_radix_cache=True,
    )


def test_wire_preserves_version_skew_refusal(env):
    """A REAL engine export survives the wire bitwise (import verdict
    ``imported``), and a version-skewed export still refuses typed
    AFTER an encode/decode round trip — the wire carries exactly the
    values the version gate judges."""
    _cfg, _model, _params, prompt = env
    a = _mk_engine(env)
    a.add_request(
        Request(request_id="mid", prompt=prompt, max_new_tokens=10)
    )
    for _ in range(5):
        a.step()
    export = a.export_prefix("mid")
    assert export is not None and export.checksums

    b = _mk_engine(env)
    assert b.import_prefix(
        decode_export(encode_export(export))
    ) == MIGRATE_IMPORTED

    skewed = dataclasses.replace(export, weights_version="v9")
    c = _mk_engine(env)
    assert c.import_prefix(
        decode_export(encode_export(skewed))
    ) == MIGRATE_WEIGHTS_VERSION


# -- the router on a fake clock + scripted daemons ---------------------------


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t
        self.sleeps = []

    def __call__(self) -> float:
        return self.t

    def sleep(self, seconds: float) -> None:
        self.sleeps.append(seconds)
        self.t += seconds


class FakeDaemon:
    """One scripted in-memory daemon.  A submission consumes the next
    script: ``tokens`` is the daemon-local generation; ``die_after=k``
    makes its stream tear (TransportError) after yielding k token
    events, and its record report ``running`` with k tokens."""

    def __init__(self, addr):
        self.addr = addr
        self.alive = True
        self.role = "mixed"
        self.scripts = []
        self.requests = {}
        self.submissions = []
        self.cancels = []
        self.seq = 0
        self.kv_blob = b""
        self.kv_export_code = 200
        self.kv_import_response = (200, {"verdicts": {}})
        self.kv_imports = []
        self.kv_request_exports = []
        self.metrics_text = ""  # served by FakeTransport.metricsz
        self.trace_records = []  # served by FakeTransport.tracez


class FakeTransport(FleetTransport):
    def __init__(self, daemons):
        self.daemons = {d.addr: d for d in daemons}
        self.traces = []  # every TraceContext any call carried

    def _d(self, addr):
        d = self.daemons.get(addr)
        if d is None or not d.alive:
            raise TransportError(addr, "connection refused")
        return d

    def _note_trace(self, trace):
        if trace is not None:
            self.traces.append(trace)

    def healthz(self, addr, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        return 200, {
            "ok": True, "role": d.role,
            "kv": {
                "device_blocks_used": 0, "device_blocks_total": 8,
                "host_blocks_used": 0,
            },
        }

    def submit(self, addr, body, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        if d.role == "decode" and body.get("phase") != "decode":
            # the real daemon's typed role gate: fresh work bounces,
            # phase-marked continuations pass
            return 503, {
                "request_id": "", "status": "rejected",
                "finish_reason": REJECT_ROLE, "tokens": [],
                "detail": "decode-role daemon takes only continuations",
            }
        d.submissions.append(dict(body))
        rid = f"{addr}/r{d.seq}"
        d.seq += 1
        script = d.scripts.pop(0) if d.scripts else {"tokens": []}
        d.requests[rid] = script
        return 200, {"request_id": rid, "status": "queued"}

    def result(self, addr, rid, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        script = d.requests.get(rid)
        if script is None:
            return 404, {"error": f"unknown request {rid}"}
        if script.get("die_after") is not None:
            return 200, {
                "request_id": rid, "status": "running",
                "tokens": script["tokens"][:script["die_after"]],
                "finish_reason": None,
            }
        return 200, {
            "request_id": rid, "status": "finished",
            "tokens": list(script["tokens"]), "finish_reason": "length",
        }

    def cancel(self, addr, rid, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        d.cancels.append(rid)
        return 200, {"cancelled": rid}

    def stream(self, addr, rid, idle_timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        script = d.requests.get(rid)
        if script is None:
            raise TransportError(addr, f"stream {rid}: HTTP 404")

        def events():
            die = script.get("die_after")
            for i, tok in enumerate(script["tokens"]):
                if die is not None and i == die:
                    raise TransportError(addr, "stream torn")
                if not d.alive:
                    raise TransportError(addr, "stream torn: killed")
                yield {"request_id": rid, "token": tok, "index": i}
            if die is not None:
                raise TransportError(addr, "stream torn")
            yield {
                "request_id": rid, "finished": True,
                "status": "finished", "finish_reason": "length",
            }

        return events()

    def kv_export(self, addr, max_blocks, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        return d.kv_export_code, d.kv_blob

    def kv_export_request(self, addr, rid, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        d.kv_request_exports.append(rid)
        return d.kv_export_code, d.kv_blob

    def kv_import(self, addr, blob, timeout, trace=None):
        self._note_trace(trace)
        d = self._d(addr)
        d.kv_imports.append(blob)
        return d.kv_import_response

    def metricsz(self, addr, timeout, trace=None):
        d = self._d(addr)
        return 200, getattr(d, "metrics_text", "")

    def tracez(self, addr, trace_id, timeout, trace=None):
        d = self._d(addr)
        return 200, {"proc": addr, "pid": 0,
                     "records": list(getattr(d, "trace_records", [])),
                     "skipped": {}}


def _fleet(n=2, **router_kw):
    clock = FakeClock()
    daemons = [FakeDaemon(f"h{i}:80") for i in range(n)]
    transport = FakeTransport(daemons)
    kw = dict(
        policy=PeerPolicy(
            probe_interval_seconds=1.0, degraded_after=1, dead_after=2,
            reprobe_backoff_seconds=4.0, reprobe_backoff_max=8.0,
        ),
    )
    kw.update(router_kw)
    router = FleetRouter(
        [d.addr for d in daemons], clock=clock, transport=transport, **kw
    )
    return router, clock, daemons


def _ring_order(router, prompt):
    """The health-blind placement order for ``prompt`` — tests script
    'the first ring choice' without assuming which address hashes
    first."""
    seen = []
    for addr in router._walk(prompt):
        if addr not in seen:
            seen.append(addr)
        if len(seen) == len(router.transport.daemons):
            break
    return [router.transport.daemons[a] for a in seen]


def test_peer_breaker_transitions_and_backoff():
    """HEALTHY -> DEGRADED on the first failure, -> DEAD after
    ``dead_after`` consecutive, backoff-scheduled re-probe, and the
    half-open recovery: a DEAD peer's first success earns DEGRADED,
    the second HEALTHY."""
    clock = FakeClock()
    policy = PeerPolicy(
        probe_interval_seconds=1.0, degraded_after=1, dead_after=3,
        reprobe_backoff_seconds=2.0, reprobe_backoff_factor=2.0,
        reprobe_backoff_max=8.0,
    )
    ps = PeerSet(["a:1"], clock, policy)
    st = ps.get("a:1")
    assert st.state == HEALTHY
    assert ps.note_failure("a:1") == DEGRADED
    assert st.next_probe_at == clock.t  # verify a shaky peer promptly
    assert ps.note_failure("a:1") == DEGRADED
    assert ps.note_failure("a:1") == DEAD
    assert st.deaths == 1
    assert st.next_probe_at == clock.t + 2.0  # first-death backoff
    assert ps.probe_due() == []
    assert ps.routable() == []  # DEAD is never routable
    clock.t += 2.0
    assert ps.probe_due() == ["a:1"]
    # half-open: one success readmits at DEGRADED, not HEALTHY
    assert ps.note_success("a:1") == DEGRADED
    assert ps.routable() == ["a:1"]
    assert ps.note_success("a:1") == HEALTHY
    # one flaky probe later must not jump straight back to DEAD
    assert ps.note_failure("a:1") == DEGRADED
    assert ps.note_success("a:1") == HEALTHY


def test_submit_retries_with_exclusion():
    """The ring's first choice refusing connections costs the CLIENT
    nothing: the submission lands on the next successor, typed 200."""
    router, _clock, _daemons = _fleet()
    prompt = [1, 2, 3, 4, 5]
    first, second = _ring_order(router, prompt)[:2]
    first.alive = False
    second.scripts.append({"tokens": [7]})
    code, rec = router.submit(
        {"prompt": prompt, "max_new_tokens": 1}
    )
    assert code == 200
    assert rec["peer"] == second.addr
    assert len(second.submissions) == 1
    assert not first.submissions
    # the failure fed the breaker
    assert router.peers.get(first.addr).failures >= 1


def test_submit_no_peer_is_typed_503():
    router, _clock, daemons = _fleet()
    for d in daemons:
        d.alive = False
    code, rec = router.submit({"prompt": [1, 2], "max_new_tokens": 4})
    assert code == 503
    assert rec["finish_reason"] == REJECT_NO_PEER
    assert router.registry.counter(
        "fleet_rejects_total", reason=REJECT_NO_PEER
    ).value == 1
    # malformed prompts are the client's problem, not a retry loop
    assert router.submit({"prompt": []})[0] == 400
    assert router.submit({"prompt": "abc"})[0] == 400


def test_stream_handoff_is_bitwise_and_index_stable():
    """The core fleet story: the backing daemon tears its stream after
    3 tokens; the router replays prompt+delivered onto the survivor as
    a forced prefix and the CLIENT sees one uninterrupted stream —
    contiguous indices, the full token sequence, one terminal."""
    router, _clock, _daemons = _fleet()
    prompt = [5, 4, 3, 2, 1]
    first, second = _ring_order(router, prompt)[:2]
    full = [11, 12, 13, 14, 15, 16]
    first.scripts.append({"tokens": full, "die_after": 3})
    second.scripts.append({"tokens": full[3:]})
    code, rec = router.submit(
        {"prompt": prompt, "max_new_tokens": len(full)}
    )
    assert code == 200
    rid = rec["request_id"]
    events = list(router.stream(rid))
    tokens = [e["token"] for e in events if "token" in e]
    indices = [e["index"] for e in events if "token" in e]
    assert tokens == full, "handed-off stream is not bitwise"
    assert indices == list(range(len(full)))
    assert events[-1] == {
        "request_id": rid, "finished": True,
        "status": "finished", "finish_reason": "length",
    }
    # the survivor was asked for EXACTLY the remainder, via a forced
    # prefix and a derived (never client-colliding) dedupe token
    replay = second.submissions[-1]
    assert replay["prompt"] == prompt + full[:3]
    assert replay["max_new_tokens"] == len(full) - 3
    assert replay["dedupe_token"] == (
        f"fleet:{router._instance}:{rid}:h1"
    )
    code, final = router.result(rid)
    assert final["handoffs"] == 1 and final["peer"] == second.addr
    assert router.registry.counter("fleet_handoffs_total").value == 1


def test_handoff_dedupe_tokens_are_globally_scoped():
    """Two routers over the SAME daemons must never derive colliding
    handoff dedupe tokens: router-local request ids restart at f000000
    in every instance, and a daemon's dedupe table outlives any one
    router — a collision answers a new router's handoff with some old
    router's handed-off stream (silent wrong tokens).  Client-supplied
    tokens seed the derivation (unique per logical request); tokenless
    requests are scoped by the router's instance nonce."""
    clock = FakeClock()
    daemons = [FakeDaemon(f"h{i}:80") for i in range(2)]
    prompt = [5, 4, 3, 2, 1]
    full = [11, 12, 13, 14, 15, 16]
    derived = []
    for dedupe in (None, None, "client-tok"):
        transport = FakeTransport(daemons)
        router = FleetRouter(
            [d.addr for d in daemons], clock=clock,
            transport=transport,
        )
        first, second = _ring_order(router, prompt)[:2]
        first.scripts.append({"tokens": full, "die_after": 3})
        second.scripts.append({"tokens": full[3:]})
        body = {"prompt": prompt, "max_new_tokens": len(full)}
        if dedupe:
            body["dedupe_token"] = dedupe
        code, rec = router.submit(body)
        assert code == 200
        tokens = [
            e["token"] for e in router.stream(rec["request_id"])
            if "token" in e
        ]
        assert tokens == full
        derived.append(second.submissions[-1]["dedupe_token"])
    anon_a, anon_b, seeded = derived
    assert anon_a != anon_b, (
        "two router instances derived the same handoff dedupe token"
    )
    assert seeded == "fleet:client-tok:h1"


def test_result_poll_survives_host_death():
    """A client that only polls still cannot lose its request: the
    failed refresh hands off, the next poll reads the survivor."""
    router, _clock, _daemons = _fleet()
    prompt = [9, 8, 7]
    first, second = _ring_order(router, prompt)[:2]
    first.scripts.append({"tokens": [1, 2], "die_after": 0})
    second.scripts.append({"tokens": [1, 2, 3, 4]})
    code, rec = router.submit({"prompt": prompt, "max_new_tokens": 4})
    rid = rec["request_id"]
    first.alive = False
    code, rec = router.result(rid)
    assert code == 200 and rec["peer"] == second.addr
    code, rec = router.result(rid)
    assert rec["status"] == "finished" and rec["tokens"] == [1, 2, 3, 4]


def test_dedupe_ledger_is_fleet_wide():
    router, _clock, _daemons = _fleet()
    body = {
        "prompt": [3, 1, 4], "max_new_tokens": 2,
        "dedupe_token": "client-42",
    }
    code, rec = router.submit(body)
    assert code == 200
    code, again = router.submit(dict(body))
    assert code == 200
    assert again["request_id"] == rec["request_id"]
    assert router.registry.counter("fleet_dedupe_hits_total").value == 1
    # one daemon submission total: the retry never re-entered the ring
    total = sum(
        len(d.submissions) for d in router.transport.daemons.values()
    )
    assert total == 1


def test_probe_tick_kills_hands_off_and_recovers():
    """The pump path end to end: probes demote a silent peer to DEAD
    (handing its open request off), the backoff gates re-probes, and
    the recovered peer gets its stale daemon request cancelled plus a
    KV warm start from the survivor."""
    router, clock, _daemons = _fleet(
        warm_start_blocks=8, warm_on_recovery=True
    )
    prompt = [2, 7, 1, 8]
    first, second = _ring_order(router, prompt)[:2]
    first.scripts.append({"tokens": [5, 5, 5], "die_after": 1})
    second.scripts.append({"tokens": [5, 5, 5]})
    second.kv_blob = b"hot-chains"
    first.kv_import_response = (200, {"verdicts": {"imported": 2}})
    code, rec = router.submit({"prompt": prompt, "max_new_tokens": 3})
    rid = rec["request_id"]
    stale_daemon_rid = router._requests[rid].daemon_rid

    first.alive = False
    clock.t += 1.0  # the submit's success pushed its next probe out
    router.probe_tick()  # failure 1 -> DEGRADED (re-probe immediately)
    assert router.peers.get(first.addr).state == DEGRADED
    router.probe_tick()  # failure 2 -> DEAD: hand off its open request
    assert router.peers.get(first.addr).state == DEAD
    assert router.registry.counter("fleet_peer_deaths_total").value == 1
    assert router._requests[rid].addr == second.addr
    assert router.registry.gauge(
        "fleet_peer_state", peer=first.addr
    ).value == 2.0

    first.alive = True
    router.probe_tick()  # backoff not elapsed: DEAD stays untouched
    assert router.peers.get(first.addr).state == DEAD
    clock.t += 4.0  # past reprobe_backoff_seconds
    router.probe_tick()  # half-open: answers -> DEGRADED + reconcile
    state = router.peers.get(first.addr)
    # half-open, never a straight DEAD->HEALTHY jump — the successful
    # warm-start import inside the same tick then completes recovery
    assert "dead->degraded" in state.transitions
    assert state.state == HEALTHY
    # the revived journal's copy was cancelled (compute hygiene) …
    assert stale_daemon_rid in first.cancels
    # … and the recovery warm-started it from the survivor's chains
    assert first.kv_imports == [b"hot-chains"]
    assert router.registry.counter(
        "fleet_kv_imports_total", status="imported"
    ).value == 2
    assert router.registry.counter(
        "fleet_kv_export_bytes_total"
    ).value == len(b"hot-chains")


def test_warm_start_counts_wire_refusals():
    """A refused import (the peer's typed 400) lands in the refusal
    counter under the wire reason — the fleet can SEE corruption."""
    router, _clock, daemons = _fleet()
    donor, newcomer = daemons[0], daemons[1]
    donor.kv_blob = b"\x00" * 32
    newcomer.kv_import_response = (400, {"reason": "integrity"})
    router.warm_start(newcomer.addr, donor=donor.addr)
    assert newcomer.kv_imports, "blob never shipped"
    assert router.registry.counter(
        "fleet_kv_wire_refusals_total", reason="integrity"
    ).value == 1
    assert router.registry.counter(
        "fleet_kv_imports_total", status="imported"
    ).value == 0


def test_kv_export_refusal_is_typed_not_breaker_evidence():
    """A live donor answering ``/v1/kv/export`` with an HTTP error is a
    RESPONSE: counted as a typed wire refusal, never breaker failure
    credit — repeated warm-start attempts must not demote a responsive
    peer toward DEAD."""
    router, _clock, daemons = _fleet()
    donor, newcomer = daemons[0], daemons[1]
    donor.kv_export_code = 503
    donor.kv_blob = b"never-shipped"
    assert router.warm_start(newcomer.addr, donor=donor.addr) == {}
    assert router.peers.get(donor.addr).failures == 0
    assert router.peers.get(donor.addr).state == HEALTHY
    assert router.registry.counter(
        "fleet_kv_wire_refusals_total", reason="export_http_503"
    ).value == 1
    assert not newcomer.kv_imports


def test_terminal_requests_evicted_after_ttl():
    """Fleet-level retention (the daemon side has journal compaction):
    terminal requests and their dedupe-ledger entries are TTL-evicted
    by the probe pump, so a long-running router does not leak every
    request it ever served."""
    router, clock, _daemons = _fleet(terminal_ttl_seconds=10.0)
    prompt = [1, 2, 3]
    first = _ring_order(router, prompt)[0]
    first.scripts.append({"tokens": [4]})
    code, rec = router.submit({
        "prompt": prompt, "max_new_tokens": 1, "dedupe_token": "c-1",
    })
    assert code == 200
    rid = rec["request_id"]
    router.result(rid)  # folds the scripted finished record: terminal
    assert router._requests[rid].terminal
    clock.t += 5.0
    router.probe_tick()
    assert rid in router._requests  # within TTL: late polls still work
    clock.t += 10.0
    router.probe_tick()
    assert rid not in router._requests
    assert "c-1" not in router._ledger
    assert router.result(rid)[0] == 404
    assert router.registry.counter("fleet_evictions_total").value == 1


def test_cancel_is_terminal_and_best_effort():
    router, _clock, _daemons = _fleet()
    code, rec = router.submit({"prompt": [6, 6], "max_new_tokens": 8})
    rid = rec["request_id"]
    code, _payload = router.cancel(rid)
    assert code == 200
    code, rec = router.result(rid)
    assert rec["status"] == "cancelled"
    assert router.cancel(rid)[0] == 404  # already terminal


# -- prefill/decode disaggregation on the fakes ------------------------------


def _disagg_fleet(roles, **router_kw):
    """A fleet whose peers carry explicit roles, both in the router's
    config AND in the fake daemons' own behavior (role gate, healthz
    advertising) — ``roles`` is a tuple aligned with peer order."""
    clock = FakeClock()
    daemons = [FakeDaemon(f"h{i}:80") for i in range(len(roles))]
    for d, role in zip(daemons, roles):
        d.role = role
    transport = FakeTransport(daemons)
    router = FleetRouter(
        [d.addr for d in daemons], clock=clock, transport=transport,
        policy=PeerPolicy(
            probe_interval_seconds=1.0, degraded_after=1, dead_after=2,
            reprobe_backoff_seconds=4.0, reprobe_backoff_max=8.0,
        ),
        roles={d.addr: role for d, role in zip(daemons, roles)},
        **router_kw,
    )
    return router, clock, daemons


def test_disagg_placement_only_prefill_capable():
    """Under a disaggregated topology fresh submissions land only on
    prefill-capable peers, whatever the ring order says — decode-role
    peers never even see (so never 503) fresh work."""
    router, _clock, daemons = _disagg_fleet(("decode", "prefill"))
    decode_d, prefill_d = daemons
    assert router.status()["disagg"] is True
    for seed in range(6):  # prompts hashing all over the ring
        prefill_d.scripts.append({"tokens": [1]})
        code, rec = router.submit({
            "prompt": [seed + 1, seed + 2, seed + 3],
            "max_new_tokens": 1,
        })
        assert code == 200
        assert rec["peer"] == prefill_d.addr
    assert not decode_d.submissions


def test_role_rejection_is_typed_not_breaker_evidence():
    """A daemon that answers fresh work with its typed role 503 (config
    drift the router has not yet probed) is a RESPONSE: the reject is
    counted under the role reason, the ring successor takes the
    request, and the breaker records ZERO failure evidence."""
    router, _clock, _daemons = _disagg_fleet(("mixed", "mixed"))
    prompt = [4, 4, 4]
    first, second = _ring_order(router, prompt)[:2]
    first.role = "decode"  # drifted; router still believes "mixed"
    second.scripts.append({"tokens": [7]})
    code, rec = router.submit({"prompt": prompt, "max_new_tokens": 1})
    assert code == 200
    assert rec["peer"] == second.addr
    assert not first.submissions
    assert router.peers.get(first.addr).failures == 0
    assert router.peers.get(first.addr).state == HEALTHY
    assert router.registry.counter(
        "fleet_rejects_total", reason=REJECT_ROLE
    ).value == 1


def test_probe_tick_learns_advertised_roles():
    """Probes fold each peer's advertised role into the routing table
    (disaggregation becomes a topology fact, not static config), and an
    explicit ``set_role`` pins the peer against re-advertising."""
    router, clock, daemons = _fleet()
    assert router.status()["disagg"] is False
    daemons[0].role = "prefill"
    daemons[1].role = "decode"
    clock.t += 1.0
    router.probe_tick()
    assert router.status()["roles"] == {
        daemons[0].addr: "prefill", daemons[1].addr: "decode",
    }
    assert router.status()["disagg"] is True
    assert router.registry.gauge(
        "fleet_role", peer=daemons[1].addr
    ).value == 2.0
    assert router.set_role(daemons[0].addr, "mixed")
    daemons[0].role = "prefill"  # still advertises prefill …
    clock.t += 1.0
    router.probe_tick()
    # … but the operator override is pinned
    assert router.status()["roles"][daemons[0].addr] == "mixed"


def test_disagg_handoff_is_bitwise_and_index_stable():
    """The tentpole end to end on the fakes: the prompt prefills on the
    prefill-role peer; at first-token time its KV blocks travel as a
    bounded chunk stream into the decode peer, and the phase-marked
    forced-prefix continuation produces the SAME token sequence — the
    client's stream never blinks, the indices never reset, and the
    prefill copy is actively reaped."""
    router, _clock, daemons = _disagg_fleet(
        ("prefill", "decode"), disagg_max_wire_bytes=128,
    )
    prefill_d, decode_d = daemons
    full = [21, 22, 23, 24, 25]
    prefill_d.scripts.append({"tokens": full})
    prefill_d.kv_blob = encode_exports(
        [_synthetic_export(np.float32, seed=31)]
    )
    decode_d.scripts.append({"tokens": full[1:]})
    decode_d.kv_import_response = (200, {"verdicts": {"imported": 2}})
    code, rec = router.submit(
        {"prompt": [9, 9, 9], "max_new_tokens": len(full)}
    )
    assert code == 200 and rec["peer"] == prefill_d.addr
    rid = rec["request_id"]
    src_rid = router._requests[rid].daemon_rid
    events = list(router.stream(rid))
    tokens = [e["token"] for e in events if "token" in e]
    indices = [e["index"] for e in events if "token" in e]
    assert tokens == full, "disaggregated stream is not bitwise"
    assert indices == list(range(len(full)))
    assert events[-1]["finished"] and events[-1]["status"] == "finished"
    # the KV travelled chunked and reassembles to the donor's bytes
    assert prefill_d.kv_request_exports == [src_rid]
    assert len(decode_d.kv_imports) == 1
    wire = decode_d.kv_imports[0]
    assert is_chunk_stream(wire)
    assert len(decode_export_chunks(wire)) == 1
    # the continuation: phase-marked, exact remainder, derived dedupe
    cont = decode_d.submissions[-1]
    assert cont["phase"] == "decode"
    assert cont["prompt"] == [9, 9, 9] + full[:1]
    assert cont["max_new_tokens"] == len(full) - 1
    assert cont["dedupe_token"] == (
        f"fleet:{router._instance}:{rid}:h1"
    )
    assert src_rid in prefill_d.cancels
    assert router.registry.counter(
        "fleet_handoff_disagg_total"
    ).value == 1
    assert router.registry.counter(
        "fleet_handoff_bytes_total"
    ).value == len(prefill_d.kv_blob)
    assert router.registry.counter(
        "fleet_kv_imports_total", status="imported"
    ).value == 2
    _code, final = router.result(rid)
    assert final["handoffs"] == 1 and final["peer"] == decode_d.addr


def test_disagg_fallback_decode_peer_death_mid_transfer():
    """The decode peer dying mid-transfer costs the client NOTHING: the
    import tear is breaker evidence plus a typed fallback, and the
    stream completes colocated, bitwise, with zero handoffs."""
    router, _clock, daemons = _disagg_fleet(("prefill", "decode"))
    prefill_d, decode_d = daemons
    full = [31, 32, 33]
    prefill_d.scripts.append({"tokens": full})
    prefill_d.kv_blob = b"prefix-blocks"
    code, rec = router.submit({"prompt": [5, 5], "max_new_tokens": 3})
    assert code == 200
    decode_d.alive = False  # dies before the transfer lands
    events = list(router.stream(rec["request_id"]))
    assert [e["token"] for e in events if "token" in e] == full
    assert events[-1]["finished"]
    assert router.registry.counter(
        "fleet_handoff_fallbacks_total", reason="decode_peer_dead"
    ).value == 1
    assert router.registry.counter(
        "fleet_handoff_disagg_total"
    ).value == 0
    assert router.peers.get(decode_d.addr).failures >= 1
    _code, final = router.result(rec["request_id"])
    assert final["status"] == "finished" and final["handoffs"] == 0


def test_disagg_fallback_version_skew_never_recomputes():
    """Typed import verdicts that land nothing (weights_version skew)
    mean a decode-side continuation would silently re-prefill the
    prompt — the router refuses the move under the verdict's own name
    and keeps decoding where the KV actually lives."""
    router, _clock, daemons = _disagg_fleet(("prefill", "decode"))
    prefill_d, decode_d = daemons
    full = [41, 42]
    prefill_d.scripts.append({"tokens": full})
    prefill_d.kv_blob = b"skewed-blocks"
    decode_d.kv_import_response = (
        200, {"verdicts": {"weights_version": 2}}
    )
    code, rec = router.submit({"prompt": [7, 7, 7], "max_new_tokens": 2})
    events = list(router.stream(rec["request_id"]))
    assert [e["token"] for e in events if "token" in e] == full
    assert router.registry.counter(
        "fleet_handoff_fallbacks_total", reason="weights_version"
    ).value == 1
    assert router.registry.counter(
        "fleet_kv_imports_total", status="weights_version"
    ).value == 2
    assert not decode_d.submissions, "continuation shipped anyway"


def test_disagg_fallback_no_decode_peer():
    """With the only decode-role peer DEAD there is no migration target
    — a typed ``no_decode_peer`` fallback, and the stream completes on
    the prefill peer untouched."""
    router, _clock, daemons = _disagg_fleet(("prefill", "decode"))
    prefill_d, decode_d = daemons
    prefill_d.scripts.append({"tokens": [51]})
    prefill_d.kv_blob = b"blocks"
    router.peers.note_failure(decode_d.addr)
    assert router.peers.note_failure(decode_d.addr) == DEAD
    code, rec = router.submit({"prompt": [3, 3], "max_new_tokens": 1})
    assert code == 200
    events = list(router.stream(rec["request_id"]))
    assert [e["token"] for e in events if "token" in e] == [51]
    assert router.registry.counter(
        "fleet_handoff_fallbacks_total", reason="no_decode_peer"
    ).value == 1
    assert not decode_d.kv_imports


# -- the real thing: subprocess smoke + soak ---------------------------------


def test_fleet_smoke_subprocess():
    """The check_fleet gate inline: router + 2 daemon subprocesses on
    loopback ports, one seeded SIGKILL mid-stream (bitwise handoff to
    the survivor), one victim restart with a remote KV warm start, one
    corrupt-import typed refusal, graceful SIGTERM exits."""
    scripts = os.path.join(REPO_ROOT, "scripts")
    sys.path.insert(0, scripts)
    try:
        import check_fleet
    finally:
        sys.path.pop(0)
    problems = check_fleet.check_paths()
    assert problems == []


@pytest.mark.slow
def test_fleet_soak_three_seeds(tmp_path):
    """The acceptance soak: 3 seeded trials of router + 3 daemons under
    a seeded SIGKILL each — zero lost accepted requests, zero duplicate
    completions, bitwise handoffs, >= 1 remote import per trial."""
    record = tmp_path / "FLEET_soak.json"
    proc = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO_ROOT, "scripts", "fleet_bench.py"),
            "--soak", "7", "--trials", "3", "--requests", "4",
            "--record", str(record),
        ],
        capture_output=True, text=True, timeout=3600,
    )
    assert proc.returncode == 0, proc.stderr[-4000:]
    assert record.exists()
