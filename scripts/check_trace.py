"""Static check: every FleetTransport call site states its trace.

Distributed tracing (docs/11_observability.md) only stitches a request
into ONE cross-process timeline if every wire crossing either forwards
a :class:`~tpu_parallel.obs.tracer.TraceContext` or deliberately
declines to.  A transport call that simply OMITS the ``trace`` kwarg is
the silent third option — the crossing happens, the receiving daemon
records orphan spans under no trace id, and the stitched timeline
quietly loses a leg.  That regression does not fail a unit test (the
request still serves), so it gets a gate instead: under
``tpu_parallel/fleet/``, every call to a transport method must pass the
``trace`` keyword explicitly — ``trace=ctx.fork()`` on a traced
crossing, ``trace=None`` where the crossing is intentionally untraced
(probes, warm-start, reconcile).

- Flagged: ``<anything>.transport.<method>(...)`` or
  ``transport.<method>(...)`` for any method in the
  :class:`FleetTransport` contract, without a ``trace=`` keyword.
- Exempt: any call whose source line span carries a
  ``# no-trace: <why>`` annotation — the escape hatch, same shape as
  ``check_io``'s ``# raw-io:``.

Registered in ``scripts/check_all.py`` and self-tested in
``tests/test_checkers.py``.  Usage: ``python scripts/check_trace.py
[paths...]`` — prints one violation per line, exits nonzero on any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

DEFAULT_PATHS = ("tpu_parallel/fleet",)

WHITELIST_MARK = "# no-trace:"

# the FleetTransport contract (fleet/router.py) — keep in sync when the
# contract grows a method; the self-test in tests/test_checkers.py
# cross-checks this set against the ABC
TRANSPORT_METHODS = frozenset({
    "healthz",
    "submit",
    "result",
    "cancel",
    "stream",
    "kv_export",
    "kv_export_request",
    "kv_import",
    "metricsz",
    "tracez",
})


def _is_transport_call(node: ast.Call) -> bool:
    """``self.transport.<m>(...)``, ``router.transport.<m>(...)`` or a
    bare ``transport.<m>(...)`` for a contract method ``<m>``."""
    func = node.func
    if not isinstance(func, ast.Attribute):
        return False
    if func.attr not in TRANSPORT_METHODS:
        return False
    recv = func.value
    if isinstance(recv, ast.Attribute) and recv.attr == "transport":
        return True
    if isinstance(recv, ast.Name) and recv.id == "transport":
        return True
    return False


def check_source(source: str, filename: str) -> List[str]:
    """Return ``file:line: message`` strings for every transport call
    in ``source`` that neither passes ``trace=`` nor carries the
    ``# no-trace: <why>`` annotation on its line span."""
    tree = ast.parse(source, filename=filename)
    lines = source.splitlines()
    problems: List[str] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not _is_transport_call(node):
            continue
        if any(kw.arg == "trace" for kw in node.keywords):
            continue
        span = lines[node.lineno - 1 : (node.end_lineno or node.lineno)]
        if any(WHITELIST_MARK in line for line in span):
            continue
        problems.append(
            f"{filename}:{node.lineno}: transport."
            f"{node.func.attr}() without an explicit trace= kwarg "
            "(pass trace=ctx.fork() on a traced crossing, trace=None "
            "for a deliberately untraced one, or annotate "
            "'# no-trace: <why>')"
        )
    return problems


def check_paths(paths=DEFAULT_PATHS) -> List[str]:
    problems: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            # a typo'd path must not walk zero files and report OK
            raise FileNotFoundError(f"check_trace: no such path: {path}")
        if os.path.isfile(path):
            files = [path]
        else:
            files = sorted(
                os.path.join(root, f)
                for root, _, names in os.walk(path)
                for f in names
                if f.endswith(".py")
            )
        for fname in files:
            with open(fname) as fh:
                problems.extend(check_source(fh.read(), fname))
    return problems


def main(argv: List[str]) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo_root)
    paths = argv[1:] or list(DEFAULT_PATHS)
    problems = check_paths(paths)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"check_trace: {len(problems)} untraced transport call(s)",
            file=sys.stderr,
        )
        return 1
    print("check_trace: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
