"""Autoregressive generation with a KV cache, fully jitted.

No reference capability exists (the reference is training-only tutorial
scripts — SURVEY.md §0); this provides the inference path users expect of a
framework.  The decode loop is a ``lax.scan`` over single-token steps: each
step appends K/V to the per-layer ``cache`` collection
(:class:`~tpu_parallel.models.layers.Attention` decode mode) and attends
against the cached prefix only — O(seq) per generated token instead of the
O(seq^2) of re-running the full forward.

Works for MHA and GQA, learned and RoPE positions, scan and unrolled layer
stacks.  Mesh serving goes through :func:`generate_sharded`: TP shards the
cache over heads exactly as activations; pipeline meshes decode via the
ring pass in :func:`tpu_parallel.parallel.pp.execute_pipeline_decode`
(per-stage KV caches, writes gated to the owning tick).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.models.gpt import GPTLM
from tpu_parallel.parallel.tp import export_single_device_params  # noqa: F401  (re-export: mesh-trained state -> generate-able params)


def _sample(
    logits: jax.Array, rng: jax.Array, temperature: float, top_k: int,
    top_p: float = 0.0,
):
    """One token per row from [batch, vocab] logits.

    ``top_k`` keeps the k highest logits; ``top_p`` in (0, 1) keeps the
    smallest prefix of the sorted distribution whose mass reaches p
    (nucleus sampling; the argmax token always survives).  Both filters
    compose (intersection) and apply after the temperature scale.
    """
    # models emit cfg.dtype (bf16) logits; sample in fp32 so the temperature
    # scale and the categorical's gumbel trick don't round at bf16
    logits = logits.astype(jnp.float32)
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if 0.0 < top_p < 1.0:
        desc = jnp.sort(logits, axis=-1)[:, ::-1]
        cum = jnp.cumsum(jax.nn.softmax(desc, axis=-1), axis=-1)
        # keep tokens whose mass BEFORE them is < p (so top-1 always stays)
        keep = cum - jax.nn.softmax(desc, axis=-1) < top_p
        cutoff = jnp.min(
            jnp.where(keep, desc, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)


def _sample_sharded(
    logits: jax.Array, rng: jax.Array, temperature: float, top_k: int,
    top_p: float, axis_name: str,
):
    """One token per row from vocab-SHARDED [batch, vocab/tp] logits, no
    full-vocab gather.

    - greedy: the two-collective global-argmax trick
      (:func:`~tpu_parallel.core.losses.vocab_parallel_argmax`).
    - temperature: Gumbel-max — each shard perturbs its slice with its own
      Gumbel noise (rng folded over the model axis) and the global argmax
      of ``logits/T + G`` is an exact softmax sample.
    - top_k: each shard's local top-k is a superset contributor to the
      global top-k; all_gather the ``tp * k`` candidates (tiny) and finish
      there.
    - top_p: needs the full sorted distribution — gathers the row
      (one [batch, vocab] all_gather per step, still far below the old
      every-step full-logits gather at [batch, seq, vocab] prefill).

    Every rank returns the SAME token (all decisions go through
    collectives), which TP decoding requires.
    """
    from tpu_parallel.core.losses import vocab_parallel_argmax
    from tpu_parallel.core.rng import fold_rng_over_axis

    if 0.0 < top_p < 1.0:
        full = lax.all_gather(logits, axis_name, axis=-1, tiled=True)
        # identical rng on every rank -> identical sample
        return _sample(full, rng, temperature, top_k, top_p)
    lf = logits.astype(jnp.float32)
    if temperature == 0.0:
        return vocab_parallel_argmax(lf, axis_name)
    lf = lf / temperature
    vs = lf.shape[-1]
    offset = lax.axis_index(axis_name) * vs
    if top_k > 0:
        k = min(top_k, vs)
        vals, idx = jax.lax.top_k(lf, k)  # [b, k] local
        cand_vals = lax.all_gather(vals, axis_name, axis=-1, tiled=True)
        cand_ids = lax.all_gather(
            idx.astype(jnp.int32) + offset, axis_name, axis=-1, tiled=True
        )
        # global top-k lives inside the tp*k candidates; mask the rest and
        # sample among candidates (identical rng/result on every rank)
        kth = jnp.sort(cand_vals, axis=-1)[:, -top_k][:, None]
        masked = jnp.where(cand_vals < kth, -jnp.inf, cand_vals)
        choice = jax.random.categorical(rng, masked, axis=-1)
        return jnp.take_along_axis(cand_ids, choice[:, None], axis=1)[:, 0]
    # pure temperature: Gumbel-max over the shards
    g = jax.random.gumbel(fold_rng_over_axis(rng, axis_name), lf.shape)
    return vocab_parallel_argmax(lf + g, axis_name)


def decode_step(
    model: GPTLM,
    params,
    cache,
    tok: jax.Array,
    positions: jax.Array,
    write_index: Optional[jax.Array] = None,
    block_table: Optional[jax.Array] = None,
):
    """One single-token decode tick — THE reusable core of every decode loop.

    ``tok``/``positions``: [batch] current tokens and their global positions.
    Returns ``(hidden [batch, 1, d_model], new_cache)``.  Shared by the
    :func:`_generate_core` scan body (aligned batches, ``write_index=None``)
    and the continuous-batching engine (``tpu_parallel.serving.engine``,
    which passes per-row ``write_index`` so each slot's K/V lands at its own
    cache depth — on its per-step tick, as the scan body of its FUSED
    multi-step tick, and as the decode phase of its UNIFIED ragged tick
    right after a :func:`prefill_extend_step` chunk phase in the same
    dispatch; sharing this one core is what makes every tick family's
    greedy output bitwise identical by construction).
    """
    hidden, updated = model.apply(
        {"params": params, "cache": cache},
        tok[:, None],
        positions=positions[:, None],
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
        write_index=write_index,
        block_table=block_table,
    )
    return hidden, updated["cache"]


def padded_prefill_inputs(lengths, width: int):
    """RIGHT-padded prefill positions for prompts of ``lengths`` in a
    ``width``-wide bucket: real tokens get 0..len-1, pad slots -1.

    The pad contract mirrors the ragged decode layout everywhere: -1
    positions are never attended (``decode_attention`` masks ``kp >= 0``),
    their nn.Embed/RoPE lookups are harmless garbage, and the cache slots
    they occupy carry position -1 until real tokens (the request's decode
    steps) overwrite them — so bucket padding costs ZERO cache capacity.
    Returns ``(positions [b, width] int32, last_idx [b] int32)`` where
    ``last_idx`` is each row's final REAL token index (the hidden state the
    lm_head must read — right padding means it is NOT row -1).
    """
    lengths = jnp.asarray(lengths, jnp.int32)
    iota = jnp.arange(width, dtype=jnp.int32)[None, :]
    positions = jnp.where(iota < lengths[:, None], iota, -1)
    return positions, lengths - 1


def prefill_step(model: GPTLM, params, tokens: jax.Array,
                 positions: jax.Array):
    """Fresh-cache prefill over ``tokens`` [b, P] at explicit ``positions``
    [b, P] — THE pad-aware prefill core of the serving engine's fast path.

    With ``positions`` from :func:`padded_prefill_inputs`, a batch of
    different-length prompts padded to one bucket width prefills as ONE
    call compiled per BUCKET shape, not per distinct length: pad slots
    write position -1 into the per-slot cache table and are never
    attended, so every real token's K/V (including int8-quantized caches —
    quantization is per (position, kv-head), invisible to batch
    composition) is bit-identical to an exact-length prefill.  Returns
    ``(hidden [b, P, d_model], cache)``.
    """
    hidden, variables = model.apply(
        {"params": params},
        tokens,
        positions=positions,
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
    )
    return hidden, variables["cache"]


def prefill_extend_step(model: GPTLM, params, cache, tokens: jax.Array,
                        positions: jax.Array, write_start: jax.Array,
                        block_table: Optional[jax.Array] = None):
    """Continue a prefill INTO an existing cache: ``tokens`` [b, T] at
    global ``positions`` [b, T] (pads -1), K/V written at cache slots
    ``write_start + [0..T)`` per row (the multi-token ``write_index`` path
    in ``models/layers.py``).

    The chunked-prefill core: a long prompt splits into budget-sized
    chunks that interleave with the engine's decode ticks — each chunk
    attends the already-cached prefix plus itself causally, which is
    mathematically identical to one monolithic prefill (scores depend only
    on stored positions).  Also the prefix-cache completion core: after
    ``CachePool.copy_prefix`` lands a cached prefix, the prompt remainder
    runs through here at ``write_start = prefix_len``.  Returns
    ``(hidden [b, T, d_model], cache)``.

    RAGGED MULTI-PHASE batches (the engine's unified tick): ``b`` is the
    whole slot pool and only SOME rows are prefilling — non-prefill rows
    ride as all-pad (every position -1) with ``write_start`` parked at
    ``seq_len``, so their writes drop whole-row and their outputs are
    never read.  Per-row ``write_start`` plus per-row pad raggedness is
    exactly the bucketed-prefill discipline, so mixing phases in one
    call changes no row's math (row-parallel ops — the same argument
    that makes batch composition invisible everywhere else).
    """
    hidden, updated = model.apply(
        {"params": params, "cache": cache},
        tokens,
        positions=positions,
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
        write_index=write_start,
        block_table=block_table,
    )
    return hidden, updated["cache"]


def verify_step(model: GPTLM, params, cache, tokens: jax.Array,
                positions: jax.Array, write_index: jax.Array,
                block_table: Optional[jax.Array] = None):
    """Score T tokens per row in ONE forward — the speculative-decoding
    verify core.  ``tokens`` [b, T] is each row's current token followed by
    its draft tokens, at global ``positions`` [b, T] (pads -1); K/V land at
    cache slots ``write_index + [0..T)`` per row (the same multi-token
    ``write_index`` scatter chunked prefill uses).

    Exactness: each token's attention reads the post-write cache and masks
    by STORED positions, so position ``p + i`` attends the prefix plus the
    drafts before it — token-for-token identical to ``i`` sequential
    :func:`decode_step` calls (the chunked-prefill argument: scores depend
    only on stored positions, and every op is row/position-parallel).  The
    returned ``hidden`` [b, T, d_model] therefore yields EXACT next-token
    distributions at every draft offset in one pass.

    Rejected drafts need NO cache rollback: their K/V sit at columns
    beyond the accepted frontier, and in the engine's aligned layout
    (column == stored position, :meth:`CachePool.assert_slot_aligned`)
    every stale column holds a position strictly greater than any query
    position that can occur before the column is overwritten — the mask
    ``kp <= qp`` keeps them invisible.  Pad offsets (positions -1) write
    -1 into the position table, invalidating their columns outright.
    """
    hidden, updated = model.apply(
        {"params": params, "cache": cache},
        tokens,
        positions=positions,
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
        write_index=write_index,
        block_table=block_table,
    )
    return hidden, updated["cache"]


def _generate_core(
    model: GPTLM,
    params,
    prompt: jax.Array,
    rng: jax.Array,
    max_new_tokens: int,
    temperature: float,
    top_k: int,
    top_p: float = 0.0,
    prompt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """The traceable prefill + decode-scan body shared by :func:`generate`
    (jit, one device) and :func:`generate_sharded` (shard_map, any mesh).

    The lm_head applies only to the LAST position's hidden state (the only
    logits sampling reads — full-prompt prefill logits were pure waste),
    column-sharded under TP: sampling then runs vocab-parallel
    (:func:`_sample_sharded`) and the per-step full-vocab all_gather
    disappears for greedy/temperature/top-k decoding.

    ``prompt_mask`` [b, P] enables RAGGED batches: rows LEFT-padded (False
    at the left, so the last slot is each row's final real token — the one
    the head reads).  Pad slots write position -1 into the per-slot cache
    position table and are never attended; each row continues from its own
    length.  None = all rows full length (the aligned fast path).
    """
    from tpu_parallel.models.gpt import _lm_head_params, _make_lm_head
    from tpu_parallel.parallel.tp import axis_size_or_none

    cfg = model.config
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds seq_len ({cfg.seq_len})"
        )
    if prompt_mask is not None and cfg.positional == "relative":
        raise NotImplementedError(
            "ragged prompts with relative position bias (the shared bias "
            "table assumes row-uniform query positions)"
        )
    # unwrapped head + one up-front FSDP gather: the wrapped head would
    # re-all_gather the vocab kernel every decode step inside the scan
    head = _make_lm_head(cfg, name=None, gather=False, fsdp_wrap=False)
    lm_params = _lm_head_params(cfg, params)

    def next_token(h, rng):
        # h: [b, t, d] hidden states; head only the final position
        logits = head.apply({"params": lm_params}, h[:, -1:])[:, 0]
        if axis_size_or_none(cfg.model_axis) is not None:
            return _sample_sharded(
                logits, rng, temperature, top_k, top_p, cfg.model_axis
            )
        return _sample(logits, rng, temperature, top_k, top_p)

    # Prefill: one batched forward over the prompt creates and fills the
    # cache ('cache' is created on the fly because it is marked mutable).
    if prompt_mask is None:
        positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
        lengths = jnp.full((b,), prompt_len, jnp.int32)
    else:
        m = prompt_mask.astype(jnp.int32)
        if m.shape != prompt.shape:
            raise ValueError(
                f"prompt_mask shape {m.shape} != prompt shape {prompt.shape}"
            )
        # real tokens get 0..len-1; pads get -1 (never attended; their
        # nn.Embed lookup clamps harmlessly — the outputs are unread)
        positions = jnp.cumsum(m, axis=1) - 1
        positions = jnp.where(m > 0, positions, -1)
        lengths = m.sum(axis=1).astype(jnp.int32)
    hidden, variables = model.apply(
        {"params": params},
        prompt,
        positions=positions,
        train=False,
        decode=True,
        hidden_only=True,
        mutable=["cache"],
    )
    rng, sub = jax.random.split(rng)
    first = next_token(hidden, sub)

    def step(carry, _):
        cache, tok, pos, rng = carry
        hidden, cache = decode_step(model, params, cache, tok, pos)
        rng, sub = jax.random.split(rng)
        nxt = next_token(hidden, sub)
        return (cache, nxt, pos + 1, rng), tok

    init = (variables["cache"], first, lengths, rng)
    (_, last, _, _), toks = lax.scan(step, init, None, length=max_new_tokens - 1)
    # scan emits the *input* token of each step; append the final sample
    return jnp.concatenate([toks.T, last[:, None]], axis=1)


@functools.partial(
    jax.jit, static_argnums=(0,),
    static_argnames=("max_new_tokens", "temperature", "top_k", "top_p"),
)
def generate(
    model: GPTLM,
    params,
    prompt: jax.Array,
    rng: Optional[jax.Array] = None,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    prompt_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations of ``prompt`` [batch, P].

    Returns [batch, max_new_tokens] of sampled tokens (greedy when
    ``temperature == 0``).  The prompt must fit the model's ``seq_len``
    together with the new tokens (the cache is allocated at ``seq_len``).
    ``prompt_mask`` serves RAGGED batches — rows LEFT-padded to a common
    length, each continuing from its own last real token (see
    :func:`_generate_core`).  Single-device params layout — for
    mesh-sharded states use :func:`generate_sharded` (or
    ``export_single_device_params`` when the weights aren't split over
    tp/pipe).
    """
    if rng is None:
        rng = jax.random.PRNGKey(0)
    return _generate_core(
        model, params, prompt, rng, max_new_tokens, temperature, top_k, top_p,
        prompt_mask=prompt_mask,
    )


def generate_sharded(
    model: GPTLM,
    params,
    prompt: jax.Array,
    mesh,
    rng: Optional[jax.Array] = None,
    *,
    max_new_tokens: int = 32,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 0.0,
    prompt_mask: Optional[jax.Array] = None,
    param_specs=None,
    batch_spec=None,
) -> jax.Array:
    """Generate under a mesh: TP-split weights stay split, batch shards DP.
    ``prompt_mask`` serves ragged (left-padded) batches, sharded like the
    prompt rows.

    The serving path for states whose weights live on multiple devices
    (``export_single_device_params`` refuses tp/pipe degree > 1 by design).
    Runs the same prefill + decode scan inside one ``shard_map``: the KV
    cache shards over heads exactly as activations do, TP collectives run
    per decode step, each data shard generates its rows, and pipe meshes
    run each forward as a ring pass over the stages (interleaved-schedule
    models excepted — the model raises).

    ``params`` is the (possibly ``nn.Partitioned``-boxed) params tree from a
    mesh init/training state; ``param_specs`` defaults to its partition
    spec.  Sampling RNG folds over the data axis so shards draw independent
    noise; it must NOT fold over the model axis (TP ranks must sample the
    same token).
    """
    import flax.linen as nn
    from jax.sharding import PartitionSpec as P

    if param_specs is None:
        param_specs = nn.get_partition_spec(params)
    if batch_spec is None:
        batch_spec = P(model.config.data_axis)
    if rng is None:
        rng = jax.random.PRNGKey(0)
    # the shard_map arity is fixed, so a placeholder all-ones mask always
    # rides along; has_mask keeps the no-mask call IDENTICAL to the aligned
    # path inside the core (an all-ones mask is semantically aligned, but
    # must not trip the ragged-vs-relative refusal)
    has_mask = prompt_mask is not None
    if prompt_mask is None:
        prompt_mask = jnp.ones(prompt.shape, jnp.bool_)
    fn = _sharded_generate_fn(
        model,
        mesh,
        _HashableTree.of(param_specs),
        batch_spec,
        max_new_tokens,
        temperature,
        top_k,
        top_p,
        has_mask,
    )
    return fn(params, prompt, prompt_mask, rng)


class _HashableTree:
    """Hashable wrapper for a pytree of hashable leaves (PartitionSpecs) —
    lets the compiled sharded-generate closures live in an lru_cache, so a
    serving loop pays trace + XLA compile once, not per call."""

    __slots__ = ("treedef", "leaves")

    def __init__(self, treedef, leaves):
        self.treedef = treedef
        self.leaves = leaves

    @classmethod
    def of(cls, tree):
        leaves, treedef = jax.tree_util.tree_flatten(tree)
        return cls(treedef, tuple(leaves))

    def tree(self):
        return jax.tree_util.tree_unflatten(self.treedef, list(self.leaves))

    def __hash__(self):
        return hash((self.treedef, self.leaves))

    def __eq__(self, other):
        return (
            isinstance(other, _HashableTree)
            and self.treedef == other.treedef
            and self.leaves == other.leaves
        )


def build_sharded_serving(
    model, mesh, param_specs, batch_specs, out_spec, core, fold_axes=None,
):
    """The one shard_map serving harness, shared by every family.

    ``core(model, params, *batch_args, rng)`` is the traceable decode body
    (:func:`_generate_core`, seq2seq's ``_seq2seq_core``, ...).  The harness
    contributes the invariants both paths must share: sampling RNG folds
    over the DATA axis only (TP ranks must draw the same sample), and
    ``check_vma=False`` — sampled tokens are replicated over the model and
    pipe axes by construction (every TP rank's decision flows through the
    vocab-parallel collectives in :func:`_sample_sharded` — or an
    identical-rng gathered sample on the top_p path; the decode ring
    psum-broadcasts over pipe), which the checker cannot prove.

    ``fold_axes`` overrides the RNG fold: the default ``None`` folds over
    the data axis (batch rows are data-sharded, shards must draw
    independent noise); the serving engine passes ``()`` — its slot arrays
    ride REPLICATED over the data axis, so every rank must draw the SAME
    noise or the replicated outputs silently diverge across ranks.
    """
    from jax.sharding import PartitionSpec as P

    from tpu_parallel.core.rng import fold_rng_over_axis

    if fold_axes is None:
        fold_axes = (model.config.data_axis,)

    def body(params, *args):
        *batch_args, rng = args
        if fold_axes:
            rng = fold_rng_over_axis(rng, tuple(fold_axes))
        return core(model, params, *batch_args, rng)

    return jax.jit(
        jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(param_specs, *batch_specs, P()),
            out_specs=out_spec,
            check_vma=False,
        )
    )


@functools.lru_cache(maxsize=32)
def _sharded_generate_fn(
    model, mesh, specs: _HashableTree, batch_spec, max_new_tokens, temperature,
    top_k, top_p=0.0, has_mask=False,
):
    def core(model_, params, prompt, prompt_mask, rng):
        return _generate_core(
            model_, params, prompt, rng, max_new_tokens, temperature, top_k,
            top_p, prompt_mask=prompt_mask if has_mask else None,
        )

    return build_sharded_serving(
        model, mesh, specs.tree(), (batch_spec, batch_spec), batch_spec, core
    )


# --- beam search --------------------------------------------------------------


def beam_cache_batch_axis(path, x):
    """Batch axis of a KV-cache leaf, by name — ONE registry for every
    family's beam search (a new cache leaf added here reorders correctly
    in both).  K/V payloads (self and cross) carry batch at ndim-4; the
    per-slot position table and the cross padding mask at ndim-2; scalar
    counters return None (pass through)."""
    name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
    if name.startswith(
        ("cached_key", "cached_value", "cross_key", "cross_value")
    ):
        return x.ndim - 4
    if name.startswith(("cached_pos", "cross_mask")):
        return x.ndim - 2
    return None


def beam_expand_cache(cache, k):
    """Replicate every batch row ``k`` ways (beam j of row i = row i*k+j)."""

    def expand(path, x):
        ax = beam_cache_batch_axis(path, x)
        return x if ax is None else jnp.repeat(x, k, axis=ax)

    return jax.tree_util.tree_map_with_path(expand, cache)


def beam_seed_src(cache, num_beams):
    """Insert an identity ``beam_src`` table beside every self-attention
    cache (lazy beam search): ``beam_src[row, slot]`` names the row whose
    cache physically holds that slot of this row's beam history.  Identity
    is correct post-prefill — every beam of a prompt holds identical
    replicated prefill slots.  Seeding happens HERE (not lazily inside the
    layer) so the decode scan's carry structure is fixed from step one."""

    def walk(d):
        if not isinstance(d, dict):
            return d
        out = {key: walk(val) for key, val in d.items()}
        if "cached_key" in d:
            ck = d["cached_key"]
            batch_ax = ck.ndim - 4  # stacked layer dims (nn.scan) lead
            rows, cache_len = ck.shape[batch_ax], ck.shape[batch_ax + 1]
            ident = jnp.arange(rows, dtype=jnp.int32)[:, None] + jnp.zeros(
                (rows, cache_len), jnp.int32
            )
            out["beam_src"] = jnp.broadcast_to(
                ident, (*ck.shape[:batch_ax], rows, cache_len)
            ) + jnp.zeros((), jnp.int32)
        return out

    return walk(cache)


def beam_advance_src(cache, row_idx):
    """Lazy-beam step update: row-gather every ``beam_src`` table by the
    winning beams' parent rows (``new[r'] = old[parent(r')]``).  The K/V
    payloads are NOT touched — that is the point: the eager alternative
    (:func:`beam_reorder_cache`) moves every layer's full cache every step.
    The slot written this step already maps to the writing row (the layer
    maintains that invariant), so the gather alone keeps the table exact."""

    def advance(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name == "beam_src":
            return jnp.take(x, row_idx, axis=x.ndim - 2)
        return x

    return jax.tree_util.tree_map_with_path(advance, cache)


def beam_reorder_cache(cache, row_idx, skip_prefixes=()):
    """Gather cache rows to follow their winning beams.  ``skip_prefixes``
    names beam-INVARIANT leaves (e.g. the cross-attention memory caches,
    identical across a row's beams by construction) whose per-step gather
    would be a provable no-op — skipping saves the HBM traffic."""

    def reorder(path, x):
        name = path[-1].key if hasattr(path[-1], "key") else str(path[-1])
        if name.startswith(tuple(skip_prefixes)):
            return x
        ax = beam_cache_batch_axis(path, x)
        return x if ax is None else jnp.take(x, row_idx, axis=ax)

    return jax.tree_util.tree_map_with_path(reorder, cache)


def beam_backtrack(first, toks, src_beams, scores):
    """Follow each row's best final beam back through the per-step
    (token, source-beam) records; returns [batch, T] token ids."""
    def backtrack(carry, xs):
        beam = carry
        step_toks, step_src = xs
        tok_here = jnp.take_along_axis(step_toks, beam[:, None], axis=1)[:, 0]
        beam = jnp.take_along_axis(step_src, beam[:, None], axis=1)[:, 0]
        return beam, tok_here

    best = jnp.argmax(scores, axis=-1)
    beam0, rev_toks = lax.scan(backtrack, best, (toks[::-1], src_beams[::-1]))
    first_tok = jnp.take_along_axis(first, beam0[:, None], axis=1)[:, 0]
    return jnp.concatenate([first_tok[:, None], rev_toks[::-1].T], axis=1)


@functools.partial(
    jax.jit, static_argnums=(0,),
    static_argnames=("max_new_tokens", "num_beams", "length_penalty", "lazy"),
)
def generate_beam(
    model: GPTLM,
    params,
    prompt: jax.Array,
    *,
    max_new_tokens: int = 32,
    num_beams: int = 4,
    length_penalty: float = 0.0,
    lazy: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Beam-search decoding: the highest-scoring continuation per prompt row.

    Returns ``(tokens [batch, max_new_tokens], scores [batch])`` where
    ``scores`` is the winning beam's total log-probability divided by
    ``len**length_penalty`` (0 = pure log-prob, 1 = per-token mean).

    Beams ride as extra batch rows through the same prefill + decode scan
    as :func:`generate`; each step takes the top ``num_beams`` of the
    ``num_beams * vocab`` joint continuations per prompt.  ``lazy=True``
    (default) follows beam ancestry through per-slot source-row tables and
    the cross-beam decode attention
    (:func:`~tpu_parallel.models.layers.beam_decode_attention`) — the KV
    cache is never re-gathered; ``lazy=False`` is the eager form that
    physically reorders every layer's cache rows each step (same tokens,
    ~2x the per-step HBM traffic — kept as the reference implementation).
    No early-termination/EOS handling — fixed-length decoding, the same
    contract as :func:`generate`.
    """
    import dataclasses

    cfg = model.config
    b, prompt_len = prompt.shape
    if prompt_len + max_new_tokens > cfg.seq_len:
        raise ValueError(
            f"prompt ({prompt_len}) + max_new_tokens ({max_new_tokens}) "
            f"exceeds seq_len ({cfg.seq_len})"
        )
    k = num_beams
    vocab = cfg.vocab_size

    # prefill ONCE per prompt row, then replicate the cache k ways (beam j
    # of prompt i is row i*k + j) — beams are identical until the first
    # expansion, so prefilling b*k rows would waste (k-1)/k of the FLOPs.
    # Prefill always runs the plain (beam_width=0) model: rows are still
    # un-expanded prompt rows.
    plain = (
        model
        if cfg.beam_width == 0
        else type(model)(dataclasses.replace(cfg, beam_width=0))
    )
    positions = jnp.broadcast_to(jnp.arange(prompt_len), (b, prompt_len))
    logits, variables = plain.apply(
        {"params": params},
        prompt,
        positions=positions,
        train=False,
        decode=True,
        mutable=["cache"],
    )

    cache0 = beam_expand_cache(variables["cache"], k)
    first_logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))  # [b, V]
    scores, first = jax.lax.top_k(first_logp, k)  # [b, k] each
    tok = first.reshape(b * k).astype(jnp.int32)

    if lazy:
        stepper = type(model)(dataclasses.replace(cfg, beam_width=k))
        cache0 = beam_seed_src(cache0, k)
    else:
        stepper = plain

    def step(carry, _):
        cache, tok, scores, pos = carry
        logits, updated = stepper.apply(
            {"params": params, "cache": cache},
            tok[:, None],
            positions=jnp.full((b * k, 1), pos, jnp.int32),
            train=False,
            decode=True,
            mutable=["cache"],
        )
        logp = jax.nn.log_softmax(logits[:, -1].astype(jnp.float32))
        # joint scores over (beam, next-token) per prompt row
        joint = scores[:, :, None] + logp.reshape(b, k, vocab)  # [b, k, V]
        new_scores, flat_idx = jax.lax.top_k(joint.reshape(b, k * vocab), k)
        src_beam = flat_idx // vocab  # [b, k] originating beam per winner
        next_tok = (flat_idx % vocab).astype(jnp.int32)
        row_idx = (src_beam + jnp.arange(b)[:, None] * k).reshape(b * k)
        if lazy:
            # follow ancestry in the tiny int32 tables only
            cache = beam_advance_src(updated["cache"], row_idx)
        else:
            # reorder cache rows to follow winning beams (shared helper: K/V
            # payloads + the position table; scalar counters pass through)
            cache = beam_reorder_cache(updated["cache"], row_idx)
        return (
            (cache, next_tok.reshape(b * k), new_scores, pos + 1),
            (next_tok, src_beam),
        )

    init = (cache0, tok, scores, jnp.int32(prompt_len))
    (cache, tok, scores, _), (toks, src_beams) = lax.scan(
        step, init, None, length=max_new_tokens - 1
    )

    # backtrack: follow each final beam to its token at every step
    # (toks/src_beams: [T-1, b, k]; the first token table is `first` [b, k])
    out = beam_backtrack(first, toks, src_beams, scores)
    best_scores = jnp.max(scores, axis=-1)
    if length_penalty:
        total_len = jnp.float32(max_new_tokens)
        best_scores = best_scores / (total_len**length_penalty)
    return out.astype(jnp.int32), best_scores
