from tpu_parallel.checkpoint.io import (
    Checkpointer,
    WeightManifest,
    WeightsCorrupt,
    abstract_state_of,
    latest_weights_step,
    load_serving_weights,
    params_fingerprint,
    save_serving_weights,
)

__all__ = [
    "Checkpointer",
    "WeightManifest",
    "WeightsCorrupt",
    "abstract_state_of",
    "latest_weights_step",
    "load_serving_weights",
    "params_fingerprint",
    "save_serving_weights",
]
