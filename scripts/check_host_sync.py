"""Static check: serving code never syncs the device inside a host loop,
and never syncs inside a ``launch`` body (the overlap-killing pattern).

The serving engine's whole perf story is dispatch amortization — one
device round-trip per TICK (the fused decode tick pays one per
``decode_steps_per_tick`` tokens).  A ``np.asarray(...)`` /
``.block_until_ready()`` / ``jax.device_get(...)`` call INSIDE a ``for``
or ``while`` loop under ``tpu_parallel/serving/`` is the tell-tale of a
per-slot (or per-item) device sync: each iteration stalls the host on
the device pipeline, and the DECODE_r06 measurement says that tax is
worth 14x at batch 1.  Tick-BOUNDARY syncs — one per engine tick, before
the host unpacks a token block — are the intended pattern and sit
outside loops by construction; a loop that genuinely needs one (e.g. the
standalone speculative host loop, which syncs once per verify tick)
annotates the line with ``# host-sync: <why>`` and is whitelisted.

The LAUNCH rule: the engine's double-buffered tick splits into
``launch()`` (dispatch, no sync) and ``collect()`` (one sync +
delivery), so tick N's host bookkeeping can overlap tick N+1's device
compute.  ONE sync anywhere on the launch side serializes the pipeline
— the host stalls before the next tick is even dispatched and the
overlap ratio silently collapses to zero.  So any device-sync call
lexically inside a function named ``launch`` or ``_launch*`` under
``tpu_parallel/serving/`` flags, loop or no loop (same ``# host-sync:``
whitelist for a justified exception).

Like ``check_clock.py`` (the injectable-clock contract) this turns a
prose rule into a tier-1 test
(``tests/test_cluster.py::test_serving_no_per_slot_host_sync`` and the
``check_all`` registry).  The check is LEXICAL: it sees calls written
inside loop/launch bodies, not syncs reached through function calls —
the gated debug fetch in ``CachePool.assert_slot_aligned`` (called per
slot under ``spec_check_invariants=True``) is out of scope by design.

Usage: ``python scripts/check_host_sync.py [paths...]`` — prints one
``file:line: <call> syncs the device ...`` per violation, exits nonzero
on any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

# device-sync reads: np/numpy.asarray + np/numpy.array materialize a jax
# array on the host; .block_until_ready() and jax.device_get() are
# explicit fences
SYNC_ATTRS = frozenset({"asarray", "array"})
SYNC_MODULES = frozenset({"np", "numpy"})
FENCE_ATTRS = frozenset({"block_until_ready", "device_get"})

DEFAULT_PATHS = ("tpu_parallel/serving", "tpu_parallel/fleet")

WHITELIST_MARK = "# host-sync:"


def _flag_of(node: ast.Call) -> str | None:
    func = node.func
    if isinstance(func, ast.Attribute):
        if (
            func.attr in SYNC_ATTRS
            and isinstance(func.value, ast.Name)
            and func.value.id in SYNC_MODULES
        ):
            return f"{func.value.id}.{func.attr}"
        if func.attr in FENCE_ATTRS:
            return f"<...>.{func.attr}"
    return None


def _is_launch_name(name: str) -> bool:
    """Function names the launch rule covers: the engine's public
    ``launch`` and its ``_launch_*`` dispatch helpers."""
    return name == "launch" or name.startswith("_launch")


def check_source(source: str, filename: str) -> List[str]:
    """Return ``file:line: message`` strings for every device-sync call
    lexically inside a ``for``/``while`` body or a comprehension's
    per-iteration positions, OR anywhere inside a ``launch``/``_launch*``
    function body (the launch/collect overlap contract), minus lines
    carrying the ``# host-sync: <why>`` whitelist annotation."""
    tree = ast.parse(source, filename=filename)
    lines = source.splitlines()
    problems: List[str] = []

    def flag(node: ast.Call, in_launch: bool) -> None:
        flagged = _flag_of(node)
        if flagged is None:
            return
        # the annotation may land on any physical line of a wrapped call
        # (black puts the closing paren — and the trailing comment — on
        # its own line), so scan the call's whole lineno..end_lineno span
        span = lines[node.lineno - 1 : (node.end_lineno or node.lineno)]
        if any(WHITELIST_MARK in line for line in span):
            return
        if in_launch:
            problems.append(
                f"{filename}:{node.lineno}: {flagged}() syncs the "
                "device inside a launch body (the overlap-killing "
                "pattern — launch dispatches, collect syncs; move it "
                "to the collect side, or annotate "
                "'# host-sync: <why>')"
            )
        else:
            problems.append(
                f"{filename}:{node.lineno}: {flagged}() syncs the "
                "device inside a host loop (per-slot sync — hoist "
                "to the tick boundary, or annotate "
                "'# host-sync: <why>')"
            )

    def walk(node: ast.AST, in_loop: bool, in_launch: bool) -> None:
        if isinstance(node, ast.Call) and (in_loop or in_launch):
            flag(node, in_launch)
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
        ):
            # comprehensions are loops too: the element expression, the
            # `if` clauses, and every generator after the first run PER
            # ITERATION; only the FIRST generator's iterable evaluates
            # once (so `np.asarray(x)` as the thing being iterated stays
            # legal while `[np.asarray(f(s)) for s in slots]` flags)
            walk(node.generators[0].iter, in_loop, in_launch)
            for i, gen in enumerate(node.generators):
                if i > 0:
                    walk(gen.iter, True, in_launch)
                walk(gen.target, True, in_launch)
                for cond in gen.ifs:
                    walk(cond, True, in_launch)
            if isinstance(node, ast.DictComp):
                walk(node.key, True, in_launch)
                walk(node.value, True, in_launch)
            else:
                walk(node.elt, True, in_launch)
            return
        enter_loop = in_loop or isinstance(node, (ast.For, ast.While))
        enter_launch = in_launch
        # a nested function DEF inside a loop body is not executed per
        # iteration at its definition site's cost — but calls inside it
        # are only flagged if ITS body contains a loop of its own, so
        # reset the loop context at function boundaries.  The launch
        # context instead TURNS ON at a launch-named def and stays on
        # for nested defs/lambdas (they run on the launch side too).
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            enter_loop = False
            if not isinstance(node, ast.Lambda) and _is_launch_name(
                node.name
            ):
                enter_launch = True
        for child in ast.iter_child_nodes(node):
            walk(child, enter_loop, enter_launch)

    walk(tree, False, False)
    return problems


def check_paths(paths=DEFAULT_PATHS) -> List[str]:
    problems: List[str] = []
    for path in paths:
        if not os.path.exists(path):
            # a typo'd path must not walk zero files and report OK
            raise FileNotFoundError(f"check_host_sync: no such path: {path}")
        if os.path.isfile(path):
            files = [path]
        else:
            files = sorted(
                os.path.join(root, f)
                for root, _, names in os.walk(path)
                for f in names
                if f.endswith(".py")
            )
        for fname in files:
            with open(fname) as fh:
                problems.extend(check_source(fh.read(), fname))
    return problems


def main(argv: List[str]) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo_root)
    paths = argv[1:] or list(DEFAULT_PATHS)
    problems = check_paths(paths)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"check_host_sync: {len(problems)} per-slot device sync(s)",
            file=sys.stderr,
        )
        return 1
    print("check_host_sync: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
