"""Causal flash attention as Pallas TPU kernels (fwd + bwd), with custom VJP.

No reference capability exists (the reference has no attention at all —
SURVEY.md §5 long-context row); this kernel serves the transformer configs and
the ≥40% MFU target: O(seq) memory instead of O(seq^2), fp32 online softmax,
bf16 MXU matmuls, block sizes aligned to the 128-lane MXU.

Layout convention: [batch, heads, seq, head_dim] inside the kernels (the
public API accepts [batch, seq, heads, head_dim] and transposes).  The causal
structure is exploited twice: key blocks beyond the query block are skipped
(not masked — skipped), and the backward kernels iterate only the triangle
they need.

Grouped-query attention is native: K/V may carry ``n_kv < n_heads`` heads and
are NEVER expanded — the BlockSpec index maps route each query head to its
K/V head's blocks, so GQA pays 1/group of MHA's K/V HBM traffic (the whole
point of GQA; a pre-kernel ``jnp.repeat`` would materialize full-MHA K/V
because Pallas operands are real buffers, not fusible broadcasts).

Two kernel variants share the masking/band geometry:

- **resident** (seq <= ``STREAM_SEQ_THRESHOLD``): one (batch, head) row's
  whole K/V lives in VMEM; the K loop runs inside the kernel and skips
  out-of-band blocks entirely.  This is the measured-fastest path at the
  bench config (512x512 tiles, seq 1024 — SWEEP_r03.json).
- **streamed** (longer seq): the K/V walk is a grid dimension; VMEM holds one
  [block_k, d] tile plus fp32 online-softmax scratch carried across grid
  steps, so residency is O(block) and seq 8k-32k fits v5e VMEM.  Out-of-band
  grid steps clamp their index map to the previous block — Pallas skips the
  DMA when the mapped block is unchanged — so causal still halves the
  traffic, not just the FLOPs.

Packed sequences: ``segment_ids`` [batch, seq] adds a same-segment condition
to the causal mask in all kernels (each query can always see itself, so no
row is ever fully masked).

Falls back to the jnp reference implementation off-TPU (CPU tests run the
kernels in interpret mode explicitly).
"""

from __future__ import annotations

import functools
import warnings
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU too (used for interpret-mode tests)
    from jax.experimental.pallas import tpu as pltpu

    _HAS_PLTPU = True
except ImportError:  # pragma: no cover
    _HAS_PLTPU = False

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
# above this K/V length the streamed kernels take over (resident K/V at
# 4096 x 64 x bf16 is ~0.5MB/operand — comfortable; 16k+ overflows v5e VMEM
# once pipelining double-buffers the operands)
STREAM_SEQ_THRESHOLD = 4096
NEG_INF = -1e30


def reference_attention(
    q: jax.Array, k: jax.Array, v: jax.Array, segment_ids: Optional[jax.Array] = None
) -> jax.Array:
    """jnp causal attention on [B, H, S, D] (fp32 softmax) — ground truth."""
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    s = q.shape[2]
    mask = jnp.tril(jnp.ones((s, s), bool))
    if segment_ids is not None:
        same = segment_ids[:, None, :, None] == segment_ids[:, None, None, :]
        mask = jnp.logical_and(mask, same)
    scores = jnp.where(mask, scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", probs.astype(q.dtype), v)


def _sds(shape, dtype, like):
    """ShapeDtypeStruct for pallas out_shape, inheriting ``like``'s varying
    axes — under shard_map's replication checker (check_vma=True) pallas
    outputs must declare their vma explicitly."""
    from tpu_parallel.core.metrics import vma_of

    vma = vma_of(like)
    if vma:
        return jax.ShapeDtypeStruct(shape, dtype, vma=frozenset(vma))
    return jax.ShapeDtypeStruct(shape, dtype)


def _kv_row_map(h: int, h_kv: int):
    """Block-row index map routing query-head row ``bh`` of a [B*H, ...] grid
    to its K/V head's row in the [B*H_KV, ...] K/V array — the native-GQA
    mechanism (no K/V expansion anywhere)."""
    if h == h_kv:
        return lambda bh_: bh_
    group = h // h_kv
    return lambda bh_: (bh_ // h) * h_kv + (bh_ % h) // group


def _window_first_k_block(qi, block_q: int, block_k: int, window: int,
                          q_offset: int = 0):
    """First key block that can intersect the sliding window of query block
    ``qi`` (tracer-safe: ``qi`` is a pallas program_id)."""
    return jnp.maximum(0, q_offset + qi * block_q - window + 1) // block_k


def _band_mask(qi, ki, shape, block_q: int, block_k: int, causal: bool,
               window: int, q_offset: int = 0):
    """Causal and/or sliding-window mask for one [block_q, block_k] score
    tile, or None when neither applies — the ONE definition all kernels
    (fwd, dq, dkv; resident and streamed) share, so forward and backward can
    never desynchronize on the band geometry.

    ``q_offset`` (static) shifts query positions relative to key positions:
    in ring attention the q chunk starts ``j * local_seq`` tokens after the
    K/V chunk it is attending, so the sliding-window band between them is
    the same geometry translated by that constant.
    """
    if not (causal or window):
        return None
    q_pos = q_offset + qi * block_q + lax.broadcasted_iota(jnp.int32, shape, 0)
    k_pos = ki * block_k + lax.broadcasted_iota(jnp.int32, shape, 1)
    mask = None
    if causal:
        mask = q_pos >= k_pos
    if window:
        # causal: one-sided band (keys at most window-1 behind the query);
        # non-causal (encoder local attention): symmetric |q - k| < window
        near = q_pos - k_pos < window
        if not causal:
            near = jnp.logical_and(near, k_pos - q_pos < window)
        mask = near if mask is None else jnp.logical_and(mask, near)
    return mask


def _stream_k_range(qi, block_q, block_k, causal, window, num_ki, q_offset=0):
    """[first, last] K-block range query block ``qi`` actually needs.  Used
    by both the streamed kernels (compute predicate) and their index maps
    (DMA clamp) — they MUST agree, so it is one function.  The range may be
    empty (first > last) for offset chunks whose window misses every key
    block; callers must clamp before using it as an index."""
    if causal:
        last = ((qi + 1) * block_q - 1) // block_k
    elif window:
        # symmetric band: the largest visible key is q_max + window - 1
        last = jnp.minimum(
            num_ki - 1,
            (q_offset + (qi + 1) * block_q - 1 + window - 1) // block_k,
        )
    else:
        last = num_ki - 1
    first = (
        _window_first_k_block(qi, block_q, block_k, window, q_offset)
        if window
        else 0
    )
    return first, last


def _stream_q_range(ki, block_q, block_k, causal, window, num_qi, q_offset=0):
    """[first, last] Q-block range that sees key block ``ki`` — the q-side
    mirror of :func:`_stream_k_range`, shared by the streamed dkv kernel's
    compute predicate and its index maps for the same must-agree reason.
    May be empty (last < first) — see _stream_k_range."""
    if causal:
        first = ki * block_k // block_q
    elif window:
        # symmetric band: the smallest query seeing key block ki is
        # k_min - window + 1 (in q-local coordinates: minus q_offset)
        first = jnp.maximum(0, ki * block_k - window + 1 - q_offset) // block_q
    else:
        first = 0
    if window:
        # queries beyond (k_block_end + window - 1) see none of this block
        # (-(-x // y) is a tracer-safe ceil); q_offset shifts the band
        last = jnp.minimum(
            num_qi - 1,
            -(-((ki + 1) * block_k + window - q_offset - 1) // block_q) - 1,
        )
    else:
        last = num_qi - 1
    return first, last


def _use_stream(s_kv: int, stream: Optional[bool]) -> bool:
    return s_kv > STREAM_SEQ_THRESHOLD if stream is None else bool(stream)


def _stream_kv_map(kv_row, block_q, block_k, causal, window, num_ki, q_offset):
    """Index map for streamed K/V (and seg-k) BlockSpecs on a (bh, qi, ki)
    grid: clamps ki into the needed range so out-of-band grid steps re-map
    to an already-fetched block (no DMA).  ONE builder shared by the forward
    and dq kernels' pallas_calls — their fetch patterns must agree with the
    kernels' _stream_k_range compute predicate."""

    def kv_map(bh_, qi, ki):
        first, last = _stream_k_range(
            qi, block_q, block_k, causal, window, num_ki, q_offset
        )
        # negative q_offset (ahead ring chunks) can drive `last` below 0 for
        # early q blocks; the index map must stay in bounds — compute is
        # predicated off for those steps anyway
        last = jnp.clip(last, 0, num_ki - 1)
        return (kv_row(bh_), jnp.clip(ki, jnp.minimum(first, last), last), 0)

    return kv_map


# --- forward kernels ----------------------------------------------------------


def _finalize_rows(acc, m, l, o_ref, lse_ref, causal):
    """Write out/lse from online-softmax state.  Causal rows always see at
    least themselves (l > 0); an offset-window ring chunk can leave rows
    with NO visible keys — those must emit the empty-partial contract
    (out = 0, lse = NEG_INF) instead of 0/0 = nan."""
    if causal:
        o_ref[0] = (acc / l).astype(o_ref.dtype)
        # log-sum-exp per query row, needed by the backward pass.  Kept as a
        # trailing length-1 lane dim: TPU blocks need the last two dims to be
        # (8k, 128k) or full — [block_q, 1] against a [bh, s, 1] array is
        # legal, [1, block_q] against [bh, s] is not.
        lse_ref[0] = m + jnp.log(l)
    else:
        empty = l <= 0.0
        o_ref[0] = jnp.where(
            empty, 0.0, acc / jnp.where(empty, 1.0, l)
        ).astype(o_ref.dtype)
        lse_ref[0] = jnp.where(
            empty, NEG_INF, m + jnp.log(jnp.where(empty, 1.0, l))
        )


def _fwd_kernel(
    q_ref, k_ref, v_ref, *rest, block_q, block_k, scale, has_segments,
    causal=True, window=0, q_offset=0,
):
    if has_segments:
        # separate q- and k-side segment refs: for self-attention both view
        # the same array; ring chunks pass the local chunk's ids vs the
        # rotating chunk's ids
        seg_q_ref, seg_k_ref, o_ref, lse_ref = rest
    else:
        o_ref, lse_ref = rest
    qi = pl.program_id(1)
    # keep MXU operands in the input dtype (bf16 on TPU: full MXU rate) and
    # accumulate fp32 via preferred_element_type; fp32 operands would run
    # the systolic array at a fraction of peak
    q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype)).astype(q_ref.dtype)
    if has_segments:
        seg_q = seg_q_ref[0]  # [bq, 1] — block qi via the index map
    # band range from the ONE shared helper (causal: blocks <= qi; full
    # mode: every block, or the symmetric window band for encoders)
    first_k_block, last_k_block = _stream_k_range(
        qi, block_q, block_k, causal, window,
        k_ref.shape[1] // block_k, q_offset,
    )
    num_k_blocks = last_k_block + 1

    def body(ki, carry):
        acc, m_prev, l_prev = carry
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        mask = _band_mask(qi, ki, s.shape, block_q, block_k, causal, window,
                          q_offset)
        if has_segments:
            seg_k = seg_k_ref[0, pl.ds(ki * block_k, block_k), :]  # [bk, 1]
            same = seg_q == seg_k.T
            mask = same if mask is None else jnp.logical_and(mask, same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc = acc * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        return acc, m_new, l_new

    d = q_ref.shape[-1]
    acc = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q, 1), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc, m, l = lax.fori_loop(first_k_block, num_k_blocks, body, (acc, m0, l0))
    _finalize_rows(acc, m, l, o_ref, lse_ref, causal)


def _fwd_kernel_stream(
    q_ref, k_ref, v_ref, *rest, block_q, block_k, scale, has_segments,
    causal, window, num_ki, q_offset=0,
):
    """Streamed forward: grid (bh, qi, ki); online-softmax state lives in
    fp32 VMEM scratch carried across the ki grid dimension."""
    if has_segments:
        seg_q_ref, seg_k_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    else:
        o_ref, lse_ref, acc_ref, m_ref, l_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    first, last = _stream_k_range(
        qi, block_q, block_k, causal, window, num_ki, q_offset
    )
    # the block the index map actually fetched (clamped copy of ki; the
    # range can be empty — min keeps the fetch index in bounds, the
    # compute predicate below keeps the empty range compute-free)
    kf = jnp.clip(ki, jnp.minimum(first, last), last)

    @pl.when(ki == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        m_ref[...] = jnp.full_like(m_ref, NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)

    @pl.when((ki >= first) & (ki <= last))
    def _compute():
        q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype)).astype(q_ref.dtype)
        k = k_ref[0]  # [block_k, d] — block kf
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(qi, kf, s.shape, block_q, block_k, causal, window,
                          q_offset)
        if has_segments:
            same = seg_q_ref[0] == seg_k_ref[0].T  # [bq, bk]
            mask = same if mask is None else jnp.logical_and(mask, same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jnp.dot(
            p.astype(v.dtype), v, preferred_element_type=jnp.float32
        )
        m_ref[...] = m_new

    @pl.when(ki == num_ki - 1)
    def _finalize():
        _finalize_rows(acc_ref[...], m_ref[...], l_ref[...], o_ref, lse_ref,
                       causal)


def _flash_fwd(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    seg_q: Optional[jax.Array],
    seg_k: Optional[jax.Array],
    *,
    block_q: int,
    block_k: int,
    interpret: bool,
    causal: bool = True,
    window: int = 0,
    stream: Optional[bool] = None,
    q_offset: int = 0,
) -> Tuple[jax.Array, jax.Array]:
    b, h, s, d = q.shape
    h_kv, s_kv = k.shape[1], k.shape[2]
    scale = 1.0 / (d**0.5)
    bh = b * h
    qf = q.reshape(bh, s, d)
    kf = k.reshape(b * h_kv, s_kv, d)
    vf = v.reshape(b * h_kv, s_kv, d)
    kv_row = _kv_row_map(h, h_kv)
    kernel_kwargs = dict(
        block_q=block_q,
        block_k=block_k,
        scale=scale,
        has_segments=seg_q is not None,
        causal=causal,
        window=window,
        q_offset=q_offset,
    )
    out_shape = [
        _sds((bh, s, d), q.dtype, qf),
        _sds((bh, s, 1), jnp.float32, qf),
    ]
    if _use_stream(s_kv, stream):
        num_ki = s_kv // block_k
        kv_map = _stream_kv_map(
            kv_row, block_q, block_k, causal, window, num_ki, q_offset
        )

        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
        ]
        args = [qf, kf, vf]
        if seg_q is not None:
            # [B, S_q, 1] q-block view and [B, S_kv, 1] (clamped) k-block
            # view; for self-attention both are the same array
            in_specs.append(
                pl.BlockSpec((1, block_q, 1), lambda bh_, qi, ki: (bh_ // h, qi, 0))
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, block_k, 1),
                    lambda bh_, qi, ki: (bh_ // h,)
                    + kv_map(bh_, qi, ki)[1:],
                )
            )
            args += [seg_q, seg_k]
        out, lse = pl.pallas_call(
            functools.partial(_fwd_kernel_stream, num_ki=num_ki, **kernel_kwargs),
            grid=(bh, s // block_q, num_ki),
            in_specs=in_specs,
            out_specs=[
                pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
                pl.BlockSpec((1, block_q, 1), lambda bh_, qi, ki: (bh_, qi, 0)),
            ],
            out_shape=out_shape,
            scratch_shapes=[
                pltpu.VMEM((block_q, d), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
                pltpu.VMEM((block_q, 1), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
        return out.reshape(b, h, s, d), lse.reshape(b, h, s)

    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
        pl.BlockSpec((1, s_kv, d), lambda bh_, qi: (kv_row(bh_), 0, 0)),
        pl.BlockSpec((1, s_kv, d), lambda bh_, qi: (kv_row(bh_), 0, 0)),
    ]
    args = [qf, kf, vf]
    if seg_q is not None:
        # all H heads of batch row b read the same blocks: the q side one
        # [block_q, 1] tile per grid step, the k side its full [S_kv, 1] lane
        in_specs.append(
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi: (bh_ // h, qi, 0))
        )
        in_specs.append(
            pl.BlockSpec((1, s_kv, 1), lambda bh_, qi: (bh_ // h, 0, 0))
        )
        args += [seg_q, seg_k]
    out, lse = pl.pallas_call(
        functools.partial(_fwd_kernel, **kernel_kwargs),
        grid=(bh, s // block_q),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi: (bh_, qi, 0)),
        ],
        out_shape=out_shape,
        interpret=interpret,
    )(*args)
    return out.reshape(b, h, s, d), lse.reshape(b, h, s)


# --- backward kernels ---------------------------------------------------------


def _bwd_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, block_k, scale, has_segments, causal=True, window=0, q_offset=0,
):
    if has_segments:
        seg_q_ref, seg_k_ref, dq_ref = rest
    else:
        (dq_ref,) = rest
    qi = pl.program_id(1)
    q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype)).astype(q_ref.dtype)
    do = do_ref[0]  # [bq, D]
    lse = lse_ref[0]  # [bq, 1]
    delta = delta_ref[0]  # [bq, 1]
    if has_segments:
        seg_q = seg_q_ref[0]  # [bq, 1] — block qi via the index map
    first_k_block, last_k_block = _stream_k_range(
        qi, block_q, block_k, causal, window,
        k_ref.shape[1] // block_k, q_offset,
    )
    num_k_blocks = last_k_block + 1

    def body(ki, dq):
        k = k_ref[0, pl.ds(ki * block_k, block_k), :]
        v = v_ref[0, pl.ds(ki * block_k, block_k), :]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(qi, ki, s.shape, block_q, block_k, causal, window,
                          q_offset)
        if has_segments:
            seg_k = seg_k_ref[0, pl.ds(ki * block_k, block_k), :]
            same = seg_q == seg_k.T
            mask = same if mask is None else jnp.logical_and(mask, same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        # empty rows (lse == NEG_INF, only in offset-window chunk mode)
        # must contribute zero: exp(s - lse) would be exp(0) = 1 on
        # their masked entries
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        return dq + jnp.dot(ds, k, preferred_element_type=jnp.float32)

    d = q_ref.shape[-1]
    dq = lax.fori_loop(
        first_k_block, num_k_blocks, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dq_kernel_stream(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, block_k, scale, has_segments, causal, window, num_ki, q_offset=0,
):
    """Streamed dq: grid (bh, qi, ki); fp32 dq accumulator in scratch."""
    if has_segments:
        seg_q_ref, seg_k_ref, dq_ref, dq_acc_ref = rest
    else:
        dq_ref, dq_acc_ref = rest
    qi = pl.program_id(1)
    ki = pl.program_id(2)
    first, last = _stream_k_range(
        qi, block_q, block_k, causal, window, num_ki, q_offset
    )
    kf = jnp.clip(ki, jnp.minimum(first, last), last)

    @pl.when(ki == 0)
    def _init():
        dq_acc_ref[...] = jnp.zeros_like(dq_acc_ref)

    @pl.when((ki >= first) & (ki <= last))
    def _compute():
        q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype)).astype(q_ref.dtype)
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)
        mask = _band_mask(qi, kf, s.shape, block_q, block_k, causal, window,
                          q_offset)
        if has_segments:
            same = seg_q_ref[0] == seg_k_ref[0].T
            mask = same if mask is None else jnp.logical_and(mask, same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        # empty rows (lse == NEG_INF, only in offset-window chunk mode)
        # must contribute zero: exp(s - lse) would be exp(0) = 1 on
        # their masked entries
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(k.dtype)
        dq_acc_ref[...] = dq_acc_ref[...] + jnp.dot(
            ds, k, preferred_element_type=jnp.float32
        )

    @pl.when(ki == num_ki - 1)
    def _finalize():
        dq_ref[0] = (dq_acc_ref[...] * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, block_k, scale, seq_len, has_segments, causal=True, window=0,
    group=1, q_offset=0,
):
    """Resident dk/dv: grid (b*h_kv, ki).  Under GQA (group > 1) the
    q/do/lse/delta operands arrive reshaped to [b*h_kv, group*seq, ...] and
    the kernel statically unrolls over the group's query heads, summing their
    contributions — the reduction over the group happens here, not via an
    expanded K/V."""
    if has_segments:
        seg_q_ref, seg_k_ref, dk_ref, dv_ref = rest
    else:
        dk_ref, dv_ref = rest
    ki = pl.program_id(1)
    k = k_ref[0]  # [block_k, D]
    v = v_ref[0]
    if has_segments:
        seg_k = seg_k_ref[0]  # [bk, 1] — block ki via the index map
    # shared q-range helper: [first, last] may be empty; fori_loop with
    # lower >= upper simply runs zero iterations
    first_q_block, last_q_block = _stream_q_range(
        ki, block_q, block_k, causal, window, seq_len // block_q, q_offset
    )
    num_q_blocks = last_q_block + 1

    def make_body(g):
        base = g * seq_len

        def body(qi, carry):
            dk, dv = carry
            q = (
                q_ref[0, pl.ds(base + qi * block_q, block_q), :]
                * jnp.asarray(scale, q_ref.dtype)
            ).astype(q_ref.dtype)
            do = do_ref[0, pl.ds(base + qi * block_q, block_q), :]
            lse = lse_ref[0, pl.ds(base + qi * block_q, block_q), :]
            delta = delta_ref[0, pl.ds(base + qi * block_q, block_q), :]
            s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
            mask = _band_mask(qi, ki, s.shape, block_q, block_k, causal, window,
                              q_offset)
            if has_segments:
                seg_q = seg_q_ref[0, pl.ds(qi * block_q, block_q), :]
                same = seg_q == seg_k.T
                mask = same if mask is None else jnp.logical_and(mask, same)
            if mask is not None:
                s = jnp.where(mask, s, NEG_INF)
            # empty rows (lse == NEG_INF, only in offset-window chunk mode)
            # must contribute zero: exp(s - lse) would be exp(0) = 1 on
            # their masked entries
            p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
            dv = dv + jnp.dot(
                p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
            )
            dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
            ds = (p * (dp - delta)).astype(q.dtype)
            dk = dk + jnp.dot(ds.T, q, preferred_element_type=jnp.float32)
            return dk, dv

        return body

    d = k_ref.shape[-1]
    zeros = jnp.zeros((block_k, d), jnp.float32)
    carry = (zeros, zeros)
    for g in range(group):  # static unroll: one pass per query head in group
        carry = lax.fori_loop(first_q_block, num_q_blocks, make_body(g), carry)
    dk, dv = carry
    # q was pre-scaled, so dk already carries one factor of `scale`
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _bwd_dkv_kernel_stream(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, *rest,
    block_q, block_k, scale, has_segments, causal, window, group, num_qi,
    q_offset=0,
):
    """Streamed dk/dv: grid (b*h_kv, ki, g, qi).  The index maps feed the
    (g, qi) walk one [block_q, ...] tile at a time; dk/dv accumulate in fp32
    scratch across the two inner grid dims and flush once per (bkv, ki)."""
    if has_segments:
        seg_q_ref, seg_k_ref, dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    else:
        dk_ref, dv_ref, dk_acc_ref, dv_acc_ref = rest
    ki = pl.program_id(1)
    g = pl.program_id(2)
    qi = pl.program_id(3)
    first_q, last_q = _stream_q_range(
        ki, block_q, block_k, causal, window, num_qi, q_offset
    )
    qf = jnp.clip(qi, first_q, jnp.maximum(last_q, first_q))

    @pl.when((g == 0) & (qi == 0))
    def _init():
        dk_acc_ref[...] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[...] = jnp.zeros_like(dv_acc_ref)

    @pl.when((qi >= first_q) & (qi <= last_q))
    def _compute():
        k = k_ref[0]
        v = v_ref[0]
        q = (q_ref[0] * jnp.asarray(scale, q_ref.dtype)).astype(q_ref.dtype)
        do = do_ref[0]
        lse = lse_ref[0]
        delta = delta_ref[0]
        s = jnp.dot(q, k.T, preferred_element_type=jnp.float32)  # [bq, bk]
        mask = _band_mask(qf, ki, s.shape, block_q, block_k, causal, window,
                          q_offset)
        if has_segments:
            same = seg_q_ref[0] == seg_k_ref[0].T
            mask = same if mask is None else jnp.logical_and(mask, same)
        if mask is not None:
            s = jnp.where(mask, s, NEG_INF)
        # empty rows (lse == NEG_INF, only in offset-window chunk mode)
        # must contribute zero: exp(s - lse) would be exp(0) = 1 on
        # their masked entries
        p = jnp.where(lse <= NEG_INF / 2, 0.0, jnp.exp(s - lse))
        dv_acc_ref[...] = dv_acc_ref[...] + jnp.dot(
            p.astype(do.dtype).T, do, preferred_element_type=jnp.float32
        )
        dp = jnp.dot(do, v.T, preferred_element_type=jnp.float32)
        ds = (p * (dp - delta)).astype(q.dtype)
        dk_acc_ref[...] = dk_acc_ref[...] + jnp.dot(
            ds.T, q, preferred_element_type=jnp.float32
        )

    @pl.when((g == pl.num_programs(2) - 1) & (qi == num_qi - 1))
    def _finalize():
        # q was pre-scaled, so dk already carries one factor of `scale`
        dk_ref[0] = dk_acc_ref[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[...].astype(dv_ref.dtype)


def _flash_bwd(
    q, k, v, seg_q, seg_k, out, lse, do, *, block_q, block_k, interpret,
    causal=True, window=0, dlse=None, stream: Optional[bool] = None,
    q_offset: int = 0,
):
    b, h, s, d = q.shape
    h_kv, s_kv = k.shape[1], k.shape[2]
    group = h // h_kv
    scale = 1.0 / (d**0.5)
    bh = b * h
    b_kv = b * h_kv
    delta = jnp.sum(out.astype(jnp.float32) * do.astype(jnp.float32), axis=-1)
    if dlse is not None:
        # chunked/ring combine: a nonzero cotangent on lse folds into the
        # same per-row correction the probs already use —
        # ds = p * (dp - (delta - dlse))
        delta = delta - dlse
    qf = q.reshape(bh, s, d)
    kf, vf = (x.reshape(b_kv, s_kv, d) for x in (k, v))
    dof = do.reshape(bh, s, d)
    lsef = lse.reshape(bh, s, 1)
    deltaf = delta.reshape(bh, s, 1)
    has_segments = seg_q is not None
    kv_row = _kv_row_map(h, h_kv)
    # the resident dkv kernel holds [group*s, d] q/do operands in VMEM, so
    # under GQA the stream decision must budget for group*s, not just s_kv —
    # e.g. group=8 at s=4096 is an 8MB bf16 q tile, past v5e VMEM
    streamed = _use_stream(max(s_kv, group * s), stream)

    # ---- dq ----
    if streamed:
        num_ki = s_kv // block_k
        kv_map = _stream_kv_map(
            kv_row, block_q, block_k, causal, window, num_ki, q_offset
        )

        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_k, d), kv_map),
            pl.BlockSpec((1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi, ki: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi, ki: (bh_, qi, 0)),
        ]
        args = [qf, kf, vf, dof, lsef, deltaf]
        if has_segments:
            in_specs.append(
                pl.BlockSpec((1, block_q, 1), lambda bh_, qi, ki: (bh_ // h, qi, 0))
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, block_k, 1),
                    lambda bh_, qi, ki: (bh_ // h,) + kv_map(bh_, qi, ki)[1:],
                )
            )
            args += [seg_q, seg_k]
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel_stream,
                block_q=block_q,
                block_k=block_k,
                scale=scale,
                has_segments=has_segments,
                causal=causal,
                window=window,
                num_ki=num_ki,
                q_offset=q_offset,
            ),
            grid=(bh, s // block_q, num_ki),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, block_q, d), lambda bh_, qi, ki: (bh_, qi, 0)
            ),
            out_shape=_sds((bh, s, d), q.dtype, qf),
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
            interpret=interpret,
        )(*args)
    else:
        in_specs = [
            pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, s_kv, d), lambda bh_, qi: (kv_row(bh_), 0, 0)),
            pl.BlockSpec((1, s_kv, d), lambda bh_, qi: (kv_row(bh_), 0, 0)),
            pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi: (bh_, qi, 0)),
            pl.BlockSpec((1, block_q, 1), lambda bh_, qi: (bh_, qi, 0)),
        ]
        args = [qf, kf, vf, dof, lsef, deltaf]
        if has_segments:
            in_specs.append(
                pl.BlockSpec((1, block_q, 1), lambda bh_, qi: (bh_ // h, qi, 0))
            )
            in_specs.append(
                pl.BlockSpec((1, s_kv, 1), lambda bh_, qi: (bh_ // h, 0, 0))
            )
            args += [seg_q, seg_k]
        dq = pl.pallas_call(
            functools.partial(
                _bwd_dq_kernel,
                block_q=block_q,
                block_k=block_k,
                scale=scale,
                has_segments=has_segments,
                causal=causal,
                window=window,
                q_offset=q_offset,
            ),
            grid=(bh, s // block_q),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, block_q, d), lambda bh_, qi: (bh_, qi, 0)),
            out_shape=_sds((bh, s, d), q.dtype, qf),
            interpret=interpret,
        )(*args)

    # ---- dk/dv ----
    dkv_out_specs = [
        pl.BlockSpec((1, block_k, d), lambda bh_, ki, *_: (bh_, ki, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh_, ki, *_: (bh_, ki, 0)),
    ]
    dkv_out_shape = [
        _sds((b_kv, s_kv, d), q.dtype, qf),
        _sds((b_kv, s_kv, d), q.dtype, qf),
    ]
    if streamed:
        num_qi = s // block_q

        def q_row(bkv_, g):
            if group == 1:
                return bkv_
            return (bkv_ // h_kv) * h + (bkv_ % h_kv) * group + g

        def qi_clip(ki, qi):
            first_q, last_q = _stream_q_range(
                ki, block_q, block_k, causal, window, num_qi, q_offset
            )
            # negative q_offset (ahead ring chunks) can push first_q past
            # the last block for late k blocks; keep the index in bounds —
            # those grid steps are compute-predicated off
            first_q = jnp.clip(first_q, 0, num_qi - 1)
            return jnp.clip(qi, first_q, jnp.maximum(last_q, first_q))

        def q_map(bkv_, ki, g, qi):
            return (q_row(bkv_, g), qi_clip(ki, qi), 0)

        in_specs = [
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_k, d), lambda bkv_, ki, g, qi: (bkv_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bkv_, ki, g, qi: (bkv_, ki, 0)),
            pl.BlockSpec((1, block_q, d), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
            pl.BlockSpec((1, block_q, 1), q_map),
        ]
        args = [qf, kf, vf, dof, lsef, deltaf]
        if has_segments:
            in_specs.append(
                pl.BlockSpec(
                    (1, block_q, 1),
                    lambda bkv_, ki, g, qi: (bkv_ // h_kv, qi_clip(ki, qi), 0),
                )
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, block_k, 1),
                    lambda bkv_, ki, g, qi: (bkv_ // h_kv, ki, 0),
                )
            )
            args += [seg_q, seg_k]
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel_stream,
                block_q=block_q,
                block_k=block_k,
                scale=scale,
                has_segments=has_segments,
                causal=causal,
                window=window,
                group=group,
                num_qi=num_qi,
                q_offset=q_offset,
            ),
            grid=(b_kv, s_kv // block_k, group, num_qi),
            in_specs=in_specs,
            out_specs=dkv_out_specs,
            out_shape=dkv_out_shape,
            scratch_shapes=[
                pltpu.VMEM((block_k, d), jnp.float32),
                pltpu.VMEM((block_k, d), jnp.float32),
            ],
            interpret=interpret,
        )(*args)
    else:
        # group the query-head operands by K/V head: [b*h_kv, group*s, ...]
        qg = q.reshape(b_kv, group * s, d)
        dog = do.reshape(b_kv, group * s, d)
        lseg = lse.reshape(b_kv, group * s, 1)
        deltag = delta.reshape(b_kv, group * s, 1)
        in_specs = [
            pl.BlockSpec((1, group * s, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, ki: (bh_, ki, 0)),
            pl.BlockSpec((1, group * s, d), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, group * s, 1), lambda bh_, ki: (bh_, 0, 0)),
            pl.BlockSpec((1, group * s, 1), lambda bh_, ki: (bh_, 0, 0)),
        ]
        args = [qg, kf, vf, dog, lseg, deltag]
        if has_segments:
            in_specs.append(
                pl.BlockSpec((1, s, 1), lambda bh_, ki: (bh_ // h_kv, 0, 0))
            )
            in_specs.append(
                pl.BlockSpec((1, block_k, 1), lambda bh_, ki: (bh_ // h_kv, ki, 0))
            )
            args += [seg_q, seg_k]
        dk, dv = pl.pallas_call(
            functools.partial(
                _bwd_dkv_kernel,
                block_q=block_q,
                block_k=block_k,
                scale=scale,
                seq_len=s,
                has_segments=has_segments,
                causal=causal,
                window=window,
                group=group,
                q_offset=q_offset,
            ),
            grid=(b_kv, s_kv // block_k),
            in_specs=in_specs,
            out_specs=dkv_out_specs,
            out_shape=dkv_out_shape,
            interpret=interpret,
        )(*args)

    return (
        dq.reshape(b, h, s, d),
        dk.reshape(b, h_kv, s_kv, d),
        dv.reshape(b, h_kv, s_kv, d),
    )


# --- public API with custom VJP ----------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11))
def _flash_finalize(
    q, k, v, seg_q, seg_k, out, lse, block_q, block_k, interpret, window, stream
):
    """Identity on ``out``; exists to attach the backward kernels.

    The forward kernel runs *outside* this custom_vjp (see
    ``_flash_attention_bhsd``) so its outputs are ordinary named values in
    the surrounding jaxpr: a ``save_only_these_names(..., "attn")`` remat
    policy can then keep them, and the backward never re-runs the forward
    kernel.  Residuals hidden inside a custom_vjp are invisible to remat
    policies — measured as a full forward-kernel re-run per layer
    (scripts/attn_wrap_bisect.py).
    """
    del q, k, v, seg_q, seg_k, lse
    return out


def _finalize_fwd(q, k, v, seg_q, seg_k, out, lse, block_q, block_k, interpret,
                  window, stream):
    return out, (q, k, v, seg_q, seg_k, out, lse)


def _finalize_bwd(block_q, block_k, interpret, window, stream, residuals, do):
    q, k, v, seg_q, seg_k, out, lse = residuals
    dq, dk, dv = _flash_bwd(
        q, k, v, seg_q, seg_k, out, lse, do,
        block_q=block_q, block_k=block_k, interpret=interpret, window=window,
        stream=stream,
    )
    # segment ids (int) carry no gradient; out/lse arrive behind
    # stop_gradient, so their zero cotangents are discarded by the caller
    return dq, dk, dv, None, None, jnp.zeros_like(out), jnp.zeros_like(lse)


_flash_finalize.defvjp(_finalize_fwd, _finalize_bwd)


def _flash_attention_bhsd(q, k, v, seg, block_q, block_k, interpret, window=0,
                          stream=None):
    from jax.ad_checkpoint import checkpoint_name

    # self-attention: q and k index the same positions, so one segment
    # array serves both sides of the kernels' (seg_q, seg_k) contract
    # stop_gradient on the *inputs*: the forward kernel then sees all-zero
    # tangents and AD bypasses it entirely (all q/k/v gradient flows through
    # _flash_finalize's backward kernels).  Stopping only the outputs is too
    # late — JVP would still trace into the pallas forward kernel.
    out, lse = _flash_fwd(
        lax.stop_gradient(q),
        lax.stop_gradient(k),
        lax.stop_gradient(v),
        seg,
        seg,
        block_q=block_q,
        block_k=block_k,
        interpret=interpret,
        window=window,
        stream=stream,
    )
    out = checkpoint_name(out, "attn")
    lse = checkpoint_name(lse, "attn")
    return _flash_finalize(
        q, k, v, seg, seg, out, lse, block_q, block_k, interpret, window, stream
    )


# --- chunk attention for ring/sequence parallelism ---------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(7, 8, 9, 10, 11, 12, 13))
def _chunk_finalize(
    q, k, v, seg_q, seg_k, out, lse, causal, block_q, block_k, interpret,
    stream, window, q_offset
):
    """Identity on ``(out, lse)``; attaches the chunk backward kernels.

    Same layout as :func:`_flash_finalize`: the forward kernel runs OUTSIDE
    this custom_vjp (on stop_gradient inputs) so its outputs are ordinary
    named jaxpr values — a ``save_only_these_names(..., "attn")`` remat
    policy keeps them and the backward (ring steps, bidirectional encoders)
    never re-runs the forward kernel.  Unlike _flash_finalize, ``lse`` stays
    a differentiable output: ring's combine_chunks needs its cotangent.
    """
    del q, k, v, seg_q, seg_k
    return out, lse


def _chunk_finalize_fwd(q, k, v, seg_q, seg_k, out, lse, causal, block_q,
                        block_k, interpret, stream, window, q_offset):
    return (out, lse), (q, k, v, seg_q, seg_k, out, lse)


def _chunk_finalize_bwd(causal, block_q, block_k, interpret, stream, window,
                        q_offset, residuals, cotangents):
    q, k, v, seg_q, seg_k, out, lse = residuals
    do, dlse = cotangents
    dq, dk, dv = _flash_bwd(
        q, k, v, seg_q, seg_k, out, lse, do,
        block_q=block_q, block_k=block_k, interpret=interpret,
        causal=causal, dlse=dlse, stream=stream,
        window=window, q_offset=q_offset,
    )
    # seg ids carry no gradient; out/lse arrive behind stop_gradient
    return dq, dk, dv, None, None, jnp.zeros_like(out), jnp.zeros_like(lse)


_chunk_finalize.defvjp(_chunk_finalize_fwd, _chunk_finalize_bwd)


def _chunk_attention_bhsd(
    q, k, v, seg_q, seg_k, causal, block_q, block_k, interpret, stream, window,
    q_offset
):
    from jax.ad_checkpoint import checkpoint_name

    out, lse = _flash_fwd(
        lax.stop_gradient(q),
        lax.stop_gradient(k),
        lax.stop_gradient(v),
        seg_q, seg_k,
        block_q=block_q, block_k=block_k,
        interpret=interpret, causal=causal, stream=stream,
        window=window, q_offset=q_offset,
    )
    out = checkpoint_name(out, "attn")
    lse = checkpoint_name(lse, "attn")
    return _chunk_finalize(
        q, k, v, seg_q, seg_k, out, lse, causal, block_q, block_k, interpret,
        stream, window, q_offset
    )


def flash_chunk_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    interpret: Optional[bool] = None,
    stream: Optional[bool] = None,
    window: int = 0,
    q_offset: int = 0,
    segment_ids_q: Optional[jax.Array] = None,
    segment_ids_kv: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """One flash-attention partial over a K/V chunk, for ring combining.

    ``q, k, v``: [batch, seq_q, heads, head_dim] / [batch, seq_kv, ...].
    Returns ``(out, lse)`` with ``out`` [batch, seq_q, heads, head_dim]
    normalized *within the chunk* and ``lse`` [batch, heads, seq_q] its
    log-sum-exp; partials from different chunks combine exactly via
    :func:`tpu_parallel.ops.ring_attention.combine_chunks`.  Differentiable
    in both outputs — the lse cotangent folds into the backward kernels'
    delta correction, which is what makes the combine's gradient exact.

    ``causal=True`` is the diagonal chunk of a sequence-sharded causal
    attention (q and k index the same positions); ``causal=False`` is a
    fully-visible (strictly-past) chunk.

    ``window``/``q_offset`` (both static) add a banded mask over global
    positions (query i sits at ``q_offset + i`` relative to the chunk's
    keys).  With ``causal=True`` the band is one-sided (key j visible iff
    ``q_offset + i - j < window``, Mistral semantics); with
    ``causal=False`` it is SYMMETRIC — ``|q_offset + i - j| < window`` —
    the encoder local-attention form.  Ring attention passes SIGNED
    ``q_offset = j * local_seq``: positive for chunks behind the queries
    (the symmetric upper side is vacuous there), NEGATIVE for chunks ahead
    (bidirectional rings — the upper side binds).  Rows whose window misses
    the whole chunk come back as empty partials (out 0, lse NEG_INF),
    which :func:`combine_chunks` weights to zero.

    ``segment_ids_q``/``segment_ids_kv`` ([batch, seq_q] / [batch, seq_kv],
    both or neither) mask packed sequences across chunks: queries attend
    only same-segment keys.  Ring attention passes the local chunk's ids as
    the q side and the currently-held (rotated) chunk's ids as the kv side.
    A row whose segment matches nothing in the chunk is an empty partial,
    handled as above.
    """
    if (segment_ids_q is None) != (segment_ids_kv is None):
        raise ValueError(
            "segment_ids_q and segment_ids_kv must be passed together"
        )
    if q.shape[2] % k.shape[2] != 0:
        raise ValueError(
            f"q heads {q.shape[2]} not a multiple of k/v heads {k.shape[2]}"
        )
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # exact-divisor tiles: a grid of s // bq with s % bq != 0 would leave
    # query rows unwritten and key rows unattended — silent corruption, not
    # an error.  gcd shrinks to the largest legal tile; warn when it bites.
    import math

    bq = math.gcd(q.shape[1], min(block_q, q.shape[1]))
    bk = math.gcd(k.shape[1], min(block_k, k.shape[1]))
    if causal:
        bk = math.gcd(bq, bk)  # causal num_k_blocks needs block_q % block_k == 0
    if bq < min(block_q, q.shape[1]) or bk < min(block_k, k.shape[1]):
        warnings.warn(
            f"flash_chunk_attention shrank tiles to {bq}x{bk}: chunk lengths "
            f"q={q.shape[1]}/kv={k.shape[1]} are not divisible by the "
            f"requested {block_q}x{block_k}",
            stacklevel=2,
        )
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    seg_q = seg_k = None
    if segment_ids_q is not None:
        seg_q = segment_ids_q.astype(jnp.int32)[:, :, None]
        seg_k = segment_ids_kv.astype(jnp.int32)[:, :, None]
    out, lse = _chunk_attention_bhsd(
        qt, kt, vt, seg_q, seg_k, causal, bq, bk, interpret, stream, window,
        q_offset
    )
    return out.transpose(0, 2, 1, 3), lse


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    segment_ids: Optional[jax.Array] = None,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    window: int = 0,
    interpret: Optional[bool] = None,
    stream: Optional[bool] = None,
) -> jax.Array:
    """Causal flash attention on [batch, seq, heads, head_dim] inputs.

    ``k``/``v`` may carry fewer heads than ``q`` (grouped-query attention:
    ``n_heads % n_kv_heads == 0``); the kernels route each query head to its
    K/V head via BlockSpec index maps — K/V are never expanded, so GQA keeps
    its 1/group HBM saving on the Pallas path.

    ``window > 0`` adds sliding-window masking: query t sees keys in
    (t - window, t] only, and whole key blocks outside the window are
    skipped, not masked — O(seq * window) compute at long sequence.

    ``stream`` selects the long-sequence kernels (K/V walked as a grid
    dimension, O(block_k) VMEM residency); ``None`` auto-selects them above
    ``STREAM_SEQ_THRESHOLD`` tokens.

    Drop-in replacement for
    :func:`tpu_parallel.models.layers.causal_attention` (the ``attn_fn``
    hook).  ``segment_ids`` [batch, seq] masks attention to same-segment
    prefixes (packed sequences) inside the kernel.  ``interpret`` defaults
    to True off-TPU so tests exercise the same kernel code on CPU.
    """
    b, s, h, d = q.shape
    h_kv = k.shape[2]
    if h % h_kv != 0:
        raise ValueError(f"q heads {h} not a multiple of k/v heads {h_kv}")
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, s)
    block_k = min(block_k, s)
    if s % block_q != 0 or s % block_k != 0 or block_q % block_k != 0:
        # O(seq^2) escape hatch for shapes the kernel can't tile — loud, not
        # silent: this is a memory/perf cliff the caller should know about
        warnings.warn(
            f"flash_attention falling back to the O(seq^2) reference path: "
            f"seq_len={s} not divisible by block_q={block_q}/block_k={block_k}",
            stacklevel=2,
        )
        from tpu_parallel.models.layers import causal_attention

        if h_kv != h:  # the dense path has no head routing — expand
            k = jnp.repeat(k, h // h_kv, axis=2)
            v = jnp.repeat(v, h // h_kv, axis=2)
        return causal_attention(q, k, v, segment_ids=segment_ids, window=window)
    seg = None
    if segment_ids is not None:
        # one int32 lane per batch row ([B, S, 1]); the kernels' BlockSpec
        # index maps route all H heads of row b to the same block
        seg = segment_ids.astype(jnp.int32)[:, :, None]
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out = _flash_attention_bhsd(
        qt, kt, vt, seg, block_q, block_k, interpret, window, stream
    )
    return out.transpose(0, 2, 1, 3)
