"""Pipeline parallelism: GPipe microbatch schedule over a ``pipe`` mesh axis.

The reference *declares* pipeline parallelism but implements none of it —
``pipeline_parallel.py`` is 39 lines of imports with zero stages, schedule, or
communication (SURVEY.md §2.2, §3.5).  This module builds the real thing the
TPU-native way, per the driver's north star: stages laid out on a ``pipe``
mesh axis, activations handed between neighbouring stages with
``lax.ppermute`` (which XLA lowers to ICI neighbour exchanges), and the
microbatch schedule expressed as a ``lax.scan`` so the compiled program is
constant-size in the number of microbatches.

Mechanics (per device, inside ``shard_map``):

- Each pipe rank holds its own stage parameters via
  :class:`~tpu_parallel.parallel.tp.ModuleShard` (stacked ``nn.Partitioned``
  over ``pipe``), so one logical module definition yields per-stage weights.
- The schedule runs ``num_microbatches + num_stages - 1`` iterations.  Rank 0
  feeds microbatch ``i`` at iteration ``i`` (and zeros afterwards); every rank
  applies its stage to its current input and ``ppermute``s the output to rank
  ``+1``; the last rank collects valid outputs for iterations
  ``>= num_stages - 1``.  The bubble is the standard GPipe
  ``(num_stages - 1) / (num_microbatches + num_stages - 1)`` fraction of the
  schedule — make ``num_microbatches >> num_stages`` to amortize it.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

from tpu_parallel.parallel.tp import ModuleShard


def execute_pipeline_step(
    module: nn.Module,
    carry: jax.Array,
    microbatch: jax.Array,
    *,
    axis_name: str,
    tick: Optional[jax.Array] = None,
    num_microbatches: Optional[int] = None,
    pass_validity: bool = False,
    **kwargs,
) -> tuple[jax.Array, jax.Array]:
    """One schedule tick: select input, run the stage, rotate outputs.

    ``carry`` is the activation received from the previous rank last tick;
    rank 0 instead consumes ``microbatch`` (valid only while microbatches
    remain — afterwards it receives garbage that is masked out downstream).

    ``pass_validity=True`` hands the stage an ``aux_scale`` scalar: 1.0 when
    this rank is processing a real microbatch this tick, 0.0 on bubble ticks
    (fill/drain) — so sown regularizers (MoE balance loss) can exclude
    garbage activations exactly.  Requires the stage module to accept an
    ``aux_scale`` keyword (``models.layers.BlockStack`` does).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    # Stage 0 reads fresh microbatches; other stages read the rotated carry.
    inputs = jnp.where(stage == 0, microbatch, carry)
    if pass_validity:
        # Rank r works on microbatch (tick - r): real iff it is in range.
        mb_index = tick - stage
        kwargs = dict(kwargs)
        kwargs["aux_scale"] = jnp.logical_and(
            mb_index >= 0, mb_index < num_microbatches
        ).astype(jnp.float32)
    outputs = module(inputs, **kwargs)
    if outputs.shape != inputs.shape:
        raise ValueError(
            f"pipeline stages must preserve activation shape; got "
            f"{inputs.shape} -> {outputs.shape}"
        )
    # Collect the last stage's result BEFORE rotation — after the ppermute it
    # would already have moved on to rank 0's carry slot.
    collected = jnp.where(stage == num_stages - 1, outputs, jnp.zeros_like(outputs))
    # Rotate: rank i -> rank i+1; the wrap-around edge (last -> 0) carries no
    # information (rank 0 ignores its carry) but keeps the permutation total.
    carry_next = lax.ppermute(
        outputs,
        axis_name,
        perm=[(i, (i + 1) % num_stages) for i in range(num_stages)],
    )
    return carry_next, collected


@jax.named_scope("execute_pipeline")
def execute_pipeline(
    module: nn.Module,
    x: jax.Array,
    *,
    num_microbatches: int,
    axis_name: str,
    broadcast_outputs: bool = False,
    pass_validity: bool = False,
    **kwargs,
) -> jax.Array:
    """Run ``module`` as a pipeline stage over the full GPipe schedule.

    ``x``: this data-shard's full input ``[batch, ...]``; it is split into
    ``num_microbatches`` along axis 0.  Returns outputs with the same leading
    shape, produced by the *last* stage; other ranks return zeros — compute
    the loss with :func:`last_stage_mask`, or pass
    ``broadcast_outputs=True`` to psum the (zero-padded) result over the pipe
    axis so every rank holds the real output (costs one all-reduce of the
    activation — fine for small heads, avoid for large logits).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    batch_size = x.shape[0]
    if batch_size % num_microbatches != 0:
        raise ValueError(
            f"per-device batch {batch_size} not divisible by "
            f"num_microbatches={num_microbatches}"
        )
    microbatch_size = batch_size // num_microbatches
    microbatches = x.reshape(num_microbatches, microbatch_size, *x.shape[1:])
    # Pad the schedule tail: after the real microbatches run out, stage 0
    # feeds zeros that never surface in a valid output slot.
    num_iterations = num_microbatches + num_stages - 1
    inputs = jnp.concatenate(
        [
            microbatches,
            jnp.zeros((num_stages - 1, *microbatches.shape[1:]), microbatches.dtype),
        ],
        axis=0,
    )

    # The rotating carry comes back from ppermute varying over the pipe axis;
    # promote the zeros init to the same varying type or the scan's
    # carry-in/carry-out types disagree under shard_map's replication checker
    from tpu_parallel.core.metrics import pvary_missing

    carry_init = pvary_missing(jnp.zeros_like(microbatches[0]), (axis_name,))
    # aux-loss collections (MoE balance) stack one entry per schedule tick;
    # with pass_validity the stage zeroes bubble-tick entries via aux_scale,
    # so only the num_microbatches real ticks contribute.
    ticks = jnp.arange(num_iterations, dtype=jnp.int32)
    _, outputs = nn.scan(
        _ScanWrapper,
        variable_broadcast="params",
        variable_axes={"losses": 0},
        split_rngs={"params": False, "dropout": True},
    )(
        module,
        axis_name=axis_name,
        num_microbatches=num_microbatches,
        pass_validity=pass_validity,
        static_kwargs=tuple(sorted(kwargs.items())),
    )(carry_init, (inputs, ticks))
    # outputs: [num_iterations, mb, ...]; valid last-stage outputs occupy the
    # final num_microbatches slots (earlier ticks were pipeline fill).  The
    # per-tick collection already zeroed every rank but the last.
    outputs = outputs[num_stages - 1 :]
    outputs = outputs.reshape(batch_size, *outputs.shape[2:])
    if broadcast_outputs:
        with jax.named_scope("pipeline_broadcast_outputs"):
            outputs = lax.psum(outputs, axis_name)
    return outputs


class _ScanWrapper(nn.Module):
    """nn.scan target: applies the wrapped stage module once per tick.

    ``static_kwargs`` carries the caller's static keyword arguments (e.g.
    ``train=False``) through the scan to the stage module — stored as a
    sorted tuple of items because flax module attributes must be hashable.
    """

    module: nn.Module
    axis_name: str
    num_microbatches: Optional[int] = None
    pass_validity: bool = False
    static_kwargs: Tuple[Tuple[str, Any], ...] = ()

    def __call__(self, carry, xs):
        microbatch, tick = xs
        return execute_pipeline_step(
            self.module,
            carry,
            microbatch,
            axis_name=self.axis_name,
            tick=tick,
            num_microbatches=self.num_microbatches,
            pass_validity=self.pass_validity,
            **dict(self.static_kwargs),
        )


def last_stage_mask(axis_name: str = "pipe") -> jax.Array:
    """1.0 on the final pipe rank, 0.0 elsewhere.

    Pipeline outputs are only valid on the last stage; multiply per-example
    losses / metric sums by this before the ``psum`` over the pipe axis so the
    invalid ranks contribute exactly zero (their gradients vanish through the
    same mask).
    """
    num_stages = lax.psum(1, axis_name)
    stage = lax.axis_index(axis_name)
    return (stage == num_stages - 1).astype(jnp.float32)


class PipelineModule(nn.Module):
    """Wrap a stage constructor into a full pipeline over ``axis_name``.

    ``stage_fn`` builds the per-stage module (e.g. a stack of
    ``n_layers // num_stages`` transformer blocks).  It must be a module
    constructor that accepts flax module kwargs — a class or
    ``functools.partial(Class, ...)``, not a zero-argument lambda (the
    wrapper instantiates it with a ``name``).  Stage parameters are made
    per-rank with :class:`ModuleShard` — each pipe rank initializes and owns
    only its stage — and the GPipe schedule above moves activations through
    the ranks.
    """

    stage_fn: Callable[[], nn.Module]
    num_microbatches: int
    axis_name: str = "pipe"
    broadcast_outputs: bool = False
    # hand the stage a per-tick aux_scale validity scalar (see
    # execute_pipeline_step); the stage must accept the keyword
    pass_validity: bool = False

    @nn.compact
    def __call__(self, x: jax.Array, **kwargs) -> jax.Array:
        stage = ModuleShard(
            module_fn=self.stage_fn, axis_name=self.axis_name, name="stage"
        )
        return execute_pipeline(
            stage,
            x,
            num_microbatches=self.num_microbatches,
            axis_name=self.axis_name,
            broadcast_outputs=self.broadcast_outputs,
            pass_validity=self.pass_validity,
            **kwargs,
        )
