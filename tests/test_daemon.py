"""Durable-daemon tests: write-ahead journal semantics, in-process
crash + journal-replay recovery (bitwise greedy parity through a
kill), dedupe-token idempotence, the drain/fast-shutdown contract on a
fake clock, the stdlib HTTP+SSE face, and the real-subprocess SIGTERM
smoke that ``scripts/check_all.py`` also runs."""

import json
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from tpu_parallel.cluster import Frontend, FrontendConfig
from tpu_parallel.daemon import (
    EXIT_CLEAN,
    EXIT_FORCED,
    REC_RECOVERY,
    REC_SHUTDOWN,
    REC_SUBMIT,
    REC_TERMINAL,
    REC_TOKENS,
    DaemonConfig,
    DaemonHTTPServer,
    JournalCorrupt,
    JournalWriter,
    ServingDaemon,
    WallClock,
    load_state,
    read_journal,
    replay_state,
)
from tpu_parallel.models import GPTLM, tiny_test
from tpu_parallel.models.generate import generate
from tpu_parallel.obs.registry import MetricRegistry
from tpu_parallel.serving import (
    REJECT_DRAINING,
    REJECTED,
    Request,
    SchedulerConfig,
    ServingEngine,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class FakeClock:
    """Callable clock + sleep — the daemon's full fake-time surface."""

    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t

    def sleep(self, seconds: float) -> None:
        self.t += seconds


@pytest.fixture(scope="module")
def env():
    cfg = tiny_test(dtype=jnp.float32, remat=False)
    model = GPTLM(cfg)
    rng = jax.random.PRNGKey(11)
    lens = [3, 5, 4, 7]
    prompts = [
        [int(t) for t in np.asarray(
            jax.random.randint(
                jax.random.fold_in(rng, i), (L,), 1, cfg.vocab_size
            )
        )]
        for i, L in enumerate(lens)
    ]
    probe = jax.random.randint(rng, (1, max(lens)), 1, cfg.vocab_size)
    params = model.init(
        {"params": jax.random.PRNGKey(1)}, probe, train=False
    )["params"]
    refs = [
        [int(t) for t in np.asarray(generate(
            model, params, jnp.asarray(p, jnp.int32)[None, :],
            max_new_tokens=8,
        ))[0]]
        for p in prompts
    ]
    return cfg, model, params, prompts, refs


def _factory(env, **fe_kw):
    cfg, model, params, _, _ = env

    def frontend_factory(clock):
        engine = ServingEngine(
            model, params, n_slots=2,
            scheduler=SchedulerConfig(max_prefills_per_tick=2),
            decode_steps_per_tick=1,
        )
        return Frontend(
            [engine], router="least",
            config=FrontendConfig(restart=None, **fe_kw),
            clock=clock, registry=MetricRegistry(),
        )

    return frontend_factory


def _daemon(env, path, clock=None, fe_kw=None, **cfg_kw):
    cfg_kw.setdefault("fsync_batch", 4)
    return ServingDaemon(
        _factory(env, **(fe_kw or {})), str(path),
        clock=clock or FakeClock(),
        config=DaemonConfig(**cfg_kw),
    )


# -- journal unit semantics -------------------------------------------------


def test_journal_roundtrip_seq_and_fsync_batching(tmp_path):
    path = str(tmp_path / "j.jsonl")
    clk = FakeClock()
    w = JournalWriter(path, clk, fsync_batch=3)
    base_syncs = w.fsyncs
    for i in range(4):
        w.append({"record": "tokens", "request_id": "r", "tokens": [i]})
    # 4 non-sync-now records at batch 3: exactly one batched fsync fired
    assert w.fsyncs == base_syncs + 1
    w.append({"record": REC_SUBMIT, "request_id": "s", "prompt": [1]})
    assert w.fsyncs == base_syncs + 2  # submits sync immediately
    w.close()
    records, torn = read_journal(path)
    assert torn == 0
    seqs = [r["seq"] for r in records]
    assert seqs == sorted(seqs) and len(set(seqs)) == len(seqs)
    assert records[0]["record"] == "journal_meta"
    # a new writer continues the sequence instead of restarting it
    w2 = JournalWriter(path, clk, next_seq=load_state(path).next_seq)
    rec = w2.append({"record": "tokens", "request_id": "r", "tokens": []})
    assert rec["seq"] > seqs[-1]
    w2.close()


def test_journal_torn_tail_tolerated_midfile_corruption_raises(tmp_path):
    path = str(tmp_path / "j.jsonl")
    w = JournalWriter(path, FakeClock())
    w.append({"record": REC_SUBMIT, "request_id": "a", "prompt": [1]})
    w.append({"record": REC_TOKENS, "request_id": "a", "tokens": [5]})
    w.close()
    with open(path, "a") as fh:
        fh.write('{"record": "tokens", "request_id": "a", "toke')  # torn
    records, torn = read_journal(path)
    assert torn == 1
    assert [r["record"] for r in records][-1] == REC_TOKENS
    # the same garbage MID-file is corruption, not a torn tail
    lines = open(path).read().splitlines()
    lines.insert(1, "not json at all")
    with open(path, "w") as fh:
        fh.write("\n".join(lines) + "\n")
    with pytest.raises(JournalCorrupt):
        read_journal(path)


def test_replay_state_folds_tokens_by_index_and_terminals():
    records = [
        {"record": REC_SUBMIT, "seq": 0, "request_id": "a",
         "dedupe_token": "da", "prompt": [1], "max_new_tokens": 4},
        {"record": REC_TOKENS, "seq": 1, "request_id": "a",
         "index": 0, "tokens": [10, 11]},
        # overlapping re-delivery (post-recovery re-stream): idempotent
        {"record": REC_TOKENS, "seq": 2, "request_id": "a",
         "index": 1, "tokens": [11, 12]},
        {"record": REC_SUBMIT, "seq": 3, "request_id": "b",
         "dedupe_token": "db", "prompt": [2], "max_new_tokens": 4},
        {"record": REC_TERMINAL, "seq": 4, "request_id": "a",
         "status": "finished", "finish_reason": "length"},
    ]
    state = replay_state(records)
    assert state.entries["a"].tokens == [10, 11, 12]
    assert not state.entries["a"].unfinished
    assert [e.request_id for e in state.unfinished] == ["b"]
    assert state.dedupe == {"da": "a", "db": "b"}
    assert state.next_seq == 5
    assert not state.clean_shutdown


# -- crash + replay recovery (the tentpole contract) ------------------------


def test_crash_replay_recovers_unfinished_bitwise(env, tmp_path):
    """kill -9 simulation mid-stream: the restarted daemon re-admits
    every accepted-but-unfinished request from the journal with its
    durable prefix forced, finishes them, and the full streams equal
    the never-crashed greedy reference bitwise.  Dedupe-token retries
    after the crash return the SAME records — no duplicate admission,
    no duplicate completion, nothing lost."""
    _, _, _, prompts, refs = env
    path = tmp_path / "j.jsonl"
    d1 = _daemon(env, path)
    for i in range(3):
        rec = d1.submit(
            Request(prompt=prompts[i], max_new_tokens=8,
                    request_id=f"r{i}"),
            dedupe_token=f"tok-{i}",
        )
        assert rec["status"] == "queued"
    for _ in range(5):
        d1.tick()
    partial = [len(d1.result(f"r{i}")["tokens"]) for i in range(3)]
    assert any(0 < n < 8 for n in partial), partial  # crash lands mid-stream
    d1.journal.abort()  # the kill -9: no shutdown record, no final sync

    d2 = _daemon(env, path)
    st = load_state(str(path))
    assert st.recoveries == 1  # the restart journaled its replay
    # idempotent client retry: same dedupe token, same record, and the
    # journal must NOT grow a second submit for it
    submits_before = sum(
        1 for r in read_journal(str(path))[0]
        if r["record"] == REC_SUBMIT
    )
    dup = d2.submit(
        Request(prompt=prompts[0], max_new_tokens=8),
        dedupe_token="tok-0",
    )
    assert dup["request_id"] == "r0" and dup["recovered"]
    assert int(d2.registry.counter("daemon_dedupe_hits_total").value) == 1
    for _ in range(60):
        if all(
            d2.result(f"r{i}")["status"] == "finished" for i in range(3)
        ):
            break
        d2.tick()
    for i in range(3):
        rec = d2.result(f"r{i}")
        assert rec["status"] == "finished"
        assert rec["tokens"] == refs[i]  # bitwise through the crash
    submits_after = sum(
        1 for r in read_journal(str(path))[0]
        if r["record"] == REC_SUBMIT
    )
    assert submits_after == submits_before  # zero duplicate admissions
    assert d2.frontend._reserved == 0
    pool = d2.frontend.replicas[0].engine.pool
    assert pool.n_free == pool.n_slots  # zero leaked reservations
    assert int(
        d2.registry.counter("daemon_recovered_requests_total").value
    ) == 3


def test_recovery_synthesizes_lost_terminals(env, tmp_path):
    """A crash can eat the terminal record after the last token was
    durable: recovery must close such requests (length / delivered-EOS)
    instead of re-admitting and over-generating."""
    path = str(tmp_path / "j.jsonl")
    w = JournalWriter(path, FakeClock())
    w.append({"record": REC_SUBMIT, "request_id": "full",
              "dedupe_token": "tf", "prompt": [3, 4],
              "max_new_tokens": 3})
    w.append({"record": REC_TOKENS, "request_id": "full", "index": 0,
              "tokens": [7, 8, 9]})  # budget exhausted, terminal lost
    w.append({"record": REC_SUBMIT, "request_id": "eos",
              "dedupe_token": "te", "prompt": [3, 4],
              "max_new_tokens": 6, "eos_token_id": 42})
    w.append({"record": REC_TOKENS, "request_id": "eos", "index": 0,
              "tokens": [7, 42]})  # EOS delivered, terminal lost
    w.abort()
    d = _daemon(env, path)
    full, eos = d.result("full"), d.result("eos")
    assert full["status"] == "finished"
    assert full["finish_reason"] == "length"
    assert eos["status"] == "finished" and eos["finish_reason"] == "eos"
    assert not d.frontend.has_work()  # nothing re-admitted
    assert int(
        d.registry.counter("daemon_recovered_completions_total").value
    ) == 2
    # and the synthesized terminals are durable for the NEXT restart
    st = load_state(path)
    assert not st.unfinished


def test_recovery_rejection_is_loud_and_typed(env, tmp_path):
    """A replayed request the restarted config can no longer admit
    terminates REJECTED with the frontend's typed reason — journaled —
    never silently dropped."""
    _, _, _, prompts, _ = env
    path = tmp_path / "j.jsonl"
    d1 = _daemon(env, path)
    d1.submit(Request(prompt=prompts[0], max_new_tokens=8,
                      request_id="big"), dedupe_token="tb")
    d1.tick()
    d1.journal.abort()
    # restart with a token budget too small for the replay
    d2 = _daemon(env, path, fe_kw={"max_inflight_tokens": 4})
    rec = d2.result("big")
    assert rec["status"] == REJECTED
    assert rec["finish_reason"] == "token_budget"
    terminals = [
        r for r in read_journal(str(path))[0]
        if r["record"] == REC_TERMINAL and r["request_id"] == "big"
    ]
    assert len(terminals) == 1 and terminals[0]["status"] == REJECTED


# -- dedupe idempotence ------------------------------------------------------


def test_dedupe_completed_request_returns_cached_result(env, tmp_path):
    _, _, _, prompts, refs = env
    d = _daemon(env, tmp_path / "j.jsonl")
    d.submit(Request(prompt=prompts[0], max_new_tokens=8,
                     request_id="x", dedupe_token="same"))
    for _ in range(30):
        if d.result("x")["status"] == "finished":
            break
        d.tick()
    accepted = int(d.registry.counter("daemon_accepted_total").value)
    again = d.submit(Request(prompt=prompts[0], max_new_tokens=8,
                             dedupe_token="same"))
    assert again["request_id"] == "x" and again["tokens"] == refs[0]
    assert int(
        d.registry.counter("daemon_accepted_total").value
    ) == accepted  # no second admission
    assert not d.frontend.has_work()


# -- drain / shutdown contract ----------------------------------------------


def test_sigterm_drain_finishes_inflight_rejects_new_exits_clean(
    env, tmp_path
):
    _, _, _, prompts, refs = env
    path = tmp_path / "j.jsonl"
    d = _daemon(env, path)
    d.submit(Request(prompt=prompts[0], max_new_tokens=8,
                     request_id="r0"))
    d.tick()
    d.request_drain()  # SIGTERM equivalent
    rc = d.run(max_ticks=100)
    assert rc == EXIT_CLEAN
    assert d.result("r0")["tokens"] == refs[0]  # in-flight finished
    # late submission refused typed `draining`
    late = d.submit(Request(prompt=prompts[1], max_new_tokens=4))
    assert late["status"] == REJECTED
    assert late["finish_reason"] == REJECT_DRAINING
    records, torn = read_journal(str(path))
    assert torn == 0
    assert records[-1]["record"] == REC_SHUTDOWN and records[-1]["clean"]
    st = load_state(str(path))
    assert st.clean_shutdown and not st.unfinished


def test_second_sigterm_forces_fast_shutdown_journal_recovers(
    env, tmp_path
):
    """SIGTERM twice = fast shutdown NOW: exit code 1, shutdown record
    not clean, and the open request survives into the next recovery."""
    _, _, _, prompts, refs = env
    path = tmp_path / "j.jsonl"
    d = _daemon(env, path)
    d.submit(Request(prompt=prompts[0], max_new_tokens=8,
                     request_id="r0"), dedupe_token="t0")
    d.tick()
    d.request_drain()
    d.request_drain()  # the second TERM
    rc = d.run(max_ticks=100)
    assert rc == EXIT_FORCED
    records, _ = read_journal(str(path))
    assert records[-1]["record"] == REC_SHUTDOWN
    assert not records[-1]["clean"]
    d2 = _daemon(env, path)
    for _ in range(40):
        if d2.result("r0")["status"] == "finished":
            break
        d2.tick()
    assert d2.result("r0")["tokens"] == refs[0]


def test_blown_grace_window_forces_shutdown(env, tmp_path):
    """A drain that cannot finish inside grace_seconds exits forced
    instead of hanging — the journal carries the remainder."""
    _, _, _, prompts, _ = env
    clk = FakeClock()
    d = _daemon(env, tmp_path / "j.jsonl", clock=clk, grace_seconds=5.0)
    d.submit(Request(prompt=prompts[0], max_new_tokens=8,
                     request_id="r0"))
    d.request_drain()
    d._begin_drain()
    clk.t += 10.0  # wall time blows straight through the grace window
    rc = d.run(max_ticks=3)
    assert rc == EXIT_FORCED
    st = load_state(str(tmp_path / "j.jsonl"))
    assert not st.clean_shutdown


# -- frontend journal hooks --------------------------------------------------


def test_frontend_journal_hook_fires_on_lifecycle_points(env, tmp_path):
    _, _, _, prompts, _ = env
    notes = []
    d = _daemon(env, tmp_path / "j.jsonl")
    d.frontend.set_journal(lambda kind, payload: notes.append(kind))
    d.frontend.submit(Request(prompt=prompts[0], max_new_tokens=2))
    assert "submit_accepted" in notes
    d.frontend.run(max_ticks=30)
    assert "terminal" in notes
    d.frontend.drain()
    assert "drain_begin" in notes


# -- HTTP + SSE face ---------------------------------------------------------


def test_http_endpoints_and_sse_stream(env, tmp_path):
    """The stdlib network face against a live wall-clock daemon: submit
    over HTTP (journal-durable), SSE stream to completion, healthz
    flip on drain, statez leak fields, cancel route."""
    import urllib.request

    _, _, _, prompts, refs = env
    d = ServingDaemon(
        _factory(env), str(tmp_path / "j.jsonl"),
        clock=WallClock(),
        config=DaemonConfig(fsync_batch=4, grace_seconds=30.0),
    )
    server = DaemonHTTPServer(d).start()
    rc_box = []
    pump = threading.Thread(
        target=lambda: rc_box.append(d.run()), daemon=True
    )
    pump.start()
    base = f"http://127.0.0.1:{server.port}"

    def call(method, path, body=None):
        data = None if body is None else json.dumps(body).encode()
        req = urllib.request.Request(base + path, data=data, method=method)
        if data is not None:
            req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=60) as resp:
                return resp.status, json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as exc:
            return exc.code, json.loads(exc.read() or b"{}")

    try:
        code, health = call("GET", "/healthz")
        assert code == 200 and health["ok"]
        code, rec = call("POST", "/v1/submit", {
            "prompt": prompts[0], "max_new_tokens": 8,
            "dedupe_token": "http-0",
        })
        assert code == 200
        rid = rec["request_id"]
        # malformed body is a 400, not a daemon error
        code, _ = call("POST", "/v1/submit", {"prompt": "nope"})
        assert code == 400
        # SSE: tokens then the finished event
        with urllib.request.urlopen(
            base + f"/v1/stream/{rid}", timeout=60
        ) as resp:
            payload = resp.read()
        events = [
            json.loads(line[len(b"data: "):])
            for line in payload.split(b"\n")
            if line.startswith(b"data: ")
        ]
        toks = [e["token"] for e in events if "token" in e]
        assert toks == refs[0]
        assert events[-1]["finished"]
        assert events[-1]["finish_reason"] == "length"
        # cancel an unknown id 404s; a live one cancels
        code, _ = call("POST", "/v1/cancel/nope")
        assert code == 404
        code, rec2 = call("POST", "/v1/submit", {
            "prompt": prompts[1], "max_new_tokens": 8,
        })
        assert code == 200
        code, _ = call("POST", f"/v1/cancel/{rec2['request_id']}")
        assert code == 200
        code, state = call("GET", "/statez")
        assert code == 200
        assert "inflight_tokens" in state["cluster"]
        # drain: healthz flips 503 for the balancer, daemon exits 0
        d.request_drain()
        pump.join(timeout=60)
        assert rc_box == [EXIT_CLEAN]
        code, health = call("GET", "/healthz")
        assert code == 503
    finally:
        server.stop()


# -- the real-subprocess smoke (also scripts/check_all.py's gate) -----------


def test_daemon_smoke_subprocess():
    """start -> HTTP submit -> SSE replay -> SIGTERM -> exit 0 with a
    clean journal, as one REAL process receiving real signals.  This is
    exactly what ``check_all``'s ``check_daemon`` runtime gate runs."""
    sys.path.insert(0, os.path.join(REPO_ROOT, "scripts"))
    try:
        import check_daemon
    finally:
        sys.path.pop(0)
    problems = check_daemon.check_paths()
    assert problems == [], "\n".join(problems)


def test_sighup_reload_journals_typed_decision(env, tmp_path):
    """SIGHUP's reload flows are journaled as typed DECISION records:
    no reload_path configured, unreadable spec, and a spec without a
    checkpoint_dir each refuse loudly instead of killing the pump."""
    # no reload_path
    d = _daemon(env, tmp_path / "a.jsonl")
    d.request_reload()
    d.run(max_ticks=1)
    recs, _ = read_journal(str(tmp_path / "a.jsonl"))
    decisions = [r for r in recs if r["record"] == "decision"]
    assert decisions and decisions[-1]["verdict"] == "no_reload_path"
    # unreadable spec file
    d2 = _daemon(env, tmp_path / "b.jsonl",
                 reload_path=str(tmp_path / "missing.json"))
    d2.request_reload()
    d2.run(max_ticks=1)
    recs, _ = read_journal(str(tmp_path / "b.jsonl"))
    assert [r for r in recs if r["record"] == "decision"][-1][
        "verdict"
    ] == "unreadable"
    # spec without a checkpoint_dir
    spec = tmp_path / "spec.json"
    spec.write_text("{}")
    d3 = _daemon(env, tmp_path / "c.jsonl", reload_path=str(spec))
    d3.request_reload()
    d3.run(max_ticks=1)
    recs, _ = read_journal(str(tmp_path / "c.jsonl"))
    assert [r for r in recs if r["record"] == "decision"][-1][
        "verdict"
    ] == "no_checkpoint_dir"
    assert int(
        d3.registry.counter("daemon_signals_total", signal="hup").value
    ) == 1


def test_torn_tail_truncated_before_reopen_double_restart(env, tmp_path):
    """A writer reopening after a torn write must TRUNCATE the fragment
    — appending onto it would weld the next record into mid-file
    garbage and brick the journal (JournalCorrupt) on the SECOND
    restart.  Two full crash+recover cycles over a torn tail must both
    succeed, with nothing durable lost."""
    _, _, _, prompts, refs = env
    path = tmp_path / "j.jsonl"
    d1 = _daemon(env, path)
    d1.submit(Request(prompt=prompts[0], max_new_tokens=8,
                      request_id="r0"), dedupe_token="t0")
    for _ in range(3):
        d1.tick()
    d1.journal.abort()
    with open(path, "a") as fh:  # the write the SIGKILL cut mid-record
        fh.write('{"record": "tokens", "request_id": "r0", "toke')
    # restart 1: fragment dropped BEFORE reading (the daemon truncates
    # ahead of load_state so recovery acts on exactly what stays
    # durable), recovery replays, MORE records append
    d2 = _daemon(env, path)
    # the fragment is GONE (not merely tolerated): the whole file —
    # including the records recovery just appended — parses torn-free
    assert read_journal(str(path))[1] == 0
    for _ in range(3):
        d2.tick()
    d2.journal.abort()  # crash again mid-stream
    # restart 2: the journal must still parse (no mid-file corruption)
    d3 = _daemon(env, path)
    for _ in range(40):
        if d3.result("r0")["status"] == "finished":
            break
        d3.tick()
    assert d3.result("r0")["tokens"] == refs[0]
    records, torn = read_journal(str(path))
    assert torn == 0  # every surviving record is parseable


def test_completed_retention_bounds_memory(env, tmp_path):
    """Terminal records past ``completed_retention`` evict oldest-first
    (with their dedupe tokens): daemon memory is bounded at any uptime,
    the open count stays exact, and an evicted token re-admits as a
    fresh request instead of replaying a record that no longer exists."""
    _, _, _, prompts, _ = env
    d = _daemon(env, tmp_path / "j.jsonl", completed_retention=2)
    rids = []
    for i in range(4):
        rec = d.submit(
            Request(prompt=prompts[i % len(prompts)], max_new_tokens=2,
                    request_id=f"r{i}"),
            dedupe_token=f"t{i}",
        )
        rids.append(rec["request_id"])
        for _ in range(20):
            if d.result(f"r{i}") is None or (
                d.result(f"r{i}")["status"] == "finished"
            ):
                break
            d.tick()
    assert len(d._requests) == 2  # bounded: only the newest two remain
    assert d.result("r0") is None and d.result("r3") is not None
    assert "t0" not in d._dedupe and "t3" in d._dedupe
    assert d._open_count == 0
    # an evicted dedupe token is a NEW admission now (fresh request id)
    again = d.submit(
        Request(prompt=prompts[0], max_new_tokens=2), dedupe_token="t0"
    )
    assert again["request_id"] != "r0"
