"""Stdlib-only streaming network frontend for the serving daemon.

One :class:`ThreadingHTTPServer` over the daemon's locked surface —
handler threads call ``daemon.submit/cancel/result/subscribe`` (which
serialize on the daemon lock) while the tick pump runs in the main
thread.  No framework, no dependency: the container bakes nothing
extra, and the protocol is plain HTTP + Server-Sent Events.

Endpoints (docs/13_daemon.md is the reference):

- ``POST /v1/submit`` — JSON body ``{"prompt": [ids], "max_new_tokens",
  "dedupe_token", "priority", "deadline", "client_id", "temperature",
  "top_k", "top_p", "eos_token_id"}``.  200 with the request record on
  accept (the submit is journal-durable before the response); typed
  rejections map to 503 (``draining`` / ``degraded`` /
  ``journal_error`` — route elsewhere) / 429 (everything else) with
  the same record shape.  Bodies over ``max_body_bytes`` are refused
  413 WITHOUT reading them (a proxy misconfiguration or a hostile
  client cannot make a handler thread buffer an unbounded payload).
  Dedupe-token replays return the existing record — acknowledged work
  is idempotent across client retries and daemon restarts.
- ``GET /v1/stream/<id>`` — SSE: every already-delivered token replays
  first (``index`` continues across daemon restarts), then live events;
  the final event carries ``finished`` + the typed ``finish_reason``.
  A client disconnect mid-stream CANCELS the request in the cluster
  (``reason="disconnected"``) — a reply nobody is reading is wasted
  compute, exactly the deadline-cancel philosophy.
- ``POST /v1/cancel/<id>`` — client cancel (200 / 404).
- ``GET /v1/result/<id>`` — the current record snapshot (200 / 404).
- ``GET /healthz`` — 200 while serving, 503 once draining (load
  balancers pull the replica out during the SIGTERM grace window).
- ``GET /statez`` — frontend summary + daemon status JSON (the bench's
  leak assertions read ``inflight_tokens`` and per-replica pools here).
- ``GET /metricsz`` — Prometheus text exposition of the shared
  registry (``daemon_*``, ``cluster_*`` and per-engine series).
- ``GET /v1/tracez[?trace_id=...]`` — this process's spooled span
  records (docs/11_observability.md): what ``scripts/trace_stitch.py``
  and the fleet router's ``/v1/requestz`` collect and stitch.
"""

from __future__ import annotations

import json
import queue as _queue
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpu_parallel.daemon.daemon import REJECT_DEGRADED, REJECT_JOURNAL
from tpu_parallel.fleet.roles import REJECT_ROLE
from tpu_parallel.obs.exporters import prometheus_text
from tpu_parallel.obs.tracer import TRACE_HEADER, TraceContext
from tpu_parallel.serving.kv_wire import (
    CHUNK_MAGIC,
    SEGMENT_OVERHEAD,
    WIRE_SEGMENT,
    ChunkReassembler,
    WireFormatError,
    decode_exports,
    encode_exports,
    is_chunk_stream,
    segment_claimed_length,
)
from tpu_parallel.serving.request import (
    REJECT_DRAINING,
    REJECTED,
    Request,
    SamplingParams,
)

# SSE subscriber poll period: how often a quiet stream wakes to emit a
# keep-alive comment — which keeps idle streams alive through proxies
# that kill silent connections, AND bounds how long a disconnected
# client can hold a stream before the write fails and cancels the
# request (the default; DaemonHTTPServer's ``sse_keepalive_seconds``
# overrides per server)
_STREAM_POLL_SECONDS = 2.0

# submit-body cap default: prompts are token-id lists, so even a
# seq_len-8k prompt with maximal ids is far below this — anything
# bigger is a misdirected upload, not a request
_MAX_BODY_BYTES = 1 << 20

# peer-KV import cap: KV payloads are raw block tensors, orders of
# magnitude above any submit body, but still bounded — a peer shipping
# more than this per transfer should chunk its exports
_MAX_KV_BODY_BYTES = 1 << 27

# typed finish_reasons that map to 503 (route elsewhere / retry later)
# rather than 429 (client-side backpressure).  ``role`` is here because
# a decode-role daemon refusing fresh work is a routing fact, not
# client backpressure: the fleet router excludes the peer and tries the
# next ring successor without charging the breaker.
_UNAVAILABLE_REASONS = frozenset(
    {REJECT_DRAINING, REJECT_DEGRADED, REJECT_JOURNAL, REJECT_ROLE}
)


def build_request(body: dict) -> Request:
    """Validate a submit payload into a :class:`Request` (ValueError on
    a malformed body — the handler maps it to 400)."""
    prompt = body.get("prompt")
    if not isinstance(prompt, list) or not prompt:
        raise ValueError("'prompt' must be a non-empty list of token ids")
    if not all(isinstance(t, int) for t in prompt):
        raise ValueError("'prompt' must contain integer token ids")
    sampling = SamplingParams(
        temperature=float(body.get("temperature", 0.0)),
        top_k=int(body.get("top_k", 0)),
        top_p=float(body.get("top_p", 0.0)),
    )
    deadline = body.get("deadline")
    return Request(
        prompt=prompt,
        max_new_tokens=int(body.get("max_new_tokens", 32)),
        sampling=sampling,
        eos_token_id=body.get("eos_token_id"),
        client_id=body.get("client_id"),
        priority=int(body.get("priority", 0)),
        deadline=None if deadline is None else float(deadline),
        dedupe_token=body.get("dedupe_token"),
    )


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    daemon = None  # set by DaemonHTTPServer
    max_body_bytes = _MAX_BODY_BYTES
    max_kv_body_bytes = _MAX_KV_BODY_BYTES
    keepalive_seconds = _STREAM_POLL_SECONDS

    # -- plumbing ----------------------------------------------------------

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _json(self, code: int, payload: dict) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _text(self, code: int, text: str, ctype: str) -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _read_body(self) -> Optional[dict]:
        try:
            length = int(self.headers.get("Content-Length", 0))
            raw = self.rfile.read(length) if length else b"{}"
            body = json.loads(raw or b"{}")
        except (ValueError, OSError):
            return None
        return body if isinstance(body, dict) else None

    # -- routes ------------------------------------------------------------

    def do_POST(self):
        d = self.daemon
        if self.path == "/v1/submit":
            try:
                length = int(self.headers.get("Content-Length", 0))
            except ValueError:
                length = -1
            if length < 0 or length > self.max_body_bytes:
                # refused WITHOUT reading the body: the unread bytes
                # mean this connection cannot be reused
                self.close_connection = True
                return self._json(413, {
                    "error": (
                        f"body of {length} bytes exceeds the "
                        f"{self.max_body_bytes}-byte submit limit"
                    ),
                })
            body = self._read_body()
            if body is None:
                return self._json(400, {"error": "malformed JSON body"})
            try:
                req = build_request(body)
            except (ValueError, TypeError) as exc:
                return self._json(400, {"error": str(exc)})
            # adopt the caller's trace context (the router forks one
            # per wire crossing); garbage parses to None = untraced
            ctx = TraceContext.parse(self.headers.get(TRACE_HEADER))
            record = d.submit(
                req,
                dedupe_token=body.get("dedupe_token"),
                phase=body.get("phase"),
                trace=ctx,
            )
            # ``ts`` is this process's clock at response time: the
            # router pairs it with its send/recv stamps to estimate the
            # cross-host clock offset the stitcher aligns with
            record = dict(record)
            record["ts"] = d.clock()
            if record["status"] == REJECTED:
                code = (
                    503
                    if record["finish_reason"] in _UNAVAILABLE_REASONS
                    else 429
                )
                return self._json(code, record)
            return self._json(200, record)
        if self.path.startswith("/v1/cancel/"):
            rid = self.path[len("/v1/cancel/"):]
            if d.cancel(rid, reason="cancelled"):
                return self._json(200, {"cancelled": rid})
            return self._json(404, {"error": f"unknown/done request {rid}"})
        if self.path == "/v1/kv/import":
            return self._kv_import()
        return self._json(404, {"error": f"no route {self.path}"})

    def _read_exact(self, n: int) -> bytes:
        """Read exactly ``n`` body bytes or raise OSError — stdlib
        ``rfile.read`` may return short on a socket boundary."""
        chunks = []
        while n > 0:
            piece = self.rfile.read(min(n, 1 << 16))
            if not piece:
                raise OSError("short read")
            chunks.append(piece)
            n -= len(piece)
        return b"".join(chunks)

    def _kv_import(self) -> None:
        """Peer KV landing, verdict counts out.  Two body shapes:

        - bare ``KVW1`` frame stream (warm-start / drain-forward):
          decoded whole, landed whole;
        - ``KVC1`` chunk stream (the disaggregation handoff hot path):
          segments are read off the socket one at a time and whole
          frames land AS THEY COMPLETE — blocks are already in the
          radix tree while later segments are still in flight
          (Mooncake-style overlap).

        Damage is a typed 400 either way — the refusal IS the
        response.  Frames that verified and landed before the damage
        stay landed (each frame is atomic and self-verifying), the
        damaged remainder never lands, and the refusing verdict tells
        the router to fall back rather than trust the transfer."""
        d = self.daemon
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = -1
        if length < 0 or length > self.max_kv_body_bytes:
            self.close_connection = True
            return self._json(413, {
                "error": (
                    f"KV payload of {length} bytes exceeds the "
                    f"{self.max_kv_body_bytes}-byte import limit"
                ),
            })

        def refuse(exc: WireFormatError, verdicts=None) -> None:
            d.registry.counter(
                "daemon_kv_wire_refusals_total", reason=exc.reason
            ).inc()
            # unread body bytes may remain after an early refusal
            self.close_connection = True
            payload = {"error": str(exc), "reason": exc.reason}
            if verdicts:
                payload["verdicts"] = verdicts
            return self._json(400, payload)

        try:
            head = self._read_exact(min(length, len(CHUNK_MAGIC)))
        except OSError:
            return self._json(400, {"error": "truncated KV payload"})

        if not is_chunk_stream(head):
            try:
                raw = head + self._read_exact(length - len(head))
            except OSError:
                return self._json(400, {"error": "truncated KV payload"})
            try:
                exports = decode_exports(raw)
            except WireFormatError as exc:
                return refuse(exc)
            verdicts = d.import_peer_kv(exports)
            return self._json(200, {
                "verdicts": verdicts,
                "imported": verdicts.get("imported", 0),
            })

        # chunk stream: feed segment by segment, landing early
        asm = ChunkReassembler()
        verdicts: dict = {}
        segments = 0
        consumed = len(head)

        def land(exports) -> None:
            if not exports:
                return
            for verdict, n in d.import_peer_kv(exports).items():
                verdicts[verdict] = verdicts.get(verdict, 0) + n

        try:
            # every read is bounded by the declared Content-Length so a
            # lying prelude can never block the handler on the socket
            if length < SEGMENT_OVERHEAD:
                raise WireFormatError(
                    WIRE_SEGMENT,
                    f"{length}-byte body, segment prelude needs "
                    f"{SEGMENT_OVERHEAD}",
                )
            prelude = head + self._read_exact(SEGMENT_OVERHEAD - len(head))
            consumed = SEGMENT_OVERHEAD
            while True:
                slen = segment_claimed_length(prelude)
                if slen > length - consumed:
                    raise WireFormatError(
                        WIRE_SEGMENT,
                        f"segment claims {slen} payload bytes, "
                        f"{length - consumed} remain in the body",
                    )
                payload = self._read_exact(slen)
                consumed += slen
                asm.feed(prelude + payload)
                segments += 1
                land(asm.drain())
                if asm.finished:
                    if consumed != length:
                        raise WireFormatError(
                            WIRE_SEGMENT,
                            f"{length - consumed} body bytes after "
                            "the terminal segment",
                        )
                    break
                if consumed >= length:
                    asm.close()  # unterminated: typed refusal
                    break
                if length - consumed < SEGMENT_OVERHEAD:
                    raise WireFormatError(
                        WIRE_SEGMENT,
                        f"{length - consumed} trailing body bytes, "
                        f"segment prelude needs {SEGMENT_OVERHEAD}",
                    )
                prelude = self._read_exact(SEGMENT_OVERHEAD)
                consumed += SEGMENT_OVERHEAD
        except WireFormatError as exc:
            return refuse(exc, verdicts)
        except OSError:
            # the sender died mid-transfer: surface it as the same
            # typed refusal the unterminated-stream close gives
            try:
                asm.close()
            except WireFormatError as exc:
                return refuse(exc, verdicts)
            return self._json(400, {"error": "truncated KV payload"})
        return self._json(200, {
            "verdicts": verdicts,
            "imported": verdicts.get("imported", 0),
            "segments": segments,
        })

    def do_GET(self):
        d = self.daemon
        if self.path == "/healthz":
            status = d.status()
            unavailable = (
                status["draining"]
                or status["stopped"]
                or status["degraded_reason"] is not None
            )
            code = 503 if unavailable else 200
            return self._json(code, {
                "ok": code == 200,
                "role": status["role"],
                "draining": status["draining"],
                "degraded_reason": status["degraded_reason"],
                "ticks": status["ticks"],
                "recoveries": status["recoveries"],
                # KV-tier occupancy: the fleet router and the
                # autopilot's role lever read pressure here instead of
                # probing blind
                "kv": d.kv_occupancy(),
                # this process's clock, for the router's probe-driven
                # clock-offset estimation (obs/stitch.py aligns on it)
                "ts": d.clock(),
            })
        if self.path == "/statez":
            return self._json(200, {
                "daemon": d.status(),
                "cluster": d.frontend.summary(),
            })
        if self.path == "/metricsz":
            return self._text(
                200, prometheus_text(d.registry),
                "text/plain; version=0.0.4",
            )
        parts = urllib.parse.urlsplit(self.path)
        if parts.path == "/v1/tracez":
            qs = urllib.parse.parse_qs(parts.query)
            trace_id = qs.get("trace_id", [None])[0]
            return self._json(200, d.trace_payload(trace_id))
        if parts.path == "/v1/kv/export":
            max_blocks = 16
            qs = urllib.parse.parse_qs(parts.query)
            if "max_blocks" in qs:
                try:
                    max_blocks = int(qs["max_blocks"][-1])
                except ValueError:
                    return self._json(400, {
                        "error": "max_blocks must be an integer",
                    })
                if max_blocks < 0:
                    return self._json(400, {
                        "error": "max_blocks must be >= 0",
                    })
            if "request_id" in qs:
                # per-request export: the prefill→decode handoff donor
                # leg (one live request's written prefix, not the hot
                # radix snapshot)
                exports = d.export_request_kv(qs["request_id"][-1])
            else:
                exports = d.export_hot_kv(max_blocks=max_blocks)
            blob = encode_exports(exports)
            self.send_response(200)
            self.send_header("Content-Type", "application/octet-stream")
            self.send_header("Content-Length", str(len(blob)))
            self.end_headers()
            self.wfile.write(blob)
            return
        if self.path.startswith("/v1/result/"):
            rid = self.path[len("/v1/result/"):]
            record = d.result(rid)
            if record is None:
                return self._json(404, {"error": f"unknown request {rid}"})
            return self._json(200, record)
        if self.path.startswith("/v1/stream/"):
            return self._stream(self.path[len("/v1/stream/"):])
        return self._json(404, {"error": f"no route {self.path}"})

    # -- SSE ---------------------------------------------------------------

    def _sse(self, payload: dict) -> None:
        self.wfile.write(f"data: {json.dumps(payload)}\n\n".encode())
        self.wfile.flush()

    def _stream(self, rid: str) -> None:
        d = self.daemon
        snapshot, q = d.subscribe(rid)
        if snapshot is None:
            return self._json(404, {"error": f"unknown request {rid}"})
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        try:
            for i, tok in enumerate(snapshot["tokens"]):
                self._sse({"request_id": rid, "token": tok, "index": i})
            if q is None:  # already terminal: replay the ending and stop
                self._sse({
                    "request_id": rid, "finished": True,
                    "status": snapshot["status"],
                    "finish_reason": snapshot["finish_reason"],
                })
                return
            while True:
                try:
                    ev = q.get(timeout=self.keepalive_seconds)
                except _queue.Empty:
                    # heartbeat: also probes whether the client is gone
                    self.wfile.write(b": keepalive\n\n")
                    self.wfile.flush()
                    continue
                if ev.token >= 0:
                    self._sse({
                        "request_id": rid, "token": ev.token,
                        "index": ev.index,
                    })
                if ev.finished:
                    record = d.result(rid) or {}
                    self._sse({
                        "request_id": rid, "finished": True,
                        "status": record.get("status"),
                        "finish_reason": ev.finish_reason,
                    })
                    return
        except (BrokenPipeError, ConnectionResetError, OSError):
            # the client hung up mid-stream: stop generating for it
            d.cancel(rid, reason="disconnected")
        finally:
            if q is not None:
                d.unsubscribe(rid, q)


class DaemonHTTPServer:
    """The daemon's network face: a threading HTTP server bound to
    ``host:port`` (port 0 = ephemeral; read ``.port`` after start),
    served from a background thread so the daemon's ``run()`` pump owns
    the main thread (where the signal handlers live)."""

    def __init__(
        self,
        daemon,
        host: str = "127.0.0.1",
        port: int = 0,
        max_body_bytes: int = _MAX_BODY_BYTES,
        max_kv_body_bytes: int = _MAX_KV_BODY_BYTES,
        sse_keepalive_seconds: float = _STREAM_POLL_SECONDS,
    ):
        if max_body_bytes < 1:
            raise ValueError(f"max_body_bytes={max_body_bytes} < 1")
        if max_kv_body_bytes < 1:
            raise ValueError(f"max_kv_body_bytes={max_kv_body_bytes} < 1")
        if sse_keepalive_seconds <= 0:
            raise ValueError(
                f"sse_keepalive_seconds={sse_keepalive_seconds} <= 0"
            )
        handler = type("_BoundHandler", (_Handler,), {
            "daemon": daemon,
            "max_body_bytes": max_body_bytes,
            "max_kv_body_bytes": max_kv_body_bytes,
            "keepalive_seconds": sse_keepalive_seconds,
        })
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.host, self.port = self.httpd.server_address[:2]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "DaemonHTTPServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, kwargs={"poll_interval": 0.1},
            daemon=True,
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
