"""Profiling, timing, and MFU accounting.

The reference's entire observability story is ``jax.named_scope`` labels
(SURVEY.md §5, tracing row).  This module keeps those (every collective in
the framework is scoped) and adds what the reference lacked: a
``jax.profiler`` trace context for Perfetto/XProf, a ``block_until_ready``
timing harness, and model-FLOPs-utilization math for the benchmark harness.
"""

from __future__ import annotations

import contextlib
import time
from typing import Callable, Dict, Optional

import jax

# Dense bf16 peak FLOPs/s per chip.  Matching is SUBSTRING-in-device_kind,
# so more-specific kinds must precede their prefixes ("tpu v4i" before
# "tpu v4", "tpu v5p" before "tpu v5") — dicts iterate in insertion order.
PEAK_FLOPS_BY_KIND = {
    "tpu v5 lite": 197e12,
    "tpu v5litepod": 197e12,
    "tpu v5p": 459e12,
    "tpu v5": 197e12,
    "tpu v4i": 138e12,
    "tpu v4": 275e12,
    "tpu v6 lite": 918e12,
    "tpu v6": 918e12,
}


def peak_flops(device=None) -> Optional[float]:
    """Peak bf16 FLOPs/s for ``device`` (None if unknown, e.g. CPU)."""
    device = device or jax.devices()[0]
    kind = getattr(device, "device_kind", "").lower()
    for key, val in PEAK_FLOPS_BY_KIND.items():
        if key in kind:
            return val
    return None


def transformer_flops_per_token(cfg) -> float:
    """Training FLOPs per token: 6*N for the matmul params + attention term.

    Standard PaLM-appendix accounting: 6 FLOPs per parameter per token
    (fwd 2 + bwd 4) over matmul-participating params, plus
    ``12 * L * d * T`` for the T-length causal attention (QK^T, softmax*V,
    fwd+bwd).  Embedding lookups are excluded (gather, not matmul); the
    untied lm_head matmul is included.

    MoE configs use ACTIVE-param accounting (the standard MoE MFU
    convention): each token runs ``moe_top_k`` experts' FFN matmuls plus
    the router projection — FLOPs scale with k, not with the total expert
    count, so a Switch model's MFU reads against the same roofline as its
    dense-equivalent.  Under expert-choice routing every expert fills its
    capacity by construction, so the per-token average is
    ``moe_capacity_factor`` experts (1.25 by default), not 1 — the FFN
    term scales by the capacity factor or expert-choice MFU reads ~25%
    high (ADVICE.md round-5 finding).
    """
    mlp_term = 2 * cfg.mlp_ratio * cfg.d_model**2
    moe_experts = getattr(cfg, "moe_experts", 0)
    if moe_experts:
        k = (
            cfg.moe_top_k
            if getattr(cfg, "moe_router", "topk") == "topk"
            else getattr(cfg, "moe_capacity_factor", 1.0)
        )
        mlp_term = k * mlp_term + cfg.d_model * moe_experts  # + router
    matmul_params = (
        cfg.vocab_size * cfg.d_model  # lm_head projection
        + cfg.n_layers * (4 * cfg.d_model**2 + mlp_term)
    )
    attn = 12 * cfg.n_layers * cfg.d_model * cfg.seq_len
    return 6 * matmul_params + attn


def mfu(tokens_per_sec_per_chip: float, cfg, device=None) -> Optional[float]:
    peak = peak_flops(device)
    if peak is None:
        return None
    return tokens_per_sec_per_chip * transformer_flops_per_token(cfg) / peak


@contextlib.contextmanager
def trace(logdir: str):
    """``with trace("/tmp/trace"):`` — dumps an XProf/Perfetto trace."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def sync(out) -> None:
    """Force completion of every array in the pytree ``out``.

    ``block_until_ready`` (all shards, all leaves) plus a device->host fetch
    of one element: on some transports (e.g. tunneled single-chip setups)
    ``block_until_ready`` can return before execution finishes; reading a
    value back cannot.  The fetch only runs on fully-addressable arrays
    (eager indexing of a multi-host global array would raise); on a pod,
    ``block_until_ready`` alone is the barrier.
    """
    out = jax.block_until_ready(out)
    leaves = [
        l
        for l in jax.tree_util.tree_leaves(out)
        if hasattr(l, "shape")
        and getattr(l, "size", 0) > 0
        and getattr(l, "is_fully_addressable", True)
    ]
    if leaves:
        leaf = leaves[0]
        jax.device_get(leaf[(0,) * leaf.ndim])


def timeit(
    fn: Callable, *args, iters: int = 10, warmup: int = 3, **kwargs
) -> float:
    """Mean seconds per call, with compile excluded and device-synced timing."""
    out = None
    for _ in range(warmup):
        out = fn(*args, **kwargs)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args, **kwargs)
    sync(out)
    return (time.perf_counter() - t0) / iters
