#!/bin/bash
# Round-4 sweep plan (VERDICT #2): finish the batch>=24 region the round-3
# HTTP 500s truncated, measure loss_chunk where it was built to matter, and
# diagnose the ~25ms layer-scan overhead by varying ONLY the remat policy
# under scan.  One process, combos serialized (single TPU claim; shared
# compile cache).  Appends JSON lines to SWEEP_r04.json.
#
# combo format: batch,remat,attn,minib,scan,chunk[,k=v...]
set -u
cd "$(dirname "$0")/.."
python scripts/sweep_bench.py \
  16,proj_attn,flash,1,0,0 \
  20,proj_attn,flash,1,0,0 \
  24,proj_attn,flash,1,0,0 \
  24,proj_attn,flash,1,0,512 \
  32,proj_attn,flash,1,0,512 \
  32,proj_attn,flash,2,0,0 \
  16,proj_attn,flash,1,0,0,flash_block_q=256,flash_block_k=256 \
  16,proj_attn,flash,1,1,0 \
  16,proj,flash,1,1,0 \
  16,full,flash,1,1,0 \
  16,1,flash,1,1,0 \
  | tee -a SWEEP_r04.json
