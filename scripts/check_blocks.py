"""Static check: every block-table mutation goes through the allocator.

The block-paged KV cache's single hard invariant is that the
:class:`~tpu_parallel.serving.cache_pool.BlockAllocator`'s refcounts and
the per-slot block tables never drift apart — a table entry pointing at a
block the allocator thinks is free is a use-after-free (the next owner's
writes scribble over live K/V), and a freed entry the allocator still
counts is a leak that starves admission.  The whole mutation surface is
therefore fenced inside ``tpu_parallel/serving/cache_pool.py``
(:class:`PagedCachePool`'s ``ensure_writable`` / ``map_prefix`` /
``release`` / ``snapshot_blocks`` / ``free_stored``); everyone else —
engine, prefix cache, benches, tests — READS tables and calls those
methods.

This makes the fence a tier-1 test
(``tests/test_paged_kv.py::test_block_table_mutations_fenced``) instead of
prose, exactly like ``check_clock.py`` / ``check_host_sync.py``: any
subscript STORE or in-place mutation whose target chain mentions a block
table (``...block_table[...] = ``, ``bt_dev.at[...]`` excluded — jax
functional updates return copies) outside ``cache_pool.py`` is flagged.
Reads (``table[slot]``, ``np.asarray(pool.block_table)``) are fine.

The KV hierarchy (``serving/kv_hierarchy.py`` — radix prefix tree +
host offload tier) and the cluster migration shim widened the fence:
those layers HOLD block references but must never mint or drop them
directly, so direct calls to the allocator's mutation methods
(``*.allocator.alloc()`` / ``.free()`` / ``.share()``) outside
``cache_pool.py`` are flagged too — references flow through the pool's
``pin_blocks`` / ``free_stored`` / ``snapshot_blocks`` /
``import_stored`` surface, which is what keeps refcount conservation
(Σ held refs == allocator refcounts) auditable in one module.  Reads
(``allocator.check()``, ``.refcount()``, ``.n_free``) stay legal
everywhere.

Usage: ``python scripts/check_blocks.py [paths...]`` — prints one
``file:line: <expr> ...`` per violation, exits nonzero on any.
"""

from __future__ import annotations

import ast
import os
import sys
from typing import List

# attribute/name spellings that identify a block-table object at a
# mutation site; matched against any link of the assignment target's
# attribute chain
TABLE_NAMES = frozenset({"block_table", "_block_table"})

DEFAULT_PATHS = (
    "tpu_parallel/serving",
    "tpu_parallel/cluster",
    "scripts",
)

# the single module allowed to mutate tables (the allocator's home)
ALLOWED_FILES = frozenset({"cache_pool.py"})

# allocator methods that mint/drop block references — callable only from
# the allowed module; everything else goes through the pool surface
ALLOCATOR_MUTATORS = frozenset({"alloc", "free", "share"})


def _chain_mentions_table(node: ast.AST) -> bool:
    """True when the expression chain under ``node`` names a block table
    (``pool.block_table``, ``self._block_table``, bare ``block_table``)."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Attribute) and sub.attr in TABLE_NAMES:
            return True
        if isinstance(sub, ast.Name) and sub.id in TABLE_NAMES:
            return True
    return False


def check_source(source: str, filename: str) -> List[str]:
    """Return ``file:line: message`` strings for every block-table
    subscript STORE (``table[...] = x``, ``table[...] += x``, ``del
    table[...]``) and every direct allocator-reference mutation
    (``*.allocator.alloc/free/share(...)``) outside the allocator
    module."""
    if os.path.basename(filename) in ALLOWED_FILES:
        return []
    tree = ast.parse(source, filename=filename)
    problems: List[str] = []

    def flag(node: ast.AST, what: str) -> None:
        problems.append(
            f"{filename}:{node.lineno}: {what} mutates a block table "
            "outside BlockAllocator (route it through PagedCachePool)"
        )

    for node in ast.walk(tree):
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            targets = list(node.targets)
        elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
            targets = [node.target]
        elif isinstance(node, ast.Delete):
            targets = list(node.targets)
        for tgt in targets:
            # only SUBSCRIPT stores are table mutations; rebinding a
            # local name (`table = pool.block_table[slot]`) is a read
            if isinstance(tgt, ast.Subscript) and _chain_mentions_table(
                tgt.value
            ):
                flag(tgt, ast.unparse(tgt))
        # reference minting/dropping: `<expr>.allocator.alloc()` etc. —
        # the radix/offload/migration layers HOLD references, only the
        # pool takes and releases them.  Reads (check / refcount /
        # n_free) are not in the mutator set and stay legal.
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr in ALLOCATOR_MUTATORS
            and isinstance(node.func.value, ast.Attribute)
            and node.func.value.attr == "allocator"
        ):
            problems.append(
                f"{filename}:{node.lineno}: {ast.unparse(node.func)}() "
                "takes/drops a block reference outside the pool (use "
                "pin_blocks / free_stored / snapshot_blocks / "
                "import_stored)"
            )
    return problems


def check_paths(paths=DEFAULT_PATHS) -> List[str]:
    problems: List[str] = []
    walked = False
    for path in paths:
        if os.path.isfile(path):
            files = [path]
        elif os.path.isdir(path):
            files = sorted(
                os.path.join(root, f)
                for root, _, names in os.walk(path)
                for f in names
                if f.endswith(".py")
            )
        else:
            raise FileNotFoundError(
                f"check_blocks: no such path {path!r} (a typo here would "
                "silently check nothing and pass)"
            )
        for fname in files:
            walked = True
            with open(fname) as fh:
                problems.extend(check_source(fh.read(), fname))
    if not walked:
        raise FileNotFoundError(
            f"check_blocks: paths {paths!r} contained no Python files"
        )
    return problems


def main(argv: List[str]) -> int:
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    os.chdir(repo_root)
    paths = argv[1:] or list(DEFAULT_PATHS)
    problems = check_paths(paths)
    for problem in problems:
        print(problem, file=sys.stderr)
    if problems:
        print(
            f"check_blocks: {len(problems)} raw block-table mutation(s)",
            file=sys.stderr,
        )
        return 1
    print("check_blocks: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv))
